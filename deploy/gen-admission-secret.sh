#!/usr/bin/env bash
# Cert bootstrap for the admission webhook front (the reference's
# installer/dockerfile/webhook-manager gen-admission-secret.sh analogue):
# self-signed CA + server cert for the in-cluster service DNS name,
# stored as a TLS secret the shim mounts, with the CA bundle substituted
# into deploy/kubernetes/webhook.yaml before applying it.
#
# Usage: deploy/gen-admission-secret.sh [namespace] [service-name]
set -euo pipefail

NAMESPACE="${1:-volcano-tpu-system}"
SERVICE="${2:-volcano-admission-service}"
SECRET="volcano-admission-secret"
WORKDIR="$(mktemp -d)"
trap 'rm -rf "$WORKDIR"' EXIT

CN="${SERVICE}.${NAMESPACE}.svc"

openssl genrsa -out "$WORKDIR/ca.key" 2048
openssl req -x509 -new -nodes -key "$WORKDIR/ca.key" -days 3650 \
  -subj "/CN=volcano-admission-ca" -out "$WORKDIR/ca.crt"

openssl genrsa -out "$WORKDIR/tls.key" 2048
openssl req -new -key "$WORKDIR/tls.key" -subj "/CN=${CN}" \
  -out "$WORKDIR/server.csr"
cat > "$WORKDIR/ext.cnf" <<EOF
subjectAltName = DNS:${SERVICE}, DNS:${SERVICE}.${NAMESPACE}, DNS:${CN}
EOF
openssl x509 -req -in "$WORKDIR/server.csr" -CA "$WORKDIR/ca.crt" \
  -CAkey "$WORKDIR/ca.key" -CAcreateserial -days 3650 \
  -extfile "$WORKDIR/ext.cnf" -out "$WORKDIR/tls.crt"

kubectl -n "$NAMESPACE" create secret tls "$SECRET" \
  --cert="$WORKDIR/tls.crt" --key="$WORKDIR/tls.key" \
  --dry-run=client -o yaml | kubectl apply -f -

CA_BUNDLE="$(base64 < "$WORKDIR/ca.crt" | tr -d '\n')"
sed "s|\${CA_BUNDLE}|${CA_BUNDLE}|g" \
  "$(dirname "$0")/kubernetes/webhook.yaml" | kubectl apply -f -

echo "admission secret ${SECRET} created; webhook configurations applied"
