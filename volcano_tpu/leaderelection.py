"""Leader election over the ObjectStore — the HA story for the scheduler
and controller-manager (ref /root/reference/cmd/scheduler/app/
server.go:111-141: resourcelock + leaderelection.RunOrDie).

The store IS the coordination backend (SURVEY §5.8: the API server is the
bus), so the lock is a Lease-style object in it: holder identity + renew
deadline. Multiple scheduler/controller replicas point at the same store
(in-process, the native C++ store, or — through the snapshot RPC shim — a
real API server); exactly one holds the lease and runs, the rest retry.
A leader that misses its renew deadline loses the lease to the first
challenger, mirroring the k8s LeaseDuration/RenewDeadline/RetryPeriod
semantics.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from .apis.objects import ObjectMeta

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 2.0


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease mirror."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    renew_time: float = 0.0
    lease_duration: float = DEFAULT_LEASE_DURATION

    KIND = "Lease"


class LeaderElector:
    """RunOrDie analogue: call run() from the current thread; it blocks,
    acquiring the lease, invoking on_started_leading, renewing every
    retry_period, and invoking on_stopped_leading if the lease is lost."""

    def __init__(self, store, name: str,
                 on_started_leading: Callable[[], None],
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 identity: Optional[str] = None,
                 namespace: str = "volcano-system",
                 lease_duration: float = DEFAULT_LEASE_DURATION,
                 renew_deadline: float = DEFAULT_RENEW_DEADLINE,
                 retry_period: float = DEFAULT_RETRY_PERIOD,
                 time_fn: Callable[[], float] = time.time,
                 mono_fn: Callable[[], float] = time.monotonic):
        # Injectable time sources (vlint VT002). Lease timestamps are
        # wall-clock (``time_fn``) — they are compared ACROSS processes
        # (native store / RPC shim replicas), where a per-process
        # monotonic clock is meaningless. The renew-deadline watchdog is
        # the opposite: a PER-PROCESS elapsed interval, so it reads
        # ``mono_fn`` — measuring it on the wall clock would let an NTP
        # step backward mask lease loss (split brain) or a step forward
        # depose a healthy leader. A federated sim pins both to its
        # virtual clock to elect deterministically.
        self.time_fn = time_fn
        self.mono_fn = mono_fn
        self.store = store
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{name}-{uuid.uuid4().hex[:8]}"
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._stop = threading.Event()
        self.leading = False

    # -- lock primitives ----------------------------------------------------

    def _lease(self) -> Optional[Lease]:
        return self.store.get("Lease", self.namespace, self.name)

    def try_acquire_or_renew(self, now: Optional[float] = None) -> bool:
        """One CAS-guarded acquire/renew attempt, mirroring k8s
        resourcelock semantics: every write carries the resourceVersion it
        read, so two challengers racing on an expired lease cannot both
        win — the loser's update conflicts and it returns False.

        Timestamps come from the elector's injectable ``time_fn``
        (wall-clock by default): leases are compared across processes
        (native store / RPC shim replicas), where a per-process
        monotonic clock is meaningless."""
        now = self.time_fn() if now is None else now
        from .store import ConflictError
        lease = self._lease()
        if lease is None:
            fresh = Lease(metadata=ObjectMeta(name=self.name,
                                              namespace=self.namespace),
                          holder=self.identity, renew_time=now,
                          lease_duration=self.lease_duration)
            try:
                self.store.create(fresh)
            except ValueError:
                return False          # lost the create race; retry later
            return True
        if lease.holder != self.identity \
                and now - lease.renew_time <= lease.lease_duration:
            return False              # live lease held by someone else
        # renew (ours) or takeover (expired): CAS on the rv we just read
        claimed = Lease(
            metadata=ObjectMeta(name=self.name, namespace=self.namespace),
            holder=self.identity, renew_time=now,
            lease_duration=self.lease_duration)
        try:
            self.store.update(
                claimed, expect_rv=lease.metadata.resource_version)
        except ConflictError:
            return False              # another challenger won this round
        return True

    def release(self) -> None:
        from .store import ConflictError
        lease = self._lease()
        if lease is not None and lease.holder == self.identity:
            released = Lease(
                metadata=ObjectMeta(name=self.name,
                                    namespace=self.namespace),
                holder=self.identity, renew_time=0.0,
                lease_duration=self.lease_duration)
            try:
                self.store.update(
                    released, expect_rv=lease.metadata.resource_version)
            except ConflictError:
                pass                  # someone already took it over
        self.leading = False

    # -- the election loop --------------------------------------------------

    def run(self) -> None:
        try:
            while not self._stop.is_set():
                if self.try_acquire_or_renew():
                    break
                self._stop.wait(self.retry_period)
            if self._stop.is_set():
                return
            self.leading = True
            renewer = threading.Thread(target=self._renew_loop, daemon=True,
                                       name=f"lease-renew-{self.name}")
            renewer.start()
            self.on_started_leading()
        finally:
            was_leading = self.leading
            self.leading = False
            self._stop.set()
            if was_leading and self.on_stopped_leading is not None:
                self.on_stopped_leading()

    def _renew_loop(self) -> None:
        last_renew = self.mono_fn()
        while not self._stop.is_set():
            self._stop.wait(self.retry_period)
            if self._stop.is_set():
                return
            if self.try_acquire_or_renew():
                last_renew = self.mono_fn()
            elif self.mono_fn() - last_renew > self.renew_deadline:
                # lost the lease: stop leading (RunOrDie klog.Fatal analogue
                # — here we signal the component loop to stop instead)
                self.leading = False
                self._stop.set()
                if self.on_stopped_leading is not None:
                    self.on_stopped_leading()
                return

    def stop(self) -> None:
        self._stop.set()
