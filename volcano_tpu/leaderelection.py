"""Leader election over the ObjectStore — the HA story for the scheduler
and controller-manager (ref /root/reference/cmd/scheduler/app/
server.go:111-141: resourcelock + leaderelection.RunOrDie).

The store IS the coordination backend (SURVEY §5.8: the API server is the
bus), so the lock is a Lease-style object in it: holder identity + renew
deadline. Multiple scheduler/controller replicas point at the same store
(in-process, the native C++ store, or — through the snapshot RPC shim — a
real API server); exactly one holds the lease and runs, the rest retry.
A leader that misses its renew deadline loses the lease to the first
challenger, mirroring the k8s LeaseDuration/RenewDeadline/RetryPeriod
semantics.

Fencing (docs/robustness.md HA section): every lease ACQUISITION —
create or takeover, never a renewal — mints a monotonically increasing
**fencing epoch**. The epoch rides the lease object, so the store's CAS
makes it split-brain safe: two challengers cannot both mint epoch E+1.
The holder exposes it as ``fencing_epoch``; the scheduler stamps every
journaled bind/evict intent with it, and the executor-side fencing gate
(cache/executors.FencedBinder/FencedEvictor) rejects any operation whose
epoch is below the highest the cluster has observed. A paused or
partitioned ex-leader that wakes up mid-bind therefore physically cannot
double-bind — safety holds by construction, not by timing.

Two consumption styles:

- ``run()``: the threaded RunOrDie loop (real deployments) — blocks,
  renews on a daemon thread, fires ``on_lease_lost`` when the renew
  deadline passes without a successful renewal;
- ``step()``: one synchronous acquire/renew attempt — the cycle-driven
  HA mode (``sim --ha N`` and the scheduler shell's per-cycle gate)
  calls it each cycle instead of spawning threads, which keeps elections
  on the virtual clock and byte-deterministic.

``FlapGuard`` reuses the device_health cool-down idiom for FLAPPING
leadership: a replica that keeps losing the lease (bad clock, overloaded
host) abstains from re-contending for a doubling window instead of
thrashing the lease between replicas.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from .apis.objects import ObjectMeta
from .store_transport import TransientStoreError

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 2.0


def partition_lease_name(base: str, pid: int) -> str:
    """The per-partition Lease name of a federated control plane
    (docs/federation.md): each partition elects its own fenced leader
    under ``<base>-p<pid>``, so fencing epochs are namespaced by
    partition id — one partition's failover can never fence another's
    leader. The sim runner and ``vcctl federation status`` share this
    naming."""
    return f"{base}-p{int(pid)}"


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease mirror, extended with the fencing
    epoch (the k8s analogue would be an annotation; leaseTransitions is
    the closest stock field). ``epoch`` increments on every ACQUISITION
    (create/takeover) and is carried unchanged across renewals, so it
    totally orders leaderships."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    renew_time: float = 0.0
    lease_duration: float = DEFAULT_LEASE_DURATION
    epoch: int = 0

    KIND = "Lease"


class FlapGuard:
    """Cool-down for flapping leadership (the device_health.DeviceHealth
    idiom applied to elections): each lease LOSS opens a doubling
    abstention window during which ``may_contend()`` is False — the
    replica sits out instead of thrashing the lease. The loss streak
    resets only once a re-acquired leadership has been HELD for a full
    base cooldown (the stability horizon) — resetting on the first
    successful renewal would make the doubling unreachable, since a
    loss always follows an acquisition. Runs on an injectable
    ``time_fn`` (the sim pins virtual time)."""

    def __init__(self, cooldown_s: float = 5.0, max_cooldown_s: float = 80.0,
                 time_fn: Callable[[], float] = time.monotonic):
        self.cooldown_s = cooldown_s
        self.max_cooldown_s = max_cooldown_s
        self.time_fn = time_fn
        self.consecutive_losses = 0
        self.total_losses = 0
        self._until: Optional[float] = None
        self._stable_after: Optional[float] = None

    def record_loss(self) -> float:
        """Leadership lost: open (or double) the abstention window.
        Returns the window length in force."""
        self.consecutive_losses += 1
        self.total_losses += 1
        window = min(self.cooldown_s * (2 ** (self.consecutive_losses - 1)),
                     self.max_cooldown_s)
        self._until = self.time_fn() + window
        self._stable_after = None
        return window

    def record_stable(self) -> None:
        """Called on every successful acquire/renew. The first call after
        a loss stamps the stability horizon (now + base cooldown); the
        streak resets only when leadership is still held past it."""
        if self.consecutive_losses == 0:
            return
        now = self.time_fn()
        if self._stable_after is None:
            self._stable_after = now + self.cooldown_s
            return
        if now >= self._stable_after:
            self.consecutive_losses = 0
            self._until = None
            self._stable_after = None

    def may_contend(self) -> bool:
        return self._until is None or self.time_fn() >= self._until

    def detail(self) -> dict:
        return {
            "may_contend": self.may_contend(),
            "consecutive_losses": self.consecutive_losses,
            "total_losses": self.total_losses,
            "cooldown_remaining_s": max(0.0, self._until - self.time_fn())
            if self._until is not None else 0.0,
        }


class LeaderElector:
    """RunOrDie analogue: call run() from the current thread; it blocks,
    acquiring the lease, invoking on_started_leading, renewing every
    retry_period, and invoking on_stopped_leading if the lease is lost."""

    def __init__(self, store, name: str,
                 on_started_leading: Callable[[], None],
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 identity: Optional[str] = None,
                 namespace: str = "volcano-system",
                 lease_duration: float = DEFAULT_LEASE_DURATION,
                 renew_deadline: float = DEFAULT_RENEW_DEADLINE,
                 retry_period: float = DEFAULT_RETRY_PERIOD,
                 time_fn: Callable[[], float] = time.time,
                 mono_fn: Callable[[], float] = time.monotonic,
                 on_lease_lost: Optional[Callable[[], None]] = None,
                 authority=None,
                 flap_guard: Optional[FlapGuard] = None):
        # Injectable time sources (vlint VT002). Lease timestamps are
        # wall-clock (``time_fn``) — they are compared ACROSS processes
        # (native store / RPC shim replicas), where a per-process
        # monotonic clock is meaningless. The renew-deadline watchdog is
        # the opposite: a PER-PROCESS elapsed interval, so it reads
        # ``mono_fn`` — measuring it on the wall clock would let an NTP
        # step backward mask lease loss (split brain) or a step forward
        # depose a healthy leader. A federated sim pins both to its
        # virtual clock to elect deterministically.
        self.time_fn = time_fn
        self.mono_fn = mono_fn
        self.store = store
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{name}-{uuid.uuid4().hex[:8]}"
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._stop = threading.Event()
        self.leading = False
        # fencing (docs/robustness.md): the epoch of OUR current (or most
        # recent) leadership. Deliberately NOT reset on lease loss — a
        # fenced ex-leader keeps stamping operations with its stale epoch,
        # which is exactly what the executor gate rejects.
        self.fencing_epoch = 0
        # distinct from on_stopped_leading (which also fires on voluntary
        # stop): fires only when the lease was LOST — renew-deadline miss
        # or an injected revocation. The scheduler's demote path hangs off
        # this.
        self.on_lease_lost = on_lease_lost
        # cluster-side epoch watermark (cache/executors.FencingAuthority):
        # advanced on every successful acquire so a deposed predecessor's
        # writes are rejectable the moment the new leader exists
        self.authority = authority
        self.flap_guard = flap_guard
        self.takeovers = 0          # acquisitions of an expired foreign lease
        self._last_renew_mono: Optional[float] = None

    # -- lock primitives ----------------------------------------------------

    def _lease(self) -> Optional[Lease]:
        return self.store.get("Lease", self.namespace, self.name)

    def try_acquire_or_renew(self, now: Optional[float] = None) -> bool:
        """One CAS-guarded acquire/renew attempt, mirroring k8s
        resourcelock semantics: every write carries the resourceVersion it
        read, so two challengers racing on an expired lease cannot both
        win — the loser's update conflicts and it returns False.

        Timestamps come from the elector's injectable ``time_fn``
        (wall-clock by default): leases are compared across processes
        (native store / RPC shim replicas), where a per-process
        monotonic clock is meaningless."""
        now = self.time_fn() if now is None else now
        from .store import ConflictError
        # The lease path rides the SAME store boundary as every other
        # scheduler write (docs/robustness.md store failure model): in a
        # hostile deployment the store here is a RetryingStoreTransport
        # composition, and a verb that fails PAST the retry budget
        # surfaces as TransientStoreError. That loses THIS attempt, not
        # the leadership — k8s renew semantics: only the renew deadline
        # (monotonic) deposes, and step() owns that watchdog.
        try:
            lease = self._lease()
        except TransientStoreError:
            return False
        if lease is None:
            fresh = Lease(metadata=ObjectMeta(name=self.name,
                                              namespace=self.namespace),
                          holder=self.identity, renew_time=now,
                          lease_duration=self.lease_duration, epoch=1)
            try:
                self.store.create(fresh)
            except (ValueError, ConflictError):
                return False          # lost the create race; retry later
            except TransientStoreError:
                return False
            self._claimed(1)
            return True
        if lease.holder != self.identity \
                and now - lease.renew_time <= lease.lease_duration:
            return False              # live lease held by someone else
        # renew (ours, while we believe we lead) carries the epoch
        # unchanged; any ACQUISITION — takeover of an expired foreign
        # lease, or re-claiming our own lease after we stopped leading
        # (a restarted incarnation, a fenced ex-leader re-contending) —
        # mints epoch+1. The CAS makes the mint race-free: two
        # challengers reading the same expired lease cannot both win the
        # write, so exactly one epoch E+1 ever exists.
        renewal = lease.holder == self.identity and self.leading
        epoch = int(getattr(lease, "epoch", 0)) + (0 if renewal else 1)
        claimed = Lease(
            metadata=ObjectMeta(name=self.name, namespace=self.namespace),
            holder=self.identity, renew_time=now,
            lease_duration=self.lease_duration, epoch=epoch)
        try:
            self.store.update(
                claimed, expect_rv=lease.metadata.resource_version)
        except ConflictError:
            return False              # another challenger won this round
        except TransientStoreError:
            return False              # write lost past the retry budget
        if not renewal and lease.holder != self.identity:
            self.takeovers += 1
        self._claimed(epoch)
        return True

    def _claimed(self, epoch: int) -> None:
        """A lease write of ours landed: record the epoch locally, feed
        the renew-deadline watchdog (monotonic), and advance the
        cluster-side watermark, so a deposed predecessor's stale-epoch
        operations are rejectable from this instant on."""
        self.fencing_epoch = epoch
        self._last_renew_mono = self.mono_fn()
        if self.authority is not None:
            self.authority.advance(epoch)

    def _write_released(self) -> None:
        from .store import ConflictError
        try:
            lease = self._lease()
        except TransientStoreError:
            return                    # best effort: expiry releases it
        if lease is not None and lease.holder == self.identity:
            # epoch survives a release: the next acquirer must mint a
            # HIGHER epoch than ours, or fencing would stop ordering
            # leaderships
            released = Lease(
                metadata=ObjectMeta(name=self.name,
                                    namespace=self.namespace),
                holder=self.identity, renew_time=0.0,
                lease_duration=self.lease_duration,
                epoch=int(getattr(lease, "epoch", 0)))
            try:
                self.store.update(
                    released, expect_rv=lease.metadata.resource_version)
            except (ConflictError, TransientStoreError):
                pass                  # someone already took it over

    def release(self) -> None:
        self._write_released()
        self.leading = False

    # -- cycle-driven (threadless) consumption ------------------------------

    def step(self, now: Optional[float] = None) -> bool:
        """One synchronous election/renew attempt; returns whether this
        replica leads AFTER the attempt. The cycle-driven HA mode
        (scheduler shell per cycle; ``sim --ha N`` on the virtual clock)
        calls this instead of running the threaded loops.

        k8s renew semantics: one failed renewal does not depose a live
        leader — leadership is lost only when ``renew_deadline`` elapses
        (on the per-process monotonic clock) without a successful
        renewal. A non-leader honours the FlapGuard abstention window
        before contending."""
        if not self.leading and self.flap_guard is not None \
                and not self.flap_guard.may_contend():
            return False
        ok = self.try_acquire_or_renew(now)
        mono = self.mono_fn()
        if ok:
            self._last_renew_mono = mono
            self.leading = True
            if self.flap_guard is not None:
                self.flap_guard.record_stable()
            return True
        if self.leading:
            if self._last_renew_mono is None \
                    or mono - self._last_renew_mono > self.renew_deadline:
                self._lose()
        return self.leading

    def _lose(self) -> None:
        """Leadership lost (renew deadline passed, or revoked): flip the
        flag, open the flap cool-down, fire the loss callbacks. The
        fencing epoch deliberately stays at its stale value — see
        __init__."""
        if not self.leading:
            return
        self.leading = False
        if self.flap_guard is not None:
            self.flap_guard.record_loss()
        if self.on_lease_lost is not None:
            self.on_lease_lost()
        if self.on_stopped_leading is not None:
            self.on_stopped_leading()

    def revoke(self) -> None:
        """Forcibly relinquish a held lease AND the local leading state —
        the chaos LeaseLossInjector's entry point (models the lease
        being administratively stolen / a partition expiring it). The
        lease is written back expired-with-epoch so any challenger can
        take over immediately with epoch+1."""
        self._write_released()
        self._lose()

    # -- the election loop --------------------------------------------------

    def run(self) -> None:
        try:
            while not self._stop.is_set():
                if self.try_acquire_or_renew():
                    break
                self._stop.wait(self.retry_period)
            if self._stop.is_set():
                return
            self.leading = True
            renewer = threading.Thread(target=self._renew_loop, daemon=True,
                                       name=f"lease-renew-{self.name}")
            renewer.start()
            self.on_started_leading()
        finally:
            was_leading = self.leading
            self.leading = False
            self._stop.set()
            if was_leading and self.on_stopped_leading is not None:
                self.on_stopped_leading()

    def _renew_loop(self) -> None:
        last_renew = self.mono_fn()
        while not self._stop.is_set():
            self._stop.wait(self.retry_period)
            if self._stop.is_set():
                return
            if self.try_acquire_or_renew():
                last_renew = self.mono_fn()
            elif self.mono_fn() - last_renew > self.renew_deadline:
                # lost the lease: stop leading (RunOrDie klog.Fatal analogue
                # — here we signal the component loop to stop instead).
                # _lose fires on_lease_lost + on_stopped_leading and opens
                # the flap cool-down.
                self._stop.set()
                self._lose()
                return

    def stop(self) -> None:
        self._stop.set()
