"""Elastic gang membership: the min/desired decision class.

An elastic gang admits at ``min_available`` and opportunistically expands
toward a ``desired`` member count as capacity frees; under pressure its
above-min members are the cheapest victims in the cluster. The decision
class is COUNT-based, not identity-based: any ``active - min`` surplus is
shrinkable and any pending member of an admitted gang is growable, so a
core member lost under churn is re-placed by the next grow pass instead
of deadlocking behind a surviving "surplus" member. Identity only enters
as a deterministic tie-order (task uid).

Annotations (PodGroup):

- ``volcano.sh/elastic-desired``: presence marks the gang elastic; the
  integer value is the target member count (clamped to >= min_available).
- ``volcano.sh/suspend``: ``"true"`` parks the gang — grow-shrink drains
  every member (a full-gang decision, so below-min is legal there and
  only there) and the allocate engines see an empty pending set until a
  ``resume`` command clears the mark.

Both annotations are rewritten exclusively by the Command funnel
(commands.py) at the cycle boundary, never mid-cycle.
"""

from __future__ import annotations

from typing import List

from ..api import TaskStatus

ELASTIC_DESIRED_ANNOTATION = "volcano.sh/elastic-desired"
SUSPEND_ANNOTATION = "volcano.sh/suspend"
# the node label naming its interconnect locality group (NodeInfo reads
# it into .topology_zone; cache/snapshot.py hashes it into zone_code)
TOPOLOGY_ZONE_LABEL = "volcano.sh/topology-zone"


def _annotations(job) -> dict:
    pg = getattr(job, "podgroup", None)
    if pg is None:
        return {}
    return getattr(pg, "annotations", None) or {}


def is_elastic(job) -> bool:
    """The elastic-desired annotation is the membership switch: absent
    means a classic rigid gang and every elastic code path must degrade
    to a byte-identical no-op."""
    return ELASTIC_DESIRED_ANNOTATION in _annotations(job)


def is_suspended(job) -> bool:
    return _annotations(job).get(SUSPEND_ANNOTATION, "") == "true"


def desired_members(job) -> int:
    """Target member count: the annotation value clamped to min_available
    (a desired below min is a malformed spec the webhook rejects, but a
    stale object may still carry one — clamping keeps the invariant)."""
    try:
        d = int(_annotations(job).get(ELASTIC_DESIRED_ANNOTATION, 0))
    except (TypeError, ValueError):
        d = 0
    return max(d, job.min_available)


def active_members(job) -> int:
    """Members currently holding (or pledged) capacity — the same count
    gang admission reads (JobInfo.ready_task_num)."""
    return job.ready_task_num()


def shrink_allowance(job) -> int:
    """How many members an elastic decision (preempt victim tier, scale
    verb, pressure shrink) may take WITHOUT a full-gang decision:
    ``active - min``, floored at zero. Rigid gangs always answer 0."""
    if not is_elastic(job):
        return 0
    return max(active_members(job) - job.min_available, 0)


def shrink_candidates(job) -> List:
    """Bound/running members in eviction-preference order: highest task
    uid first. When a gang is fully placed these are exactly the members
    admission filled last; under churn the order stays total and
    deterministic regardless of which members survived. Callers must cap
    the slice they take at shrink_allowance (or drain fully for the
    suspend full-gang decision)."""
    out: List = []
    for status in (TaskStatus.BOUND, TaskStatus.RUNNING):
        out.extend(job.task_status_index.get(status, {}).values())
    out.sort(key=lambda t: t.uid, reverse=True)
    return out


def grow_candidates(job) -> List:
    """Pending members with a real request, lowest uid first — the order
    grow fills them. Only members whose placement the solver can account
    for are growable (best-effort pendings already ride backfill)."""
    pending = [t for t in job.task_status_index.get(TaskStatus.PENDING,
                                                    {}).values()
               if not t.init_resreq.is_empty()]
    pending.sort(key=lambda t: t.uid)
    return pending


def allocate_pending_filter(job, tasks):
    """Session hook consumed by allocate._pending_tasks (attribute
    ``ssn.elastic_pending_filter``, installed by the elastic_gang
    plugin): narrows the pending set the batched solvers see so the
    min/desired split becomes a solver-visible decision class.

    - rigid gang: unchanged (byte-identical to the pre-elastic planner);
    - suspended: empty — a parked gang asks for nothing;
    - admitted (active >= min): empty — expansion beyond min belongs to
      the grow-shrink stage, which only moves when no starving gang
      wants the capacity, so surplus members can never outbid admission;
    - not yet admitted: the first ``min - active`` pendings by uid, so
      the gang vote fires exactly at min and the admission footprint is
      the smallest the gang can run with.
    """
    if not tasks or not is_elastic(job):
        return tasks
    if is_suspended(job):
        return []
    need = job.min_available - active_members(job)
    if need <= 0:
        return []
    ordered = sorted(tasks, key=lambda t: t.uid)
    return ordered[:need]
