"""grow-shrink action: the elastic stage between allocate and preempt.

Runs after allocate (admission at min already settled this cycle) and
before preempt/reclaim (so voluntarily freed capacity is visible before
anyone considers forced victims). Four sub-passes, in order:

1. suspend drain — a suspended gang gives up EVERY member. This is the
   one full-gang decision in the file: below-min is legal here because
   the whole gang stops, not a fraction of it.
2. scale shrink — a gang bound above its (possibly just re-written)
   desired count sheds the excess, highest-uid members first.
3. pressure shrink — when admission-starved gangs are waiting, shed a
   bounded number of above-min members per cycle from the most-inflated
   elastic gangs, before preempt has to pick forced victims.
4. grow — only when NO gang is starving for admission: place pending
   members of admitted elastic gangs toward desired through the host
   placer (predicates + node order, so the topology compactness bonus
   steers members into the gang's anchor zone), binding through
   ``ssn.allocate`` -> dispatch -> cache.bind — the journaled funnel.

Every grow/shrink additionally journals an ``elastic_grow`` /
``elastic_shrink`` control record stamped with the fencing epoch
(vlint VT020: elastic mutations ride journaled+fenced funnels).
"""

from __future__ import annotations

import logging

from ..api import TaskStatus
from ..metrics import (register_below_min_eviction, register_gang_growth,
                       register_gang_shrink, set_elastic_members,
                       set_topology_spread)
from ..obs import trace as obs_trace
from ..obs.lifecycle import TIMELINE
from ..utils.scheduler_helper import (predicate_nodes, prioritize_nodes,
                                      select_best_node)
from ..actions.base import Action
from .membership import (active_members, desired_members, grow_candidates,
                         is_elastic, is_suspended, shrink_allowance,
                         shrink_candidates)

log = logging.getLogger(__name__)


def _conf_int(ssn, key: str, default: int) -> int:
    for conf in getattr(ssn, "configurations", []) or []:
        if getattr(conf, "name", "") == "grow-shrink":
            try:
                return int((conf.arguments or {}).get(key, default))
            except (TypeError, ValueError):
                return default
    return default


class GrowShrinkAction(Action):
    NAME = "grow-shrink"

    def __init__(self):
        # per-cycle stats, harvested by the sim runner / report after
        # each execute (reset at entry)
        self.last_stats = {}

    def execute(self, ssn) -> None:
        with obs_trace.span("grow_shrink"):
            self._execute(ssn)

    # -- journal witness ----------------------------------------------------

    def _journal_elastic(self, ssn, kind: str, task, reason: str = "") -> None:
        """Every elastic mutation leaves a durable, epoch-stamped control
        record beside the bind/evict intent the session funnel already
        wrote — the VT020 witness and the soak's byte-diff evidence.
        The record carries a lifecycle ctx stamp (vlint VT022) so a
        journal follower continues the job's timeline; the local store
        records the same event first and dedupes the replay."""
        cache = ssn.cache
        epoch = cache.fencing_epoch()
        ctx = TIMELINE.stamp(part=getattr(cache, "obs_part", None),
                             epoch=epoch)
        if ctx is not None:
            ev = "grow" if kind == "elastic_grow" else "shrink"
            TIMELINE.record(task.job, ev, ctx=ctx,
                            node=task.node_name or None,
                            reason=reason or None)
        journal = getattr(cache, "journal", None)
        if journal is None:
            return
        fields = {
            "job": task.job, "task": task.uid, "node": task.node_name,
            "reason": reason, "epoch": epoch}
        if ctx is not None:
            fields["ctx"] = ctx
        journal.record_control(kind, fields)

    # -- mutation funnels ---------------------------------------------------

    def _shrink_one(self, ssn, job, task, reason: str,
                    full_gang: bool = False) -> bool:
        """Evict one elastic member through the session funnel. Refuses
        to go below min unless this is a full-gang decision (suspend
        drain); the below-min counter is the witness that the guard
        held — it must stay zero outside full-gang drains."""
        if not full_gang and active_members(job) - 1 < job.min_available:
            register_below_min_eviction()
            log.error("refusing below-min shrink of %s (%s)", job.uid, reason)
            return False
        if task.node_name not in ssn.nodes:
            # the member's node left the snapshot (drained/cordoned):
            # there is no session-visible placement to release this
            # cycle. Retry next cycle — a restore brings the node back,
            # a node death requeues the member through the cache funnel.
            return False
        ssn.evict(task, f"elastic-{reason}")
        self._journal_elastic(ssn, "elastic_shrink", task, reason)
        register_gang_shrink(reason)
        self.last_stats["shrinks"] = self.last_stats.get("shrinks", 0) + 1
        return True

    def _grow_one(self, ssn, job, task) -> bool:
        """Place one pending member of an admitted gang. The gang is
        ready (active >= min), so ``ssn.allocate`` dispatches the bind
        immediately — cache.bind journals + fences it."""
        fit = [n for n in ssn.node_list
               if task.resreq.less_equal(n.idle) and n.ready]
        if not fit:
            return False
        feasible, _ = predicate_nodes(task, fit, ssn.predicate_fn)
        if not feasible:
            return False
        scores = prioritize_nodes(task, feasible, ssn.batch_node_order_fn,
                                  ssn.node_order_fn)
        node = select_best_node(scores)
        if node is None:
            return False
        ssn.allocate(task, node)
        self._journal_elastic(ssn, "elastic_grow", task, "grow")
        register_gang_growth()
        self.last_stats["grows"] = self.last_stats.get("grows", 0) + 1
        return True

    # -- the stage ----------------------------------------------------------

    def _execute(self, ssn) -> None:
        self.last_stats = {"grows": 0, "shrinks": 0, "suspended_drained": 0}
        elastic = sorted((j for j in ssn.jobs.values() if is_elastic(j)),
                         key=lambda j: j.uid)
        if not elastic:
            self._publish_gauges(ssn, elastic)
            return

        # 1. suspend drain: the full-gang decision.
        for job in elastic:
            if not is_suspended(job):
                continue
            drained = 0
            for task in shrink_candidates(job):
                if self._shrink_one(ssn, job, task, "suspend",
                                    full_gang=True):
                    drained += 1
            if drained:
                self.last_stats["suspended_drained"] += 1

        # 2. scale shrink: above the (possibly freshly scaled) desired.
        for job in elastic:
            if is_suspended(job):
                continue
            excess = active_members(job) - desired_members(job)
            if excess <= 0:
                continue
            excess = min(excess, shrink_allowance(job))
            for task in shrink_candidates(job)[:excess]:
                self._shrink_one(ssn, job, task, "scale")

        # 3/4. pressure shrink vs grow: starving gangs get first claim.
        starving = self._starving_exists(ssn)
        if starving:
            budget = _conf_int(ssn, "max-pressure-shrinks", 2)
            donors = sorted((j for j in elastic
                             if not is_suspended(j) and shrink_allowance(j) > 0),
                            key=lambda j: (-shrink_allowance(j), j.uid))
            for job in donors:
                if budget <= 0:
                    break
                take = min(shrink_allowance(job), budget)
                for task in shrink_candidates(job)[:take]:
                    if self._shrink_one(ssn, job, task, "pressure"):
                        budget -= 1
        else:
            max_grows = _conf_int(ssn, "max-grows-per-cycle", 0)
            grown = 0
            for job in elastic:
                if is_suspended(job) or not job.ready():
                    continue
                need = desired_members(job) - active_members(job)
                for task in grow_candidates(job)[:max(need, 0)]:
                    if max_grows and grown >= max_grows:
                        break
                    if self._grow_one(ssn, job, task):
                        grown += 1

        self._publish_gauges(ssn, elastic)

    @staticmethod
    def _starving_exists(ssn) -> bool:
        """A valid, unadmitted gang with real pending requests is waiting
        for capacity — elastic surplus must not outbid admission."""
        for job in ssn.jobs.values():
            if job.ready():
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            pend = job.task_status_index.get(TaskStatus.PENDING, {})
            if any(not t.init_resreq.is_empty() for t in pend.values()):
                return True
        return False

    def _publish_gauges(self, ssn, elastic) -> None:
        above_min = sum(max(active_members(j) - j.min_available, 0)
                        for j in elastic)
        set_elastic_members(above_min)
        spread = 0
        for job in ssn.jobs.values():
            zones = set()
            for status in (TaskStatus.BOUND, TaskStatus.RUNNING,
                           TaskStatus.BINDING, TaskStatus.ALLOCATED):
                for t in job.task_status_index.get(status, {}).values():
                    node = ssn.nodes.get(t.node_name)
                    if node is not None and node.topology_zone:
                        zones.add(node.topology_zone)
            if len(zones) > 1:
                spread += 1
        set_topology_spread(spread)
        self.last_stats["above_min_members"] = above_min
        self.last_stats["topology_spread"] = spread


# self-registration: actions/__init__ imports this module for the side
# effect (guarded against the grow_shrink -> actions.base import cycle),
# so "grow-shrink" resolves from conf like any in-tree action
from ..framework.registry import register_action  # noqa: E402

register_action(GrowShrinkAction())
