"""Elastic gangs: min/desired membership as a scheduler decision class.

- membership.py — annotations, counts, the allocate pending filter;
- commands.py — the journaled+fenced suspend/resume/scale funnel;
- grow_shrink.py — the elastic stage between allocate and preempt.

The plugin half (pending filter installation, victim guards, topology
node-order bonus) lives in plugins/elastic_gang.py; the device victim
tier in actions/evict_tpu.py.
"""

from .commands import VERBS, CommandFunnel
from .membership import (ELASTIC_DESIRED_ANNOTATION, SUSPEND_ANNOTATION,
                         TOPOLOGY_ZONE_LABEL, active_members,
                         allocate_pending_filter, desired_members,
                         grow_candidates, is_elastic, is_suspended,
                         shrink_allowance, shrink_candidates)

__all__ = [
    "CommandFunnel", "VERBS", "GrowShrinkAction",
    "ELASTIC_DESIRED_ANNOTATION", "SUSPEND_ANNOTATION",
    "TOPOLOGY_ZONE_LABEL",
    "active_members", "allocate_pending_filter", "desired_members",
    "grow_candidates", "is_elastic", "is_suspended", "shrink_allowance",
    "shrink_candidates",
]


def __getattr__(name):
    # GrowShrinkAction is exported lazily: grow_shrink.py imports
    # actions.base, and an eager import here would close the
    # elastic_gang -> actions -> elastic_gang cycle at package-init time
    if name == "GrowShrinkAction":
        from .grow_shrink import GrowShrinkAction
        return GrowShrinkAction
    raise AttributeError(name)
