"""Lifecycle Command funnel: suspend / resume / scale, journaled + fenced.

The bus/v1alpha1 Command CR reduced to one in-process funnel. Operators
(vcctl, the sim's job_command events, tests) submit verbs against a gang;
nothing mutates scheduler-visible state at submit time. The scheduler
shell drains the funnel exactly once per cycle, at the cycle boundary
BEFORE the snapshot opens, so a verb's annotation rewrite is atomic with
respect to scheduling decisions — no cycle ever sees half a command.

Contract (docs/design/elastic-gangs.md, enforced by vlint VT020):

- ``submit()`` journals a ``command`` control record — durable (fsynced)
  and stamped with the CURRENT fencing epoch — before the verb becomes
  visible to the consumer queue. A submit carrying a stale expected
  epoch is rejected outright: a deposed leader's verbs never enqueue.
- ``consume()`` applies each verb as an annotation rewrite on the live
  job, marks the job dirty for the incremental snapshot, and journals a
  ``command_applied`` record stamped with the apply-time epoch. Verbs
  against jobs that disappeared are dropped (journaled as such).
- suspend does NOT evict here. It only marks the gang; the drain runs
  through grow-shrink's session evict path — the journaled evict funnel
  — never around it.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional, Tuple

from .membership import ELASTIC_DESIRED_ANNOTATION, SUSPEND_ANNOTATION

log = logging.getLogger(__name__)

VERBS = ("suspend", "resume", "scale")


class CommandFunnel:
    """Single-consumer command queue bound to one SchedulerCache."""

    def __init__(self, cache):
        self._cache = cache
        self._lock = threading.Lock()
        self._pending: List[Tuple[str, str, Optional[int]]] = []
        self.submitted = 0
        self.rejected = 0
        self.applied = 0
        self.dropped = 0

    # -- producer side ------------------------------------------------------

    def submit(self, verb: str, job_uid: str, value: Optional[int] = None,
               expected_epoch: Optional[int] = None) -> bool:
        """Enqueue a lifecycle verb. Returns False (without enqueueing)
        when ``expected_epoch`` no longer matches the cache's fencing
        epoch — the submitter lost a leadership race and its intent is
        stale by definition."""
        if verb not in VERBS:
            raise ValueError(f"unknown command verb {verb!r}")
        if verb == "scale":
            if value is None:
                raise ValueError("scale requires a member-count value")
            value = int(value)
            if value < 0:
                raise ValueError("scale value must be >= 0")
        else:
            value = None
        epoch = self._cache.fencing_epoch()
        if expected_epoch is not None and expected_epoch != epoch:
            with self._lock:
                self.rejected += 1
            log.warning("command %s(%s) rejected: epoch %s != current %s",
                        verb, job_uid, expected_epoch, epoch)
            return False
        journal = getattr(self._cache, "journal", None)
        if journal is not None:
            journal.record_control("command", {
                "verb": verb, "job": job_uid, "value": value, "epoch": epoch})
        with self._lock:
            self._pending.append((verb, job_uid, value))
            self.submitted += 1
        return True

    # -- consumer side (scheduler shell, cycle boundary) --------------------

    def consume(self) -> int:
        """Drain and apply every queued verb against the live cache.
        Returns the number applied. Runs under the cache lock so watcher
        threads never observe a half-rewritten annotation set."""
        with self._lock:
            batch, self._pending = list(self._pending), []
        if not batch:
            return 0
        cache = self._cache
        journal = getattr(cache, "journal", None)
        applied = dropped = 0
        with cache._lock:
            for verb, job_uid, value in batch:
                job = cache.jobs.get(job_uid)
                if job is None or getattr(job, "podgroup", None) is None:
                    dropped += 1
                    if journal is not None:
                        journal.record_control("command_dropped", {
                            "verb": verb, "job": job_uid, "value": value,
                            "epoch": cache.fencing_epoch()})
                    log.warning("command %s(%s) dropped: job gone",
                                verb, job_uid)
                    continue
                ann = job.podgroup.annotations
                if verb == "suspend":
                    ann[SUSPEND_ANNOTATION] = "true"
                elif verb == "resume":
                    ann.pop(SUSPEND_ANNOTATION, None)
                else:  # scale
                    ann[ELASTIC_DESIRED_ANNOTATION] = str(value)
                cache.mark_job_dirty(job.uid)
                if journal is not None:
                    journal.record_control("command_applied", {
                        "verb": verb, "job": job_uid, "value": value,
                        "epoch": cache.fencing_epoch()})
                applied += 1
        with self._lock:
            self.applied += applied
            self.dropped += dropped
        return applied

    def resolve_job(self, name: str, namespace: str = "default"
                    ) -> Optional[str]:
        """Map an operator-facing job name to the cache's job uid.
        Accepts a raw uid, the namespace-qualified form store-ingested
        jobs carry, or a (namespace, name) pair matched against the live
        job set — so vcctl works against sim jobs (bare-name uids) and
        store-wired ones alike."""
        jobs = self._cache.jobs
        if name in jobs:
            return name
        qualified = f"{namespace}/{name}"
        if qualified in jobs:
            return qualified
        for uid, job in jobs.items():
            if getattr(job, "name", None) == name and \
                    getattr(job, "namespace", "default") == namespace:
                return uid
        return None

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def stats(self) -> dict:
        with self._lock:
            return {"submitted": self.submitted, "applied": self.applied,
                    "rejected": self.rejected, "dropped": self.dropped,
                    "pending": len(self._pending)}
