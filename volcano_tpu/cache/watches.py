"""Resumable watch streams: the informer contract over the store boundary.

A real informer's watch connection dies all the time; the client
re-watches from its last seen resourceVersion and — when the server
answers 410 Gone — relists and reconciles. This module gives the cache
wiring (store_wiring.py) exactly that behavior over any store-shaped
source (the raw ObjectStore, or the faulty/retrying transports of
store_transport.py):

- :class:`ResumableWatch` is ONE stream: it tracks the last delivered
  resourceVersion (bookmarks keep it fresh while idle), normalizes the
  event stream against its ``known`` object map so the downstream cache
  handler sees each object's lifecycle exactly once (a replayed ADDED
  for a known pod is delivered as UPDATED with the previous object; a
  DELETED for an unknown key is dropped), and recovers a torn stream by
  re-watching from ``last_rv`` — or, on :class:`GoneError`, by the
  list-then-watch relist that neither double-adds pods nor drops a
  delete that raced the relist (tests/test_store_transport.py proves
  both properties);
- :class:`WatchManager` owns a cache's streams: ``step()`` (called by
  the scheduler epilogue and per sim cycle) resumes whatever tore,
  ticks bookmarks, resets the retry funnel's per-cycle budget, and
  publishes stream staleness to /healthz?detail and
  volcano_store_watch_staleness.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

from ..store import ADDED, BOOKMARK, DELETED, UPDATED, GoneError

log = logging.getLogger(__name__)


def _key(obj) -> str:
    return obj.metadata.key()


class ResumableWatch:
    """One resumable watch stream over ``source`` for ``kind``; delivers
    normalized (event, obj, old) triples to ``handler`` — the cache
    wiring's per-kind informer handler."""

    def __init__(self, source, kind: str, handler: Callable):
        self.source = source
        self.kind = kind
        self.handler = handler
        self.last_rv = 0
        # key -> (obj, rv at delivery): the informer store. Normalizing
        # against it is what makes resume/relist exactly-once for the
        # downstream cache (cache.add_task is NOT idempotent — a
        # double-ADD double-counts a placed pod's accounting).
        self.known: Dict[str, Tuple[object, int]] = {}
        self.handle = None
        self.resumes = 0
        self.relists = 0
        self._start()

    # -- lifecycle -----------------------------------------------------------

    def _start(self) -> None:
        """Initial list-then-watch (the informer ListAndWatch): a
        consistent list anchors ``last_rv``; the subscription replays
        anything newer from the backlog — no gap, no overlap."""
        self._apply_list()
        self._subscribe(self.last_rv)

    def _subscribe(self, since_rv: int) -> None:
        self.handle = self.source.watch(self.kind, self._on_event,
                                        since_rv=since_rv, with_rv=True)

    def cancel(self) -> None:
        if self.handle is not None and hasattr(self.handle, "cancel"):
            self.handle.cancel()
        elif self.handle is not None:
            # raw-store watcher token
            self.source.unwatch(self.kind, self.handle)
        self.handle = None

    @property
    def torn(self) -> bool:
        return self.handle is None or getattr(self.handle, "torn", False)

    def tear(self) -> None:
        """Test/sim affordance: kill the stream as the transport would."""
        if self.handle is not None and hasattr(self.handle, "tear"):
            self.handle.tear()
        else:
            self.cancel()

    # -- event normalization -------------------------------------------------

    def _on_event(self, event: str, obj, old, rv: int) -> None:
        if event == BOOKMARK:
            self.last_rv = max(self.last_rv, rv)
            return
        self.last_rv = max(self.last_rv, rv)
        key = _key(obj)
        prev = self.known.get(key)
        if event == DELETED:
            if prev is None:
                return                    # never knew it: nothing to undo
            self.known.pop(key, None)
            self.handler(DELETED, obj, None)
            return
        if prev is not None and rv and rv <= prev[1]:
            return                        # duplicate/stale replay
        self.known[key] = (obj, rv or getattr(obj.metadata,
                                              "resource_version", 0))
        if prev is None:
            self.handler(ADDED, obj, None)
        else:
            # an ADDED replay of a known object is an UPDATE downstream;
            # prefer the event's own old snapshot when the store sent one
            self.handler(UPDATED, obj,
                         old if old is not None else prev[0])

    # -- recovery ------------------------------------------------------------

    def _apply_list(self) -> None:
        """Reconcile ``known`` (and the downstream cache) against a
        fresh consistent list: new keys ADD, changed keys UPDATE with the
        previously delivered object, keys missing from the list are the
        deletes that raced — delivered as DELETED, never silently
        dropped. Unchanged keys are skipped (no double-add)."""
        objs, rv = self.source.list_with_rv(self.kind)
        listed = {_key(o): o for o in objs}
        for key in sorted(set(self.known) - set(listed)):
            prev, _ = self.known.pop(key)
            self.handler(DELETED, prev, None)
        for key in sorted(listed):
            obj = listed[key]
            orv = getattr(obj.metadata, "resource_version", 0)
            prev = self.known.get(key)
            if prev is None:
                self.known[key] = (obj, orv)
                self.handler(ADDED, obj, None)
            elif orv > prev[1]:
                self.known[key] = (obj, orv)
                self.handler(UPDATED, obj, prev[0])
        self.last_rv = max(self.last_rv, rv)

    def resume(self) -> Optional[str]:
        """Recover a torn stream: re-watch from ``last_rv`` (the backlog
        replays what was missed), falling back to the full relist on 410
        Gone. Returns the outcome ("resume"|"relist") or None when the
        stream is live."""
        if not self.torn:
            return None
        from .. import metrics
        self.cancel()
        try:
            self._subscribe(self.last_rv)
            self.resumes += 1
            metrics.register_watch_resume("resume")
            return "resume"
        except GoneError:
            self._apply_list()
            self._subscribe(self.last_rv)
            self.relists += 1
            metrics.register_watch_resume("relist")
            return "relist"

    def detail(self) -> dict:
        return {"kind": self.kind, "last_rv": self.last_rv,
                "torn": self.torn, "known": len(self.known),
                "resumes": self.resumes, "relists": self.relists}


class WatchManager:
    """A cache's resumable watch streams plus the per-cycle upkeep the
    scheduler shell drives (Scheduler._cycle_epilogue → ``step()``)."""

    def __init__(self, source):
        self.source = source
        self.watches: List[ResumableWatch] = []

    def add(self, kind: str, handler: Callable) -> ResumableWatch:
        w = ResumableWatch(self.source, kind, handler)
        self.watches.append(w)
        return w

    def torn(self) -> List[ResumableWatch]:
        return [w for w in self.watches if w.torn]

    def staleness(self) -> int:
        """Max resourceVersion lag across streams — how far the most
        behind (torn) stream trails the store."""
        cur = self.source.current_rv() \
            if hasattr(self.source, "current_rv") else 0
        return max((cur - w.last_rv for w in self.watches), default=0)

    def step(self) -> int:
        """One upkeep tick: resume torn streams, emit bookmarks so idle
        streams' resume points stay inside the backlog window, reset the
        retry funnel's per-cycle budget, publish staleness + the store
        /healthz?detail fragment. Returns the number of streams
        recovered."""
        from .. import metrics
        recovered = 0
        for w in self.watches:
            try:
                if w.resume() is not None:
                    recovered += 1
            except Exception:
                # a failed resume (e.g. the relist itself hit a transient
                # past the retry budget) leaves the stream torn; the next
                # step retries — degradation, not a crashed cycle
                log.exception("watch resume for %s failed; stream stays "
                              "torn until the next cycle", w.kind)
        if hasattr(self.source, "emit_bookmarks"):
            self.source.emit_bookmarks()
        if hasattr(self.source, "new_cycle"):
            self.source.new_cycle()
        metrics.set_store_watch_staleness(self.staleness())
        detail = {"wired": True, "staleness": self.staleness(),
                  "streams": [w.detail() for w in self.watches]}
        if hasattr(self.source, "detail"):
            detail["retry_funnel"] = self.source.detail()
        metrics.set_store_detail(detail)
        return recovered
