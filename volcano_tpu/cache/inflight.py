"""In-flight side-effect ledger: the liveness half of the feedback plane.

A bind/evict that the executor ACCEPTED is not DONE — the cluster still
owes the scheduler a feedback ack (the kubelet flipping the pod Running,
the delete confirmation for an eviction). Every prior robustness layer
assumed that ack arrives promptly and exactly once; this ledger drops
that assumption (docs/robustness.md, feedback failure model): every
journaled bind/evict the executor accepted registers an intent with an
ACK DEADLINE here, the FeedbackChannel (cache/feedback.py) resolves
entries as acks are consumed, and the scheduler epilogue's watchdog
(``SchedulerCache.process_expired_inflight``) re-validates expired
entries against cluster truth and resolves them through the existing
journaled repair/rollback/resync ladder — so a delayed, dropped,
duplicated or reordered ack can never wedge in-flight state forever.

One entry per task uid: registering a NEW intent for a uid supersedes
the older one — the newest executor-accepted operation owns the task,
and a late ack for the superseded intent is exactly what the
FeedbackChannel's normalizer classifies stale.

All timing runs on an injectable ``time_fn`` (vlint VT002); the sim pins
it to the virtual clock so watchdog expiry is deterministic.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

# A cluster ack outstanding longer than this is presumed lost and the
# watchdog re-validates the side effect against cluster truth. Wall
# deployments keep the generous default (a busy kubelet can be slow);
# the sim pins a few virtual periods so soaks exercise expiry.
DEFAULT_ACK_TIMEOUT_S = 60.0


class InflightEntry:
    """One executor-accepted side effect awaiting its cluster ack."""

    __slots__ = ("op", "uid", "job", "node", "seq", "registered_at",
                 "deadline")

    def __init__(self, op: str, uid: str, job: str, node: str,
                 seq: Optional[int], registered_at: float,
                 deadline: float):
        self.op = op                    # "bind" | "evict"
        self.uid = uid
        self.job = job
        self.node = node                # bind target / evictee's node
        self.seq = seq                  # journal seq of the intent (or None)
        self.registered_at = registered_at
        self.deadline = deadline

    def __repr__(self):
        return (f"InflightEntry(op={self.op}, uid={self.uid}, "
                f"node={self.node}, deadline={self.deadline})")


class InflightLedger:
    """Open in-flight entries keyed by task uid, with resolution
    counters. Thread-safe (the cache funnels and watch threads race)."""

    def __init__(self, time_fn=time.monotonic,
                 ack_timeout_s: float = DEFAULT_ACK_TIMEOUT_S):
        self.time_fn = time_fn
        self.ack_timeout_s = ack_timeout_s
        self._lock = threading.Lock()
        self._open: Dict[str, InflightEntry] = {}
        self.registered = 0
        # resolution -> count (all-time for this ledger): acked (the
        # normal path), superseded (a newer intent took the task, or the
        # expired entry no longer matched cache intent), repaired (the
        # watchdog recovered a lost ack), rolled_back (cluster truth
        # lacked the bind), reissued (cluster truth lacked the evict;
        # re-queued through resync), aborted (executor failed — nothing
        # was in flight), lost (node death requeued the member), gone
        # (the task left the cache)
        self.resolved: Dict[str, int] = {}

    def register(self, op: str, uid: str, job: str, node: str = "",
                 seq: Optional[int] = None) -> InflightEntry:
        """Arm the ack deadline for an intent about to execute; any older
        open entry for the uid is superseded (the newest intent owns the
        task)."""
        now = self.time_fn()
        entry = InflightEntry(op, uid, job, node, seq, now,
                              now + self.ack_timeout_s)
        with self._lock:
            if uid in self._open:
                self.resolved["superseded"] = \
                    self.resolved.get("superseded", 0) + 1
            self._open[uid] = entry
            self.registered += 1
        return entry

    def resolve(self, op: Optional[str], uid: str,
                how: str = "acked") -> bool:
        """Close the open entry for ``uid`` (``op=None`` matches either
        op). Returns whether an entry was closed; idempotent."""
        with self._lock:
            entry = self._open.get(uid)
            if entry is None or (op is not None and entry.op != op):
                return False
            del self._open[uid]
            self.resolved[how] = self.resolved.get(how, 0) + 1
        return True

    def abort(self, op: str, uid: str) -> bool:
        """The executor failed and the funnel rolled back: nothing is in
        flight."""
        return self.resolve(op, uid, "aborted")

    def task_deleted(self, uid: str) -> None:
        """The task left the cache (gang completed / pod deleted). A
        pending EVICT entry resolves as acked — the delete IS the evict
        confirmation; a pending bind entry is moot."""
        with self._lock:
            entry = self._open.pop(uid, None)
            if entry is None:
                return
            how = "acked" if entry.op == "evict" else "gone"
            self.resolved[how] = self.resolved.get(how, 0) + 1

    def expired(self, now: Optional[float] = None) -> List[InflightEntry]:
        """Entries past their ack deadline, registration order. NOT
        removed — the watchdog resolves each with its verdict."""
        now = self.time_fn() if now is None else now
        with self._lock:
            return [e for e in self._open.values() if e.deadline <= now]

    def entries(self) -> List[InflightEntry]:
        with self._lock:
            return list(self._open.values())

    def open_count(self) -> int:
        with self._lock:
            return len(self._open)

    def oldest_age(self, now: Optional[float] = None) -> float:
        now = self.time_fn() if now is None else now
        with self._lock:
            if not self._open:
                return 0.0
            return max(now - e.registered_at for e in self._open.values())

    def clear(self) -> None:
        """Process death: the ledger is volatile (the journal, not this,
        is the durable record — startup reconciliation re-derives what
        matters)."""
        with self._lock:
            self._open.clear()

    def detail(self, now: Optional[float] = None) -> dict:
        """The /healthz?detail "inflight" fragment / vcctl payload."""
        now = self.time_fn() if now is None else now
        with self._lock:
            return {
                "open": len(self._open),
                "oldest_age_s": round(
                    max((now - e.registered_at
                         for e in self._open.values()), default=0.0), 3),
                "ack_timeout_s": self.ack_timeout_s,
                "registered": self.registered,
                "resolved": dict(sorted(self.resolved.items())),
            }
