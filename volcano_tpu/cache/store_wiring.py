"""Informer wiring: ObjectStore events → SchedulerCache mutations.

Mirrors /root/reference/pkg/scheduler/cache/event_handlers.go:47-880 (AddPod,
AddPodGroupV1beta1, AddQueueV1beta1, AddNode...) with the in-process store as
the watch source. Pods carry their gang membership in the
``scheduling.k8s.io/group-name`` annotation exactly like the reference
(pg_controller_handler.go:52-71).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..api import (JobInfo, NodeInfo, PodGroup, PodGroupPhase, QueueInfo,
                   Resource, TaskInfo, TaskStatus)
from ..apis.objects import Pod, PodGroupCR, QueueCR
from ..store import ADDED, DELETED, UPDATED, ObjectStore
from .cache import SchedulerCache
from .executors import (StoreBinder, StoreEvictor, StoreStatusUpdater,
                        StoreVolumeBinder)

GROUP_NAME_ANNOTATION = "scheduling.k8s.io/group-name"


def pod_status(pod: Pod) -> TaskStatus:
    """Pod phase + nodeName → TaskStatus (the reference's getTaskStatus)."""
    phase = pod.status.phase
    if phase == "Running":
        return TaskStatus.RUNNING
    if phase == "Succeeded":
        return TaskStatus.SUCCEEDED
    if phase == "Failed":
        return TaskStatus.FAILED
    if pod.status.node_name:
        return TaskStatus.BOUND
    return TaskStatus.PENDING


def pod_to_task(pod: Pod) -> TaskInfo:
    group = pod.metadata.annotations.get(GROUP_NAME_ANNOTATION, "")
    job_uid = f"{pod.metadata.namespace}/{group}" if group else ""
    tpl = pod.template
    return TaskInfo(
        uid=pod.metadata.uid, name=pod.metadata.name,
        namespace=pod.metadata.namespace, job=job_uid,
        resreq=tpl.resources.clone() if tpl.resources else Resource(),
        status=pod_status(pod), priority=tpl.priority,
        node_name=pod.status.node_name,
        task_role=pod.metadata.annotations.get("volcano.sh/task-spec",
                                               pod.metadata.name),
        node_selector=tpl.node_selector, tolerations=tpl.tolerations,
        affinity=tpl.affinity, labels=tpl.labels,
        annotations=pod.metadata.annotations,
        preemptable=pod.metadata.annotations.get(
            "volcano.sh/preemptable", "false") == "true",
        revocable_zone=pod.metadata.annotations.get(
            "volcano.sh/revocable-zone", ""),
        topology_policy=pod.metadata.annotations.get(
            "volcano.sh/numa-topology-policy", ""),
        creation_timestamp=pod.metadata.creation_timestamp,
        host_ports=[p for c in tpl.containers
                    for p in c.get("ports", [])],
        pod=pod)


def podgroup_to_job(pg: PodGroupCR) -> JobInfo:
    uid = f"{pg.metadata.namespace}/{pg.metadata.name}"
    mirror = PodGroup(name=pg.metadata.name, namespace=pg.metadata.namespace,
                      queue=pg.spec.queue, min_member=pg.spec.min_member,
                      min_resources=pg.spec.min_resources,
                      priority_class_name=pg.spec.priority_class_name,
                      phase=pg.status.phase,
                      annotations=pg.metadata.annotations,
                      labels=pg.metadata.labels)
    job = JobInfo(uid=uid, name=pg.metadata.name,
                  namespace=pg.metadata.namespace, queue=pg.spec.queue,
                  min_available=pg.spec.min_member, podgroup=mirror,
                  creation_timestamp=pg.metadata.creation_timestamp)
    return job


def wire_cache_to_store(store: ObjectStore,
                        cache: Optional[SchedulerCache] = None,
                        resumable: Optional[bool] = None,
                        event_filter: Optional[Callable] = None,
                        ) -> SchedulerCache:
    """Subscribe a SchedulerCache to the store; side effects write back via
    StoreBinder/StoreEvictor (the REST-out half of the bus).

    ``store`` may be the raw ObjectStore or the production transport
    composition (store_transport.RetryingStoreTransport over it) — the
    executors write through whatever is handed in, which is how every
    scheduler-side store write rides the retry funnel (vlint VT016).

    ``resumable`` wraps each watch in a cache/watches.ResumableWatch
    (resourceVersion tracking, torn-stream resume, 410-Gone relist — the
    informer contract) and attaches the WatchManager as
    ``cache.watch_manager`` so the scheduler epilogue can drive stream
    upkeep. Default: on whenever the store supports consistent lists
    (list_with_rv); pass False to force the legacy direct wiring.

    ``event_filter(kind, obj) -> bool`` scopes Pod/PodGroup ingestion —
    the server-side filtered watch of a federated deployment (each
    partition's cache holds only its queue subset's jobs,
    docs/federation.md). The filter must be STABLE per object (queue
    ownership does not move outside the drain funnel, which schedules
    the queue on NO partition until the flip)."""
    if cache is None:
        cache = SchedulerCache(binder=StoreBinder(store),
                               evictor=StoreEvictor(store),
                               status_updater=StoreStatusUpdater(store),
                               volume_binder=StoreVolumeBinder(store))

    # PriorityClass name -> value, resolved into JobInfo.priority
    # (event_handlers.go AddPriorityClass:633)
    priorities: dict = {}

    def on_priority_class(event: str, pc, old) -> None:
        if event in (ADDED, UPDATED):
            priorities[pc.metadata.name] = pc.value
        elif event == DELETED:
            priorities.pop(pc.metadata.name, None)
        for job in cache.jobs.values():
            if job.podgroup is not None and \
                    job.podgroup.priority_class_name in priorities:
                value = priorities[job.podgroup.priority_class_name]
                if job.priority != value:
                    job.priority = value
                    cache.mark_job_dirty(job.uid)

    def on_pod(event: str, pod: Pod, old: Optional[Pod]) -> None:
        task = pod_to_task(pod)
        if not task.job:
            return
        if event == ADDED:
            _ensure_job(cache, task.job, pod.metadata.namespace)
            cache.add_task(task)
        elif event == UPDATED:
            old_task = pod_to_task(old) if old is not None else None
            if old_task is not None and old_task.job == task.job:
                job = cache.jobs.get(task.job)
                if job is not None and task.uid in job.tasks:
                    cached = job.tasks[task.uid]
                    prev_status = cached.status
                    new_status = pod_status(pod)
                    if not cached.node_name and pod.status.node_name:
                        # bound elsewhere (scheduler restart recovery)
                        cache.delete_task(cached)
                        cache.add_task(task)
                    elif prev_status != new_status:
                        # status flips enter through the FeedbackChannel
                        # normalizer (vlint VT017): the RUNNING flip is
                        # the kubelet ack — stale/duplicate replays off
                        # a resumed stream must not resurrect a dead
                        # placement (docs/robustness.md feedback
                        # failure model)
                        cache.feedback.pod_status_event(cached, new_status)
                    return
            _ensure_job(cache, task.job, pod.metadata.namespace)
            cache.add_task(task)
        elif event == DELETED:
            job = cache.jobs.get(task.job)
            if job is not None and task.uid in job.tasks:
                cache.delete_task(job.tasks[task.uid])
                if not job.tasks and job.podgroup is None:
                    # the PodGroup went first and this was the last pod:
                    # drop the empty shell so a long-running store-wired
                    # cache (and the sim's drain check) doesn't hold one
                    # JobInfo per completed job forever
                    cache.remove_job(task.job)

    def on_podgroup(event: str, pg: PodGroupCR, old) -> None:
        uid = f"{pg.metadata.namespace}/{pg.metadata.name}"
        if event in (ADDED, UPDATED):
            existing = cache.jobs.get(uid)
            fresh = podgroup_to_job(pg)
            fresh.priority = priorities.get(pg.spec.priority_class_name, 0)
            if existing is None:
                cache.add_job(fresh)
            else:
                existing.podgroup = fresh.podgroup
                existing.min_available = fresh.min_available
                existing.queue = fresh.queue
                existing.priority = fresh.priority
                cache.mark_job_dirty(uid)
        elif event == DELETED:
            job = cache.jobs.get(uid)
            if job is not None:
                job.podgroup = None
                cache.mark_job_dirty(uid)
                if not job.tasks:
                    # no pods left either: the job is fully gone
                    cache.remove_job(uid)

    def on_queue(event: str, q: QueueCR, old) -> None:
        if event in (ADDED, UPDATED):
            cache.add_queue(QueueInfo(
                uid=q.metadata.name, name=q.metadata.name,
                weight=q.spec.weight, capability=q.spec.capability,
                reclaimable=q.spec.reclaimable, state=q.status.state,
                annotations=q.metadata.annotations))
        elif event == DELETED:
            cache.remove_queue(q.metadata.name)

    def on_resource_quota(event: str, quota, old) -> None:
        # namespace weights for drf's namespace fairness
        # (event_handlers.go:740-837)
        if event == DELETED:
            cache.delete_resource_quota(quota)
        else:
            cache.add_resource_quota(quota)

    if event_filter is not None:
        def _filtered(kind, handler):
            def wrapped(event, obj, old):
                if not event_filter(kind, obj):
                    return
                handler(event, obj, old)
            return wrapped
        on_pod = _filtered("Pod", on_pod)
        on_podgroup = _filtered("PodGroup", on_podgroup)

    handlers = [("ResourceQuota", on_resource_quota),
                ("PriorityClass", on_priority_class),
                ("Pod", on_pod),
                ("PodGroup", on_podgroup),
                ("Queue", on_queue)]
    if resumable is None:
        resumable = hasattr(store, "list_with_rv") \
            and hasattr(store, "current_rv")
    if resumable:
        from .watches import WatchManager
        manager = WatchManager(store)
        for kind, handler in handlers:
            manager.add(kind, handler)
        cache.watch_manager = manager
    else:
        for kind, handler in handlers:
            store.watch(kind, handler)
    return cache


def _ensure_job(cache: SchedulerCache, job_uid: str, namespace: str) -> None:
    """Pods may arrive before their PodGroup (event_handlers.go
    getOrCreateJob); create a placeholder job that the PodGroup event
    completes."""
    if job_uid not in cache.jobs:
        name = job_uid.split("/", 1)[1]
        job = JobInfo(uid=job_uid, name=name, namespace=namespace)
        job.podgroup = None
        cache.add_job(job)
