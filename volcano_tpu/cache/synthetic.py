"""Synthetic snapshot generator for the BASELINE benchmark configs.

The reference has no simulated multi-node backend (SURVEY.md §4: its only
multi-node testing is a kind cluster) — this generator is the rebuild's
10k-pods/2k-nodes harness (BASELINE.md configs 2-5).
"""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

from ..api import (JobInfo, NodeInfo, PodGroup, PodGroupPhase, QueueInfo,
                   Resource, TaskInfo, TaskStatus)
from .cache import SchedulerCache
from .executors import FakeBinder, FakeEvictor

GI = 1 << 30


def make_cluster(num_nodes: int, cpu_milli: int = 32000,
                 mem: int = 128 * GI, pods: int = 110,
                 gpus: int = 0, seed: int = 0) -> List[NodeInfo]:
    nodes = []
    for i in range(num_nodes):
        scalars = {"nvidia.com/gpu": float(gpus)} if gpus else None
        alloc = Resource(cpu_milli, mem, scalars)
        alloc.max_task_num = pods
        nodes.append(NodeInfo(name=f"node-{i:05d}", allocatable=alloc))
    return nodes


def make_jobs(num_tasks: int, num_jobs: int, queues: List[str],
              cpu_range=(500, 4000), mem_range=(1 * GI, 8 * GI),
              gang_fraction: float = 1.0, gpus_per_task: int = 0,
              running_fraction: float = 0.0, nodes: Optional[List[NodeInfo]] = None,
              seed: int = 0, phase: PodGroupPhase = PodGroupPhase.INQUEUE,
              name_prefix: str = "",
              ) -> List[JobInfo]:
    """num_tasks split over num_jobs; each job is a gang
    (minAvailable = task count * gang_fraction). running_fraction of jobs
    is pre-placed onto nodes (for preempt/reclaim configs). ``name_prefix``
    keeps arrival batches' uids distinct from a live cluster's (churn)."""
    rng = random.Random(seed)
    sizes = _split(num_tasks, num_jobs, rng)
    jobs: List[JobInfo] = []
    node_cycle = 0
    for j, size in enumerate(sizes):
        queue = queues[j % len(queues)]
        running = rng.random() < running_fraction
        min_avail = max(1, int(size * gang_fraction))
        name = f"{name_prefix}job-{j:05d}"
        pg = PodGroup(name=name, queue=queue, min_member=min_avail,
                      phase=PodGroupPhase.RUNNING if running else phase)
        job = JobInfo(uid=name, name=name, queue=queue,
                      min_available=min_avail, podgroup=pg,
                      priority=rng.randint(0, 10),
                      creation_timestamp=float(j))
        cpu = rng.randrange(*cpu_range, 100)
        mem = rng.randrange(mem_range[0], mem_range[1], GI // 4)
        scalars = {"nvidia.com/gpu": float(gpus_per_task)} if gpus_per_task else None
        for t in range(size):
            task = TaskInfo(uid=f"{name}-{t}", name=f"{name}-{t}", job=name,
                            resreq=Resource(cpu, mem, scalars),
                            creation_timestamp=float(j * 100000 + t))
            if running and nodes:
                # place round-robin wherever it fits
                for _ in range(len(nodes)):
                    node = nodes[node_cycle % len(nodes)]
                    node_cycle += 1
                    if task.resreq.less_equal(node.idle):
                        task.status = TaskStatus.RUNNING
                        job.add_task_info(task)
                        node.add_task(job.tasks[task.uid])
                        break
                else:
                    task.status = TaskStatus.PENDING
                    job.add_task_info(task)
            else:
                job.add_task_info(task)
        jobs.append(job)
    return jobs


def _split(total: int, parts: int, rng: random.Random) -> List[int]:
    if parts >= total:
        return [1] * total
    base = total // parts
    sizes = [base] * parts
    for i in rng.sample(range(parts), total - base * parts):
        sizes[i] += 1
    return sizes


def baseline_config(name: str, seed: int = 0):
    """Build (cache, binder, evictor) for a BASELINE.md config:

    - "tiny":    example/job.yaml analogue — 1 gang of 3, 10 nodes
    - "1k":      1k pending pods / 200 nodes, gang+priority
    - "10k":     10k pods / 2k nodes, 3 queues (drf+proportion)
    - "100k":    100k pods / 20k nodes — the sharded-solver scale config
    - "preempt": 5k running + 5k pending / 1k nodes
    - "gpu":     2k nodes x 8 GPUs, GPU-requesting tasks
    """
    binder, evictor = FakeBinder(), FakeEvictor()
    cache = SchedulerCache(binder=binder, evictor=evictor)

    if name == "tiny":
        nodes = make_cluster(10, cpu_milli=4000, mem=8 * GI)
        jobs = make_jobs(3, 1, ["default"], cpu_range=(900, 1000),
                         mem_range=(GI, GI + 1), seed=seed)
        queues = [QueueInfo(name="default", weight=1)]
    elif name == "1k":
        nodes = make_cluster(200, seed=seed)
        jobs = make_jobs(1000, 50, ["default"], seed=seed)
        queues = [QueueInfo(name="default", weight=1)]
    elif name == "10k":
        nodes = make_cluster(2000, seed=seed)
        jobs = make_jobs(10000, 200, ["q1", "q2", "q3"], seed=seed)
        queues = [QueueInfo(name="q1", weight=3), QueueInfo(name="q2", weight=2),
                  QueueInfo(name="q3", weight=1)]
    elif name == "20k":
        # the long-axis scale config (SURVEY §5.7: nodes 2k -> tens of k)
        nodes = make_cluster(5000, seed=seed)
        jobs = make_jobs(20000, 400, ["q1", "q2", "q3"], seed=seed)
        queues = [QueueInfo(name="q1", weight=3), QueueInfo(name="q2", weight=2),
                  QueueInfo(name="q3", weight=1)]
    elif name == "100k":
        # the 100k-pod scale config (ISSUE 18): 100k pods / 20k nodes.
        # Synthetic worlds keep the plugins' [T,N] feasibility/static
        # contributions abstaining (no selectors/taints), so the unified
        # sharded solver stays on its masked_static=None path — an 8 GB
        # dense matrix at this shape would be the first thing to OOM.
        nodes = make_cluster(20000, seed=seed)
        jobs = make_jobs(100000, 2000, ["q1", "q2", "q3"], seed=seed)
        queues = [QueueInfo(name="q1", weight=3), QueueInfo(name="q2", weight=2),
                  QueueInfo(name="q3", weight=1)]
    elif name == "preempt":
        nodes = make_cluster(1000, seed=seed)
        jobs = make_jobs(10000, 200, ["q1", "q2"], running_fraction=0.5,
                         nodes=nodes, seed=seed)
        queues = [QueueInfo(name="q1", weight=1), QueueInfo(name="q2", weight=1)]
    elif name == "preempt-small":
        # 1/10th preempt mix — the largest config where the callback engine
        # stays tractable for the eviction-parity comparison
        nodes = make_cluster(100, seed=seed)
        jobs = make_jobs(1000, 20, ["q1", "q2"], running_fraction=0.5,
                         nodes=nodes, seed=seed)
        queues = [QueueInfo(name="q1", weight=1), QueueInfo(name="q2", weight=1)]
    elif name == "gpu":
        nodes = make_cluster(2000, gpus=8, seed=seed)
        jobs = make_jobs(8000, 160, ["default"], gpus_per_task=1, seed=seed)
        queues = [QueueInfo(name="default", weight=1)]
    elif name == "gpu-small":
        # 1/10th gpu mix — the largest GPU config where the callback engine
        # stays tractable for the admission-parity comparison
        nodes = make_cluster(200, gpus=8, seed=seed)
        jobs = make_jobs(800, 16, ["default"], gpus_per_task=1, seed=seed)
        queues = [QueueInfo(name="default", weight=1)]
    else:
        raise ValueError(f"unknown baseline config {name!r}")

    for q in queues:
        cache.add_queue(q)
    for n in nodes:
        cache.add_node(n)
    for j in jobs:
        cache.add_job(j)
    return cache, binder, evictor


def preempt_mix_cache(n_nodes: int = 200, n_tasks: int = 1000,
                      n_jobs: int = 40, seed: int = 0):
    """The standard running+pending preempt scenario shared by the
    multichip dryrun (__graft_entry__) and the 8-vs-1 parity tests
    (tests/test_parallel.py) — ONE definition so they pin the same mix.
    Returns (cache, binder, evictor)."""
    binder, evictor = FakeBinder(), FakeEvictor()
    cache = SchedulerCache(binder=binder, evictor=evictor)
    nodes = make_cluster(n_nodes, seed=seed)
    jobs = make_jobs(n_tasks, n_jobs, ["q1", "q2"], running_fraction=0.5,
                     nodes=nodes, seed=seed)
    for q in (QueueInfo(name="q1", weight=1), QueueInfo(name="q2", weight=1)):
        cache.add_queue(q)
    for n in nodes:
        cache.add_node(n)
    for j in jobs:
        cache.add_job(j)
    return cache, binder, evictor
