"""Intent journal: write-ahead durability for bind/evict side effects.

The cache executes side effects (binder/evictor calls against the
cluster) and only then commits the outcome to its in-memory state. A
process crash between the two leaves the cluster and the next scheduler
incarnation disagreeing about where a task lives — the classic path to a
double-bind. The journal closes that window with the standard WAL
discipline (docs/robustness.md):

1. ``record_intent(op, task, node)`` appends one JSONL record BEFORE the
   executor call;
2. the executor runs;
3. ``ack(seq, ok)`` appends the outcome — ``ok=False`` for an executor
   failure the cache already rolled back (the resync queue owns the
   retry; nothing is outstanding).

An intent with no ack is exactly the crash window: the side effect may
or may not have reached the cluster. ``reconcile()`` replays those
against cache truth at startup — with a cluster oracle when one exists
(the sim's executor records; a store-wired deployment's pod state),
idempotent redo when none does — so a scheduler killed mid-cycle
restarts with zero double-binds and zero orphaned allocations.

Durability: an INTENT is flushed+fsynced before its executor runs —
single-op funnels sync per intent, ``bind_batch`` group-commits every
intent of the batch with ONE fsync before the first executor call —
because an executed side effect with no durable intent is exactly the
double-bind window the WAL exists to close. ACKS are fsync-BATCHED
(``fsync_batch`` records per fsync; the scheduler flushes the tail each
cycle): losing an ack to a crash merely makes reconciliation re-examine
a settled intent, which is idempotent. The file rotates by compaction
once it crosses ``max_bytes``: acked records are dropped, unacked
intents rewritten to a fresh file via write-tmp-then-rename.
``path=None`` keeps the journal in memory — the sim's restart harness
and tests use that; the sync calls become no-ops because the process
itself is the durability domain there.

Kill-switch: ``VOLCANO_TPU_JOURNAL=0`` detaches journaling wholesale
(SchedulerCache treats a configured journal as absent).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Callable, Dict, List, Optional

log = logging.getLogger(__name__)

DEFAULT_FSYNC_BATCH = 64
DEFAULT_MAX_BYTES = 8 << 20


def journal_enabled() -> bool:
    """Kill-switch for intent journaling: set VOLCANO_TPU_JOURNAL=0 to
    run without the write-ahead log even when one is configured."""
    return os.environ.get("VOLCANO_TPU_JOURNAL", "1") \
        .lower() not in ("0", "false", "off")


class Intent:
    """One journaled side-effect intent (decoded view)."""

    __slots__ = ("seq", "op", "task", "job", "node", "via", "fresh",
                 "epoch", "ctx")

    def __init__(self, seq: int, op: str, task: str, job: str, node: str,
                 via: str = "", fresh: bool = True, epoch: int = 0,
                 ctx: Optional[dict] = None):
        self.seq = seq
        self.op = op                  # "bind" | "evict"
        self.task = task              # task uid
        self.job = job                # owning job uid
        self.node = node              # bind target / evictee's node
        self.via = via                # "" (scheduler cycle) | "resync"
        # optional correlation context (obs/lifecycle.py): the logical
        # {cycle, part, epoch, eid} stamp that lets a follower/restart
        # continue the job's timeline exactly-once. None keeps the
        # record byte-identical to the pre-ctx shape.
        self.ctx = ctx
        # fresh=True: a NEW placement (the optimistic phase moved the
        # task from unplaced to this node). False: a RE-bind of a task
        # already validly placed — rolling that back must not strip the
        # still-live previous placement.
        self.fresh = fresh
        # the leader's fencing epoch at intent time (docs/robustness.md
        # HA section): 0 for standalone schedulers. Recorded so the
        # journal totally orders side effects across leaderships and a
        # replayed record names the leadership that issued it.
        self.epoch = epoch

    def __repr__(self):
        return (f"Intent(seq={self.seq}, op={self.op}, task={self.task}, "
                f"node={self.node})")


class IntentJournal:
    """Append-only JSONL intent/ack log with batched fsync and
    compaction-based rotation. Thread-safe: the cache's bind/evict
    funnels may run from multiple threads."""

    def __init__(self, path: Optional[str] = None,
                 fsync_batch: int = DEFAULT_FSYNC_BATCH,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        self.path = path
        self.fsync_batch = max(int(fsync_batch), 1)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._seq = 0
        self._unsynced = 0
        self._bytes = 0
        self.rotations = 0
        self.appended = 0
        self.fsyncs = 0
        # seq -> intent, dropped on ack; what reconcile() replays
        self._open: Dict[int, Intent] = {}
        # warm-standby transport (docs/robustness.md HA section): every
        # appended record is also delivered to subscribers — in-memory
        # mode this IS the replication stream a standby's JournalFollower
        # tails; file mode subscribers see the same records the file gets
        self._subscribers: List[Callable[[dict], None]] = []
        self._fh = None
        if path is not None:
            self._recover_existing(path)
            self._fh = open(path, "a", encoding="utf-8")
            self._bytes = self._fh.tell()

    # -- durability ---------------------------------------------------------

    def _recover_existing(self, path: str) -> None:
        """Load an existing journal file: rebuild the open-intent set and
        continue the sequence after the highest seq seen. Truncated or
        garbled tail lines (a crash mid-append) are skipped — a torn
        intent was by definition never followed by its side effect's
        ack, and its executor call may not have begun either; dropping
        it is the conservative read."""
        if not os.path.exists(path):
            return
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                self._apply_record(rec)

    def _apply_record(self, rec: dict) -> None:
        seq = int(rec.get("seq", 0))
        self._seq = max(self._seq, seq)
        if rec.get("kind") == "intent":
            self._open[seq] = Intent(seq, rec["op"], rec["task"],
                                     rec.get("job", ""), rec.get("node", ""),
                                     rec.get("via", ""),
                                     bool(rec.get("fresh", True)),
                                     int(rec.get("epoch", 0)),
                                     rec.get("ctx"))
        elif rec.get("kind") == "ack":
            self._open.pop(seq, None)

    def _append(self, rec: dict, sync_now: bool = False) -> None:
        """Caller holds self._lock. In-memory mode (path=None) keeps no
        record stream at all — ``_open`` IS the recoverable state there,
        because the process itself is the durability domain."""
        self.appended += 1
        if self._fh is None:
            return
        line = json.dumps(rec, separators=(",", ":"))
        self._fh.write(line + "\n")
        self._bytes += len(line) + 1
        self._unsynced += 1
        if sync_now or self._unsynced >= self.fsync_batch:
            self._sync()
        if self._bytes > self.max_bytes:
            self._rotate()

    def _sync(self) -> None:
        self._fh.flush()
        try:
            os.fsync(self._fh.fileno())
        except OSError:                              # pragma: no cover
            pass
        self.fsyncs += 1
        self._unsynced = 0

    def _rotate(self) -> None:
        """Compact: rewrite only the open (unacked) intents — the only
        records a restart can act on — to a fresh file, atomically."""
        tmp = f"{self.path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for seq in sorted(self._open):
                it = self._open[seq]
                rec = {"kind": "intent", "seq": it.seq, "op": it.op,
                       "task": it.task, "job": it.job, "node": it.node,
                       "via": it.via, "fresh": it.fresh,
                       "epoch": it.epoch}
                if it.ctx is not None:
                    rec["ctx"] = it.ctx
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._bytes = self._fh.tell()
        self._unsynced = 0
        self.rotations += 1

    # -- the WAL surface ----------------------------------------------------

    def subscribe(self, fn: Callable[[dict], None]) -> None:
        """Register a record observer (a standby's JournalFollower).
        Called with each appended record dict AFTER the journal's own
        bookkeeping (and outside its lock)."""
        with self._lock:
            self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._subscribers:
                self._subscribers.remove(fn)

    def _publish(self, rec: dict) -> None:
        with self._lock:
            subs = list(self._subscribers)
        for fn in subs:
            fn(rec)

    def record_intent(self, op: str, task, node: str = "",
                      via: str = "", fresh: bool = True,
                      epoch: int = 0, ctx: Optional[dict] = None) -> int:
        """Journal a side-effect intent BEFORE the executor runs, stamped
        with the issuing leader's fencing ``epoch`` and (optionally) its
        correlation ``ctx`` — the lifecycle-timeline stamp a follower or
        restarted process ingests to continue the job's story
        (obs/lifecycle.py). Returns the seq to ack with."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            intent = Intent(seq, op, task.uid, task.job,
                            node or task.node_name or "", via, fresh,
                            epoch, ctx)
            self._open[seq] = intent
            rec = {"kind": "intent", "seq": seq, "op": op,
                   "task": intent.task, "job": intent.job,
                   "node": intent.node, "via": via, "fresh": fresh,
                   "epoch": epoch}
            if ctx is not None:
                rec["ctx"] = ctx
            self._append(rec)
        self._publish(rec)
        return seq

    def record_control(self, kind: str, fields: Optional[dict] = None
                       ) -> int:
        """Journal a CONTROL record — a cross-partition reserve/transfer
        protocol step (docs/federation.md) or any other coordination
        breadcrumb that must be durable and visible to every journal
        subscriber, but opens no bind/evict crash window. Control
        records share the seq space (the journal totally orders them
        against side-effect intents), are flushed+fsynced immediately
        (a reserve must be durable before anyone acts on it), never
        enter the open-intent set, and are dropped by compaction like
        acked records; ``reconcile()`` ignores them. Returns the seq."""
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec = {"kind": kind, "seq": seq}
            if fields:
                rec.update(fields)
            self._append(rec, sync_now=True)
        self._publish(rec)
        return seq

    def ack(self, seq: int, ok: bool = True) -> None:
        """Journal the executor outcome. ``ok=False`` records a failure
        whose cache rollback already ran — the intent is settled either
        way (the resync queue owns any retry)."""
        with self._lock:
            self._open.pop(seq, None)
            rec = {"kind": "ack", "seq": seq, "ok": bool(ok)}
            self._append(rec)
        self._publish(rec)

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None and self._unsynced:
                self._sync()

    def unacked(self) -> List[Intent]:
        """Open intents in append order — the crash window a restart
        must reconcile."""
        with self._lock:
            return [self._open[s] for s in sorted(self._open)]

    def compact(self) -> None:
        """Force a compaction rotation (reconcile() calls this after
        settling the open set so the next recovery starts clean). A
        no-op in memory mode: ``_open`` is already exactly the open
        set."""
        with self._lock:
            if self._fh is not None:
                self._rotate()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                if self._unsynced:
                    self._sync()
                self._fh.close()
                self._fh = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._open)


class JournalFollower:
    """Warm-standby replay (docs/robustness.md HA section): applies the
    leader's journal record stream to a STANDBY's SchedulerCache so the
    standby stays converged and failover is lease-acquire →
    startup_reconcile → resume instead of a cold rebuild.

    The replay contract: an intent alone changes nothing (it is exactly
    the leader's crash window); the ACK resolves it —

    - bind  + ok    → assert the bind into cache state (_assert_bound);
    - bind  + !ok   → the leader rolled back (executor failure) or the
                      reconciler rolled back a crash window: undo any
                      optimistic state (_rollback_bind; a no-op on a
                      standby that never applied the intent);
    - evict + ok    → reflect the eviction (_repair_releasing);
    - evict + !ok   → nothing happened cluster-side.

    Transports: subscribe to an in-memory journal (``attach``), or poll a
    journal file with ``FileTailer`` and feed ``apply_record``. ``seed``
    preloads the journal's currently-open intents, so a follower started
    (or restarted) mid-stream still resolves acks whose intents predate
    its subscription."""

    def __init__(self, cache):
        self.cache = cache
        self._pending: Dict[int, dict] = {}
        self.applied = 0            # acks that changed cache state
        self._journal: Optional[IntentJournal] = None

    # -- transports ---------------------------------------------------------

    def attach(self, journal: IntentJournal) -> None:
        """Subscribe to an in-memory/live journal and seed from its open
        intents (idempotent per journal)."""
        self.seed(journal)
        journal.subscribe(self.apply_record)
        self._journal = journal

    def detach(self) -> None:
        if self._journal is not None:
            self._journal.unsubscribe(self.apply_record)
            self._journal = None

    def seed(self, journal: IntentJournal) -> None:
        for it in journal.unacked():
            rec = {
                "kind": "intent", "seq": it.seq, "op": it.op,
                "task": it.task, "job": it.job, "node": it.node,
                "via": it.via, "fresh": it.fresh, "epoch": it.epoch}
            if it.ctx is not None:
                rec["ctx"] = it.ctx
            self._pending[it.seq] = rec
            self._ingest_timeline(rec)

    # journal record kind -> lifecycle event the follower continues the
    # timeline with (obs/lifecycle.py); intents map to "<op>_intent"
    _TIMELINE_KINDS = {"elastic_grow": "grow", "elastic_shrink": "shrink"}

    def _ingest_timeline(self, rec: dict) -> None:
        """Continue job timelines from the ctx stamps riding the record
        stream — what lets a standby/newborn process hold the events it
        never witnessed. Exactly-once: the store dedupes on the ctx's
        (part, eid), so re-seeding, rotation replay, or a torn tail
        re-read is a no-op."""
        if rec.get("kind") == "queue_move_done":
            # one record per queue; per-job ctx stamps ride in "jobs"
            jobs = rec.get("jobs")
            if isinstance(jobs, dict) and jobs:
                from ..obs.lifecycle import TIMELINE
                for job, ctx in jobs.items():
                    if isinstance(ctx, dict):
                        TIMELINE.ingest(job, "move", ctx,
                                        queue=rec.get("queue"),
                                        frm=rec.get("frm"),
                                        to=rec.get("to"))
            return
        ctx = rec.get("ctx")
        job = rec.get("job", "")
        if not isinstance(ctx, dict) or not job:
            return
        from ..obs.lifecycle import TIMELINE
        if rec.get("kind") == "intent":
            ev = f"{rec.get('op', 'bind')}_intent"
        else:
            ev = self._TIMELINE_KINDS.get(rec.get("kind"))
            if ev is None:
                return
        TIMELINE.ingest(job, ev, ctx, node=rec.get("node") or None,
                        reason=rec.get("reason") or None,
                        frm=rec.get("frm"), to=rec.get("to"))

    # -- the replay ---------------------------------------------------------

    def apply_record(self, rec: dict) -> None:
        kind = rec.get("kind")
        self._ingest_timeline(rec)
        if kind == "intent":
            self._pending[int(rec.get("seq", 0))] = rec
            return
        if kind != "ack":
            return
        intent = self._pending.pop(int(rec.get("seq", 0)), None)
        if intent is None:
            return                       # pre-seed history; already settled
        with self.cache._lock:
            job = self.cache.jobs.get(intent.get("job", ""))
            task = job.tasks.get(intent["task"]) if job is not None else None
        if task is None:
            return                       # task gone: the ack is moot
        if intent["op"] == "bind":
            if rec.get("ok"):
                _assert_bound(self.cache, job, task, intent["node"])
            else:
                _rollback_bind(self.cache, job, task, intent["node"],
                               bool(intent.get("fresh", True)))
        elif rec.get("ok"):
            _repair_releasing(self.cache, job, task)
        else:
            return                       # failed evict: nothing happened
        self.applied += 1


class FileTailer:
    """Poll a journal FILE for new records — the standby transport for
    real (multi-process) deployments, where the in-memory subscription
    stream does not cross the process boundary. Tracks a byte offset and
    restarts from 0 when the file was compacted (rotation rewrites only
    the open intents via rename) — replaying the rewritten open intents
    is idempotent (intents alone change nothing, and the follower's
    apply operations are idempotent). Incomplete tail lines (a writer
    mid-append) are left for the next poll."""

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._head: Optional[bytes] = None

    def poll(self) -> List[dict]:
        if not os.path.exists(self.path):
            return []
        # Rotation detection must not rely on the file SHRINKING: a
        # lagging tailer can sit mid-way through the old file while the
        # compacted rewrite is LARGER than its offset — reading on from
        # the stale offset would skip rewritten open intents and tear a
        # record. The first line identifies the file generation
        # (compaction rewrites starting at the lowest open seq), so a
        # changed head restarts the tail; the shrink check backstops the
        # rare head-preserving rotation.
        with open(self.path, "rb") as fb:
            head = fb.readline()
        if head.endswith(b"\n") and head != self._head:
            if self._head is not None:
                self._offset = 0
            self._head = head
        size = os.path.getsize(self.path)
        if size < self._offset:
            self._offset = 0             # head-preserving rotation
        if size == self._offset:
            return []
        out: List[dict] = []
        with open(self.path, "r", encoding="utf-8") as f:
            f.seek(self._offset)
            while True:
                line = f.readline()
                if not line:
                    break
                if not line.endswith("\n"):
                    break                # torn tail: retry next poll
                self._offset = f.tell()
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
        return out


class ReconcileReport:
    """What the startup reconciler did with the journal's crash window."""

    def __init__(self):
        self.replayed = 0          # unacked intents examined
        self.repaired_binds = 0    # cluster had the bind; cache re-asserted
        self.rolled_back = 0       # cluster lacked it; optimistic state undone
        self.redone = 0            # no oracle: side effect re-issued
        self.repaired_evicts = 0   # cluster executed the evict; cache caught up
        self.stale = 0             # task/job gone; intent moot
        self.failed = 0            # redo raised; handed to the resync queue

    def as_dict(self) -> dict:
        return {k: getattr(self, k) for k in
                ("replayed", "repaired_binds", "rolled_back", "redone",
                 "repaired_evicts", "stale", "failed")}

    def __repr__(self):
        return f"ReconcileReport({self.as_dict()})"


def reconcile(cache, journal: IntentJournal,
              cluster_binds: Optional[Dict[str, str]] = None,
              cluster_evicts: Optional[Callable[[str], bool]] = None
              ) -> ReconcileReport:
    """Replay the journal's unacked intents against cache truth — the
    restart half of the WAL (call before the first scheduling cycle).

    ``cluster_binds`` (task uid -> node of every bind the CLUSTER
    executed) and ``cluster_evicts`` (uid -> bool) are the truth oracle:
    when present, each open bind intent resolves to either *repair*
    (the cluster has the bind; re-assert it onto cache state so the next
    cycle cannot re-place the task elsewhere) or *rollback* (the cluster
    never saw it; undo the optimistic BOUND so the task re-enters the
    pending pool). Without an oracle the intent is *redone* through the
    executor — safe because redoing a bind onto its JOURNALED node is
    idempotent cluster-side, and the journal never lets a restart invent
    a different node. Either way: zero double-binds.

    Every examined intent is acked (settled) and the journal compacted.
    """
    from .. import metrics

    report = ReconcileReport()
    for intent in journal.unacked():
        report.replayed += 1
        try:
            _reconcile_one(cache, journal, intent, report,
                           cluster_binds, cluster_evicts)
        except Exception:
            # isolated like run_once isolates actions: one intent whose
            # repair blows up (e.g. the rebuilt cache can no longer hold
            # the journaled task) must not leave the REST of the crash
            # window unsettled
            log.exception("reconciling %r failed; settling it as failed",
                          intent)
            report.failed += 1
            journal.ack(intent.seq, ok=False)
    journal.compact()
    journal.flush()
    for result, n in (("repaired", report.repaired_binds
                       + report.repaired_evicts),
                      ("rolled_back", report.rolled_back),
                      ("redone", report.redone),
                      ("stale", report.stale),
                      ("failed", report.failed)):
        if n:
            metrics.register_journal_replay(result, n)
    cache.last_reconcile = report.as_dict()
    return report


def _reconcile_one(cache, journal, intent, report: ReconcileReport,
                   cluster_binds, cluster_evicts) -> None:
    from ..api import TaskStatus, allocated_status
    with cache._lock:
        job = cache.jobs.get(intent.job)
        task = job.tasks.get(intent.task) if job is not None else None
    if task is None:
        report.stale += 1
        journal.ack(intent.seq, ok=False)
        return
    if intent.op == "bind":
        if cluster_binds is not None:
            if cluster_binds.get(intent.task) == intent.node:
                _assert_bound(cache, job, task, intent.node)
                report.repaired_binds += 1
                journal.ack(intent.seq, ok=True)
            else:
                _rollback_bind(cache, job, task, intent.node,
                               intent.fresh)
                report.rolled_back += 1
                journal.ack(intent.seq, ok=False)
            return
        # no oracle: redo onto the journaled node. A task some LATER
        # settled intent/cycle already re-placed is final — the same
        # staleness rule the resync queue applies.
        with cache._lock:
            placed = allocated_status(task.status) \
                and task.node_name and task.node_name != intent.node
        if placed:
            report.stale += 1
            journal.ack(intent.seq, ok=False)
            return
        try:
            redo = task.shallow_clone()
            redo.node_name = intent.node
            cache._bind_volumes(redo)        # like every other bind path
            cache.binder.bind(redo, intent.node)
            _assert_bound(cache, job, task, intent.node)
            report.redone += 1
            journal.ack(intent.seq, ok=True)
        except Exception:
            log.exception("journal redo bind %s -> %s failed; handing "
                          "to the resync queue", intent.task, intent.node)
            _rollback_bind(cache, job, task, intent.node, intent.fresh)
            report.failed += 1
            journal.ack(intent.seq, ok=False)
            retry = task.shallow_clone()
            retry.node_name = intent.node
            cache.resync_task(retry)
        return
    # evict
    if cluster_evicts is not None:
        if cluster_evicts(intent.task):
            _repair_releasing(cache, job, task)
            report.repaired_evicts += 1
            journal.ack(intent.seq, ok=True)
        else:
            # the evict never reached the cluster: the decision died
            # with the old process; the next cycle re-decides
            report.rolled_back += 1
            journal.ack(intent.seq, ok=False)
        return
    try:
        cache.evictor.evict(task, "journal-reconcile")
        _repair_releasing(cache, job, task)
        report.redone += 1
        journal.ack(intent.seq, ok=True)
    except Exception:
        log.exception("journal redo evict %s failed; handing to the "
                      "resync queue", intent.task)
        report.failed += 1
        journal.ack(intent.seq, ok=False)
        cache.resync_task(task.shallow_clone(), op="evict")


def _repair_releasing(cache, job, task) -> None:
    """Reflect a cluster-executed evict into cache state: job status AND
    the node's task mirror — the node stores a CLONE, so skipping
    update_task would leave a phantom pre-evict entry occupying idle."""
    from ..api import TaskStatus
    with cache._lock:
        cache._mark_task_dirty(task)
        job.update_task_status(task, TaskStatus.RELEASING)
        node = cache.nodes.get(task.node_name)
        if node is not None and task.uid in node.tasks:
            node.update_task(task)


def _assert_bound(cache, job, task, node_name: str) -> None:
    """Make cache state reflect a bind the cluster definitely executed:
    the task is BOUND on ``node_name`` and accounted there exactly once."""
    from ..api import TaskStatus, allocated_status
    with cache._lock:
        cache._mark_task_dirty(task)
        if allocated_status(task.status) and task.node_name == node_name:
            return                       # cache already agrees
        prev_node = cache.nodes.get(task.node_name) \
            if task.node_name and task.node_name != node_name else None
        if prev_node is not None and task.uid in prev_node.tasks:
            cache._dirty_nodes.add(prev_node.name)
            prev_node.remove_task(task)
        task.node_name = node_name
        job.update_task_status(task, TaskStatus.BOUND)
        cache._dirty_nodes.add(node_name)
        node = cache.nodes.get(node_name)
        if node is not None and task.uid not in node.tasks:
            node.add_task(task)


def _rollback_bind(cache, job, task, node_name: str,
                   fresh: bool = True) -> None:
    """Undo optimistic bind state the cluster never saw: a FRESH
    placement returns to the pending pool (the next cycle re-places
    it). A non-fresh intent was a RE-bind of a task the cluster still
    validly runs on its previous node — stripping that placement would
    set up the next cycle to re-place a task that is still live
    elsewhere (a double-bind), so the cache state is left standing."""
    from ..api import TaskStatus
    if not fresh:
        return
    with cache._lock:
        if task.status == TaskStatus.PENDING and not task.node_name:
            return                       # rollback already ran pre-crash
        cache._mark_task_dirty(task)
        node = cache.nodes.get(task.node_name or node_name)
        if node is not None and task.uid in node.tasks:
            cache._dirty_nodes.add(node.name)
            node.remove_task(task)
        job.update_task_status(task, TaskStatus.PENDING)
        task.node_name = ""
