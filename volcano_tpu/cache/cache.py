"""SchedulerCache: the host-side mirror of cluster state.

Mirrors /root/reference/pkg/scheduler/cache/cache.go:75-893 — jobs/nodes/
queues indexes fed by events, ``snapshot()`` producing a deep-copied
ClusterInfo per cycle, and Bind/Evict side effects executed through
swappable executors with a rate-limited resync queue on failure.

Differences by design: event ingestion is direct method calls (the in-process
ObjectStore pushes them; there is no client-go), and binds are synchronous by
default for determinism — an async mode mirrors the reference's
goroutine-per-bind with the same "skip nodes with in-flight binding tasks at
snapshot" guard (cache.go:822-827).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..api import (ClusterInfo, JobInfo, NamespaceCollection, NamespaceInfo,
                   NodeInfo, PodGroupPhase, QueueInfo, Resource, TaskInfo,
                   TaskStatus, allocated_status)
from .executors import (Binder, Evictor, FakeBinder, FakeEvictor,
                        StatusUpdater, VolumeBinder)
from ..obs.lifecycle import TIMELINE
from ..obs.trace import TRACE as OBS_TRACE
from .feedback import FeedbackChannel
from .inflight import InflightLedger
from .journal import IntentJournal, journal_enabled

log = logging.getLogger(__name__)


def incremental_snapshot_enabled() -> bool:
    """Kill-switch for the incremental snapshot + persistent tensor state
    (docs/performance.md). Default ON; set VOLCANO_TPU_INCREMENTAL_SNAPSHOT=0
    to force the historical full deep-clone every cycle (also how the sim's
    A/B determinism test proves the two paths decide identically)."""
    return os.environ.get("VOLCANO_TPU_INCREMENTAL_SNAPSHOT", "1") \
        .lower() not in ("0", "false", "off")


class RateLimitedQueue:
    """workqueue.RateLimitingInterface analogue (the errTasks queue,
    cache.go:115,777-799): per-item exponential backoff — the k8s
    ItemExponentialFailureRateLimiter (base * 2^failures, capped) — plus a
    per-item retry budget: once an item has failed ``max_retries`` times,
    add_rate_limited refuses it (returns False) so a permanently failing
    side effect cannot spin in the queue forever. The caller dead-letters
    refused items (SchedulerCache.dead_letter)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 10.0,
                 max_retries: Optional[int] = None,
                 time_fn=time.monotonic):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.max_retries = max_retries
        # injectable time source: the simulator (volcano_tpu/sim) pins this
        # to its virtual clock so retry backoff expires on deterministic
        # virtual cycles instead of whenever the host gets there
        self.time_fn = time_fn
        self._heap: List[Tuple[float, int, str, object]] = []
        self._failures: Dict[str, int] = {}
        self._seq = itertools.count()
        self._lock = threading.Lock()

    def add_rate_limited(self, key: str, item: object) -> bool:
        with self._lock:
            n = self._failures.get(key, 0)
            if self.max_retries is not None and n >= self.max_retries:
                # keep the failure count: a later add for the same key
                # (e.g. the scheduler re-placing the rolled-back task onto
                # the same broken path) is refused again instead of
                # restarting a full retry burst — only forget() (redrive)
                # grants a fresh budget
                return False
            self._failures[key] = n + 1
            delay = min(self.base_delay * (2 ** n), self.max_delay)
            heapq.heappush(self._heap,
                           (self.time_fn() + delay, next(self._seq), key,
                            item))
            return True

    def failures(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    def forget(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def pop_ready(self, max_items: Optional[int] = None
                  ) -> List[Tuple[str, object]]:
        """Items whose backoff expired, oldest-deadline first.
        ``max_items`` bounds the per-call work (the cycle-budget
        contract, vlint VT018): items past the cap stay queued, already
        ready, and drain on the next call — bounded work per cycle,
        nothing dropped."""
        now = self.time_fn()
        out = []
        with self._lock:
            while self._heap and self._heap[0][0] <= now:
                if max_items is not None and len(out) >= max_items:
                    break
                _, _, key, item = heapq.heappop(self._heap)
                out.append((key, item))
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)


# A bind/evict that fails this many RETRIES (after the initial attempt)
# dead-letters instead of re-queueing — with the default 5ms base delay
# the budget spans ~20s of exponential backoff, past any transient
# apiserver hiccup the resync queue is meant to absorb.
DEFAULT_RESYNC_MAX_RETRIES = 12


def _dead_letter_max() -> int:
    """Cap on the dead-letter set (docs/robustness.md overload failure
    model): under pathological job churn every distinct failing job
    parks one entry, so the set grows with distinct-job cardinality
    unless bounded. Past the cap the OLDEST entry is evicted (counted in
    volcano_dead_letter_evicted_total and warned about in
    /healthz?detail) — an eviction means redrive can no longer recover
    that side effect, which is the honest signal at that point: the
    failure plane is outgrowing the parking lot. <=0 disables the cap."""
    try:
        return int(os.environ.get("VOLCANO_TPU_DEAD_LETTER_MAX", 4096))
    except ValueError:
        return 4096


class SchedulerCache:
    def __init__(self, binder: Optional[Binder] = None,
                 evictor: Optional[Evictor] = None,
                 status_updater: Optional[StatusUpdater] = None,
                 volume_binder: Optional[VolumeBinder] = None,
                 default_queue: str = "default",
                 resync_max_retries: Optional[int]
                 = DEFAULT_RESYNC_MAX_RETRIES,
                 journal: Optional[IntentJournal] = None,
                 time_fn=time.time):
        self._lock = threading.RLock()
        # injectable wall-clock source (vlint VT002): stamps
        # schedule_start_timestamp on ingested jobs; the simulator pins
        # it to its virtual clock (like resync_queue.time_fn) so queueing
        # -delay metrics are deterministic under replay
        self.time_fn = time_fn
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespace_collections: Dict[str, NamespaceCollection] = {}
        self.binder = binder or FakeBinder()
        self.evictor = evictor or FakeEvictor()
        self.status_updater = status_updater or StatusUpdater()
        self.volume_binder = volume_binder or VolumeBinder()
        self.default_queue = default_queue
        if default_queue:
            self.queues.setdefault(default_queue, QueueInfo(name=default_queue))
        self.err_tasks: List[TaskInfo] = []       # failure record (tests)
        self.resync_queue = RateLimitedQueue(     # errTasks (cache.go:777-799)
            max_retries=resync_max_retries)
        # side effects that exhausted their retry budget, key -> (op, task).
        # Never retried automatically (the failure is not transient by
        # definition of the budget); ops inspect it and redrive_dead_letter
        # re-queues after the underlying fault is fixed.
        self.dead_letter: Dict[str, Tuple[str, TaskInfo]] = {}
        # bounded (insertion-ordered dict; oldest evicted past the cap —
        # see _dead_letter_max): churn cannot pin unbounded TaskInfo
        # graphs through the dead-letter parking lot
        self.dead_letter_max = _dead_letter_max()
        self.dead_letter_evicted = 0
        # write-ahead intent journal (cache/journal.py): bind/evict/resync
        # funnels record intents before their executor call and acks after,
        # so a crash window is replayable at restart (reconcile_journal).
        # VOLCANO_TPU_JOURNAL=0 detaches a configured journal wholesale.
        self.journal = journal if (journal is not None
                                   and journal_enabled()) else None
        self.last_reconcile: Optional[dict] = None
        # HA fencing (docs/robustness.md): the scheduler shell points this
        # at its elector's fencing epoch (Scheduler.attach_elector); every
        # journaled side-effect intent is stamped with it, and the fenced
        # executor gates reject stale-epoch operations. Standalone
        # schedulers stamp 0.
        self.fencing_epoch_fn: Callable[[], int] = lambda: 0
        self.binding_tasks: Dict[str, str] = {}   # task uid -> node, in flight
        # Incremental snapshot state (docs/performance.md): every mutation
        # path records the touched node/job/queue keys; snapshot() re-clones
        # only those and structurally shares the rest with the previous
        # snapshot. _dirty_all forces the next snapshot to full-rebuild
        # (initial state, external bulk mutation, kill-switch re-enable).
        self._dirty_nodes: Set[str] = set()
        self._dirty_jobs: Set[str] = set()
        self._dirty_queues: Set[str] = set()
        self._dirty_all = True
        self._snap_nodes: Dict[str, NodeInfo] = {}
        self._snap_jobs: Dict[str, JobInfo] = {}
        self._snap_queues: Dict[str, QueueInfo] = {}
        self._snap_epoch = 0
        # node names whose snapshot row changed since the persistent tensor
        # state last refreshed (cache/snapshot.PersistentNodeTensors)
        self._tensor_dirty: Set[str] = set()
        self.tensor_cache = None
        # federation (docs/federation.md): a per-partition snapshot
        # scope — callable ClusterInfo -> ClusterInfo (PartitionMap.scope)
        # applied AFTER the incremental build, so the clone caches stay
        # whole-cluster while the session only sees this partition's
        # queues/jobs/node shard. None (default) = unscoped.
        self.snapshot_scope: Optional[Callable] = None
        # wall-clock + dirty-ratio breakdown of the last snapshot()
        # (bench.py snapshot_clone_ms / open_dirty_ms extras)
        self.last_snapshot_stats: Dict[str, object] = {}
        # outstanding speculative-snapshot dirt (docs/performance.md
        # pipelining): speculative_snapshot MOVES the dirty sets into the
        # staged basis (so post-stage mutations land in empty sets and
        # the commit-boundary delta is exact, including re-mutation of
        # keys that were already dirty); the moved keys live here until
        # adopt consumes them, discard restores them, or a real
        # _snapshot_impl reabsorbs them first.
        self._spec_dirt: Optional[dict] = None
        # event-driven fast-admit feed (docs/performance.md): when a
        # scheduler enables it, add_job records arrivals here so
        # Scheduler.fast_admit scans only what arrived since the last
        # drain instead of every job. Off by default — an unconsumed
        # feed must not grow without bound.
        self.fast_admit_feed = False
        self._new_job_uids: Set[str] = set()
        # result of the last shadow-verifier pass (verify_state_integrity)
        self.last_verify: Dict[str, object] = {}
        # store-wired caches carry their resumable watch streams here
        # (cache/watches.WatchManager, attached by wire_cache_to_store);
        # the scheduler epilogue drives step() — torn-stream resume,
        # bookmarks, retry-budget reset (docs/robustness.md store
        # failure model). None for direct-fed caches (tests, sim default)
        self.watch_manager = None
        # the feedback plane (docs/robustness.md feedback failure
        # model): every executor-accepted bind/evict arms an ack
        # deadline in the in-flight ledger; the FeedbackChannel is the
        # ONE funnel cluster acks enter the cache through (vlint VT017),
        # and the scheduler epilogue's watchdog
        # (process_expired_inflight) re-validates expired entries so a
        # lost ack can never wedge in-flight state forever.
        self.inflight = InflightLedger()
        self.feedback = FeedbackChannel(self)
        # cluster-truth probe for the watchdog: entry -> True (the side
        # effect is live cluster-side), False (it is not), None
        # (unknown). None (the default probe-less state) presumes
        # executed — the executor DID ack the call — so expiry recovers
        # the lost ack instead of inventing a rollback.
        self.inflight_oracle_fn: Optional[Callable] = None
        # lifecycle-timeline attribution (obs/lifecycle.py): the
        # partition id this cache's funnel events are stamped with —
        # 0 standalone; the federated sim/member wiring sets the real
        # pid. Observability only: nothing decision-plane reads it.
        self.obs_part = 0

    # -- intent journal (cache/journal.py) ----------------------------------

    def attach_journal(self, journal: Optional[IntentJournal]) -> None:
        """Swap the write-ahead journal in (or out with None); honours the
        VOLCANO_TPU_JOURNAL kill-switch like the constructor does."""
        self.journal = journal if (journal is not None
                                   and journal_enabled()) else None

    def fencing_epoch(self) -> int:
        """The issuing leadership's fencing epoch for executor-effecting
        operations (0 standalone). Every executor-effecting funnel stamps
        its intent with this — vlint VT008 enforces the witness."""
        return self.fencing_epoch_fn()

    def _journal_intent(self, op: str, task: TaskInfo, node: str = "",
                        via: str = "", sync: bool = True,
                        fresh: bool = True) -> Optional[int]:
        """Record a side-effect intent, stamped with the current fencing
        epoch. ``sync=True`` (the default for single-op funnels) makes
        the intent DURABLE — flushed+fsynced — before the caller runs
        the executor, which is the WAL guarantee reconciliation rests on;
        batch funnels journal all their intents first and group-commit
        with one flush() instead. ``fresh`` marks a NEW placement (vs a
        re-bind of an already-placed task), which decides whether a
        crash-window rollback may strip the task's placement
        (journal._rollback_bind)."""
        epoch = self.fencing_epoch()
        # lifecycle stamp (obs/lifecycle.py; vlint VT022): the intent's
        # correlation ctx both records the timeline event HERE and rides
        # inside the durable record, so a follower/restart continues the
        # same timeline exactly-once (dedupe on the ctx's part+eid)
        ctx = TIMELINE.stamp(part=self.obs_part, epoch=epoch)
        if ctx is not None:
            TIMELINE.record(task.job, f"{op}_intent", ctx=ctx,
                            node=node or task.node_name or None,
                            via=via or None)
        if op == "bind":
            # cross-lane causal arc (merged federated traces): the bind
            # intent opens/continues the job's flow; the RUNNING ack and
            # any queue move step it, completion closes it
            OBS_TRACE.flow_step("bind_intent", f"job:{task.job}",
                                task=task.uid)
        if self.journal is None:
            return None
        seq = self.journal.record_intent(op, task, node, via, fresh,
                                         epoch=epoch, ctx=ctx)
        if sync:
            self.journal.flush()
        return seq

    def _journal_ack(self, seq: Optional[int], ok: bool) -> None:
        if seq is not None and self.journal is not None:
            self.journal.ack(seq, ok)

    def _register_inflight(self, op: str, task: TaskInfo, node: str = "",
                           seq: Optional[int] = None) -> None:
        """Arm the in-flight ledger's ack deadline for an intent about to
        execute (cache/inflight.py) — every executor-effecting funnel
        calls this next to its ``_journal_intent`` (vlint VT017). An
        executor failure aborts the entry in the rollback path; the
        cluster's feedback ack (or the watchdog) resolves it otherwise."""
        self.inflight.register(op, task.uid, task.job,
                               node or task.node_name or "", seq)

    def reconcile_journal(self, cluster_binds=None, cluster_evicts=None):
        """Startup reconciliation: settle the journal's crash window
        against cache truth (journal.reconcile). Returns the
        ReconcileReport, or None when no journal is attached."""
        if self.journal is None:
            return None
        from .journal import reconcile
        return reconcile(self, self.journal, cluster_binds, cluster_evicts)

    # -- dirty-set marks (incremental snapshot) -----------------------------

    def mark_node_dirty(self, name: str) -> None:
        """Record that ``name``'s live state changed outside the cache's
        own mutators (sim node drain/restore, direct test mutation) so the
        next snapshot re-clones it instead of reusing the cached clone."""
        self._dirty_nodes.add(name)

    def mark_job_dirty(self, uid: str) -> None:
        self._dirty_jobs.add(uid)

    def mark_queue_dirty(self, uid: str) -> None:
        self._dirty_queues.add(uid)

    def mark_all_dirty(self) -> None:
        """Invalidate every cached clone — the blunt instrument for bulk
        external mutation."""
        self._dirty_all = True

    def _mark_task_dirty(self, task: TaskInfo) -> None:
        """One task moved: its job's gang state and (when placed) its
        node's accounting changed. Caller holds self._lock."""
        if task.job:
            self._dirty_jobs.add(task.job)
        if task.node_name:
            self._dirty_nodes.add(task.node_name)

    # -- ingestion (event_handlers.go analogues) ----------------------------

    def add_node(self, node: NodeInfo) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self._dirty_nodes.add(node.name)

    def remove_node(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)
            self._dirty_nodes.add(name)

    def add_queue(self, queue: QueueInfo) -> None:
        with self._lock:
            self.queues[queue.uid] = queue
            self._dirty_queues.add(queue.uid)

    def remove_queue(self, uid: str) -> None:
        with self._lock:
            self.queues.pop(uid, None)
            self._dirty_queues.add(uid)

    def add_job(self, job: JobInfo) -> None:
        with self._lock:
            if job.schedule_start_timestamp is None:
                job.schedule_start_timestamp = self.time_fn()
            self.jobs[job.uid] = job
            self._dirty_jobs.add(job.uid)
            if self.fast_admit_feed:
                self._new_job_uids.add(job.uid)

    def drain_new_jobs(self) -> List[str]:
        """Consume the fast-admit arrival feed (sorted for determinism);
        empty unless ``fast_admit_feed`` is on."""
        with self._lock:
            uids = sorted(self._new_job_uids)
            self._new_job_uids.clear()
        return uids

    def remove_job(self, uid: str) -> None:
        with self._lock:
            job = self.jobs.pop(uid, None)
            self._dirty_jobs.add(uid)
            if job is not None:
                for task_uid in job.tasks:
                    self._drop_retry_state(task_uid)
                    self.inflight.task_deleted(task_uid)
                    self.binding_tasks.pop(task_uid, None)
                # a parked podgroup-status flush for a removed job is moot
                key = f"pg_status/{uid}"
                if self.dead_letter.pop(key, None) is not None:
                    from .. import metrics
                    metrics.set_dead_letter_size(len(self.dead_letter))
                self.resync_queue.forget(key)

    def get_or_create_job(self, uid: str, **kwargs) -> JobInfo:
        with self._lock:
            if uid not in self.jobs:
                self.jobs[uid] = JobInfo(uid=uid, **kwargs)
                self._dirty_jobs.add(uid)
            return self.jobs[uid]

    def add_task(self, task: TaskInfo) -> None:
        """Pod added: index into its job and, if placed, its node
        (event_handlers.go addTask)."""
        with self._lock:
            job = self.get_or_create_job(task.job)
            job.add_task_info(task)
            if task.node_name and task.node_name in self.nodes:
                self.nodes[task.node_name].add_task(task)
            self._mark_task_dirty(task)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        with self._lock:
            job = self.jobs.get(task.job)
            if job is None:
                return
            job.update_task_status(job.tasks[task.uid], status)
            if task.node_name and task.node_name in self.nodes:
                self.nodes[task.node_name].update_task(job.tasks[task.uid])
            self._mark_task_dirty(task)
        if status == TaskStatus.RUNNING:
            # belt-and-braces: however the RUNNING confirmation reached
            # the cache (the FeedbackChannel is the sanctioned route),
            # the bind is no longer in flight
            self.inflight.resolve("bind", task.uid)

    def delete_task(self, task: TaskInfo) -> None:
        with self._lock:
            # mark BEFORE mutating: node.remove_task clears task.node_name
            self._mark_task_dirty(task)
            job = self.jobs.get(task.job)
            if job is not None:
                job.delete_task_info(task)
            if task.node_name and task.node_name in self.nodes:
                node = self.nodes[task.node_name]
                node.remove_task(task)
                self._release_numa(node, task.uid)
            self._drop_retry_state(task.uid)
            self.binding_tasks.pop(task.uid, None)
        # the pod left the cluster: a pending evict entry is thereby
        # CONFIRMED, a pending bind entry is moot (cache/inflight.py)
        self.inflight.task_deleted(task.uid)

    @staticmethod
    def _release_numa(node, task_uid: str) -> None:
        """Return the task's committed cpusets to the node topology — the
        in-process equivalent of the node agent refreshing the Numatopology
        CR after a pod dies (numa_info.go Release)."""
        sets = node.numa_allocations.pop(task_uid, None)
        if sets and node.numa_info is not None:
            node.numa_info.release(sets)

    # -- snapshot (cache.go:801-893) ----------------------------------------

    def add_resource_quota(self, quota) -> None:
        """AddResourceQuota (event_handlers.go:740-770): track the
        volcano.sh/namespace.weight key of spec.hard per namespace; the
        snapshot's NamespaceInfo takes the max across the namespace's
        quotas (namespace_info.go quotaItem semantics)."""
        ns = quota.metadata.namespace
        col = self.namespace_collections.setdefault(
            ns, NamespaceCollection(ns))
        weight = int(quota.hard.get(NamespaceCollection.WEIGHT_KEY, 0))
        col.update(quota.metadata.name, weight)

    def delete_resource_quota(self, quota) -> None:
        """DeleteResourceQuota (event_handlers.go:790-812)."""
        col = self.namespace_collections.get(quota.metadata.namespace)
        if col is not None:
            col.delete(quota.metadata.name)

    def snapshot(self) -> ClusterInfo:
        """Clone-on-dirty snapshot (docs/performance.md): nodes/jobs/queues
        whose keys were not touched since the previous snapshot — and whose
        previous clone the session never mutated (the ``_touched`` witness)
        — are structurally SHARED with the previous snapshot instead of
        deep-cloned. Sharing is exact because a reused clone is, by the
        witness, byte-equal to what a fresh ``clone()`` would produce
        (aggregates are invariants of the unchanged task set, and the
        immutable fields were already shared per the Resource contract).
        Falls back to the historical full deep-clone when
        VOLCANO_TPU_INCREMENTAL_SNAPSHOT=0 or after mark_all_dirty()."""
        from ..obs import trace as obs_trace
        with obs_trace.span("snapshot_clone"):
            ci = self._snapshot_impl()
        if self.snapshot_scope is not None:
            ci = self.snapshot_scope(ci)
        return ci

    def _snapshot_impl(self, stage: bool = False):
        """Build one clone-on-dirty ClusterInfo. ``stage=False`` (the
        historical path) also CONSUMES the incremental bookkeeping:
        stores the clone maps, clears the dirty sets, bumps the epoch.
        ``stage=True`` (speculative_snapshot) leaves every piece of
        cache bookkeeping untouched and instead returns ``(ci, staged)``
        where ``staged`` carries what adopt_speculative_snapshot would
        need to install later — the read-only open the pipelined shell's
        speculation rides (docs/performance.md)."""
        t0 = time.perf_counter()
        touched_nodes: List[str] = []
        touched_jobs: List[str] = []
        tensor_rows: Set[str] = set()
        with self._lock:
            self._reabsorb_spec_dirt_locked()
            incremental = incremental_snapshot_enabled()
            full = self._dirty_all or not incremental
            ci = ClusterInfo()
            inflight_nodes = set(self.binding_tasks.values())
            reused_nodes = cloned_nodes = 0
            for name, node in self.nodes.items():
                if not node.ready:
                    continue
                # nodes with in-flight async binds are skipped to avoid
                # double-booking (cache.go:822-827)
                if name in inflight_nodes:
                    continue
                prev = None if full else self._snap_nodes.get(name)
                if (prev is not None
                        and name not in self._dirty_nodes
                        and not prev._touched and not node._touched
                        and prev.unschedulable == node.unschedulable):
                    ci.nodes[name] = prev
                    reused_nodes += 1
                else:
                    ci.nodes[name] = node.clone()
                    if stage:
                        # defer the witness reset to adopt time: a
                        # discarded speculation must leave the real
                        # snapshot's re-clone decision exactly as it was
                        touched_nodes.append(name)
                    else:
                        node._touched = False
                        self._tensor_dirty.add(name)
                    cloned_nodes += 1
                    tensor_rows.add(name)
            for uid, q in self.queues.items():
                prev = None if full else self._snap_queues.get(uid)
                if (prev is not None and uid not in self._dirty_queues
                        and prev.weight == q.weight
                        and prev.state == q.state
                        and prev.reclaimable == q.reclaimable
                        and prev.capability is q.capability):
                    ci.queues[uid] = prev
                else:
                    ci.queues[uid] = q.clone()
            reused_jobs = 0
            for uid, job in self.jobs.items():
                if job.podgroup is None:
                    continue
                prev = None if full else self._snap_jobs.get(uid)
                if (prev is not None
                        and uid not in self._dirty_jobs
                        and not prev._touched and not job._touched
                        and prev.podgroup is job.podgroup
                        and prev.priority == job.priority
                        and prev.min_available == job.min_available
                        and prev.queue == job.queue):
                    # per-cycle scratch a fresh clone would start without
                    if prev.nodes_fit_errors:
                        prev.nodes_fit_errors = {}
                    if prev.job_fit_errors:
                        prev.job_fit_errors = ""
                    ci.jobs[uid] = prev
                    reused_jobs += 1
                else:
                    ci.jobs[uid] = job.clone()
                    if stage:
                        touched_jobs.append(uid)
                    else:
                        job._touched = False
            for name, col in self.namespace_collections.items():
                ci.namespaces[name] = col.snapshot()
            for job in ci.jobs.values():
                ci.namespaces.setdefault(job.namespace,
                                         NamespaceInfo(job.namespace))
            ci.node_list = list(ci.nodes.values())
            n_nodes = len(ci.nodes)
            stats = {
                "full": full,
                "clone_s": time.perf_counter() - t0,
                "dirty_nodes": cloned_nodes,
                "reused_nodes": reused_nodes,
                "reused_jobs": reused_jobs,
                "dirty_ratio": (cloned_nodes / n_nodes) if n_nodes else 0.0,
            }
            if stage:
                # clone maps and epoch untouched: stamp the epoch the
                # snapshot WILL get if adopted, and hand back everything
                # adopt needs. The dirty sets MOVE into the staged basis
                # (_spec_dirt): post-stage mutations then accumulate in
                # empty sets, so the commit boundary's delta is exact —
                # including a re-mutation of a key that was already dirty
                # at stage time (the cycle's own bind set).
                ci.snap_epoch = self._snap_epoch + 1
                staged = {
                    "epoch": self._snap_epoch,
                    "dirty_all": self._dirty_all,
                    "incremental": incremental,
                    "nodes": dict(ci.nodes),
                    "jobs": dict(ci.jobs),
                    "queues": dict(ci.queues),
                    "dirty_nodes": frozenset(self._dirty_nodes),
                    "dirty_jobs": frozenset(self._dirty_jobs),
                    "dirty_queues": frozenset(self._dirty_queues),
                    "touched_nodes": touched_nodes,
                    "touched_jobs": touched_jobs,
                    "tensor_rows": tensor_rows,
                    "stats": stats,
                }
                self._spec_dirt = staged
                self._dirty_nodes.clear()
                self._dirty_jobs.clear()
                self._dirty_queues.clear()
                return ci, staged
            if incremental:
                self._snap_nodes = dict(ci.nodes)
                self._snap_jobs = dict(ci.jobs)
                self._snap_queues = dict(ci.queues)
                self._dirty_all = False
            else:
                # keep nothing: a later re-enable must rebuild from scratch
                self._snap_nodes = {}
                self._snap_jobs = {}
                self._snap_queues = {}
                self._dirty_all = True
            self._dirty_nodes.clear()
            self._dirty_jobs.clear()
            self._dirty_queues.clear()
            self._snap_epoch += 1
            ci.snap_epoch = self._snap_epoch
            self.last_snapshot_stats = stats
        from .. import metrics
        metrics.update_snapshot_stats(stats["dirty_nodes"],
                                      stats["dirty_ratio"])
        if full:
            metrics.register_snapshot_full_rebuild("clone")
        return ci

    # -- speculative snapshot (docs/performance.md pipelining) --------------

    def speculative_snapshot(self):
        """Read-only clone-on-dirty snapshot for the pipelined shell's
        speculative open: builds the same ClusterInfo ``snapshot()``
        would, but consumes NOTHING — dirty sets, clone maps, epoch and
        mutation witnesses all stay as they were, so the next real
        ``snapshot()`` is unaffected whether the speculation commits or
        is discarded. Returns ``(ci, staged)``;
        ``adopt_speculative_snapshot(staged)`` promotes the staged
        bookkeeping iff nothing mutated in between."""
        from ..obs import trace as obs_trace
        with obs_trace.span("snapshot_clone", speculative=True):
            ci, staged = self._snapshot_impl(stage=True)
        if self.snapshot_scope is not None:
            ci = self.snapshot_scope(ci)
        return ci, staged

    def _reabsorb_spec_dirt_locked(self) -> None:
        """Merge an outstanding speculative basis's moved dirty keys back
        into the live dirty sets (caller holds the lock). Every real
        snapshot build runs this first, so a snapshot taken while a
        speculation is in flight — or after one was discarded without an
        explicit restore — can never reuse a stale clone."""
        sd = self._spec_dirt
        if sd is None:
            return
        self._spec_dirt = None
        self._dirty_nodes.update(sd["dirty_nodes"])
        self._dirty_jobs.update(sd["dirty_jobs"])
        self._dirty_queues.update(sd["dirty_queues"])

    def discard_speculative_snapshot(self, staged) -> None:
        """Give the staged basis's moved dirty keys back (conflict path /
        abandoned speculation). No-op if a real snapshot already
        reabsorbed them, or if a newer speculation staged since."""
        with self._lock:
            if self._spec_dirt is staged:
                self._reabsorb_spec_dirt_locked()

    def speculation_delta(self, staged) -> Dict[str, object]:
        """What mutated since the speculative snapshot was staged — the
        dirty keys accumulated since the stage moved the sets (exact:
        re-mutations of stage-time-dirty keys show up too), plus whether
        the snapshot epoch moved (another snapshot ran, or
        invalidate_device_state fired). The conflict check at the
        pipelined commit boundary is a pure function of this delta."""
        with self._lock:
            # a post-stage mark_all_dirty (drift repair, bulk external
            # mutation) invalidates the staged clones wholesale without
            # touching the key sets — treat it like an epoch move
            stale = (self._spec_dirt is not staged
                     or self._dirty_all != staged["dirty_all"])
            return {
                "epoch_moved": stale
                or self._snap_epoch != staged["epoch"],
                "nodes": set(self._dirty_nodes),
                "jobs": set(self._dirty_jobs),
                "queues": set(self._dirty_queues),
            }

    def adopt_speculative_snapshot(self, staged) -> bool:
        """Promote a staged speculative snapshot to THE snapshot —
        exactly what ``snapshot()`` would have produced had it run now,
        because the precondition is that nothing mutated since staging
        (epoch unchanged, zero dirty keys since the stage moved the
        sets). Installs the clone maps, clears the witnesses the staged
        build deferred, consumes the moved dirt and bumps the epoch.
        Returns False (adopting nothing) on any mutation since staging —
        the caller re-snapshots."""
        with self._lock:
            if self._spec_dirt is not staged \
                    or self._snap_epoch != staged["epoch"] \
                    or self._dirty_all != staged["dirty_all"] \
                    or self._dirty_nodes or self._dirty_jobs \
                    or self._dirty_queues:
                return False
            self._spec_dirt = None      # consumed: the clones embody it
            if staged["incremental"]:
                self._snap_nodes = dict(staged["nodes"])
                self._snap_jobs = dict(staged["jobs"])
                self._snap_queues = dict(staged["queues"])
                self._dirty_all = False
            else:
                self._snap_nodes = {}
                self._snap_jobs = {}
                self._snap_queues = {}
                self._dirty_all = True
            # deferred witness resets: the same ``_touched = False`` the
            # real snapshot performs at clone time. Sound here because
            # every cache mutator dirty-marks (VT001), and new dirt
            # refused adoption above.
            for name in staged["touched_nodes"]:
                node = self.nodes.get(name)
                if node is not None:
                    node._touched = False
            for uid in staged["touched_jobs"]:
                job = self.jobs.get(uid)
                if job is not None:
                    job._touched = False
            self._tensor_dirty.update(staged["tensor_rows"])
            self._dirty_nodes.clear()
            self._dirty_jobs.clear()
            self._dirty_queues.clear()
            self._snap_epoch += 1
            stats = staged["stats"]
            self.last_snapshot_stats = stats
        from .. import metrics
        metrics.update_snapshot_stats(stats["dirty_nodes"],
                                      stats["dirty_ratio"])
        if stats["full"]:
            metrics.register_snapshot_full_rebuild("clone")
        return True

    def tensor_refresh(self, snapshot_nodes: Dict[str, NodeInfo], rnames,
                       snap_epoch: Optional[int] = None):
        """Persistent device-resident NodeTensors for the CURRENT snapshot
        (docs/performance.md): scatter-updates only the rows the dirty set
        named since the last refresh instead of rebuilding f32[N,R] arrays
        from Python dicts. ``snapshot_nodes`` must be the node dict the
        latest snapshot() returned (Session.nodes before any session
        mutation — values identical to live state at snapshot time);
        ``snap_epoch`` guards against a stale session refreshing over a
        newer snapshot's delta. Returns None when the incremental path is
        unavailable (kill-switch off, epoch mismatch) — callers build a
        plain NodeTensors then."""
        if not incremental_snapshot_enabled():
            return None
        from .snapshot import PersistentNodeTensors
        with self._lock:
            if snap_epoch is not None and snap_epoch != self._snap_epoch:
                return None
            tc = self.tensor_cache
            if tc is None or tc.rnames.names != rnames.names:
                tc = PersistentNodeTensors(rnames)
                self.tensor_cache = tc
            dirty = self._tensor_dirty
            self._tensor_dirty = set()
            stats = tc.refresh(snapshot_nodes, dirty)
        if stats["full"]:
            from .. import metrics
            metrics.register_snapshot_full_rebuild("tensor")
        return tc

    def tensor_refresh_speculative(self, snapshot_nodes: Dict[str, NodeInfo],
                                   rnames, staged):
        """Device tensors for a SPECULATIVE snapshot (docs/performance.md
        pipelining): scatter the union of the pending tensor-dirty rows
        and the staged clone rows onto the persistent mirrors — a
        value-idempotent write; the next REAL refresh re-applies the same
        rows because ``_tensor_dirty`` is deliberately NOT consumed here
        — then pin the resulting epoch so the in-flight solve keeps a
        stable A while cycle N's binds publish B. Returns the pinned
        ``TensorEpochView`` (caller must ``retire_epoch`` it), or None
        when the incremental path is unavailable."""
        if not incremental_snapshot_enabled():
            return None
        from .snapshot import PersistentNodeTensors
        with self._lock:
            if staged["epoch"] != self._snap_epoch:
                return None
            tc = self.tensor_cache
            if tc is None or tc.rnames.names != rnames.names:
                tc = PersistentNodeTensors(rnames)
                self.tensor_cache = tc
            dirty = set(self._tensor_dirty) | set(staged["tensor_rows"])
            stats = tc.refresh(snapshot_nodes, dirty)
            view = tc.pin_epoch()
        if stats["full"]:
            from .. import metrics
            metrics.register_snapshot_full_rebuild("tensor")
        return view

    def invalidate_device_state(self) -> None:
        """Device-fault containment (docs/robustness.md): after an XLA
        OOM/device-lost the device-resident tensor mirrors cannot be
        trusted (device loss frees them outright). Bump the snapshot
        epoch — any in-flight session's tensor_refresh now refuses to
        apply its delta — and drop the persistent tensor cache so the
        next device consumer rebuilds from host truth from scratch."""
        with self._lock:
            self._snap_epoch += 1
            self.tensor_cache = None
            self._tensor_dirty = set()

    # -- drift self-healing (docs/robustness.md) ----------------------------

    def verify_state_integrity(self, repair: bool = True) -> dict:
        """Shadow verifier: re-derive what a from-scratch snapshot/tensor
        build would produce and diff it against the incremental caches
        that the NEXT cycle would reuse. Any mismatch is state drift — a
        missed dirty-mark or mutation-witness hole that clone-on-dirty
        would silently serve as a stale placement input — counted in
        ``volcano_state_drift_total{layer}`` and repaired (``repair=True``)
        by forcing the existing full-rebuild paths: ``mark_all_dirty()``
        for the clone layer, dropping ``tensor_cache`` for the tensor
        layer. Designed to run OFF-CYCLE (the scheduler shell calls it
        after the e2e-timed window, every ``drift_verify_every`` cycles).

        Entries the next snapshot would re-clone anyway (dirty-marked,
        mutation-witnessed, or guard-field mismatches) are skipped: they
        are not drift, they are the incremental machinery working."""
        t0 = time.perf_counter()
        drift = {"node": [], "job": [], "tensor": []}
        # Phase 1 (lock): snapshot the candidate key/object pairs only.
        with self._lock:
            node_cand = [] if self._dirty_all else [
                (name, prev, self.nodes.get(name))
                for name, prev in self._snap_nodes.items()]
            job_cand = [] if self._dirty_all else [
                (uid, prev, self.jobs.get(uid))
                for uid, prev in self._snap_jobs.items()]
            tc = self.tensor_cache
            tensor_cand = [] if tc is None else [
                (name, prev, tc.index.get(name))
                for name, prev in self._snap_nodes.items()]
            checked_nodes = len(self._snap_nodes)
            checked_jobs = len(self._snap_jobs)
        # Phase 2 (no lock): the O(cluster) fingerprint diff — watch/
        # controller threads keep feeding the cache meanwhile. An entry a
        # concurrent mutator races (torn comparison raising) is skipped:
        # that mutation dirty-marks it, so it is re-cloned anyway.
        suspects = {"node": [], "job": [], "tensor": []}
        for name, prev, live in node_cand:
            try:
                if (live is not None and live.ready
                        and not prev._touched and not live._touched
                        and prev.unschedulable == live.unschedulable
                        and not self._node_matches(prev, live)):
                    suspects["node"].append(name)
            except Exception:
                continue
        for uid, prev, live in job_cand:
            try:
                if (live is not None and live.podgroup is not None
                        and not prev._touched and not live._touched
                        and prev.podgroup is live.podgroup
                        and prev.priority == live.priority
                        and prev.min_available == live.min_available
                        and prev.queue == live.queue
                        and not self._job_matches(prev, live)):
                    suspects["job"].append(uid)
            except Exception:
                continue
        rn = tc.rnames if tc is not None else None
        for name, prev, i in tensor_cand:
            try:
                if i is not None \
                        and not self._tensor_row_matches(tc, i, prev, rn):
                    suspects["tensor"].append(name)
            except Exception:
                continue
        # Phase 3 (lock): confirm each suspect against the CURRENT skip
        # conditions (a mutation that raced phase 2 dirty-marked its key,
        # which is not drift) and repair.
        with self._lock:
            if not self._dirty_all:
                for name in suspects["node"]:
                    live = self.nodes.get(name)
                    prev = self._snap_nodes.get(name)
                    if (prev is not None and live is not None and live.ready
                            and name not in self._dirty_nodes
                            and not prev._touched and not live._touched
                            and prev.unschedulable == live.unschedulable
                            and not self._node_matches(prev, live)):
                        drift["node"].append(name)
                for uid in suspects["job"]:
                    live = self.jobs.get(uid)
                    prev = self._snap_jobs.get(uid)
                    if (prev is not None and live is not None
                            and live.podgroup is not None
                            and uid not in self._dirty_jobs
                            and not prev._touched and not live._touched
                            and prev.podgroup is live.podgroup
                            and not self._job_matches(prev, live)):
                        drift["job"].append(uid)
            if self.tensor_cache is tc and tc is not None:
                for name in suspects["tensor"]:
                    i = tc.index.get(name)
                    prev = self._snap_nodes.get(name)
                    if (i is not None and prev is not None
                            and name not in self._tensor_dirty
                            and not self._tensor_row_matches(tc, i, prev,
                                                             rn)):
                        drift["tensor"].append(name)
            repaired = False
            if repair:
                if drift["node"] or drift["job"]:
                    self._dirty_all = True
                    repaired = True
                if drift["tensor"]:
                    self.tensor_cache = None
                    self._tensor_dirty = set()
                    repaired = True
            stats = {
                "drift": {k: sorted(v) for k, v in drift.items() if v},
                "drift_total": sum(len(v) for v in drift.values()),
                "repaired": repaired,
                "checked_nodes": checked_nodes,
                "checked_jobs": checked_jobs,
                "verify_s": time.perf_counter() - t0,
            }
            self.last_verify = stats
        from .. import metrics
        for layer, names in drift.items():
            if names:
                metrics.register_state_drift(layer, len(names))
        metrics.set_drift_verify_stats(stats["drift_total"],
                                       stats["verify_s"])
        return stats

    @staticmethod
    def _node_matches(prev: NodeInfo, live: NodeInfo) -> bool:
        """Would reusing ``prev`` equal a fresh ``live.clone()``? The
        same fields the incremental-snapshot oracle test asserts."""
        if (prev.allocatable is not live.allocatable
                or prev.used_ports != live.used_ports):
            return False
        for field in ("idle", "used", "releasing", "pipelined"):
            if getattr(prev, field) != getattr(live, field):
                return False
        return ({u: (t.status, t.node_name) for u, t in prev.tasks.items()}
                == {u: (t.status, t.node_name)
                    for u, t in live.tasks.items()})

    @staticmethod
    def _job_matches(prev: JobInfo, live: JobInfo) -> bool:
        if prev.allocated != live.allocated:
            return False
        return ({u: t.status for u, t in prev.tasks.items()}
                == {u: t.status for u, t in live.tasks.items()})

    @staticmethod
    def _tensor_row_matches(tc, i: int, node: NodeInfo, rnames) -> bool:
        """Row ``i`` of the persistent tensors vs what ``_write_row``
        would derive from the snapshot clone today."""
        import numpy as np
        for field in ("idle", "used", "releasing", "pipelined",
                      "allocatable"):
            if not np.array_equal(getattr(tc, field)[i],
                                  getattr(node, field).to_vector(rnames)):
                return False
        from .snapshot import BIG_MAX_TASKS, zone_code
        want_max = node.max_task_num if node.max_task_num > 0 \
            else BIG_MAX_TASKS
        return (int(tc.max_tasks[i]) == want_max
                and int(tc.ntasks[i]) == len(node.tasks)
                and int(tc.zone_code[i])
                == zone_code(getattr(node, "topology_zone", "")))

    # -- side effects (cache.go:549-666) ------------------------------------

    def bind(self, task: TaskInfo) -> None:
        """Mark the optimistic Binding state FIRST, then execute the bind
        through the Binder (the reference's AddBindingTask-then-async-Bind
        order, cache.go:602-666) — so the watch event that flips the pod to
        Running lands after, never before, the cache's own update."""
        newly_placed = False
        prev_status = None
        with self._lock:
            job = self.jobs.get(task.job)
            if job is not None and task.uid in job.tasks:
                self._dirty_jobs.add(task.job)
                if task.node_name:
                    self._dirty_nodes.add(task.node_name)
                cached = job.tasks[task.uid]
                prev_status = cached.status
                prev_node = cached.node_name
                if prev_node:
                    self._dirty_nodes.add(prev_node)
                if not prev_node:
                    newly_placed = True
                    cached.node_name = task.node_name
                    job.update_task_status(cached, TaskStatus.BOUND)
                    if task.node_name in self.nodes:
                        self.nodes[task.node_name].add_task(cached)
                else:
                    job.update_task_status(cached, TaskStatus.BOUND)
                    if prev_node in self.nodes:
                        self.nodes[prev_node].update_task(cached)
        seq = self._journal_intent("bind", task, task.node_name,
                                   fresh=newly_placed)
        self._register_inflight("bind", task, task.node_name, seq)
        try:
            self._bind_volumes(task)
            self.binder.bind(task, task.node_name)
            self._journal_ack(seq, True)
        except Exception:
            # roll back exactly what the optimistic phase did
            with self._lock:
                job = self.jobs.get(task.job)
                if job is not None and task.uid in job.tasks:
                    cached = job.tasks[task.uid]
                    if newly_placed:
                        if cached.node_name in self.nodes:
                            self.nodes[cached.node_name].remove_task(cached)
                        job.update_task_status(cached, TaskStatus.PENDING)
                        cached.node_name = ""
                    elif prev_status is not None:
                        job.update_task_status(cached, prev_status)
                        if cached.node_name in self.nodes:
                            self.nodes[cached.node_name].update_task(cached)
                self.err_tasks.append(task)
            self._journal_ack(seq, False)
            self.inflight.abort("bind", task.uid)
            self.resync_task(task)

    def bind_batch(self, tasks) -> None:
        """Batched bind: one optimistic pass with per-node aggregated
        accounting, then the Binder calls, with per-task rollback on binder
        failure. Semantics match bind() per task; the aggregation removes the
        per-task Resource arithmetic that dominates a 10k-bind cycle."""
        from ..api import Resource
        agg = {}
        placed = []
        with self._lock:
            for task in tasks:
                job = self.jobs.get(task.job)
                if job is None or task.uid not in job.tasks:
                    continue
                self._dirty_jobs.add(task.job)
                if task.node_name:
                    self._dirty_nodes.add(task.node_name)
                cached = job.tasks[task.uid]
                if cached.node_name:
                    # re-bind of an already-placed task: rare; full path
                    self._dirty_nodes.add(cached.node_name)
                    job.update_task_status(cached, TaskStatus.BOUND)
                    if cached.node_name in self.nodes:
                        self.nodes[cached.node_name].update_task(cached)
                    placed.append((task, False))
                    continue
                cached.node_name = task.node_name
                job.update_task_status(cached, TaskStatus.BOUND)
                node = self.nodes.get(task.node_name)
                if node is not None:
                    if node.gpu_devices:
                        node.add_task(cached)        # full path: card packing
                    else:
                        # the clone keeps status BOUND so a later
                        # remove_task/update_task re-accounts correctly
                        node.tasks[cached.uid] = cached.shallow_clone()
                        agg.setdefault(task.node_name, Resource()).add(
                            cached.resreq)
                placed.append((task, True))
            for name, r in agg.items():
                node = self.nodes[name]
                node.idle.sub(r)
                node.used.add(r)
        # group commit: journal EVERY intent of the batch durably (one
        # fsync) before the first executor call — the WAL ordering the
        # reconciler relies on, at batch cost instead of per-bind cost
        seqs = [self._journal_intent("bind", task, task.node_name,
                                     sync=False, fresh=newly)
                for task, newly in placed]
        if self.journal is not None and placed:
            self.journal.flush()
        for (task, newly), seq in zip(placed, seqs):
            self._register_inflight("bind", task, task.node_name, seq)
        for (task, newly), seq in zip(placed, seqs):
            try:
                self._bind_volumes(task)
                self.binder.bind(task, task.node_name)
                self._journal_ack(seq, True)
            except Exception:
                with self._lock:
                    job = self.jobs.get(task.job)
                    if job is not None and task.uid in job.tasks:
                        cached = job.tasks[task.uid]
                        if newly:
                            node = self.nodes.get(cached.node_name)
                            if node is not None:
                                node.remove_task(cached)
                            job.update_task_status(cached, TaskStatus.PENDING)
                            cached.node_name = ""
                    self.err_tasks.append(task)
                self._journal_ack(seq, False)
                self.inflight.abort("bind", task.uid)
                self.resync_task(task)

    def _bind_volumes(self, task: TaskInfo) -> None:
        """Volume allocate+bind at pod-bind time. The reference splits this
        across Statement.Allocate (assume) and Commit (bind,
        statement.go:230-292); in-process PVC binding carries no node
        constraint, so the whole sequence runs here with identical end
        state: the pod's claims go Bound when the pod binds."""
        volumes = self.volume_binder.get_pod_volumes(
            task, self.nodes.get(task.node_name))
        self.volume_binder.allocate_volumes(task, task.node_name, volumes)
        self.volume_binder.bind_volumes(task, volumes)

    def evict(self, task: TaskInfo, reason: str) -> None:
        """Execute eviction: pod condition + delete (cache.go:549-599)."""
        seq = self._journal_intent("evict", task)
        self._register_inflight("evict", task, seq=seq)
        try:
            self.evictor.evict(task, reason)
            self._journal_ack(seq, True)
        except Exception:
            with self._lock:
                self.err_tasks.append(task)
            self._journal_ack(seq, False)
            self.inflight.abort("evict", task.uid)
            self.resync_task(task, op="evict")
            return
        with self._lock:
            job = self.jobs.get(task.job)
            if job is not None and task.uid in job.tasks:
                self._mark_task_dirty(task)
                job.update_task_status(job.tasks[task.uid], TaskStatus.RELEASING)
                if task.node_name in self.nodes:
                    self.nodes[task.node_name].update_task(job.tasks[task.uid])

    def requeue_lost_member(self, jid: str, uid: str,
                            lost_node: Optional[str] = None,
                            detach: bool = True) -> bool:
        """Validate-then-requeue for a gang member the cluster lost (a
        node died with its pods; the delete+controller-recreate is
        implicit). The validation is what makes a node death racing an
        unacked bind safe: only a member the cache still places on
        ``lost_node`` (or an unplaced mid-rollback one) requeues — a
        member a newer intent re-placed elsewhere is that intent's
        business. Any open in-flight entry and ``binding_tasks`` marker
        for the member resolves here: a dead node's ack never comes, so
        leaving either armed would strand them until the watchdog
        (docs/robustness.md feedback failure model). ``detach=False``
        skips the node-mirror detach when the node itself is about to
        leave the cache wholesale. Returns whether the member was
        requeued."""
        with self._lock:
            job = self.jobs.get(jid)
            cached = job.tasks.get(uid) if job is not None else None
            if cached is None:
                return False
            if lost_node is not None and cached.node_name \
                    and cached.node_name != lost_node:
                return False
            if cached.node_name:
                self._dirty_nodes.add(cached.node_name)
            self._dirty_jobs.add(jid)
            node = self.nodes.get(cached.node_name)
            if detach and node is not None and uid in node.tasks:
                node.remove_task(cached)
            cached.node_name = ""
            job.update_task_status(cached, TaskStatus.PENDING)
            self.binding_tasks.pop(uid, None)
        self.inflight.resolve(None, uid, "lost")
        return True

    def rearm_inflight_from_state(self) -> int:
        """Rebuild the (volatile) in-flight ledger from cache truth — a
        fresh incarnation's ledger is empty while the relisted state
        still shows tasks whose cluster ack is outstanding: BOUND means
        a bind awaiting its RUNNING ack, RELEASING an eviction awaiting
        its delete confirmation. Run by ``Scheduler.startup_reconcile``
        AFTER the journal's crash window settles, so an ack lost around
        a process death still meets the watchdog instead of wedging the
        task forever (the kill + dropped-evict-ack compose the ack-chaos
        soak exposed). Returns the number of entries armed."""
        pending: List[Tuple[str, str, str, str]] = []
        with self._lock:
            for jid, job in self.jobs.items():
                for uid, task in job.tasks.items():
                    if task.status == TaskStatus.BOUND and task.node_name:
                        pending.append(("bind", uid, jid, task.node_name))
                    elif task.status == TaskStatus.RELEASING:
                        pending.append(("evict", uid, jid,
                                        task.node_name or ""))
        for op, uid, jid, node in pending:
            self.inflight.register(op, uid, jid, node)
        return len(pending)

    # -- in-flight watchdog (docs/robustness.md feedback failure model) -----

    def process_expired_inflight(self) -> Dict[str, int]:
        """The ack watchdog, driven from the scheduler epilogue: drain
        any delayed watch-path acks, then re-validate every in-flight
        entry whose ack deadline passed against cluster truth
        (``inflight_oracle_fn``) and resolve it through the existing
        repair machinery — the FeedbackChannel normalizer for recovered
        acks, ``journal._rollback_bind`` for binds the cluster lacks,
        the resync ladder for evicts that never landed. Never a raw
        mutation. Returns {resolution: count} for the entries settled
        this pass."""
        from .. import metrics
        self.feedback.deliver_due()
        ledger = self.inflight
        now = ledger.time_fn()
        out: Dict[str, int] = {}
        for entry in ledger.expired(now):
            try:
                resolution = self._resolve_expired_inflight(entry)
            except Exception:
                log.exception("resolving expired in-flight entry %r "
                              "failed; it stays armed", entry)
                continue
            if resolution:
                out[resolution] = out.get(resolution, 0) + 1
                metrics.register_inflight_expired(entry.op, resolution)
        metrics.set_inflight_stats(ledger.open_count(),
                                   ledger.oldest_age(now),
                                   ledger.detail(now))
        return out

    def _resolve_expired_inflight(self, entry) -> Optional[str]:
        """Settle ONE expired entry; returns its resolution label."""
        from .journal import _rollback_bind
        ledger = self.inflight
        with self._lock:
            job = self.jobs.get(entry.job)
            cached = job.tasks.get(entry.uid) if job is not None else None
            if cached is None:
                intended = False
            elif entry.op == "bind":
                intended = (cached.status == TaskStatus.BOUND
                            and cached.node_name == entry.node)
            else:
                intended = cached.status == TaskStatus.RELEASING
        if cached is None:
            ledger.resolve(entry.op, entry.uid, "gone")
            return "gone"
        if not intended:
            # the cache moved on (re-placement, completion ack raced the
            # deadline): the entry no longer describes live intent
            ledger.resolve(entry.op, entry.uid, "superseded")
            return "superseded"
        truth = None
        if self.inflight_oracle_fn is not None:
            truth = self.inflight_oracle_fn(entry)
        if entry.op == "bind":
            if truth is False:
                # the cluster does not run this placement (the pod died
                # or was deleted under us): undo the optimistic state
                # with the reconciler's own rollback helper — the task
                # re-enters the pending pool and the next cycle's
                # journaled+fenced allocate re-places it
                _rollback_bind(self, job, cached, entry.node, fresh=True)
                with self._lock:
                    self.binding_tasks.pop(entry.uid, None)
                ledger.resolve("bind", entry.uid, "rolled_back")
                return "rolled_back"
            # executed (True) or unknown (the executor DID accept the
            # bind): only the feedback was lost — recover the ack
            # through the normalizer, exactly as the wire would deliver
            self.feedback.ack_running(entry.job, entry.uid, entry.node,
                                      source="watchdog")
            ledger.resolve("bind", entry.uid, "repaired")
            return "repaired"
        if truth is False:
            # the evict never took cluster-side effect: re-issue it
            # through the resync ladder (journaled+fenced retry with a
            # budget; dead-letters on exhaustion)
            ledger.resolve("evict", entry.uid, "reissued")
            self.resync_task(cached.shallow_clone(), op="evict")
            return "reissued"
        self.feedback.ack_evicted(entry.job, entry.uid, source="watchdog")
        ledger.resolve("evict", entry.uid, "repaired")
        return "repaired"

    def resync_task(self, task: TaskInfo, op: str = "bind") -> None:
        """Queue a failed side effect for rate-limited retry
        (cache.go:777-799 resyncTask -> errTasks.AddRateLimited); a task
        past its retry budget moves to the dead-letter set instead."""
        self._resync_or_dead_letter(f"{op}/{task.uid}", op, task)

    def _resync_or_dead_letter(self, key: str, op: str,
                               task: TaskInfo) -> None:
        if not self.resync_queue.add_rate_limited(key, (op, task)):
            evicted = 0
            with self._lock:
                fresh = key not in self.dead_letter
                # re-parking an existing key refreshes its age (it is
                # the set's newest failure again)
                self.dead_letter.pop(key, None)
                self.dead_letter[key] = (op, task)
                while 0 < self.dead_letter_max < len(self.dead_letter):
                    # evict the OLDEST parked entry (insertion order):
                    # bounded memory beats a silent unbounded pin — the
                    # eviction is counted and warned about
                    oldest = next(iter(self.dead_letter))
                    self.dead_letter.pop(oldest)
                    self.resync_queue.forget(oldest)
                    self.dead_letter_evicted += 1
                    evicted += 1
                size = len(self.dead_letter)
            from .. import metrics
            metrics.set_dead_letter_size(size)
            if evicted:
                metrics.register_dead_letter_evicted(evicted)
                log.error("dead-letter set overflowed its cap (%d): "
                          "evicted %d oldest side effect(s) — redrive "
                          "cannot recover them", self.dead_letter_max,
                          evicted)
            if fresh:
                # count logical events, not cycles: a PENDING-rolled-back
                # task re-placed every cycle keeps hitting the refused
                # budget, but it is still ONE dead-lettered side effect
                metrics.register_dead_letter(op)

    def _drop_retry_state(self, task_uid: str) -> None:
        """A deleted task's queued retries and dead-letter entry are moot
        — purge them so dead_letter cannot pin TaskInfo objects (and their
        job/node references) forever. Caller holds self._lock."""
        dropped = False
        for key in (f"bind/{task_uid}", f"evict/{task_uid}"):
            dropped = (self.dead_letter.pop(key, None)
                       is not None) or dropped
            self.resync_queue.forget(key)
        if dropped:
            from .. import metrics
            metrics.set_dead_letter_size(len(self.dead_letter))

    def redrive_dead_letter(self) -> int:
        """Re-queue every dead-lettered side effect with a fresh retry
        budget — the operator affordance for after the underlying fault
        (bad node, apiserver outage) is fixed. Returns how many moved."""
        with self._lock:
            items = list(self.dead_letter.items())
            self.dead_letter.clear()
        moved = 0
        # the walk is operator-invoked (not cycle work) and the set
        # evicts its oldest past the dead_letter_max cap
        # vlint: disable=VT018 -- operator redrive, bounded by the cap
        for key, (op, task) in items:
            self.resync_queue.forget(key)
            if self.resync_queue.add_rate_limited(key, (op, task)):
                moved += 1
            else:
                # the queue refused even a fresh budget (max_retries 0):
                # re-park instead of silently dropping the side effect
                with self._lock:
                    self.dead_letter[key] = (op, task)
        from .. import metrics
        metrics.set_dead_letter_size(len(self.dead_letter))
        return moved

    def _resync_stale(self, op: str, task: TaskInfo) -> bool:
        """A queued retry is STALE when the cluster moved on while it sat
        in backoff: the task was deleted, or (bind) a later scheduling
        cycle already re-placed the rolled-back task — retrying then would
        bind the pod a second time (possibly onto a different node) and
        double-count it on two nodes' accounting. Any allocated status
        counts as re-placed, not just BOUND: a re-bound task ack'd to
        RUNNING by the watch stream between cycles is exactly as final
        (caught by the sim's chaos replay, which acks binds the way a
        live cluster does)."""
        with self._lock:
            job = self.jobs.get(task.job)
            cached = job.tasks.get(task.uid) if job is not None else None
            if cached is None:
                return True
            if op == "bind" and (allocated_status(cached.status)
                                 or (cached.node_name
                                     and cached.node_name != task.node_name)):
                return True
        return False

    def _resync_bind_valid(self, task: TaskInfo) -> bool:
        """A queued bind retry is only re-executable while it is still
        the placement decision the scheduler would stand behind: the
        task is PENDING (a rollback state — NOT evicted/RELEASING, which
        _resync_stale lets through) and either unplaced or still pointing
        at the retry's own target (the re-bind rollback keeps node_name),
        and the target node is present, ready, and can hold the task
        RIGHT NOW on both idle and future_idle (respecting pipelined
        reservations made against releasing capacity since the retry was
        queued)."""
        with self._lock:
            job = self.jobs.get(task.job)
            cached = job.tasks.get(task.uid) if job is not None else None
            node = self.nodes.get(task.node_name)
            if (cached is None or node is None or not node.ready
                    or cached.status != TaskStatus.PENDING
                    or cached.node_name not in ("", task.node_name)):
                return False
            if cached.node_name == task.node_name \
                    and cached.uid in node.tasks:
                # still accounted on the target (re-bind rollback kept
                # the placement): no room check — it holds its own room
                return True
            return (task.init_resreq.less_equal(node.idle)
                    and task.init_resreq.less_equal(node.future_idle()))

    def process_resync_tasks(self, max_items: Optional[int] = None) -> int:
        """Retry side effects whose backoff expired (processResyncTask,
        cache.go:781-799) — the scheduler shell calls this every cycle.
        Returns the number of successful retries. Stale entries (see
        _resync_stale) are dropped, not retried. ``max_items`` bounds
        the per-cycle retry work (the cycle-budget contract, vlint
        VT018); capped-out items stay queued and drain next cycle."""
        done = 0
        for key, (op, task) in self.resync_queue.pop_ready(max_items):
            if op == "pg_status":
                # a parked podgroup status flush (the item is the
                # JobInfo): re-flush the job's LATEST status — the
                # queued snapshot is stale by definition; dropping the
                # retry when the job is gone
                with self._lock:
                    live = self.jobs.get(task.uid)
                if live is None or live.podgroup is None:
                    self.resync_queue.forget(key)
                    continue
                try:
                    self.status_updater.update_pod_group(live)
                    self.resync_queue.forget(key)
                    done += 1
                except Exception:
                    self._resync_or_dead_letter(key, op, live)
                continue
            if self._resync_stale(op, task):
                self.resync_queue.forget(key)
                continue
            if op == "bind" and not self._resync_bind_valid(task):
                # the placement decision behind this retry is no longer
                # valid — the task was evicted/recreated or the target
                # node filled up while the retry sat in backoff. Binding
                # anyway would race the scheduler's OWN re-placement of
                # the task (a double-bind) and over-commit the node (the
                # half-applied BOUND-but-not-on-node corruption the chaos
                # skew soak exposed). Drop it: the allocate loop re-places
                # pending tasks every cycle anyway.
                self.resync_queue.forget(key)
                continue
            seq = self._journal_intent(op, task, task.node_name,
                                       via="resync")
            self._register_inflight(op, task, task.node_name, seq)
            try:
                if op == "bind":
                    self._bind_volumes(task)
                    self.binder.bind(task, task.node_name)
                    with self._lock:
                        job = self.jobs.get(task.job)
                        if job is not None and task.uid in job.tasks:
                            self._mark_task_dirty(task)
                            cached = job.tasks[task.uid]
                            cached.node_name = task.node_name
                            job.update_task_status(cached, TaskStatus.BOUND)
                            node = self.nodes.get(task.node_name)
                            if node is not None \
                                    and cached.uid not in node.tasks:
                                node.add_task(cached)
                else:
                    self.evictor.evict(task, "resync")
                    with self._lock:
                        job = self.jobs.get(task.job)
                        if job is not None and task.uid in job.tasks:
                            self._mark_task_dirty(task)
                            cached = job.tasks[task.uid]
                            job.update_task_status(cached,
                                                   TaskStatus.RELEASING)
                            # the node mirror holds a CLONE: without this
                            # update it keeps the pre-evict status and its
                            # idle/releasing accounting (exactly what the
                            # direct evict() path maintains) — preempt
                            # then sees a phantom RUNNING victim
                            if cached.node_name in self.nodes:
                                self.nodes[cached.node_name].update_task(
                                    cached)
                self._journal_ack(seq, True)
                self.resync_queue.forget(key)
                done += 1
            except Exception:
                self._journal_ack(seq, False)
                self.inflight.abort(op, task.uid)
                self._resync_or_dead_letter(key, op, task)
        return done

    FORWARD_CLUSTER_KEY = "volcano.sh/forward-cluster"

    def bind_pod_group(self, job: JobInfo, cluster: str) -> None:
        """Multi-cluster forwarding (podgroupBinder, cache.go:275-312):
        annotate every task's pod and the PodGroup with the silo cluster so
        the target cluster's control plane takes over the gang."""
        for task in job.tasks.values():
            task.annotations[self.FORWARD_CLUSTER_KEY] = cluster
            pod = getattr(task, "pod", None)
            if pod is not None:
                pod.metadata.annotations[self.FORWARD_CLUSTER_KEY] = cluster
        job.podgroup.annotations[self.FORWARD_CLUSTER_KEY] = cluster
        self._dirty_jobs.add(job.uid)
        self.status_updater.update_pod_group(job)

    def update_job_status(self, job: JobInfo) -> None:
        try:
            self.status_updater.update_pod_group(job)
        except Exception:
            # a store write that failed past the retrying transport's
            # budget (docs/robustness.md store failure model): the cycle
            # must not crash, and the STORE must not be left disagreeing
            # about the phase forever — the store's bind gate refuses
            # pods whose PodGroup it still sees Pending. Park a
            # pg_status retry; process_resync_tasks re-flushes the
            # job's LATEST status once the backoff expires.
            log.exception("podgroup status write for %s failed; queued "
                          "for resync", job.uid)
            self._resync_or_dead_letter(f"pg_status/{job.uid}",
                                        "pg_status", job)
        with self._lock:
            cached = self.jobs.get(job.uid)
            if cached is not None and cached.podgroup is not job.podgroup:
                # the PodGroup mirror is normally ALIASED between the live
                # job and its snapshot clones, so phase/condition writes are
                # visible everywhere; only an actual replacement re-dirties
                cached.podgroup = job.podgroup
                self._dirty_jobs.add(job.uid)

    def update_scheduler_numa_info(self, numa_sets) -> None:
        """Commit cpuset assignments chosen by the numaaware plugin back to
        the live node topology (cache interface UpdateSchedulerNumaInfo;
        session.go:435-437). ``numa_sets`` is {node_name: {task_uid:
        ResNumaSets}}; per-task records let delete_task release them
        (re-committing a uid first releases its previous assignment, so the
        writeback is idempotent across sessions)."""
        with self._lock:
            for node_name, per_task in numa_sets.items():
                node = self.nodes.get(node_name)
                if node is None or node.numa_info is None:
                    continue
                self._dirty_nodes.add(node_name)
                for task_uid, res_sets in per_task.items():
                    self._release_numa(node, task_uid)
                    node.numa_info.allocate(res_sets)
                    node.numa_allocations[task_uid] = res_sets

    def client(self):
        return None
