"""SchedulerCache: the host-side mirror of cluster state.

Mirrors /root/reference/pkg/scheduler/cache/cache.go:75-893 — jobs/nodes/
queues indexes fed by events, ``snapshot()`` producing a deep-copied
ClusterInfo per cycle, and Bind/Evict side effects executed through
swappable executors with a rate-limited resync queue on failure.

Differences by design: event ingestion is direct method calls (the in-process
ObjectStore pushes them; there is no client-go), and binds are synchronous by
default for determinism — an async mode mirrors the reference's
goroutine-per-bind with the same "skip nodes with in-flight binding tasks at
snapshot" guard (cache.go:822-827).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..api import (ClusterInfo, JobInfo, NamespaceCollection, NamespaceInfo,
                   NodeInfo, PodGroupPhase, QueueInfo, Resource, TaskInfo,
                   TaskStatus, allocated_status)
from .executors import (Binder, Evictor, FakeBinder, FakeEvictor,
                        StatusUpdater, VolumeBinder)


class RateLimitedQueue:
    """workqueue.RateLimitingInterface analogue (the errTasks queue,
    cache.go:115,777-799): per-item exponential backoff — the k8s
    ItemExponentialFailureRateLimiter (base * 2^failures, capped) — plus a
    per-item retry budget: once an item has failed ``max_retries`` times,
    add_rate_limited refuses it (returns False) so a permanently failing
    side effect cannot spin in the queue forever. The caller dead-letters
    refused items (SchedulerCache.dead_letter)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 10.0,
                 max_retries: Optional[int] = None,
                 time_fn=time.monotonic):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.max_retries = max_retries
        # injectable time source: the simulator (volcano_tpu/sim) pins this
        # to its virtual clock so retry backoff expires on deterministic
        # virtual cycles instead of whenever the host gets there
        self.time_fn = time_fn
        self._heap: List[Tuple[float, int, str, object]] = []
        self._failures: Dict[str, int] = {}
        self._seq = itertools.count()
        self._lock = threading.Lock()

    def add_rate_limited(self, key: str, item: object) -> bool:
        with self._lock:
            n = self._failures.get(key, 0)
            if self.max_retries is not None and n >= self.max_retries:
                # keep the failure count: a later add for the same key
                # (e.g. the scheduler re-placing the rolled-back task onto
                # the same broken path) is refused again instead of
                # restarting a full retry burst — only forget() (redrive)
                # grants a fresh budget
                return False
            self._failures[key] = n + 1
            delay = min(self.base_delay * (2 ** n), self.max_delay)
            heapq.heappush(self._heap,
                           (self.time_fn() + delay, next(self._seq), key,
                            item))
            return True

    def failures(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    def forget(self, key: str) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def pop_ready(self) -> List[Tuple[str, object]]:
        now = self.time_fn()
        out = []
        with self._lock:
            while self._heap and self._heap[0][0] <= now:
                _, _, key, item = heapq.heappop(self._heap)
                out.append((key, item))
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)


# A bind/evict that fails this many RETRIES (after the initial attempt)
# dead-letters instead of re-queueing — with the default 5ms base delay
# the budget spans ~20s of exponential backoff, past any transient
# apiserver hiccup the resync queue is meant to absorb.
DEFAULT_RESYNC_MAX_RETRIES = 12


class SchedulerCache:
    def __init__(self, binder: Optional[Binder] = None,
                 evictor: Optional[Evictor] = None,
                 status_updater: Optional[StatusUpdater] = None,
                 volume_binder: Optional[VolumeBinder] = None,
                 default_queue: str = "default",
                 resync_max_retries: Optional[int]
                 = DEFAULT_RESYNC_MAX_RETRIES):
        self._lock = threading.RLock()
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespace_collections: Dict[str, NamespaceCollection] = {}
        self.binder = binder or FakeBinder()
        self.evictor = evictor or FakeEvictor()
        self.status_updater = status_updater or StatusUpdater()
        self.volume_binder = volume_binder or VolumeBinder()
        self.default_queue = default_queue
        if default_queue:
            self.queues.setdefault(default_queue, QueueInfo(name=default_queue))
        self.err_tasks: List[TaskInfo] = []       # failure record (tests)
        self.resync_queue = RateLimitedQueue(     # errTasks (cache.go:777-799)
            max_retries=resync_max_retries)
        # side effects that exhausted their retry budget, key -> (op, task).
        # Never retried automatically (the failure is not transient by
        # definition of the budget); ops inspect it and redrive_dead_letter
        # re-queues after the underlying fault is fixed.
        self.dead_letter: Dict[str, Tuple[str, TaskInfo]] = {}
        self.binding_tasks: Dict[str, str] = {}   # task uid -> node, in flight

    # -- ingestion (event_handlers.go analogues) ----------------------------

    def add_node(self, node: NodeInfo) -> None:
        with self._lock:
            self.nodes[node.name] = node

    def remove_node(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)

    def add_queue(self, queue: QueueInfo) -> None:
        with self._lock:
            self.queues[queue.uid] = queue

    def remove_queue(self, uid: str) -> None:
        with self._lock:
            self.queues.pop(uid, None)

    def add_job(self, job: JobInfo) -> None:
        with self._lock:
            if job.schedule_start_timestamp is None:
                job.schedule_start_timestamp = time.time()
            self.jobs[job.uid] = job

    def remove_job(self, uid: str) -> None:
        with self._lock:
            job = self.jobs.pop(uid, None)
            if job is not None:
                for task_uid in job.tasks:
                    self._drop_retry_state(task_uid)

    def get_or_create_job(self, uid: str, **kwargs) -> JobInfo:
        with self._lock:
            if uid not in self.jobs:
                self.jobs[uid] = JobInfo(uid=uid, **kwargs)
            return self.jobs[uid]

    def add_task(self, task: TaskInfo) -> None:
        """Pod added: index into its job and, if placed, its node
        (event_handlers.go addTask)."""
        with self._lock:
            job = self.get_or_create_job(task.job)
            job.add_task_info(task)
            if task.node_name and task.node_name in self.nodes:
                self.nodes[task.node_name].add_task(task)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        with self._lock:
            job = self.jobs.get(task.job)
            if job is None:
                return
            job.update_task_status(job.tasks[task.uid], status)
            if task.node_name and task.node_name in self.nodes:
                self.nodes[task.node_name].update_task(job.tasks[task.uid])

    def delete_task(self, task: TaskInfo) -> None:
        with self._lock:
            job = self.jobs.get(task.job)
            if job is not None:
                job.delete_task_info(task)
            if task.node_name and task.node_name in self.nodes:
                node = self.nodes[task.node_name]
                node.remove_task(task)
                self._release_numa(node, task.uid)
            self._drop_retry_state(task.uid)

    @staticmethod
    def _release_numa(node, task_uid: str) -> None:
        """Return the task's committed cpusets to the node topology — the
        in-process equivalent of the node agent refreshing the Numatopology
        CR after a pod dies (numa_info.go Release)."""
        sets = node.numa_allocations.pop(task_uid, None)
        if sets and node.numa_info is not None:
            node.numa_info.release(sets)

    # -- snapshot (cache.go:801-893) ----------------------------------------

    def add_resource_quota(self, quota) -> None:
        """AddResourceQuota (event_handlers.go:740-770): track the
        volcano.sh/namespace.weight key of spec.hard per namespace; the
        snapshot's NamespaceInfo takes the max across the namespace's
        quotas (namespace_info.go quotaItem semantics)."""
        ns = quota.metadata.namespace
        col = self.namespace_collections.setdefault(
            ns, NamespaceCollection(ns))
        weight = int(quota.hard.get(NamespaceCollection.WEIGHT_KEY, 0))
        col.update(quota.metadata.name, weight)

    def delete_resource_quota(self, quota) -> None:
        """DeleteResourceQuota (event_handlers.go:790-812)."""
        col = self.namespace_collections.get(quota.metadata.namespace)
        if col is not None:
            col.delete(quota.metadata.name)

    def snapshot(self) -> ClusterInfo:
        with self._lock:
            ci = ClusterInfo()
            inflight_nodes = set(self.binding_tasks.values())
            for name, node in self.nodes.items():
                if not node.ready:
                    continue
                # nodes with in-flight async binds are skipped to avoid
                # double-booking (cache.go:822-827)
                if name in inflight_nodes:
                    continue
                ci.nodes[name] = node.clone()
            for uid, q in self.queues.items():
                ci.queues[uid] = q.clone()
            for uid, job in self.jobs.items():
                if job.podgroup is None:
                    continue
                ci.jobs[uid] = job.clone()
            for name, col in self.namespace_collections.items():
                ci.namespaces[name] = col.snapshot()
            for job in ci.jobs.values():
                ci.namespaces.setdefault(job.namespace,
                                         NamespaceInfo(job.namespace))
            ci.node_list = list(ci.nodes.values())
            return ci

    # -- side effects (cache.go:549-666) ------------------------------------

    def bind(self, task: TaskInfo) -> None:
        """Mark the optimistic Binding state FIRST, then execute the bind
        through the Binder (the reference's AddBindingTask-then-async-Bind
        order, cache.go:602-666) — so the watch event that flips the pod to
        Running lands after, never before, the cache's own update."""
        newly_placed = False
        prev_status = None
        with self._lock:
            job = self.jobs.get(task.job)
            if job is not None and task.uid in job.tasks:
                cached = job.tasks[task.uid]
                prev_status = cached.status
                prev_node = cached.node_name
                if not prev_node:
                    newly_placed = True
                    cached.node_name = task.node_name
                    job.update_task_status(cached, TaskStatus.BOUND)
                    if task.node_name in self.nodes:
                        self.nodes[task.node_name].add_task(cached)
                else:
                    job.update_task_status(cached, TaskStatus.BOUND)
                    if prev_node in self.nodes:
                        self.nodes[prev_node].update_task(cached)
        try:
            self._bind_volumes(task)
            self.binder.bind(task, task.node_name)
        except Exception:
            # roll back exactly what the optimistic phase did
            with self._lock:
                job = self.jobs.get(task.job)
                if job is not None and task.uid in job.tasks:
                    cached = job.tasks[task.uid]
                    if newly_placed:
                        if cached.node_name in self.nodes:
                            self.nodes[cached.node_name].remove_task(cached)
                        job.update_task_status(cached, TaskStatus.PENDING)
                        cached.node_name = ""
                    elif prev_status is not None:
                        job.update_task_status(cached, prev_status)
                        if cached.node_name in self.nodes:
                            self.nodes[cached.node_name].update_task(cached)
                self.err_tasks.append(task)
            self.resync_task(task)

    def bind_batch(self, tasks) -> None:
        """Batched bind: one optimistic pass with per-node aggregated
        accounting, then the Binder calls, with per-task rollback on binder
        failure. Semantics match bind() per task; the aggregation removes the
        per-task Resource arithmetic that dominates a 10k-bind cycle."""
        from ..api import Resource
        agg = {}
        placed = []
        with self._lock:
            for task in tasks:
                job = self.jobs.get(task.job)
                if job is None or task.uid not in job.tasks:
                    continue
                cached = job.tasks[task.uid]
                if cached.node_name:
                    # re-bind of an already-placed task: rare; full path
                    job.update_task_status(cached, TaskStatus.BOUND)
                    if cached.node_name in self.nodes:
                        self.nodes[cached.node_name].update_task(cached)
                    placed.append((task, False))
                    continue
                cached.node_name = task.node_name
                job.update_task_status(cached, TaskStatus.BOUND)
                node = self.nodes.get(task.node_name)
                if node is not None:
                    if node.gpu_devices:
                        node.add_task(cached)        # full path: card packing
                    else:
                        # the clone keeps status BOUND so a later
                        # remove_task/update_task re-accounts correctly
                        node.tasks[cached.uid] = cached.shallow_clone()
                        agg.setdefault(task.node_name, Resource()).add(
                            cached.resreq)
                placed.append((task, True))
            for name, r in agg.items():
                node = self.nodes[name]
                node.idle.sub(r)
                node.used.add(r)
        for task, newly in placed:
            try:
                self._bind_volumes(task)
                self.binder.bind(task, task.node_name)
            except Exception:
                with self._lock:
                    job = self.jobs.get(task.job)
                    if job is not None and task.uid in job.tasks:
                        cached = job.tasks[task.uid]
                        if newly:
                            node = self.nodes.get(cached.node_name)
                            if node is not None:
                                node.remove_task(cached)
                            job.update_task_status(cached, TaskStatus.PENDING)
                            cached.node_name = ""
                    self.err_tasks.append(task)
                self.resync_task(task)

    def _bind_volumes(self, task: TaskInfo) -> None:
        """Volume allocate+bind at pod-bind time. The reference splits this
        across Statement.Allocate (assume) and Commit (bind,
        statement.go:230-292); in-process PVC binding carries no node
        constraint, so the whole sequence runs here with identical end
        state: the pod's claims go Bound when the pod binds."""
        volumes = self.volume_binder.get_pod_volumes(
            task, self.nodes.get(task.node_name))
        self.volume_binder.allocate_volumes(task, task.node_name, volumes)
        self.volume_binder.bind_volumes(task, volumes)

    def evict(self, task: TaskInfo, reason: str) -> None:
        """Execute eviction: pod condition + delete (cache.go:549-599)."""
        try:
            self.evictor.evict(task, reason)
        except Exception:
            with self._lock:
                self.err_tasks.append(task)
            self.resync_task(task, op="evict")
            return
        with self._lock:
            job = self.jobs.get(task.job)
            if job is not None and task.uid in job.tasks:
                job.update_task_status(job.tasks[task.uid], TaskStatus.RELEASING)
                if task.node_name in self.nodes:
                    self.nodes[task.node_name].update_task(job.tasks[task.uid])

    def resync_task(self, task: TaskInfo, op: str = "bind") -> None:
        """Queue a failed side effect for rate-limited retry
        (cache.go:777-799 resyncTask -> errTasks.AddRateLimited); a task
        past its retry budget moves to the dead-letter set instead."""
        self._resync_or_dead_letter(f"{op}/{task.uid}", op, task)

    def _resync_or_dead_letter(self, key: str, op: str,
                               task: TaskInfo) -> None:
        if not self.resync_queue.add_rate_limited(key, (op, task)):
            with self._lock:
                fresh = key not in self.dead_letter
                self.dead_letter[key] = (op, task)
            if fresh:
                # count logical events, not cycles: a PENDING-rolled-back
                # task re-placed every cycle keeps hitting the refused
                # budget, but it is still ONE dead-lettered side effect
                from .. import metrics
                metrics.register_dead_letter(op)

    def _drop_retry_state(self, task_uid: str) -> None:
        """A deleted task's queued retries and dead-letter entry are moot
        — purge them so dead_letter cannot pin TaskInfo objects (and their
        job/node references) forever. Caller holds self._lock."""
        for key in (f"bind/{task_uid}", f"evict/{task_uid}"):
            self.dead_letter.pop(key, None)
            self.resync_queue.forget(key)

    def redrive_dead_letter(self) -> int:
        """Re-queue every dead-lettered side effect with a fresh retry
        budget — the operator affordance for after the underlying fault
        (bad node, apiserver outage) is fixed. Returns how many moved."""
        with self._lock:
            items = list(self.dead_letter.items())
            self.dead_letter.clear()
        for key, (op, task) in items:
            self.resync_queue.forget(key)
            self.resync_queue.add_rate_limited(key, (op, task))
        return len(items)

    def _resync_stale(self, op: str, task: TaskInfo) -> bool:
        """A queued retry is STALE when the cluster moved on while it sat
        in backoff: the task was deleted, or (bind) a later scheduling
        cycle already re-placed the rolled-back task — retrying then would
        bind the pod a second time (possibly onto a different node) and
        double-count it on two nodes' accounting. Any allocated status
        counts as re-placed, not just BOUND: a re-bound task ack'd to
        RUNNING by the watch stream between cycles is exactly as final
        (caught by the sim's chaos replay, which acks binds the way a
        live cluster does)."""
        with self._lock:
            job = self.jobs.get(task.job)
            cached = job.tasks.get(task.uid) if job is not None else None
            if cached is None:
                return True
            if op == "bind" and (allocated_status(cached.status)
                                 or (cached.node_name
                                     and cached.node_name != task.node_name)):
                return True
        return False

    def process_resync_tasks(self) -> int:
        """Retry side effects whose backoff expired (processResyncTask,
        cache.go:781-799) — the scheduler shell calls this every cycle.
        Returns the number of successful retries. Stale entries (see
        _resync_stale) are dropped, not retried."""
        done = 0
        for key, (op, task) in self.resync_queue.pop_ready():
            if self._resync_stale(op, task):
                self.resync_queue.forget(key)
                continue
            try:
                if op == "bind":
                    self._bind_volumes(task)
                    self.binder.bind(task, task.node_name)
                    with self._lock:
                        job = self.jobs.get(task.job)
                        if job is not None and task.uid in job.tasks:
                            cached = job.tasks[task.uid]
                            cached.node_name = task.node_name
                            job.update_task_status(cached, TaskStatus.BOUND)
                            node = self.nodes.get(task.node_name)
                            if node is not None \
                                    and cached.uid not in node.tasks:
                                node.add_task(cached)
                else:
                    self.evictor.evict(task, "resync")
                    with self._lock:
                        job = self.jobs.get(task.job)
                        if job is not None and task.uid in job.tasks:
                            job.update_task_status(job.tasks[task.uid],
                                                   TaskStatus.RELEASING)
                self.resync_queue.forget(key)
                done += 1
            except Exception:
                self._resync_or_dead_letter(key, op, task)
        return done

    FORWARD_CLUSTER_KEY = "volcano.sh/forward-cluster"

    def bind_pod_group(self, job: JobInfo, cluster: str) -> None:
        """Multi-cluster forwarding (podgroupBinder, cache.go:275-312):
        annotate every task's pod and the PodGroup with the silo cluster so
        the target cluster's control plane takes over the gang."""
        for task in job.tasks.values():
            task.annotations[self.FORWARD_CLUSTER_KEY] = cluster
            pod = getattr(task, "pod", None)
            if pod is not None:
                pod.metadata.annotations[self.FORWARD_CLUSTER_KEY] = cluster
        job.podgroup.annotations[self.FORWARD_CLUSTER_KEY] = cluster
        self.status_updater.update_pod_group(job)

    def update_job_status(self, job: JobInfo) -> None:
        self.status_updater.update_pod_group(job)
        with self._lock:
            cached = self.jobs.get(job.uid)
            if cached is not None:
                cached.podgroup = job.podgroup

    def update_scheduler_numa_info(self, numa_sets) -> None:
        """Commit cpuset assignments chosen by the numaaware plugin back to
        the live node topology (cache interface UpdateSchedulerNumaInfo;
        session.go:435-437). ``numa_sets`` is {node_name: {task_uid:
        ResNumaSets}}; per-task records let delete_task release them
        (re-committing a uid first releases its previous assignment, so the
        writeback is idempotent across sessions)."""
        with self._lock:
            for node_name, per_task in numa_sets.items():
                node = self.nodes.get(node_name)
                if node is None or node.numa_info is None:
                    continue
                for task_uid, res_sets in per_task.items():
                    self._release_numa(node, task_uid)
                    node.numa_info.allocate(res_sets)
                    node.numa_allocations[task_uid] = res_sets

    def client(self):
        return None
