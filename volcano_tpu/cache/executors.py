"""Side-effect executors: Binder/Evictor/StatusUpdater interfaces, default
in-process implementations, and the recording fakes used by action-level
tests (mirrors /root/reference/pkg/scheduler/cache/cache.go:119-312 and the
fakes in pkg/scheduler/util/test_utils.go:96-178).

Fencing (docs/robustness.md HA section): ``FencingAuthority`` is the
cluster-side epoch watermark — the highest lease fencing epoch any
acquisition has published. ``FencedBinder``/``FencedEvictor`` wrap an
executor chain and reject any operation whose caller's epoch is below
the watermark (``FencedError``), which is what makes a paused/partitioned
ex-leader's late bind physically unable to reach the cluster: split-brain
safety by construction, not by lease timing."""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional

from ..api import TaskInfo


class FencedError(RuntimeError):
    """An executor operation carried a fencing epoch below the highest
    the cluster has observed — the caller is a deposed leader. A plain
    Exception on purpose: the cache funnel's normal rollback path undoes
    the optimistic state, exactly as for any other executor failure."""

    def __init__(self, op: str, epoch: int, current: int):
        super().__init__(
            f"fenced: {op} carries stale fencing epoch {epoch} "
            f"(cluster has observed {current}); a deposed leader may "
            f"not mutate cluster state")
        self.op = op
        self.epoch = epoch
        self.current = current


class FencingAuthority:
    """The cluster's monotonic fencing-epoch watermark (in a real
    deployment this is the Lease object itself, enforced at admission;
    in-process and in the sim it is this shared object). Electors call
    ``advance`` on every successful lease write; executor gates call
    ``check`` before every side effect."""

    def __init__(self):
        self._lock = threading.Lock()
        self._current = 0
        self.rejections = 0

    def advance(self, epoch: int) -> None:
        with self._lock:
            if epoch > self._current:
                self._current = epoch

    def current(self) -> int:
        with self._lock:
            return self._current

    def check(self, op: str, epoch: int) -> None:
        """Admit an operation stamped with ``epoch``: raises FencedError
        when it is stale, advances the watermark otherwise (an op from a
        newer leader than any lease write we have seen proves that
        leadership exists)."""
        with self._lock:
            if epoch < self._current:
                self.rejections += 1
                current = self._current
            else:
                self._current = max(self._current, epoch)
                return
        from .. import metrics
        metrics.register_fencing_rejection(op)
        raise FencedError(op, epoch, current)


class FencingRegistry:
    """Per-partition fencing authorities for the federated control plane
    (docs/federation.md): epochs are namespaced by partition id — each
    partition's Lease mints its own monotonic epoch sequence, and each
    partition's executor gate checks against its OWN watermark.
    Authorities are created on demand and shared by reference, so the
    reserve ledger and the per-partition electors see one truth."""

    def __init__(self):
        self._lock = threading.Lock()
        self._authorities: Dict[int, FencingAuthority] = {}

    def authority(self, pid: int) -> FencingAuthority:
        with self._lock:
            auth = self._authorities.get(pid)
            if auth is None:
                auth = self._authorities[pid] = FencingAuthority()
            return auth

    def current(self, pid: int) -> int:
        return self.authority(pid).current()

    def rejections(self) -> int:
        """Total stale-epoch rejections across every partition."""
        with self._lock:
            return sum(a.rejections for a in self._authorities.values())


class Binder:
    def bind(self, task: TaskInfo, hostname: str) -> None:
        raise NotImplementedError


class Evictor:
    def evict(self, task: TaskInfo, reason: str) -> None:
        raise NotImplementedError


class FencedBinder(Binder):
    """Binder gate: admits each bind through the authority at the
    caller's current epoch (``epoch_fn`` — the replica's elector epoch,
    0 for standalone schedulers, which the authority only rejects once a
    real leadership exists)."""

    def __init__(self, inner: Binder, epoch_fn: Callable[[], int],
                 authority: FencingAuthority):
        self.inner = inner
        self.epoch_fn = epoch_fn
        self.authority = authority

    def bind(self, task: TaskInfo, hostname: str) -> None:
        self.authority.check("bind", self.epoch_fn())
        self.inner.bind(task, hostname)


class FencedEvictor(Evictor):
    """Evictor twin of FencedBinder."""

    def __init__(self, inner: Evictor, epoch_fn: Callable[[], int],
                 authority: FencingAuthority):
        self.inner = inner
        self.epoch_fn = epoch_fn
        self.authority = authority

    def evict(self, task: TaskInfo, reason: str) -> None:
        self.authority.check("evict", self.epoch_fn())
        self.inner.evict(task, reason)


class StatusUpdater:
    def update_pod_condition(self, task: TaskInfo, condition: dict) -> None:
        pass

    def update_pod_group(self, job) -> None:
        pass


class VolumeBinder:
    def get_pod_volumes(self, task: TaskInfo, node) -> Optional[object]:
        return None

    def allocate_volumes(self, task: TaskInfo, hostname: str, volumes) -> None:
        pass

    def bind_volumes(self, task: TaskInfo, volumes) -> None:
        pass


class FakeBinder(Binder):
    """Records ns/name -> node (test_utils.go:96-110)."""

    def __init__(self):
        self.binds: Dict[str, str] = {}
        self._lock = threading.Lock()

    def bind(self, task: TaskInfo, hostname: str) -> None:
        with self._lock:
            self.binds[task.key()] = hostname


class FakeEvictor(Evictor):
    """Records evicted task keys (test_utils.go:112-140)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.evicts: List[str] = []
        self.channel: "queue.Queue[str]" = queue.Queue()

    def evict(self, task: TaskInfo, reason: str) -> None:
        with self._lock:
            self.evicts.append(task.key())
        self.channel.put(task.key())


class SequenceBinder(FakeBinder):
    """FakeBinder that also records the ORDER of successful binds as
    (task uid, node) pairs — the simulator's determinism witness
    (volcano_tpu/sim/runner.py): two replays of the same trace+seed must
    produce identical sequences, and the sim's post-cycle feedback walks
    the tail of this list to ack binds into RUNNING state."""

    def __init__(self):
        super().__init__()
        self.sequence: List[tuple] = []

    def bind(self, task: TaskInfo, hostname: str) -> None:
        super().bind(task, hostname)
        with self._lock:
            self.sequence.append((task.uid, hostname))


class SequenceEvictor(FakeEvictor):
    """FakeEvictor recording eviction order by task uid (see
    SequenceBinder)."""

    def __init__(self):
        super().__init__()
        self.sequence: List[str] = []

    def evict(self, task: TaskInfo, reason: str) -> None:
        super().evict(task, reason)
        with self._lock:
            self.sequence.append(task.uid)


class FakeStatusUpdater(StatusUpdater):
    pass


class FakeVolumeBinder(VolumeBinder):
    pass


class StoreBinder(Binder):
    """Binder that writes the bind back into an ObjectStore (the in-process
    analogue of POSTing pods/<p>/binding to the API server, cache.go:124-138)."""

    def __init__(self, store):
        self.store = store

    def bind(self, task: TaskInfo, hostname: str) -> None:
        self.store.bind_pod(task.namespace, task.name, hostname)


class StoreEvictor(Evictor):
    def __init__(self, store):
        self.store = store

    def evict(self, task: TaskInfo, reason: str) -> None:
        self.store.evict_pod(task.namespace, task.name, reason)


class StoreVolumeBinder(VolumeBinder):
    """Volume binder over store PVC objects — the in-process analogue of
    the k8s SchedulerVolumeBinder wrap (cache.go:241-273): GetPodVolumes
    finds the pod's unbound claims, AllocateVolumes assumes them onto the
    host (task.volume_ready mirrors the reference's VolumeReady), and
    BindVolumes commits Pending -> Bound."""

    def __init__(self, store):
        self.store = store

    def _claims(self, task: TaskInfo):
        pod = getattr(task, "pod", None)
        template = getattr(pod, "template", None)
        for v in getattr(template, "volumes", None) or []:
            name = v.get("claimName")
            if not name:
                continue
            pvc = self.store.get("PersistentVolumeClaim", task.namespace,
                                 name)
            if pvc is not None:
                yield pvc

    def get_pod_volumes(self, task: TaskInfo, node) -> Optional[list]:
        unbound = [p for p in self._claims(task)
                   if p.status.phase != "Bound"]
        return unbound or None

    def allocate_volumes(self, task: TaskInfo, hostname: str, volumes) -> None:
        for pvc in volumes or []:
            pvc.status.node = hostname
        task.volume_ready = not volumes

    def bind_volumes(self, task: TaskInfo, volumes) -> None:
        if task.volume_ready:
            return
        for pvc in volumes or []:
            pvc.status.phase = "Bound"
            pvc.status.node = task.node_name
            self.store.update_status(pvc)


class StoreStatusUpdater(StatusUpdater):
    """Writes PodGroup status back to the store (the jobUpdater's
    UpdatePodGroup PUT, job_updater.go:95-108)."""

    def __init__(self, store):
        self.store = store

    def update_pod_group(self, job) -> None:
        pg = self.store.get("PodGroup", job.namespace, job.podgroup.name)
        if pg is None:
            return
        pg.status.phase = job.podgroup.phase
        pg.status.conditions = list(job.podgroup.conditions)
        # forward-cluster and similar scheduler-written annotations
        # propagate with the status (podgroupBinder, cache.go:275-312)
        for k, v in job.podgroup.annotations.items():
            pg.metadata.annotations.setdefault(k, v)
        # FailedScheduling events for unschedulable gangs (the cache's
        # EventRecorder emissions, cache.go:597-641)
        if hasattr(self.store, "record_event"):
            for c in pg.status.conditions:
                if c.get("type") == "Unschedulable" \
                        and c.get("status") == "True":
                    self.store.record_event(
                        "PodGroup", job.namespace, job.podgroup.name,
                        "Warning", "FailedScheduling",
                        c.get("message", ""))
                    break
        self.store.update_status(pg)
