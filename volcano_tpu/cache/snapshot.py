"""Snapshot → dense tensor marshaling: the Go↔sidecar boundary of the
north-star design, collapsed into one process.

The reference's hot loops iterate (pending task × node) pairs through plugin
callbacks (util.PredicateNodes / PrioritizeNodes,
/root/reference/pkg/scheduler/util/scheduler_helper.go:71-192). Here the
session is materialized once per action into:

- per-node state arrays f32[N,R] (idle/used/releasing/pipelined/allocatable),
- per-task request rows f32[R],
- a static feasibility mask bool[T,N] assembled from plugin feasibility fns
  (node selectors, taints, unschedulable, affinity — everything that does not
  depend on mutable node usage),
- a static score matrix f32[T,N] from plugin static-score fns,
- ScoreWeights for the in-kernel dynamic scorers.

Buffers are NumPy until the final device_put so marshaling stays cheap.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import NodeInfo, Resource, ResourceNames, TaskInfo
from ..ops.place import NodeState
from ..ops.scores import ScoreWeights

BIG_MAX_TASKS = 1 << 30


class NodeTensors:
    """Dense node-state arrays, index-aligned with ``names`` order."""

    def __init__(self, nodes: Sequence[NodeInfo], rnames: ResourceNames):
        self.rnames = rnames
        self.names: List[str] = [n.name for n in nodes]
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}
        N, R = len(nodes), len(rnames)
        self.idle = np.zeros((N, R), np.float32)
        self.used = np.zeros((N, R), np.float32)
        self.releasing = np.zeros((N, R), np.float32)
        self.pipelined = np.zeros((N, R), np.float32)
        self.allocatable = np.zeros((N, R), np.float32)
        self.max_tasks = np.zeros(N, np.int32)
        self.ntasks = np.zeros(N, np.int32)
        for i, n in enumerate(nodes):
            self.idle[i] = n.idle.to_vector(rnames)
            self.used[i] = n.used.to_vector(rnames)
            self.releasing[i] = n.releasing.to_vector(rnames)
            self.pipelined[i] = n.pipelined.to_vector(rnames)
            self.allocatable[i] = n.allocatable.to_vector(rnames)
            self.max_tasks[i] = n.max_task_num if n.max_task_num > 0 else BIG_MAX_TASKS
            self.ntasks[i] = len(n.tasks)

    def node_state(self) -> NodeState:
        import jax.numpy as jnp
        return NodeState(
            idle=jnp.asarray(self.idle),
            future_idle=jnp.asarray(self.idle + self.releasing - self.pipelined),
            used=jnp.asarray(self.used),
            ntasks=jnp.asarray(self.ntasks))


def discover_resource_names(nodes: Sequence[NodeInfo],
                            tasks: Sequence[TaskInfo]) -> ResourceNames:
    rs: List[Resource] = [n.allocatable for n in nodes]
    rs += [t.resreq for t in tasks]
    return ResourceNames.discover(rs)


def task_requests(tasks: Sequence[TaskInfo], rnames: ResourceNames) -> np.ndarray:
    T, R = len(tasks), len(rnames)
    req = np.zeros((T, R), np.float32)
    for i, t in enumerate(tasks):
        req[i] = t.init_resreq.to_vector(rnames)
    return req


def assemble_feasibility(ssn, tasks: Sequence[TaskInfo],
                         node_t: NodeTensors) -> Optional[np.ndarray]:
    """AND of all plugin feasibility contributions; base mask excludes
    not-ready nodes (snapshot already dropped them) — plugins add selectors/
    taints/affinity (predicates plugin) and revocable-zone windows (tdm).
    Returns None when every plugin abstained (mask would be all-true) so
    callers can skip the [T,N] transfer entirely."""
    mask = None
    for fn in ssn.feasibility_fns.values():
        m = fn(ssn, tasks, node_t)
        if m is None:
            continue
        mask = m if mask is None else (mask & m)
    return mask


def assemble_static_score(ssn, tasks: Sequence[TaskInfo],
                          node_t: NodeTensors) -> Optional[np.ndarray]:
    """Sum of static score matrices; None when every plugin abstained (a
    constant-zero matrix) so callers can skip the [T,N] transfer."""
    score = None
    for fn in ssn.static_score_fns.values():
        s = fn(ssn, tasks, node_t)
        if s is None:
            continue
        s = s.astype(np.float32)
        score = s if score is None else (score + s)
    return score


def assemble_weights(ssn, rnames: ResourceNames) -> ScoreWeights:
    """Merge plugin weight contributions into one ScoreWeights. Plugins set
    e.g. {'binpack_weight': 1, 'binpack_res': {...}} or {'least_req_weight': 1}
    via ssn.set_dynamic_score_weights. binpack_res stays numpy — jit converts
    at dispatch, and host callers avoid a device->host RTT."""
    binpack_res = np.zeros(len(rnames), np.float32)
    vals = {"binpack_weight": 0.0, "least_req_weight": 0.0,
            "most_req_weight": 0.0, "balanced_weight": 0.0}
    for w in ssn.dynamic_score_weights.values():
        for k in vals:
            vals[k] += float(w.get(k, 0.0))
        for rname, rw in (w.get("binpack_res") or {}).items():
            if rname in rnames.index:
                binpack_res[rnames.index[rname]] += float(rw)
    return ScoreWeights(binpack_weight=vals["binpack_weight"],
                        binpack_res=binpack_res,
                        least_req_weight=vals["least_req_weight"],
                        most_req_weight=vals["most_req_weight"],
                        balanced_weight=vals["balanced_weight"])
