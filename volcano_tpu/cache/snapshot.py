"""Snapshot → dense tensor marshaling: the Go↔sidecar boundary of the
north-star design, collapsed into one process.

The reference's hot loops iterate (pending task × node) pairs through plugin
callbacks (util.PredicateNodes / PrioritizeNodes,
/root/reference/pkg/scheduler/util/scheduler_helper.go:71-192). Here the
session is materialized once per action into:

- per-node state arrays f32[N,R] (idle/used/releasing/pipelined/allocatable),
- per-task request rows f32[R],
- a static feasibility mask bool[T,N] assembled from plugin feasibility fns
  (node selectors, taints, unschedulable, affinity — everything that does not
  depend on mutable node usage),
- a static score matrix f32[T,N] from plugin static-score fns,
- ScoreWeights for the in-kernel dynamic scorers.

Buffers are NumPy until the final device_put so marshaling stays cheap.
"""

from __future__ import annotations

import heapq
import time
import zlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..api import NodeInfo, Resource, ResourceNames, TaskInfo
from ..ops.place import NodeState
from ..ops.scores import ScoreWeights

BIG_MAX_TASKS = 1 << 30


def zone_code(zone: str) -> int:
    """Stable i32 code for a topology-zone name (0 = unzoned). The
    interconnect-distance matrix the topology term consumes is
    block-constant over zones, so it factors into this per-node axis —
    the only shape the row-wise dirty-set/scatter contract below can
    carry. crc32 is content-addressed (no per-process interning table),
    so codes survive restarts and row churn; the kernel only ever
    compares codes for equality, never orders them."""
    if not zone:
        return 0
    return (zlib.crc32(zone.encode("utf-8")) & 0x7FFFFFFF) or 1


class NodeTensors:
    """Dense node-state arrays, index-aligned with ``names`` order."""

    def __init__(self, nodes: Sequence[NodeInfo], rnames: ResourceNames):
        # NodeTensors is built per solve from the open session and dropped
        # with it; only PersistentNodeTensors (below) outlives cycles, and
        # it stores value copies guarded by the session epoch + _touched
        # witness — hence the VT014 waivers:
        # vlint: disable=VT014 -- per-solve object, dies with the session
        self.rnames = rnames
        # vlint: disable=VT014 -- per-solve object, dies with the session
        self.names: List[str] = [n.name for n in nodes]
        # vlint: disable=VT014 -- per-solve object, dies with the session
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}
        N, R = len(nodes), len(rnames)
        self.idle = np.zeros((N, R), np.float32)
        self.used = np.zeros((N, R), np.float32)
        self.releasing = np.zeros((N, R), np.float32)
        self.pipelined = np.zeros((N, R), np.float32)
        self.allocatable = np.zeros((N, R), np.float32)
        self.max_tasks = np.zeros(N, np.int32)
        self.ntasks = np.zeros(N, np.int32)
        self.zone_code = np.zeros(N, np.int32)
        for i, n in enumerate(nodes):
            self.idle[i] = n.idle.to_vector(rnames)
            self.used[i] = n.used.to_vector(rnames)
            self.releasing[i] = n.releasing.to_vector(rnames)
            self.pipelined[i] = n.pipelined.to_vector(rnames)
            self.allocatable[i] = n.allocatable.to_vector(rnames)
            self.max_tasks[i] = n.max_task_num if n.max_task_num > 0 else BIG_MAX_TASKS
            self.ntasks[i] = len(n.tasks)
            self.zone_code[i] = zone_code(getattr(n, "topology_zone", ""))

    def node_state(self) -> NodeState:
        import jax.numpy as jnp
        return NodeState(
            idle=jnp.asarray(self.idle),
            future_idle=jnp.asarray(self.idle + self.releasing - self.pipelined),
            used=jnp.asarray(self.used),
            ntasks=jnp.asarray(self.ntasks))

    def device_allocatable(self):
        import jax.numpy as jnp
        return jnp.asarray(self.allocatable)

    def device_max_tasks(self):
        import jax.numpy as jnp
        return jnp.asarray(self.max_tasks)

    def device_zone_code(self):
        import jax.numpy as jnp
        return jnp.asarray(self.zone_code)


def sharded_node_layout(node_t, D: int):
    """Device-resident node tensors padded to a multiple of the mesh size
    ``D`` — the unified sharded solver's input contract (its node axis
    must split evenly across the shards). Padding happens ON DEVICE
    (``jnp.pad`` over the already-resident ``node_state()`` arrays): a
    host-side ``np.pad`` would force a full [N,R] re-upload every cycle
    and — worse — read the host mirrors instead of the pinned epoch the
    persistent tensor cache hands a speculative solve, going stale the
    moment cycle N's binds scatter-update the live epoch. Pad rows are
    zero-capacity (``max_tasks`` 0), so the kernels' ``ntasks <
    max_tasks`` predicate makes them unselectable — the same hole
    contract PersistentNodeTensors relies on for removed nodes.

    Mesh changes (a mid-cycle heal or a probe readmission,
    allocate._with_fallback/_probe_quarantined) re-run this at the new
    ``D``: the heal path retires the tensor epoch first
    (``invalidate_device_state``), so the next ``_node_tensors`` call
    rebuilds PersistentNodeTensors — a full re-upload through the same
    scatter path steady-state deltas use — and the re-pad here sizes the
    node axis for the surviving device count. The pad rows are decision
    inert at EVERY D (zero capacity), which is half of why the healed
    solve is byte-identical to the pre-fault one; the other half is the
    unified kernel's mesh-size invariance (ops/unified.py).
    Returns ``(NodeState, allocatable, max_tasks, n_pad)``."""
    import jax.numpy as jnp
    state = node_t.node_state()
    alloc = node_t.device_allocatable()
    maxt = node_t.device_max_tasks()
    n_pad = (-state.idle.shape[0]) % D
    if n_pad:
        state = NodeState(
            idle=jnp.pad(state.idle, ((0, n_pad), (0, 0))),
            future_idle=jnp.pad(state.future_idle, ((0, n_pad), (0, 0))),
            used=jnp.pad(state.used, ((0, n_pad), (0, 0))),
            ntasks=jnp.pad(state.ntasks, (0, n_pad)))
        alloc = jnp.pad(alloc, ((0, n_pad), (0, 0)))
        maxt = jnp.pad(maxt, (0, n_pad))
    return state, alloc, maxt, n_pad


def _delta_bucket(n: int) -> int:
    """Pad dirty-row scatter updates to power-of-two buckets so a churning
    dirty count does not mint a fresh XLA scatter shape every cycle
    (Scheduler.prewarm warms the ladder)."""
    b = 8
    while b < n:
        b *= 2
    return b


class TensorEpochView:
    """One PINNED epoch of the double-buffered device pair
    (docs/performance.md, pipelining). JAX arrays are immutable, so the
    A/B pair falls out of functional updates: ``pin_epoch`` freezes
    references to the CURRENT device arrays (epoch A) and every later
    scatter/rebuild publishes NEW arrays into the owner (epoch B) without
    disturbing A. The view also freezes the row maps and value-copies
    of the host mirrors, so an in-flight speculative solve keeps reading
    a stable snapshot while cycle N's binds scatter-update the live
    epoch. Duck-types the ``NodeTensors`` surface the solve consumes
    (names/index/host arrays/node_state/device_allocatable/
    device_max_tasks). Retire through ``PersistentNodeTensors
    .retire_epoch`` on commit or discard — the live-pin gauge
    (``volcano_tensor_epochs_live``) is how a leak shows up."""

    def __init__(self, owner: "PersistentNodeTensors", epoch: int,
                 device: dict, names: List[str], index: Dict[str, int],
                 rnames: ResourceNames, host: Dict[str, np.ndarray]):
        self._owner = owner
        self.epoch = epoch
        self._device = device
        self.names = names
        self.index = index
        self.rnames = rnames
        for f, arr in host.items():
            setattr(self, f, arr)
        self._node_state: Optional[NodeState] = None
        self.retired = False

    def node_state(self) -> NodeState:
        if self._node_state is None:
            from ..ops.place import make_node_state
            dev = self._device
            self._node_state = make_node_state(
                dev["idle"], dev["releasing"], dev["pipelined"],
                dev["used"], dev["ntasks"])
        return self._node_state

    def device_allocatable(self):
        return self._device["allocatable"]

    def device_max_tasks(self):
        return self._device["max_tasks"]

    def device_zone_code(self):
        return self._device["zone_code"]


class PersistentNodeTensors:
    """NodeTensors that survive across scheduling cycles.

    Host numpy mirrors stay authoritative and are updated row-wise from the
    dirty set; device copies are updated with padded scatter writes
    (``array.at[idx].set``) instead of re-uploading f32[N,R] from Python
    dicts every cycle. Node identity maps to a STABLE row index: removed
    nodes leave a neutralized hole (all-zero row, ``max_tasks`` 0 — the
    kernels' ``ntasks < max_tasks`` predicate makes a hole unselectable,
    the same contract the sharded engine's N-padding relies on) that a
    lowest-index free list hands to the next added node, so row order —
    and therefore argmax tie-breaking — survives node churn.

    Falls back to a full rebuild when the dirty ratio exceeds
    ``rebuild_ratio`` or the row count (shape bucket) changes; both are
    observable via ``volcano_snapshot_full_rebuilds_total{layer="tensor"}``.

    Duck-types ``NodeTensors`` (names/index/arrays/node_state) so every
    consumer of the per-cycle build works unchanged.

    Epoch pair (docs/performance.md pipelining): ``epoch`` counts device
    publishes (every scatter or full rebuild); ``pin_epoch`` hands an
    in-flight speculative solve a frozen ``TensorEpochView`` of the
    current epoch, and subsequent publishes leave the pinned arrays
    untouched (functional ``.at[].set`` allocates fresh buffers). The
    pin/retire protocol exists so epoch lifetime is explicit and
    observable, not implied by GC."""

    def __init__(self, rnames: ResourceNames, rebuild_ratio: float = 0.5):
        self.rnames = rnames
        self.rebuild_ratio = rebuild_ratio
        self.names: List[str] = []
        self.index: Dict[str, int] = {}
        self._free: List[int] = []           # heap of hole rows
        R = len(rnames)
        self.idle = np.zeros((0, R), np.float32)
        self.used = np.zeros((0, R), np.float32)
        self.releasing = np.zeros((0, R), np.float32)
        self.pipelined = np.zeros((0, R), np.float32)
        self.allocatable = np.zeros((0, R), np.float32)
        self.max_tasks = np.zeros(0, np.int32)
        self.ntasks = np.zeros(0, np.int32)
        self.zone_code = np.zeros(0, np.int32)
        self._device: Optional[dict] = None  # field -> jnp array
        self._node_state: Optional[NodeState] = None
        self.last_refresh: Dict[str, object] = {}
        # epoch-pair bookkeeping (publish/retire protocol)
        self.epoch = 0
        self.live_pins = 0

    _ROW_FIELDS = ("idle", "used", "releasing", "pipelined", "allocatable",
                   "max_tasks", "ntasks", "zone_code")

    def _write_row(self, i: int, node: NodeInfo) -> None:
        rn = self.rnames
        self.idle[i] = node.idle.to_vector(rn)
        self.used[i] = node.used.to_vector(rn)
        self.releasing[i] = node.releasing.to_vector(rn)
        self.pipelined[i] = node.pipelined.to_vector(rn)
        self.allocatable[i] = node.allocatable.to_vector(rn)
        self.max_tasks[i] = (node.max_task_num if node.max_task_num > 0
                             else BIG_MAX_TASKS)
        self.ntasks[i] = len(node.tasks)
        self.zone_code[i] = zone_code(getattr(node, "topology_zone", ""))

    def _clear_row(self, i: int) -> None:
        for f in ("idle", "used", "releasing", "pipelined", "allocatable"):
            getattr(self, f)[i] = 0.0
        self.max_tasks[i] = 0                # ntasks < max_tasks never holds
        self.ntasks[i] = 0
        self.zone_code[i] = 0

    def full_build(self, nodes: Dict[str, NodeInfo]) -> None:
        """Rebuild every row in snapshot order — byte-equal to a fresh
        ``NodeTensors(list(nodes.values()), rnames)``."""
        self.names = list(nodes)
        self.index = {n: i for i, n in enumerate(self.names)}
        self._free = []
        N, R = len(self.names), len(self.rnames)
        for f in ("idle", "used", "releasing", "pipelined", "allocatable"):
            setattr(self, f, np.zeros((N, R), np.float32))
        self.max_tasks = np.zeros(N, np.int32)
        self.ntasks = np.zeros(N, np.int32)
        self.zone_code = np.zeros(N, np.int32)
        for i, node in enumerate(nodes.values()):
            self._write_row(i, node)
        self._device = None
        self._node_state = None
        self.epoch += 1                      # publish: next upload is B

    def refresh(self, nodes: Dict[str, NodeInfo],
                changed: Set[str]) -> Dict[str, object]:
        """Apply one snapshot delta. ``nodes`` is the snapshot's node dict
        (ready nodes only); ``changed`` the names whose rows may differ.
        Returns the refresh stats dict ({"full": bool, "rows": int})."""
        t0 = time.perf_counter()
        removed = [n for n in self.index if n not in nodes]
        added = [n for n in nodes if n not in self.index]
        touch = [n for n in changed if n in self.index]
        delta = len(removed) + len(added) + len(touch)
        base = len(self.index)
        full = (base == 0
                or delta / base > self.rebuild_ratio
                or len(added) > len(removed) + len(self._free))
        if full:
            self.full_build(nodes)
            self.last_refresh = {"full": True, "rows": len(self.names),
                                 "host_s": time.perf_counter() - t0}
            return self.last_refresh
        rows: List[int] = []
        for name in removed:
            i = self.index.pop(name)
            self.names[i] = ""
            heapq.heappush(self._free, i)
            self._clear_row(i)
            rows.append(i)
        for name in added:
            i = heapq.heappop(self._free)
            self.index[name] = i
            self.names[i] = name
            self._write_row(i, nodes[name])
            rows.append(i)
        for name in touch:
            i = self.index[name]
            self._write_row(i, nodes[name])
            rows.append(i)
        host_s = time.perf_counter() - t0
        if rows:
            self._scatter_device(np.asarray(sorted(rows), np.int32))
        self.last_refresh = {"full": False, "rows": len(rows),
                             "host_s": host_s}
        return self.last_refresh

    # -- device residency ---------------------------------------------------

    def _scatter_device(self, rows: np.ndarray) -> None:
        if self._device is None:
            return                            # first node_state() uploads
        import jax.numpy as jnp
        # pad the row set to a pow2 bucket (repeating the last index —
        # duplicate scatter of identical values is deterministic) so the
        # per-cycle dirty count does not key fresh XLA scatter shapes
        pad = _delta_bucket(len(rows)) - len(rows)
        idx_np = np.pad(rows, (0, pad), mode="edge")
        idx = jnp.asarray(idx_np)
        dev = self._device
        for f in self._ROW_FIELDS:
            dev[f] = dev[f].at[idx].set(jnp.asarray(getattr(self, f)[idx_np]))
        self._node_state = None
        # publish: ``.at[].set`` allocated FRESH device arrays, so any
        # pinned TensorEpochView keeps reading the pre-scatter epoch
        self.epoch += 1

    def _ensure_device(self) -> dict:
        if self._device is None:
            import jax.numpy as jnp
            # jnp.array, NOT jnp.asarray: on the CPU backend asarray
            # may ZERO-COPY a 64-byte-aligned numpy buffer, silently
            # aliasing the "immutable" device array onto the host
            # mirror this class mutates in place every refresh — a
            # pinned TensorEpochView then reads post-pin state, and
            # whether it happens depends on where the allocator put
            # the mirror (an alignment-dependent flake). The forced
            # copy is one host memcpy per cold upload.
            self._device = {f: jnp.array(getattr(self, f))
                            for f in self._ROW_FIELDS}
            self._node_state = None
        return self._device

    def node_state(self) -> NodeState:
        if self._node_state is None:
            from ..ops.place import make_node_state
            dev = self._ensure_device()
            self._node_state = make_node_state(
                dev["idle"], dev["releasing"], dev["pipelined"],
                dev["used"], dev["ntasks"])
        return self._node_state

    def device_allocatable(self):
        return self._ensure_device()["allocatable"]

    def device_max_tasks(self):
        return self._ensure_device()["max_tasks"]

    # -- epoch pair (docs/performance.md pipelining) ------------------------

    _HOST_FIELDS = ("idle", "used", "releasing", "pipelined", "allocatable",
                    "max_tasks", "ntasks", "zone_code")

    def pin_epoch(self) -> TensorEpochView:
        """Freeze the CURRENT epoch for an in-flight speculative solve:
        device array references (immutable — later scatters publish new
        arrays), copies of the host mirrors, and the row maps. The caller
        MUST pair this with ``retire_epoch`` on commit or discard."""
        dev = dict(self._ensure_device())
        view = TensorEpochView(
            self, self.epoch, dev, list(self.names), dict(self.index),
            self.rnames,
            {f: getattr(self, f).copy() for f in self._HOST_FIELDS})
        self.live_pins += 1
        from .. import metrics
        metrics.set_tensor_epochs_live(self.live_pins)
        return view

    def retire_epoch(self, view: Optional[TensorEpochView]) -> None:
        """Release one pinned epoch (idempotent per view): drops the
        bookkeeping so the live-pin gauge stays honest; the arrays free
        whenever the last holder lets go."""
        if view is None or view.retired:
            return
        view.retired = True
        self.live_pins = max(self.live_pins - 1, 0)
        from .. import metrics
        metrics.set_tensor_epochs_live(self.live_pins)

    def prewarm_epoch_pair(self) -> None:
        """Pay the cold epoch-pair costs at startup instead of inside the
        first pipelined cycle (the 708ms-vs-470ms first-churn-cycle
        outlier): the initial device upload, the pinned view's host-mirror
        copies, and the pinned ``node_state`` future-idle program all
        allocate here, so ``pin_epoch`` on the live path is pure
        bookkeeping."""
        if not self.names:
            return
        view = self.pin_epoch()
        try:
            view.node_state()
        finally:
            self.retire_epoch(view)

    def prewarm_delta(self, sizes: Sequence[int]) -> int:
        """Compile the padded scatter-update programs for the given dirty
        counts (snapped to the pow2 bucket ladder) with no-op writes, so
        steady-state churn cycles never pay a cold scatter compile
        (Scheduler.prewarm calls this next to the solver shapes)."""
        if not self.names:
            return 0
        self._ensure_device()
        warmed = set()
        for n in sizes:
            b = _delta_bucket(max(int(n), 1))
            if b in warmed:
                continue
            # b zero-indices re-writing row 0's current values: a no-op
            # that compiles exactly the bucket-b scatter the live path uses
            self._scatter_device(np.zeros(b, np.int32))
            warmed.add(b)
        return len(warmed)


_HOLE_NODE: Optional[NodeInfo] = None


def node_infos_for(ssn, node_t) -> List[NodeInfo]:
    """Session NodeInfos row-aligned with ``node_t.names`` — what plugin
    mask/score builders iterate. PersistentNodeTensors rows freed by node
    removal are holes (name ``""``); they map to one shared inert NodeInfo
    (unschedulable, empty) so builders stay index-aligned without
    per-plugin hole handling. Hole columns are unselectable in-kernel
    regardless: their row is zeroed with ``max_tasks`` 0."""
    global _HOLE_NODE
    nodes = ssn.nodes
    out: List[NodeInfo] = []
    for name in node_t.names:
        node = nodes.get(name)
        if node is None:
            if _HOLE_NODE is None:
                _HOLE_NODE = NodeInfo(name="", unschedulable=True)
            node = _HOLE_NODE
        out.append(node)
    return out


def discover_resource_names(nodes: Sequence[NodeInfo],
                            tasks: Sequence[TaskInfo]) -> ResourceNames:
    rs: List[Resource] = [n.allocatable for n in nodes]
    rs += [t.resreq for t in tasks]
    return ResourceNames.discover(rs)


def task_requests(tasks: Sequence[TaskInfo], rnames: ResourceNames) -> np.ndarray:
    T, R = len(tasks), len(rnames)
    req = np.zeros((T, R), np.float32)
    for i, t in enumerate(tasks):
        req[i] = t.init_resreq.to_vector(rnames)
    return req


def assemble_feasibility(ssn, tasks: Sequence[TaskInfo],
                         node_t: NodeTensors) -> Optional[np.ndarray]:
    """AND of all plugin feasibility contributions; base mask excludes
    not-ready nodes (snapshot already dropped them) — plugins add selectors/
    taints/affinity (predicates plugin) and revocable-zone windows (tdm).
    Returns None when every plugin abstained (mask would be all-true) so
    callers can skip the [T,N] transfer entirely."""
    mask = None
    for fn in ssn.feasibility_fns.values():
        m = fn(ssn, tasks, node_t)
        if m is None:
            continue
        mask = m if mask is None else (mask & m)
    return mask


def assemble_static_score(ssn, tasks: Sequence[TaskInfo],
                          node_t: NodeTensors) -> Optional[np.ndarray]:
    """Sum of static score matrices; None when every plugin abstained (a
    constant-zero matrix) so callers can skip the [T,N] transfer."""
    score = None
    for fn in ssn.static_score_fns.values():
        s = fn(ssn, tasks, node_t)
        if s is None:
            continue
        s = s.astype(np.float32)
        score = s if score is None else (score + s)
    return score


def assemble_weights(ssn, rnames: ResourceNames) -> ScoreWeights:
    """Merge plugin weight contributions into one ScoreWeights. Plugins set
    e.g. {'binpack_weight': 1, 'binpack_res': {...}} or {'least_req_weight': 1}
    via ssn.set_dynamic_score_weights. binpack_res stays numpy — jit converts
    at dispatch, and host callers avoid a device->host RTT."""
    binpack_res = np.zeros(len(rnames), np.float32)
    vals = {"binpack_weight": 0.0, "least_req_weight": 0.0,
            "most_req_weight": 0.0, "balanced_weight": 0.0}
    for w in ssn.dynamic_score_weights.values():
        for k in vals:
            vals[k] += float(w.get(k, 0.0))
        for rname, rw in (w.get("binpack_res") or {}).items():
            if rname in rnames.index:
                binpack_res[rnames.index[rname]] += float(rw)
    return ScoreWeights(binpack_weight=vals["binpack_weight"],
                        binpack_res=binpack_res,
                        least_req_weight=vals["least_req_weight"],
                        most_req_weight=vals["most_req_weight"],
                        balanced_weight=vals["balanced_weight"])
