"""FeedbackChannel: the normalizing funnel for cluster→cache acks.

Every kubelet/status ack — the RUNNING flip confirming a bind, the
delete-and-recreate confirming an eviction — enters the cache through
here (vlint VT017 pins ack consumption to this module), because the
feedback plane is HOSTILE (docs/robustness.md, feedback failure model):
acks arrive late, twice, out of order, or for placements that have since
died. The channel classifies each ack against the cache's CURRENT intent
before applying anything:

- ``applied``   — the ack matches the live intent (a BOUND task on that
                  node flips RUNNING; a RELEASING task requeues);
- ``duplicate`` — the ack's effect already happened (RUNNING already /
                  requeue already applied); re-applying is idempotent
                  for evictions and a no-op for binds;
- ``stale``     — the ack belongs to a superseded intent (a RUNNING ack
                  for a since-evicted or re-placed task must NOT
                  resurrect the dead placement; an evict ack for a task
                  a newer bind owns must not strip it);
- ``unknown``   — the task left the cache (gang completed); moot.

Applied acks also resolve the in-flight ledger (cache/inflight.py), so
ledger state and cache state settle together. The ledger's watchdog
feeds recovered acks back through this same normalizer
(``source="watchdog"``) — repair is never a raw mutation.

Store-wired deployments route the pod-status watch events here
(``pod_status_event``); with a seeded ``chaos.AckFaultInjector``
attached, RUNNING acks on the watch path are additionally delayed,
dropped or duplicated on the injectable clock — the store-wired ack
chaos variant, composing with the PR 13 torn streams.
"""

from __future__ import annotations

import heapq
import itertools
import logging
from typing import Callable, Dict, List, Optional, Tuple

from ..api import TaskStatus
from ..obs.lifecycle import TIMELINE
from ..obs.trace import TRACE as OBS_TRACE

log = logging.getLogger(__name__)


class FeedbackChannel:
    def __init__(self, cache):
        self.cache = cache
        # watch-path ack chaos (store-wired rigs): seeded per-ack faults
        # + a delayed-delivery heap on the injectable clock; attach_injector
        self.injector = None
        self.time_fn: Optional[Callable[[], float]] = None
        self._pending: List[Tuple[float, int, str, str, str]] = []
        self._seq = itertools.count()
        # watchdog-recovered evict acks hand the requeue to the harness
        # (the sim's controller-recreate analogue) when a hook is set;
        # cache-local state is already settled either way
        self.on_watchdog_evict: Optional[Callable[[str, str], None]] = None
        # (kind, verdict) -> count; deterministic (seeded chaos only)
        self.counts: Dict[Tuple[str, str], int] = {}

    def _count(self, kind: str, verdict: str) -> None:
        from .. import metrics
        with self.cache._lock:
            key = (kind, verdict)
            self.counts[key] = self.counts.get(key, 0) + 1
        metrics.register_feedback_ack(kind, verdict)

    # -- the normalizer -----------------------------------------------------

    def ack_running(self, jid: str, uid: str, node: Optional[str] = None,
                    source: str = "cluster",
                    ctx: Optional[dict] = None) -> str:
        """Consume one kubelet RUNNING ack for (task, node). ``node=None``
        skips the placement check (the HA convergence sweep, which swept
        cluster-confirmed binds before this funnel existed). An optional
        ``ctx`` (a correlation stamp carried by a remote/replayed
        verdict) is ingested exactly-once instead of minting a fresh
        one. Returns the verdict."""
        cache = self.cache
        with cache._lock:
            job = cache.jobs.get(jid)
            cached = job.tasks.get(uid) if job is not None else None
            if cached is None:
                verdict = "unknown"
            elif node is not None and cached.node_name != node:
                # the placement this ack confirms is dead — the task was
                # evicted/requeued and possibly re-placed elsewhere; a
                # duplicate/late RUNNING ack must not resurrect it
                verdict = "stale"
            elif cached.status == TaskStatus.BOUND:
                verdict = "applied"
            elif cached.status == TaskStatus.RUNNING:
                verdict = "duplicate"
            else:
                verdict = "stale"
            if verdict == "applied":
                # resolve BEFORE the flip: update_task_status carries a
                # belt-and-braces resolve whose default "acked" label
                # would otherwise swallow the watchdog's "repaired"
                cache.inflight.resolve(
                    "bind", uid,
                    "acked" if source == "cluster" else "repaired")
                cache.update_task_status(cached, TaskStatus.RUNNING)
                cache.binding_tasks.pop(uid, None)
        if verdict == "applied":
            # lifecycle witness (vlint VT022): the applied verdict is the
            # RUNNING milestone of the job's causal timeline — stamped
            # with the owning cache's partition and THIS leadership's
            # epoch (a failover's successor ack carries the successor
            # epoch, which is what stitches the timeline across the
            # handoff), deduped on a carried ctx
            if ctx is None:
                ctx = TIMELINE.stamp(part=getattr(cache, "obs_part", None))
            TIMELINE.record(jid, "running", ctx=ctx,
                            node=node or None, source=source, task=uid)
            OBS_TRACE.flow_step("running_ack", f"job:{jid}", task=uid)
        if source != "converge" or verdict == "applied":
            # the HA convergence sweep probes every live bind each cycle;
            # only its applies are acks — the probes are sweep noise
            self._count("running", verdict)
        return verdict

    def ack_evicted(self, jid: str, uid: str,
                    source: str = "cluster",
                    ctx: Optional[dict] = None) -> str:
        """Consume one eviction confirmation (pod delete + controller
        recreate, collapsed): a RELEASING task requeues PENDING; a
        PENDING-unplaced task means the requeue already happened (a
        replayed confirmation — ``duplicate``, a no-op); anything else
        is a superseded intent's ack and is dropped. An optional ``ctx``
        dedupes like ``ack_running``'s. Returns the verdict."""
        cache = self.cache
        with cache._lock:
            job = cache.jobs.get(jid)
            cached = job.tasks.get(uid) if job is not None else None
            if cached is None:
                verdict = "unknown"
            elif cached.status == TaskStatus.RELEASING:
                verdict = "applied"
            elif cached.status == TaskStatus.PENDING \
                    and not cached.node_name:
                # the requeue already happened (a replayed confirmation,
                # or the watchdog repaired the drop first): a no-op
                verdict = "duplicate"
            else:
                # a newer bind owns the task (BOUND/RUNNING): the evict
                # ack is for a dead intent — settling to the LATER intent
                # is exactly the reorder contract
                verdict = "stale"
            if verdict == "applied":
                if cached.node_name:
                    cache.mark_node_dirty(cached.node_name)
                cache.mark_job_dirty(jid)
                node = cache.nodes.get(cached.node_name)
                if node is not None and uid in node.tasks:
                    node.remove_task(cached)
                cached.node_name = ""
                job.update_task_status(cached, TaskStatus.PENDING)
                cache.binding_tasks.pop(uid, None)
        if verdict == "applied":
            cache.inflight.resolve(
                "evict", uid, "acked" if source == "cluster" else "repaired")
            # lifecycle witness (vlint VT022): the applied eviction IS
            # the evicted-and-requeued milestone of the timeline
            if ctx is None:
                ctx = TIMELINE.stamp(part=getattr(cache, "obs_part", None))
            TIMELINE.record(jid, "evicted", ctx=ctx, source=source,
                            task=uid)
            if source == "watchdog" and self.on_watchdog_evict is not None:
                self.on_watchdog_evict(jid, uid)
        self._count("evicted", verdict)
        return verdict

    # -- the watch path (store-wired deployments) ---------------------------

    def pod_status_event(self, cached, status: TaskStatus) -> None:
        """Route a pod-status watch event: RUNNING flips are kubelet acks
        and go through the normalizer (fault-injected when an injector is
        attached); every other transition is watch truth and applies
        directly."""
        if status != TaskStatus.RUNNING:
            self.cache.update_task_status(cached, status)
            return
        jid, uid, node = cached.job, cached.uid, cached.node_name
        fault = self.injector.roll("running") \
            if self.injector is not None else None
        if fault == "drop":
            return                       # the watchdog recovers it
        if fault in ("delay", "reorder"):
            self._push(self.injector.delay_s, jid, uid, node)
            return
        if fault == "duplicate":
            self._push(self.injector.delay_s, jid, uid, node)
        elif fault == "stale":
            self._push(self.injector.stale_delay_s, jid, uid, node)
        self.ack_running(jid, uid, node)

    def attach_injector(self, injector, time_fn) -> None:
        """Arm seeded watch-path ack chaos (store-wired rigs): ``roll``ed
        per RUNNING ack; delayed deliveries drain on ``deliver_due``
        (driven by the scheduler epilogue's watchdog step)."""
        self.injector = injector
        self.time_fn = time_fn

    def _push(self, delay_s: float, jid: str, uid: str, node: str) -> None:
        now = self.time_fn() if self.time_fn is not None else 0.0
        heapq.heappush(self._pending,
                       (now + delay_s, next(self._seq), jid, uid, node))

    def deliver_due(self, now: Optional[float] = None) -> int:
        """Apply delayed watch-path acks whose due time passed."""
        if not self._pending:
            return 0
        if now is None:
            now = self.time_fn() if self.time_fn is not None else 0.0
        n = 0
        while self._pending and self._pending[0][0] <= now + 1e-9:
            _, _, jid, uid, node = heapq.heappop(self._pending)
            self.ack_running(jid, uid, node)
            n += 1
        return n

    def pending(self) -> int:
        return len(self._pending)
