from .cache import SchedulerCache, incremental_snapshot_enabled
from .feedback import FeedbackChannel
from .inflight import InflightLedger
from .executors import (Binder, Evictor, FakeBinder, FakeEvictor,
                        FakeStatusUpdater, FakeVolumeBinder, SequenceBinder,
                        SequenceEvictor, StatusUpdater, StoreBinder,
                        StoreEvictor, VolumeBinder)
from .snapshot import (NodeTensors, PersistentNodeTensors,
                       assemble_feasibility, assemble_static_score,
                       assemble_weights, discover_resource_names,
                       node_infos_for, task_requests)

__all__ = [
    "SchedulerCache", "incremental_snapshot_enabled",
    "FeedbackChannel", "InflightLedger",
    "Binder", "Evictor", "FakeBinder", "FakeEvictor", "FakeStatusUpdater",
    "FakeVolumeBinder", "SequenceBinder", "SequenceEvictor", "StatusUpdater",
    "StoreBinder", "StoreEvictor", "VolumeBinder",
    "NodeTensors", "PersistentNodeTensors", "assemble_feasibility",
    "assemble_static_score", "assemble_weights", "discover_resource_names",
    "node_infos_for", "task_requests",
]
