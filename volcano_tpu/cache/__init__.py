from .cache import SchedulerCache
from .executors import (Binder, Evictor, FakeBinder, FakeEvictor,
                        FakeStatusUpdater, FakeVolumeBinder, SequenceBinder,
                        SequenceEvictor, StatusUpdater, StoreBinder,
                        StoreEvictor, VolumeBinder)
from .snapshot import (NodeTensors, assemble_feasibility, assemble_static_score,
                       assemble_weights, discover_resource_names, task_requests)

__all__ = [
    "SchedulerCache",
    "Binder", "Evictor", "FakeBinder", "FakeEvictor", "FakeStatusUpdater",
    "FakeVolumeBinder", "SequenceBinder", "SequenceEvictor", "StatusUpdater",
    "StoreBinder", "StoreEvictor", "VolumeBinder",
    "NodeTensors", "assemble_feasibility", "assemble_static_score",
    "assemble_weights", "discover_resource_names", "task_requests",
]
