"""Prometheus metrics with the reference's metric names
(/root/reference/pkg/scheduler/metrics/metrics.go:38-130, queue.go), so
dashboards and the benchmark harness read identically.

Falls back to an in-process recorder if prometheus_client is unavailable.
"""

from __future__ import annotations

import collections
import os
import threading
from typing import Dict, Optional, Tuple

try:
    from prometheus_client import Counter, Gauge, Histogram, start_http_server
    _HAVE_PROM = True
except Exception:                                            # pragma: no cover
    _HAVE_PROM = False

_SUBSYSTEM = "volcano"

# Each in-process duration series is RING-BOUNDED (a long-running scheduler
# must not grow a list forever at one observation per cycle): the deque
# keeps the newest ``cap`` observations while ``count``/``total`` keep the
# monotonic all-time view the mark/since API and the Prometheus-fallback
# histogram exposition need. VOLCANO_TPU_METRICS_RING overrides the cap.
DEFAULT_DURATION_CAP = 4096


def _duration_cap() -> int:
    try:
        return max(1, int(os.environ.get("VOLCANO_TPU_METRICS_RING",
                                         DEFAULT_DURATION_CAP)))
    except ValueError:
        return DEFAULT_DURATION_CAP


class _Series:
    __slots__ = ("data", "count", "total")

    def __init__(self):
        self.data = collections.deque(maxlen=_duration_cap())
        self.count = 0              # all-time observations (never truncated)
        self.total = 0.0            # all-time sum, for _count/_sum exposition

    def observe(self, v: float) -> None:
        self.data.append(v)
        self.count += 1
        self.total += v


_lock = threading.Lock()
# local mirror (always kept, powers tests and the CLI without scraping)
_durations: Dict[Tuple[str, ...], _Series] = collections.defaultdict(_Series)
_gauges: Dict[Tuple[str, ...], float] = {}
_counters: Dict[Tuple[str, ...], float] = collections.defaultdict(float)

# scheduler health (docs/robustness.md): "healthy" | "degraded", plus the
# consecutive-failed-cycle count the crash-loop guard exports. /healthz
# answers 200/503 from this.
HEALTHY = "healthy"
DEGRADED = "degraded"
_health = {"state": HEALTHY, "consecutive_failures": 0}
# structured operational detail served by /healthz?detail (JSON):
# components push dicts here (device cool-down state, journal reconcile
# summary) next to the gauges/counters the payload derives
_health_detail: Dict[str, dict] = {}

if _HAVE_PROM:
    _e2e = Histogram(f"{_SUBSYSTEM}_e2e_scheduling_latency_milliseconds",
                     "E2e scheduling latency in ms")
    _action = Histogram(f"{_SUBSYSTEM}_action_scheduling_latency_microseconds",
                        "Action latency in us", ["action"])
    _plugin = Histogram(f"{_SUBSYSTEM}_plugin_scheduling_latency_microseconds",
                        "Plugin latency in us", ["plugin", "OnSession"])
    _task_lat = Histogram(f"{_SUBSYSTEM}_task_scheduling_latency_milliseconds",
                          "Task scheduling latency in ms")
    _attempts = Counter(f"{_SUBSYSTEM}_schedule_attempts_total",
                        "Schedule attempts", ["result"])
    _preempt_victims = Gauge(f"{_SUBSYSTEM}_pod_preemption_victims",
                             "Current preemption victims")
    _preempt_total = Counter(f"{_SUBSYSTEM}_total_preemption_attempts",
                             "Total preemption attempts")
    _unsched_tasks = Gauge(f"{_SUBSYSTEM}_unschedule_task_count",
                           "Unschedulable tasks", ["job_id"])
    _unsched_jobs = Counter(f"{_SUBSYSTEM}_unschedule_job_count",
                            "Unschedulable jobs")
    _q_alloc = Gauge(f"{_SUBSYSTEM}_queue_allocated_milli_cpu",
                     "Queue allocated mcpu", ["queue_name"])
    _q_alloc_mem = Gauge(f"{_SUBSYSTEM}_queue_allocated_memory_bytes",
                         "Queue allocated memory", ["queue_name"])
    _q_deserved = Gauge(f"{_SUBSYSTEM}_queue_deserved_milli_cpu",
                        "Queue deserved mcpu", ["queue_name"])
    _q_deserved_mem = Gauge(f"{_SUBSYSTEM}_queue_deserved_memory_bytes",
                            "Queue deserved memory", ["queue_name"])
    _q_share = Gauge(f"{_SUBSYSTEM}_queue_share", "Queue share", ["queue_name"])
    _q_weight = Gauge(f"{_SUBSYSTEM}_queue_weight", "Queue weight", ["queue_name"])
    _health_g = Gauge(f"{_SUBSYSTEM}_scheduler_healthy",
                      "1 healthy, 0 degraded (crash-loop guard)")
    _action_fail = Counter(f"{_SUBSYSTEM}_action_failures_total",
                           "Actions that raised and were skipped", ["action"])
    _solver_fb = Counter(f"{_SUBSYSTEM}_solver_fallback_total",
                         "Device-solver failures degraded to the sequential "
                         "placer", ["action"])
    _dead_letter = Counter(f"{_SUBSYSTEM}_resync_dead_letter_total",
                           "Side effects dropped from the resync queue after "
                           "the per-item retry cap", ["op"])
    _snap_dirty_nodes = Gauge(f"{_SUBSYSTEM}_snapshot_dirty_nodes",
                              "Nodes re-cloned by the last incremental "
                              "snapshot (docs/performance.md)")
    _snap_dirty_ratio = Gauge(f"{_SUBSYSTEM}_snapshot_dirty_ratio",
                              "Re-cloned fraction of the last snapshot's "
                              "node set (1.0 = full rebuild)")
    _snap_full = Counter(f"{_SUBSYSTEM}_snapshot_full_rebuilds_total",
                         "Snapshots (layer=clone) or tensor refreshes "
                         "(layer=tensor) that fell back to a full rebuild",
                         ["layer"])
    _dead_letter_size = Gauge(f"{_SUBSYSTEM}_resync_dead_letter_size",
                              "Side effects currently parked in the "
                              "dead-letter set (redrive to drain)")
    _state_drift = Counter(f"{_SUBSYSTEM}_state_drift_total",
                           "Incremental-state drift events the shadow "
                           "verifier detected and repaired "
                           "(layer=node|job|tensor)", ["layer"])
    _journal_replay = Counter(f"{_SUBSYSTEM}_journal_replayed_total",
                              "Unacked journal intents settled by startup "
                              "reconciliation", ["result"])
    _device_faults = Counter(f"{_SUBSYSTEM}_device_faults_total",
                             "Device errors (XLA OOM / device-lost) "
                             "contained by the cool-down state machine",
                             ["kind"])
    _device_ok = Gauge(f"{_SUBSYSTEM}_device_healthy",
                       "1 device engines available, 0 cooling down "
                       "(allocate degraded to the CPU engine)")
    _device_degraded = Counter(
        f"{_SUBSYSTEM}_device_degraded_cycles_total",
        "Allocate cycles that ran on the CPU placer because the "
        "device cool-down window was open")
    _device_quarantines = Counter(
        f"{_SUBSYSTEM}_device_quarantines_total",
        "Devices pulled out of the mesh by an attributed fault "
        "(docs/robustness.md mesh failure model)", ["kind"])
    _mesh_heals = Counter(
        f"{_SUBSYSTEM}_mesh_heals_total",
        "Mid-cycle mesh re-formations: a device fault during solve "
        "quarantined the shard and the same solve re-dispatched over "
        "the survivors", ["trigger"])
    _mesh_healthy = Gauge(
        f"{_SUBSYSTEM}_mesh_devices_healthy",
        "Devices currently eligible for live sharded solves "
        "(known minus quarantined)")
    _degradation_rung = Gauge(
        f"{_SUBSYSTEM}_degradation_rung",
        "The sharded engine's current degradation-ladder rung: 0 full "
        "mesh, 1 shrunken mesh, 2 single device, 3 CPU placer")
    _leader_g = Gauge(f"{_SUBSYSTEM}_leader",
                      "1 this replica holds the scheduler lease, 0 "
                      "follower/fenced (docs/robustness.md HA)")
    _fencing_rej = Counter(f"{_SUBSYSTEM}_fencing_rejections_total",
                           "Executor operations rejected for carrying a "
                           "stale fencing epoch (a deposed leader's "
                           "write)", ["op"])
    _failovers = Counter(f"{_SUBSYSTEM}_failovers_total",
                         "Leadership takeovers (a replica acquired an "
                         "expired foreign lease and resumed scheduling)")
    _partition_leader = Gauge(f"{_SUBSYSTEM}_partition_leader",
                              "1 this replica leads the labelled "
                              "federation partition (docs/federation.md)",
                              ["partition"])
    _xp_reserves = Counter(
        f"{_SUBSYSTEM}_cross_partition_reserves_total",
        "Cross-partition reserve/transfer protocol steps by result "
        "(requested|granted|rejected|expired)", ["result"])
    _admission_batch = Histogram(
        f"{_SUBSYSTEM}_admission_batch_size",
        "Jobs per batched admission submit (docs/federation.md)",
        buckets=(1, 4, 16, 64, 256, 1024, 4096))
    _speculation = Counter(
        f"{_SUBSYSTEM}_speculation_total",
        "Pipelined-cycle speculation outcomes at the commit boundary "
        "(hit|partial|conflict; docs/performance.md)", ["outcome"])
    _fast_admit_g = Counter(
        f"{_SUBSYSTEM}_fast_admit_gangs_total",
        "Gangs bound by the event-driven fast-admit path between full "
        "cycles (docs/performance.md)")
    _fast_admit_b = Counter(
        f"{_SUBSYSTEM}_fast_admit_binds_total",
        "Tasks bound by the event-driven fast-admit path")
    _tensor_epochs = Gauge(
        f"{_SUBSYSTEM}_tensor_epochs_live",
        "Pinned PersistentNodeTensors epochs currently live (the A side "
        "of the double-buffered pair; >1 sustained is a retire leak)")
    _store_retries = Counter(
        f"{_SUBSYSTEM}_store_retries_total",
        "Store verb attempts through the retrying transport funnel "
        "(result=ok|retry|exhausted; docs/robustness.md store failure "
        "model)", ["verb", "result"])
    _store_faults = Counter(
        f"{_SUBSYSTEM}_store_faults_total",
        "Faults injected/observed at the store boundary "
        "(kind=transient|conflict|latency|torn)", ["verb", "kind"])
    _watch_resumes = Counter(
        f"{_SUBSYSTEM}_store_watch_resumes_total",
        "Torn watch streams recovered (outcome=resume: backlog replay "
        "from the last resourceVersion; outcome=relist: 410 Gone, "
        "reconciled against a fresh list)", ["outcome"])
    _watch_stale = Gauge(
        f"{_SUBSYSTEM}_store_watch_staleness",
        "Max resourceVersion lag across live watch streams (torn "
        "streams fall behind until resumed)")
    _inflight_expired = Counter(
        f"{_SUBSYSTEM}_inflight_expired_total",
        "In-flight bind/evict entries whose cluster ack deadline passed, "
        "re-validated and resolved by the watchdog "
        "(docs/robustness.md feedback failure model)",
        ["op", "resolution"])
    _inflight_oldest = Gauge(
        f"{_SUBSYSTEM}_inflight_oldest_seconds",
        "Age of the oldest executor-accepted side effect still awaiting "
        "its cluster ack (0 when nothing is in flight)")
    _inflight_open = Gauge(
        f"{_SUBSYSTEM}_inflight_open",
        "Executor-accepted side effects currently awaiting their "
        "cluster ack")
    _ack_faults = Counter(
        f"{_SUBSYSTEM}_ack_faults_total",
        "Feedback-plane faults injected by the seeded ack chaos harness "
        "(kind=delay|drop|duplicate|reorder|stale)", ["kind"])
    _feedback_acks = Counter(
        f"{_SUBSYSTEM}_feedback_acks_total",
        "Cluster acks consumed through the FeedbackChannel normalizer "
        "by verdict (docs/robustness.md feedback failure model)",
        ["kind", "verdict"])
    _budget_exhausted = Counter(
        f"{_SUBSYSTEM}_cycle_budget_exhausted_total",
        "Cycles whose deadline budget ran out before the labelled "
        "action could dispatch (it deferred to the next cycle; "
        "docs/robustness.md overload failure model)", ["action"])
    _deferred_actions = Counter(
        f"{_SUBSYSTEM}_deferred_actions_total",
        "Actions deferred to the next cycle by the cycle deadline "
        "budget (carry-over ordering; docs/robustness.md)")
    _backpressure = Counter(
        f"{_SUBSYSTEM}_admission_backpressure_total",
        "Submissions refused by the admission front door's bounded "
        "pending-work budget (reason=queue_depth|bytes|priority_shed)",
        ["reason"])
    _admission_depth = Gauge(
        f"{_SUBSYSTEM}_admission_pending_depth",
        "Pending tasks currently charged against the admission "
        "backpressure budget")
    _admission_bytes = Gauge(
        f"{_SUBSYSTEM}_admission_pending_bytes",
        "Estimated bytes of pending work charged against the admission "
        "backpressure budget")
    _dl_evicted = Counter(
        f"{_SUBSYSTEM}_dead_letter_evicted_total",
        "Oldest dead-letter entries evicted to keep the set bounded "
        "under pathological churn (docs/robustness.md)")
    _audit_evicted = Counter(
        f"{_SUBSYSTEM}_audit_latest_evicted_total",
        "Oldest per-job audit records evicted to keep the decision "
        "audit's live-job map bounded (docs/observability.md)")
    _rebalance_moves = Counter(
        f"{_SUBSYSTEM}_rebalance_moves_total",
        "Load-driven partition rebalancer decisions "
        "(result=moved|refused|abstained; docs/federation.md)",
        ["result"])
    _partition_count = Gauge(
        f"{_SUBSYSTEM}_partition_count",
        "Live federation partitions (elastic membership; "
        "docs/federation.md)")
    _partition_splits = Counter(
        f"{_SUBSYSTEM}_partition_splits_total",
        "Elastic membership splits through the journaled "
        "partition_spawn funnel (result=executed|refused)", ["result"])
    _partition_merges = Counter(
        f"{_SUBSYSTEM}_partition_merges_total",
        "Elastic membership merges through the journaled "
        "partition_retire funnel (result=begun|completed|refused)",
        ["result"])
    _elastic_members = Gauge(
        f"{_SUBSYSTEM}_elastic_members",
        "Bound above-min members across elastic gangs (the flex the "
        "grow/shrink stage manages; docs/design/elastic-gangs.md)")
    _gang_growths = Counter(
        f"{_SUBSYSTEM}_gang_growths_total",
        "Elastic gang members placed by the grow/shrink stage beyond "
        "admission (docs/design/elastic-gangs.md)")
    _gang_shrinks = Counter(
        f"{_SUBSYSTEM}_gang_shrinks_total",
        "Elastic gang members evicted by an elastic decision "
        "(reason=scale|pressure|suspend)", ["reason"])
    _topology_spread = Gauge(
        f"{_SUBSYSTEM}_topology_spread",
        "Multi-member gangs currently spanning more than one topology "
        "zone (0 with the compactness term doing its job and capacity "
        "permitting)")
    _below_min_evictions = Counter(
        f"{_SUBSYSTEM}_elastic_below_min_evictions_total",
        "Evictions that took an elastic gang below min outside a "
        "full-gang decision — the invariant witness, expected 0")
    _slo_compliance = Gauge(
        f"{_SUBSYSTEM}_slo_compliance",
        "Fraction of retained timeline samples within the labelled "
        "objective's threshold (obs/slo.py; docs/observability.md)",
        ["slo"])
    _slo_burn_rate = Gauge(
        f"{_SUBSYSTEM}_slo_burn_rate",
        "Error-budget burn rate of the labelled objective over the "
        "labelled look-back window (1.0 = spending the budget exactly)",
        ["slo", "window"])


def set_elastic_members(n: int) -> None:
    """Publish the bound above-min member count across elastic gangs —
    the volcano_elastic_members gauge the grow/shrink stage moves."""
    with _lock:
        _gauges[("elastic_members",)] = float(n)
    if _HAVE_PROM:
        _elastic_members.set(n)


def register_gang_growth(n: int = 1) -> None:
    """The grow/shrink stage placed ``n`` elastic members beyond
    admission (toward desired)."""
    with _lock:
        _counters[("gang_growths",)] += n
    if _HAVE_PROM:
        _gang_growths.inc(n)


def register_gang_shrink(reason: str, n: int = 1) -> None:
    """An elastic decision evicted ``n`` gang members
    (reason=scale|pressure|suspend)."""
    with _lock:
        _counters[("gang_shrinks", reason)] += n
    if _HAVE_PROM:
        _gang_shrinks.labels(reason=reason).inc(n)


def set_topology_spread(n: int) -> None:
    """Publish the count of multi-member gangs spanning more than one
    topology zone (volcano_topology_spread)."""
    with _lock:
        _gauges[("topology_spread",)] = float(n)
    if _HAVE_PROM:
        _topology_spread.set(n)


def register_below_min_eviction(n: int = 1) -> None:
    """An eviction took an elastic gang below min OUTSIDE a full-gang
    decision — the never-below-min invariant witness (expected 0; the
    elastic-churn scenario asserts it)."""
    with _lock:
        _counters[("elastic_below_min_evictions",)] += n
    if _HAVE_PROM:
        _below_min_evictions.inc(n)


def elastic_counts() -> Dict[str, float]:
    """Current elastic-gang outcome counts (grows, per-reason shrinks as
    ``shrink/<reason>``, below-min eviction witness); the sim reads these
    and takes a before/after delta for per-run sections."""
    with _lock:
        out: Dict[str, float] = {}
        for k, v in _counters.items():
            if k[0] == "gang_growths":
                out["grows"] = out.get("grows", 0.0) + v
            elif k[0] == "gang_shrinks":
                out[f"shrink/{k[1]}"] = v
            elif k[0] == "elastic_below_min_evictions":
                out["below_min"] = v
        return out


def update_e2e_duration(seconds: float) -> None:
    with _lock:
        _durations[("e2e",)].observe(seconds * 1e3)
    if _HAVE_PROM:
        _e2e.observe(seconds * 1e3)


def set_health(state: str, consecutive_failures: int = 0) -> None:
    """Publish the scheduler shell's health verdict (the crash-loop guard
    in scheduler.run calls this every cycle; docs/robustness.md)."""
    with _lock:
        _health["state"] = state
        _health["consecutive_failures"] = consecutive_failures
        _gauges[("scheduler_healthy",)] = 1.0 if state == HEALTHY else 0.0
    if _HAVE_PROM:
        _health_g.set(1.0 if state == HEALTHY else 0.0)


def health() -> Tuple[str, int]:
    with _lock:
        return _health["state"], _health["consecutive_failures"]


def health_detail() -> dict:
    """The structured /healthz?detail payload: shell health plus the
    robustness-layer state a probe or operator wants in one read —
    dead-letter backlog, device cool-down, drift counters, journal
    replay totals (docs/robustness.md)."""
    with _lock:
        drift = {k[1]: v for k, v in _counters.items()
                 if k[0] == "state_drift"}
        journal = {k[1]: v for k, v in _counters.items()
                   if k[0] == "journal_replayed"}
        fenced = {k[1]: v for k, v in _counters.items()
                  if k[0] == "fencing_rejections"}
        return {
            "state": _health["state"],
            "consecutive_failures": _health["consecutive_failures"],
            "dead_letter_size": int(
                _gauges.get(("resync_dead_letter_size",), 0)),
            "device": dict(_health_detail.get("device",
                                              {"available": True})),
            "state_drift_total": drift,
            "journal_replayed_total": journal,
            # HA role reporting (docs/robustness.md): which role this
            # replica is in, its fencing epoch, and how many stale-epoch
            # writes the fencing gate has stopped
            "leader": dict(_health_detail.get("leader",
                                              {"leading": False,
                                               "role": "standalone",
                                               "epoch": 0})),
            "fencing_rejections_total": fenced,
            "failovers_total": _counters.get(("failovers",), 0),
            # federation (docs/federation.md): per-partition leadership/
            # ownership entries published by PartitionMember, plus the
            # cross-partition reserve counters
            "federation": dict(_health_detail.get("federation",
                                                  {"enabled": False})),
            "cross_partition_reserves_total": {
                k[1]: v for k, v in _counters.items()
                if k[0] == "cross_partition_reserves"},
            # elastic membership (docs/federation.md): the live count
            # plus split/merge outcome rollups; per-partition elastic
            # state lives under federation.elastic
            "partition_count": int(_gauges.get(("partition_count",), 0)),
            "partition_splits_total": {
                k[1]: v for k, v in _counters.items()
                if k[0] == "partition_splits"},
            "partition_merges_total": {
                k[1]: v for k, v in _counters.items()
                if k[0] == "partition_merges"},
            # the store boundary (docs/robustness.md store failure
            # model): retry-funnel + fault + watch-stream state pushed by
            # the transports/watch manager, plus the counter totals
            "store": dict(_health_detail.get("store",
                                             {"wired": False})),
            "store_faults_total": {
                "/".join(k[1:]): v for k, v in _counters.items()
                if k[0] == "store_faults"},
            "store_retries_total": {
                "/".join(k[1:]): v for k, v in _counters.items()
                if k[0] == "store_retries"},
            # the feedback plane (docs/robustness.md feedback failure
            # model): the in-flight ledger's open set + watchdog
            # resolutions pushed by process_expired_inflight, plus the
            # expiry counter rollup
            "inflight": dict(_health_detail.get("inflight", {"open": 0})),
            "inflight_expired_total": {
                "/".join(k[1:]): v for k, v in _counters.items()
                if k[0] == "inflight_expired"},
            # the overload plane (docs/robustness.md overload failure
            # model): cycle-budget exhaustion, admission backpressure,
            # bounded-set evictions (each eviction is a WARNING: state
            # was dropped to stay bounded) and the rebalancer state
            "overload": _overload_detail_locked(),
            # the SLO plane (docs/observability.md): the engine's last
            # published evaluation (compliance + per-window burn rates)
            "slo": [dict(obj) for obj in _health_detail.get("slo", [])],
        }


def _overload_detail_locked() -> dict:
    """Caller holds _lock: the /healthz?detail overload section."""
    exhausted = {k[1]: v for k, v in _counters.items()
                 if k[0] == "cycle_budget_exhausted"}
    dl_evicted = int(_counters.get(("dead_letter_evicted",), 0))
    audit_evicted = int(_counters.get(("audit_latest_evicted",), 0))
    warnings = []
    if dl_evicted:
        warnings.append(
            f"dead_letter_evicted={dl_evicted}: the bounded dead-letter "
            f"set overflowed and dropped its oldest side effects — "
            f"redrive cannot recover them; investigate the failing path")
    if audit_evicted:
        warnings.append(
            f"audit_latest_evicted={audit_evicted}: decision-audit "
            f"records were evicted under job-churn pressure; why() may "
            f"miss old jobs")
    return {
        "cycle_budget_exhausted_total": exhausted,
        "deferred_actions_total": int(
            _counters.get(("deferred_actions",), 0)),
        "backpressure_total": {k[1]: v for k, v in _counters.items()
                               if k[0] == "admission_backpressure"},
        "admission_pending_depth": int(
            _gauges.get(("admission_pending_depth",), 0)),
        "dead_letter_evicted_total": dl_evicted,
        "audit_latest_evicted_total": audit_evicted,
        "rebalance": dict(_health_detail.get("rebalance", {})),
        "warnings": warnings,
    }


def register_store_retry(verb: str, result: str) -> None:
    """One store verb attempt through the retrying transport funnel
    settled with ``result`` (ok|retry|exhausted) — the
    volcano_store_retries_total{verb,result} series
    (docs/robustness.md store failure model)."""
    with _lock:
        _counters[("store_retries", verb, result)] += 1
    if _HAVE_PROM:
        _store_retries.labels(verb=verb, result=result).inc()


def register_store_fault(verb: str, kind: str) -> None:
    """A fault (transient|conflict|latency|torn) was injected or
    observed at the store boundary on ``verb``."""
    with _lock:
        _counters[("store_faults", verb, kind)] += 1
    if _HAVE_PROM:
        _store_faults.labels(verb=verb, kind=kind).inc()


def register_watch_resume(outcome: str) -> None:
    """A torn watch stream recovered: ``resume`` (backlog replay from
    its last resourceVersion) or ``relist`` (410 Gone; reconciled
    against a fresh consistent list)."""
    with _lock:
        _counters[("store_watch_resumes", outcome)] += 1
    if _HAVE_PROM:
        _watch_resumes.labels(outcome=outcome).inc()


def set_store_watch_staleness(lag: int) -> None:
    with _lock:
        _gauges[("store_watch_staleness",)] = float(lag)
    if _HAVE_PROM:
        _watch_stale.set(float(lag))


def set_store_detail(detail: dict) -> None:
    """Publish the store-boundary operational fragment of
    /healthz?detail (retry funnel totals, watch stream states)."""
    with _lock:
        _health_detail["store"] = dict(detail)


def store_counts() -> Dict[str, Dict[str, float]]:
    """Current store-boundary counters, grouped — the sim report and
    vcctl `store status` read these (take before/after deltas for
    per-run rates)."""
    with _lock:
        return {
            "retries": {"/".join(k[1:]): v for k, v in _counters.items()
                        if k[0] == "store_retries"},
            "faults": {"/".join(k[1:]): v for k, v in _counters.items()
                       if k[0] == "store_faults"},
            "watch_resumes": {k[1]: v for k, v in _counters.items()
                              if k[0] == "store_watch_resumes"},
        }


def register_inflight_expired(op: str, resolution: str) -> None:
    """One in-flight entry expired past its ack deadline and the
    watchdog resolved it (repaired|rolled_back|reissued|superseded|gone)
    — volcano_inflight_expired_total{op,resolution}."""
    with _lock:
        _counters[("inflight_expired", op, resolution)] += 1
    if _HAVE_PROM:
        _inflight_expired.labels(op=op, resolution=resolution).inc()


def set_inflight_stats(open_count: int, oldest_s: float,
                       detail: Optional[dict] = None) -> None:
    """Published by the watchdog step each epilogue: how much is in
    flight and for how long (the liveness gauges of the feedback
    failure model)."""
    with _lock:
        _gauges[("inflight_open",)] = float(open_count)
        _gauges[("inflight_oldest_seconds",)] = float(oldest_s)
        if detail is not None:
            _health_detail["inflight"] = dict(detail)
    if _HAVE_PROM:
        _inflight_open.set(open_count)
        _inflight_oldest.set(oldest_s)


def register_ack_fault(kind: str) -> None:
    """The seeded ack chaos harness injected one feedback-plane fault
    (delay|drop|duplicate|reorder|stale)."""
    with _lock:
        _counters[("ack_faults", kind)] += 1
    if _HAVE_PROM:
        _ack_faults.labels(kind=kind).inc()


def register_feedback_ack(kind: str, verdict: str) -> None:
    """One cluster ack consumed through the FeedbackChannel normalizer
    settled with ``verdict`` (applied|duplicate|stale|unknown)."""
    with _lock:
        _counters[("feedback_acks", kind, verdict)] += 1
    if _HAVE_PROM:
        _feedback_acks.labels(kind=kind, verdict=verdict).inc()


def set_slo_status(status) -> None:
    """Publish one SLO-engine evaluation (obs/slo.py): the
    volcano_slo_compliance{slo} / volcano_slo_burn_rate{slo,window}
    gauges plus the ``slo`` section of /healthz?detail. Replaces the
    previous evaluation wholesale — objectives that disappeared (a
    per-class expansion whose class drained away) must not linger as
    stale samples."""
    with _lock:
        for k in [k for k in _gauges
                  if k[0] in ("slo_compliance", "slo_burn_rate")]:
            del _gauges[k]
        for obj in status:
            name = str(obj.get("slo", ""))
            _gauges[("slo_compliance", name)] = float(
                obj.get("compliance", 1.0))
            for window, rate in (obj.get("burn_rate") or {}).items():
                _gauges[("slo_burn_rate", name, str(window))] = float(rate)
        _health_detail["slo"] = [dict(obj) for obj in status]
    if _HAVE_PROM:
        for obj in status:
            name = str(obj.get("slo", ""))
            _slo_compliance.labels(slo=name).set(
                float(obj.get("compliance", 1.0)))
            for window, rate in (obj.get("burn_rate") or {}).items():
                _slo_burn_rate.labels(slo=name,
                                      window=str(window)).set(float(rate))


def register_speculation(outcome: str) -> None:
    """One pipelined-cycle conflict-check verdict: ``hit`` (the
    speculative solve committed, snapshot promoted), ``partial`` (the
    solve replayed onto a fresh snapshot, suffix re-solved), or
    ``conflict`` (speculation discarded, cycle re-solved serially).
    The issue-named series volcano_speculation_{hits,conflicts}_total
    are the outcome="hit"/"conflict" samples of this counter."""
    with _lock:
        _counters[("speculation", outcome)] += 1
    if _HAVE_PROM:
        _speculation.labels(outcome=outcome).inc()


def speculation_counts() -> Dict[str, float]:
    """Current speculation outcome counts {outcome: n} (bench/sim read
    these; take a before/after delta for per-run rates)."""
    with _lock:
        return {k[1]: v for k, v in _counters.items()
                if k[0] == "speculation"}


def register_fast_admit(gangs: int, binds: int) -> None:
    with _lock:
        _counters[("fast_admit_gangs",)] += gangs
        _counters[("fast_admit_binds",)] += binds
    if _HAVE_PROM:
        _fast_admit_g.inc(gangs)
        _fast_admit_b.inc(binds)


def fast_admit_counts() -> Dict[str, float]:
    with _lock:
        return {"gangs": _counters.get(("fast_admit_gangs",), 0.0),
                "binds": _counters.get(("fast_admit_binds",), 0.0)}


def set_tensor_epochs_live(n: int) -> None:
    with _lock:
        _gauges[("tensor_epochs_live",)] = float(n)
    if _HAVE_PROM:
        _tensor_epochs.set(n)


def register_action_failure(action: str) -> None:
    """An action raised inside run_once and was isolated/skipped."""
    with _lock:
        _counters[("action_failures", action)] += 1
    if _HAVE_PROM:
        _action_fail.labels(action=action).inc()


def register_solver_fallback(action: str) -> None:
    """A batched device solve failed and the cycle completed through the
    sequential per-task placer instead."""
    with _lock:
        _counters[("solver_fallback", action)] += 1
    if _HAVE_PROM:
        _solver_fb.labels(action=action).inc()


def update_snapshot_stats(dirty_nodes: int, dirty_ratio: float) -> None:
    """Published by SchedulerCache.snapshot every cycle: how much of the
    cluster the incremental snapshot actually re-cloned. A dirty_ratio
    pinned at 1.0 means clone-on-dirty is not engaging (external bulk
    mutation, kill-switch off, or a mark_all_dirty storm)."""
    with _lock:
        _gauges[("snapshot_dirty_nodes",)] = float(dirty_nodes)
        _gauges[("snapshot_dirty_ratio",)] = float(dirty_ratio)
    if _HAVE_PROM:
        _snap_dirty_nodes.set(dirty_nodes)
        _snap_dirty_ratio.set(dirty_ratio)


def register_snapshot_full_rebuild(layer: str) -> None:
    """A snapshot (layer="clone") or persistent-tensor refresh
    (layer="tensor") fell back to a full rebuild — expected at startup and
    after bulk mutation; a steady stream of these is a fallback storm."""
    with _lock:
        _counters[("snapshot_full_rebuilds", layer)] += 1
    if _HAVE_PROM:
        _snap_full.labels(layer=layer).inc()


def set_dead_letter_size(size: int) -> None:
    """Current dead-letter set size — the cache updates this on every
    mutation of the set (park, purge, redrive); /healthz detail and the
    redrive CLI read it."""
    with _lock:
        _gauges[("resync_dead_letter_size",)] = float(size)
    if _HAVE_PROM:
        _dead_letter_size.set(size)


def dead_letter_size() -> int:
    with _lock:
        return int(_gauges.get(("resync_dead_letter_size",), 0))


def register_state_drift(layer: str, n: int = 1) -> None:
    """The shadow verifier found ``n`` drifted entries in ``layer``
    (node|job|tensor) — a silent-corruption event turned into a counted,
    repaired one (docs/robustness.md)."""
    with _lock:
        _counters[("state_drift", layer)] += n
    if _HAVE_PROM:
        _state_drift.labels(layer=layer).inc(n)


def set_drift_verify_stats(drift_total: int, verify_s: float) -> None:
    with _lock:
        _gauges[("drift_last_verify_total",)] = float(drift_total)
        _gauges[("drift_last_verify_s",)] = float(verify_s)


def register_journal_replay(result: str, n: int = 1) -> None:
    """Startup reconciliation settled ``n`` unacked journal intents with
    the given outcome (repaired|rolled_back|redone|stale|failed)."""
    with _lock:
        _counters[("journal_replayed", result)] += n
    if _HAVE_PROM:
        _journal_replay.labels(result=result).inc(n)


def register_device_degraded_cycle() -> None:
    """An allocate cycle ran on the CPU placer because the device
    cool-down window was open."""
    with _lock:
        _counters[("device_degraded_cycles",)] += 1
    if _HAVE_PROM:
        _device_degraded.inc()


def register_device_fault(kind: str) -> None:
    """A device error (oom|device_lost|xla) was classified and contained
    by the allocate cool-down state machine."""
    with _lock:
        _counters[("device_faults", kind)] += 1
    if _HAVE_PROM:
        _device_faults.labels(kind=kind).inc()


def set_device_health(available: bool, detail: Optional[dict] = None) -> None:
    """Publish the device cool-down state (device_health.DeviceHealth
    pushes on every transition); detail lands in /healthz?detail."""
    with _lock:
        _gauges[("device_healthy",)] = 1.0 if available else 0.0
        _health_detail["device"] = dict(detail) if detail else {
            "available": available}
    if _HAVE_PROM:
        _device_ok.set(1.0 if available else 0.0)


def register_device_quarantine(kind: str) -> None:
    """An attributed device fault quarantined one shard — the mesh heals
    around it instead of dumping the solve on the CPU placer."""
    with _lock:
        _counters[("device_quarantines", kind)] += 1
    if _HAVE_PROM:
        _device_quarantines.labels(kind=kind).inc()


def register_device_readmission() -> None:
    """A quarantined device's probe dry-run succeeded and the device
    rejoined the mesh (epoch bumped by the caller)."""
    with _lock:
        _counters[("device_readmissions",)] += 1


def register_mesh_heal(trigger: str) -> None:
    """A mid-cycle mesh heal: the failing shard was quarantined, the
    tensor epoch retired, and the SAME solve re-dispatched over the
    surviving devices within the same cycle."""
    with _lock:
        _counters[("mesh_heals", trigger)] += 1
    if _HAVE_PROM:
        _mesh_heals.labels(trigger=trigger).inc()


def set_mesh_devices_healthy(healthy: int, known: int) -> None:
    """Publish the per-device lattice's healthy-device count (pushed by
    DeviceHealth on every transition, like set_device_health)."""
    with _lock:
        _gauges[("mesh_devices_healthy",)] = float(healthy)
        _gauges[("mesh_devices_known",)] = float(known)
    if _HAVE_PROM:
        _mesh_healthy.set(float(healthy))


def set_degradation_rung(rung: int) -> None:
    """Publish the sharded engine's current degradation-ladder rung
    (0 full mesh, 1 shrunken mesh, 2 single device, 3 CPU placer)."""
    with _lock:
        _gauges[("degradation_rung",)] = float(rung)
    if _HAVE_PROM:
        _degradation_rung.set(float(rung))


def mesh_counts() -> Dict[str, float]:
    """Snapshot of the mesh-containment counters for delta-based
    reporting (sim/report.py ``mesh`` section): flattened
    ``heals/<trigger>``, ``quarantines/<kind>``, plus readmissions,
    degraded cycles and the current rung/healthy gauges."""
    with _lock:
        out: Dict[str, float] = {}
        for key, v in _counters.items():
            if key[0] == "mesh_heals":
                out[f"heals/{key[1]}"] = v
            elif key[0] == "device_quarantines":
                out[f"quarantines/{key[1]}"] = v
        out["readmissions"] = _counters.get(("device_readmissions",), 0)
        out["degraded_cycles"] = _counters.get(("device_degraded_cycles",),
                                               0)
        out["rung"] = _gauges.get(("degradation_rung",), 0.0)
        out["devices_healthy"] = _gauges.get(("mesh_devices_healthy",), 0.0)
        return out


def set_leader(leading: bool, role: str = "", epoch: int = 0) -> None:
    """Publish this replica's leadership state (the scheduler's HA gate
    calls it on every role transition and each gated cycle); role/epoch
    land in /healthz?detail under "leader"."""
    with _lock:
        _gauges[("leader",)] = 1.0 if leading else 0.0
        _health_detail["leader"] = {"leading": bool(leading),
                                    "role": role, "epoch": int(epoch)}
    if _HAVE_PROM:
        _leader_g.set(1.0 if leading else 0.0)


def register_fencing_rejection(op: str) -> None:
    """The fencing gate rejected a stale-epoch executor operation — a
    deposed leader tried to mutate cluster state and was stopped
    (docs/robustness.md HA section)."""
    with _lock:
        _counters[("fencing_rejections", op)] += 1
    if _HAVE_PROM:
        _fencing_rej.labels(op=op).inc()


def register_failover() -> None:
    """A replica took over an expired foreign lease and resumed
    scheduling."""
    with _lock:
        _counters[("failovers",)] += 1
    if _HAVE_PROM:
        _failovers.inc()


def set_partition_leader(partition: int, leading: bool, epoch: int = 0,
                         detail: Optional[dict] = None) -> None:
    """Publish a federation partition's leadership state
    (docs/federation.md): the labelled gauge plus the per-partition
    entry of /healthz?detail's "federation" section. Each partition
    member publishes its own entry; entries merge by partition id."""
    with _lock:
        _gauges[("partition_leader", str(partition))] = \
            1.0 if leading else 0.0
        fed = _health_detail.setdefault("federation", {"enabled": True})
        fed["enabled"] = True
        entry = {"leading": bool(leading), "epoch": int(epoch)}
        if detail:
            entry.update(detail)
        fed[str(partition)] = entry
    if _HAVE_PROM:
        _partition_leader.labels(partition=str(partition)).set(
            1.0 if leading else 0.0)


def register_cross_partition_reserve(result: str, n: int = 1) -> None:
    """A cross-partition reserve/transfer protocol step settled with the
    given result (requested|granted|rejected|expired) — the federated
    reclaim funnel's audit counter (docs/federation.md)."""
    with _lock:
        _counters[("cross_partition_reserves", result)] += n
    if _HAVE_PROM:
        _xp_reserves.labels(result=result).inc(n)


def observe_admission_batch(size: int) -> None:
    """One batched admission submit of ``size`` jobs went through the
    amortized validate-then-single-store-write path
    (webhooks/admission.submit_job_batch)."""
    with _lock:
        _durations[("admission_batch",)].observe(float(size))
    if _HAVE_PROM:
        _admission_batch.observe(size)


def register_dead_letter(op: str) -> None:
    """A failed side effect exhausted its resync retry budget and was
    parked in the cache's dead-letter set."""
    with _lock:
        _counters[("resync_dead_letter", op)] += 1
    if _HAVE_PROM:
        _dead_letter.labels(op=op).inc()


def register_cycle_budget_exhausted(action: str) -> None:
    """A cycle's deadline budget ran out before ``action`` could
    dispatch; it (and the rest of the pipeline) deferred to the next
    cycle with carry-over ordering (docs/robustness.md overload
    failure model)."""
    with _lock:
        _counters[("cycle_budget_exhausted", action)] += 1
    if _HAVE_PROM:
        _budget_exhausted.labels(action=action).inc()


def register_deferred_actions(n: int) -> None:
    with _lock:
        _counters[("deferred_actions",)] += n
    if _HAVE_PROM:
        _deferred_actions.inc(n)


def register_backpressure(reason: str, n: int = 1) -> None:
    """The admission front door refused work under its bounded
    pending-work budget (reason=queue_depth|bytes|priority_shed) —
    volcano_admission_backpressure_total{reason}."""
    with _lock:
        _counters[("admission_backpressure", reason)] += n
    if _HAVE_PROM:
        _backpressure.labels(reason=reason).inc(n)


def set_admission_pending(depth: int, nbytes: float) -> None:
    """Published by the admission budget on every charge/credit: how
    much accepted-but-unscheduled work the front door is carrying."""
    with _lock:
        _gauges[("admission_pending_depth",)] = float(depth)
        _gauges[("admission_pending_bytes",)] = float(nbytes)
    if _HAVE_PROM:
        _admission_depth.set(depth)
        _admission_bytes.set(float(nbytes))


def register_dead_letter_evicted(n: int = 1) -> None:
    """The bounded dead-letter set evicted its oldest entries to stay
    under its cap — operator signal that the backlog of permanently
    failing side effects is outgrowing what redrive can recover."""
    with _lock:
        _counters[("dead_letter_evicted",)] += n
    if _HAVE_PROM:
        _dl_evicted.inc(n)


def register_audit_evicted(n: int = 1) -> None:
    """The decision audit's per-live-job map evicted its oldest records
    to stay bounded under pathological job-churn cardinality."""
    with _lock:
        _counters[("audit_latest_evicted",)] += n
    if _HAVE_PROM:
        _audit_evicted.inc(n)


def register_rebalance_move(result: str) -> None:
    """One load-driven rebalancer decision settled
    (result=moved|refused|abstained; docs/federation.md)."""
    with _lock:
        _counters[("rebalance_moves", result)] += 1
    if _HAVE_PROM:
        _rebalance_moves.labels(result=result).inc()


def set_rebalance_detail(partition: int, detail: dict) -> None:
    """Publish one partition's rebalancer state for /healthz?detail and
    ``vcctl federation rebalance-status`` (process-local, like the
    flight-recorder verbs)."""
    with _lock:
        _health_detail.setdefault("rebalance", {})[str(partition)] = \
            dict(detail)


def set_partition_count(n: int) -> None:
    """Publish the live federation partition count — the
    volcano_partition_count gauge the elastic membership moves
    (docs/federation.md)."""
    with _lock:
        _gauges[("partition_count",)] = float(n)
        fed = _health_detail.setdefault("federation", {"enabled": True})
        fed["partition_count"] = int(n)
    if _HAVE_PROM:
        _partition_count.set(n)


def register_partition_split(result: str) -> None:
    """One elastic split decision settled (result=executed|refused) —
    volcano_partition_splits_total{result}."""
    with _lock:
        _counters[("partition_splits", result)] += 1
    if _HAVE_PROM:
        _partition_splits.labels(result=result).inc()


def register_partition_merge(result: str) -> None:
    """One elastic merge step settled (result=begun|completed|refused)
    — volcano_partition_merges_total{result}."""
    with _lock:
        _counters[("partition_merges", result)] += 1
    if _HAVE_PROM:
        _partition_merges.labels(result=result).inc()


def set_elastic_detail(partition: int, detail: dict) -> None:
    """Publish one partition's elastic-membership state into
    /healthz?detail's federation section (``federation.elastic``) for
    ``vcctl federation elastic-status``."""
    with _lock:
        fed = _health_detail.setdefault("federation", {"enabled": True})
        fed.setdefault("elastic", {})[str(partition)] = dict(detail)


# In-process mirror key -> Prometheus family for the no-prometheus_client
# /metrics fallback: first tuple element maps to (family name, label name,
# type). Keys absent here expose as volcano_<key0> gauges with a generic
# "key" label, so new series never silently disappear from scrapes.
_EXPO_GAUGES = {
    "scheduler_healthy": (f"{_SUBSYSTEM}_scheduler_healthy", None),
    "preemption_victims": (f"{_SUBSYSTEM}_pod_preemption_victims", None),
    "unschedule_tasks": (f"{_SUBSYSTEM}_unschedule_task_count", "job_id"),
    "queue_allocated": (f"{_SUBSYSTEM}_queue_allocated_milli_cpu",
                        "queue_name"),
    "queue_share": (f"{_SUBSYSTEM}_queue_share", "queue_name"),
    "snapshot_dirty_nodes": (f"{_SUBSYSTEM}_snapshot_dirty_nodes", None),
    "snapshot_dirty_ratio": (f"{_SUBSYSTEM}_snapshot_dirty_ratio", None),
    "resync_dead_letter_size": (f"{_SUBSYSTEM}_resync_dead_letter_size",
                                None),
    "device_healthy": (f"{_SUBSYSTEM}_device_healthy", None),
    "mesh_devices_healthy": (f"{_SUBSYSTEM}_mesh_devices_healthy", None),
    "degradation_rung": (f"{_SUBSYSTEM}_degradation_rung", None),
    "leader": (f"{_SUBSYSTEM}_leader", None),
    "partition_leader": (f"{_SUBSYSTEM}_partition_leader", "partition"),
    "partition_count": (f"{_SUBSYSTEM}_partition_count", None),
    "tensor_epochs_live": (f"{_SUBSYSTEM}_tensor_epochs_live", None),
    "elastic_members": (f"{_SUBSYSTEM}_elastic_members", None),
    "topology_spread": (f"{_SUBSYSTEM}_topology_spread", None),
    "store_watch_staleness": (f"{_SUBSYSTEM}_store_watch_staleness", None),
    "inflight_open": (f"{_SUBSYSTEM}_inflight_open", None),
    "inflight_oldest_seconds": (f"{_SUBSYSTEM}_inflight_oldest_seconds",
                                None),
    "admission_pending_depth": (f"{_SUBSYSTEM}_admission_pending_depth",
                                None),
    "admission_pending_bytes": (f"{_SUBSYSTEM}_admission_pending_bytes",
                                None),
    "slo_compliance": (f"{_SUBSYSTEM}_slo_compliance", "slo"),
    # tuple label spec: one label per key component (slo, window)
    "slo_burn_rate": (f"{_SUBSYSTEM}_slo_burn_rate", ("slo", "window")),
}
_EXPO_COUNTERS = {
    "attempts": (f"{_SUBSYSTEM}_schedule_attempts_total", "result"),
    "preemption_attempts": (f"{_SUBSYSTEM}_total_preemption_attempts",
                            None),
    "unschedule_jobs": (f"{_SUBSYSTEM}_unschedule_job_count", None),
    "action_failures": (f"{_SUBSYSTEM}_action_failures_total", "action"),
    "solver_fallback": (f"{_SUBSYSTEM}_solver_fallback_total", "action"),
    "resync_dead_letter": (f"{_SUBSYSTEM}_resync_dead_letter_total", "op"),
    "snapshot_full_rebuilds": (
        f"{_SUBSYSTEM}_snapshot_full_rebuilds_total", "layer"),
    "state_drift": (f"{_SUBSYSTEM}_state_drift_total", "layer"),
    "journal_replayed": (f"{_SUBSYSTEM}_journal_replayed_total", "result"),
    "device_faults": (f"{_SUBSYSTEM}_device_faults_total", "kind"),
    "device_degraded_cycles": (
        f"{_SUBSYSTEM}_device_degraded_cycles_total", None),
    "gang_growths": (f"{_SUBSYSTEM}_gang_growths_total", None),
    "gang_shrinks": (f"{_SUBSYSTEM}_gang_shrinks_total", "reason"),
    "elastic_below_min_evictions": (
        f"{_SUBSYSTEM}_elastic_below_min_evictions_total", None),
    "fencing_rejections": (f"{_SUBSYSTEM}_fencing_rejections_total", "op"),
    "failovers": (f"{_SUBSYSTEM}_failovers_total", None),
    "cross_partition_reserves": (
        f"{_SUBSYSTEM}_cross_partition_reserves_total", "result"),
    "speculation": (f"{_SUBSYSTEM}_speculation_total", "outcome"),
    "fast_admit_gangs": (f"{_SUBSYSTEM}_fast_admit_gangs_total", None),
    "fast_admit_binds": (f"{_SUBSYSTEM}_fast_admit_binds_total", None),
    # tuple label specs render one label per key component (the
    # two-label store series of docs/robustness.md's store failure model)
    "store_retries": (f"{_SUBSYSTEM}_store_retries_total",
                      ("verb", "result")),
    "store_faults": (f"{_SUBSYSTEM}_store_faults_total", ("verb", "kind")),
    "store_watch_resumes": (f"{_SUBSYSTEM}_store_watch_resumes_total",
                            "outcome"),
    "inflight_expired": (f"{_SUBSYSTEM}_inflight_expired_total",
                         ("op", "resolution")),
    "ack_faults": (f"{_SUBSYSTEM}_ack_faults_total", "kind"),
    "feedback_acks": (f"{_SUBSYSTEM}_feedback_acks_total",
                      ("kind", "verdict")),
    "cycle_budget_exhausted": (
        f"{_SUBSYSTEM}_cycle_budget_exhausted_total", "action"),
    "deferred_actions": (f"{_SUBSYSTEM}_deferred_actions_total", None),
    "admission_backpressure": (
        f"{_SUBSYSTEM}_admission_backpressure_total", "reason"),
    "dead_letter_evicted": (f"{_SUBSYSTEM}_dead_letter_evicted_total",
                            None),
    "audit_latest_evicted": (f"{_SUBSYSTEM}_audit_latest_evicted_total",
                             None),
    "rebalance_moves": (f"{_SUBSYSTEM}_rebalance_moves_total", "result"),
    "partition_splits": (f"{_SUBSYSTEM}_partition_splits_total", "result"),
    "partition_merges": (f"{_SUBSYSTEM}_partition_merges_total", "result"),
}
# duration-series key -> (family, label name, unit suffix already in name)
_EXPO_DURATIONS = {
    "e2e": (f"{_SUBSYSTEM}_e2e_scheduling_latency_milliseconds", None),
    "task": (f"{_SUBSYSTEM}_task_scheduling_latency_milliseconds", None),
    "action": (f"{_SUBSYSTEM}_action_scheduling_latency_microseconds",
               "action"),
    "plugin": (f"{_SUBSYSTEM}_plugin_scheduling_latency_microseconds",
               "plugin"),
    "admission_batch": (f"{_SUBSYSTEM}_admission_batch_size", None),
}


def _expo_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _expo_name(raw: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in str(raw))
    return out if not out[:1].isdigit() else "_" + out


def fallback_exposition() -> bytes:
    """Valid Prometheus text exposition (version 0.0.4) rendered from the
    in-process mirror — what /metrics serves when prometheus_client is
    not installed. Scrapers and the prometheus text parser read it like
    the real thing: gauges and counters sample-per-label, duration series
    as summary ``_count``/``_sum`` pairs (all-time, truncation-immune)."""
    families: Dict[str, list] = {}

    def add(name: str, mtype: str, label,
            labelv, value: float,
            suffix: str = "") -> None:
        fam = families.setdefault(name, [mtype])
        if isinstance(label, tuple) and labelv is not None:
            # multi-label series (e.g. store_retries{verb,result}): one
            # label per key component, padded with "" when short
            vals = list(labelv) + [""] * (len(label) - len(labelv))
            pairs = ",".join(f'{ln}="{_expo_escape(lv)}"'
                             for ln, lv in zip(label, vals))
            fam.append(f"{name}{suffix}{{{pairs}}} {float(value)}")
        elif label is not None and labelv is not None:
            fam.append(f'{name}{suffix}{{{label}="{_expo_escape(labelv)}"}}'
                       f" {float(value)}")
        else:
            fam.append(f"{name}{suffix} {float(value)}")

    with _lock:
        for key, value in sorted(_gauges.items(), key=str):
            spec = _EXPO_GAUGES.get(key[0])
            if spec is None:
                name = f"{_SUBSYSTEM}_{_expo_name(key[0])}"
                label, labelv = ("key", ":".join(key[1:])) \
                    if len(key) > 1 else (None, None)
            elif isinstance(spec[1], tuple):
                # multi-label gauge (e.g. slo_burn_rate{slo,window})
                name, label = spec
                labelv = tuple(key[1:]) if len(key) > 1 else None
            else:
                name, label = spec
                labelv = key[1] if label is not None and len(key) > 1 \
                    else None
            add(name, "gauge", label, labelv, value)
        for key, value in sorted(_counters.items(), key=str):
            spec = _EXPO_COUNTERS.get(key[0])
            if spec is None:
                name = f"{_SUBSYSTEM}_{_expo_name(key[0])}_total"
                label, labelv = ("key", ":".join(key[1:])) \
                    if len(key) > 1 else (None, None)
            elif isinstance(spec[1], tuple):
                name, label = spec
                labelv = tuple(key[1:]) if len(key) > 1 else None
            else:
                name, label = spec
                labelv = key[1] if label is not None and len(key) > 1 \
                    else None
            add(name, "counter", label, labelv, value)
        for key, series in sorted(_durations.items(), key=str):
            spec = _EXPO_DURATIONS.get(key[0])
            if spec is None:
                name = f"{_SUBSYSTEM}_{_expo_name(key[0])}_duration"
                label = "key" if len(key) > 1 else None
            else:
                name, label = spec
            labelv = ":".join(key[1:]) if label is not None and len(key) > 1 \
                else None
            add(name, "summary", label, labelv, series.count,
                suffix="_count")
            add(name, "summary", label, labelv, series.total, suffix="_sum")

    lines = []
    for name, fam in families.items():
        lines.append(f"# HELP {name} volcano_tpu in-process mirror")
        lines.append(f"# TYPE {name} {fam[0]}")
        lines.extend(fam[1:])
    return ("\n".join(lines) + "\n").encode()


def start_metrics_server(port: int = 8080, host: str = ""):
    """Serve /metrics (Prometheus exposition), /healthz, and the flight
    recorder's /debug endpoints — the --listen-address endpoint of
    cmd/scheduler/app (options.go:32,94).

    /healthz answers 200 "ok" while the shell is healthy and 503
    "degraded (N consecutive failed cycles)" once the crash-loop guard
    trips, so a liveness probe can distinguish slow from crash-looping.

    /debug/traces serves the recorder's Chrome trace-event JSON ring
    (perfetto-loadable); /debug/why?job=NAME serves the timeline-backed
    decision explanation for a gang; /debug/timeline?job=NAME serves its
    full retained lifecycle timeline (docs/observability.md). Returns
    the http.server instance (daemon thread)."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            import json
            status = 200
            if self.path.startswith("/healthz"):
                state, fails = health()
                if state != HEALTHY:
                    status = 503
                if "detail" in self.path:
                    body = json.dumps(health_detail(),
                                      sort_keys=True).encode()
                    ctype = "application/json"
                else:
                    if state == HEALTHY:
                        body = b"ok"
                    else:
                        body = (f"degraded ({fails} consecutive failed "
                                f"cycles)").encode()
                    ctype = "text/plain"
            elif self.path.startswith("/metrics"):
                if _HAVE_PROM:
                    from prometheus_client import (CONTENT_TYPE_LATEST,
                                                   generate_latest)
                    body = generate_latest()
                    ctype = CONTENT_TYPE_LATEST
                else:
                    body = fallback_exposition()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.startswith("/debug/traces"):
                from ..obs import TRACE
                body = TRACE.dump().encode()
                ctype = "application/json"
            elif self.path.startswith("/debug/timeline"):
                from urllib.parse import parse_qs, urlparse
                from ..obs import TIMELINE
                ctype = "application/json"
                q = parse_qs(urlparse(self.path).query)
                job = (q.get("job") or [None])[0]
                if not job:
                    status = 400
                    body = json.dumps(
                        {"error": "missing ?job= query parameter"}).encode()
                else:
                    tl = TIMELINE.timeline(job)
                    if tl is None:
                        status = 404
                        body = json.dumps(
                            {"error": f"no timeline retained for job "
                                      f"{job!r}",
                             "jobs_retained":
                                 TIMELINE.job_count()}).encode()
                    else:
                        body = json.dumps(tl, sort_keys=True).encode()
            elif self.path.startswith("/debug/why"):
                from urllib.parse import parse_qs, urlparse
                from ..obs import AUDIT
                from ..obs.lifecycle import why as timeline_why
                ctype = "application/json"
                q = parse_qs(urlparse(self.path).query)
                job = (q.get("job") or [None])[0]
                if not job:
                    status = 400
                    body = json.dumps(
                        {"error": "missing ?job= query parameter"}).encode()
                else:
                    # timeline-backed: the audit ring's verdict extended
                    # with causal history the ring ages out of, so a gang
                    # denied 200 cycles ago still explains itself
                    rec = timeline_why(job)
                    if rec is None:
                        status = 404
                        body = json.dumps(
                            {"error": f"no decision recorded for job "
                                      f"{job!r} in the retained window",
                             "cycles_retained":
                                 AUDIT.cycles_retained()}).encode()
                    else:
                        body = json.dumps(rec, sort_keys=True).encode()
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="vc-metrics").start()
    return server


def solver_trace(name: str):
    """JAX profiler hook around a device solve (SURVEY §5.1): a
    StepTraceAnnotation so the solve shows up as a named step in a
    `jax.profiler` trace. Enabled by VOLCANO_TPU_JAX_PROFILE=1; with
    VOLCANO_TPU_JAX_PROFILE_DIR set, the first annotated solve also starts
    a trace capture into that directory (stopped at interpreter exit)."""
    import contextlib
    import os
    if not os.environ.get("VOLCANO_TPU_JAX_PROFILE"):
        return contextlib.nullcontext()
    import jax
    trace_dir = os.environ.get("VOLCANO_TPU_JAX_PROFILE_DIR")
    global _trace_started
    start = False
    with _lock:
        # check-and-set under the module lock (vlint VT007): two threads'
        # first annotated solves must not both start a capture
        if trace_dir and not _trace_started:
            _trace_started = True
            start = True
    if start:
        import atexit
        jax.profiler.start_trace(trace_dir)
        atexit.register(jax.profiler.stop_trace)
    return jax.profiler.StepTraceAnnotation(name)


_trace_started = False


def update_action_duration(action: str, seconds: float) -> None:
    with _lock:
        _durations[("action", action)].observe(seconds * 1e6)
    if _HAVE_PROM:
        _action.labels(action=action).observe(seconds * 1e6)


def update_plugin_duration(plugin: str, event: str, seconds: float) -> None:
    with _lock:
        _durations[("plugin", plugin, event)].observe(seconds * 1e6)
    if _HAVE_PROM:
        _plugin.labels(plugin=plugin, OnSession=event).observe(seconds * 1e6)


def update_task_schedule_duration(seconds: float) -> None:
    with _lock:
        _durations[("task",)].observe(seconds * 1e3)
    if _HAVE_PROM:
        _task_lat.observe(seconds * 1e3)


def register_schedule_attempt(result: str) -> None:
    with _lock:
        _counters[("attempts", result)] += 1
    if _HAVE_PROM:
        _attempts.labels(result=result).inc()


def update_preemption_victims(count: int) -> None:
    with _lock:
        _gauges[("preemption_victims",)] = count
    if _HAVE_PROM:
        _preempt_victims.set(count)


def register_preemption_attempt(n: int = 1) -> None:
    with _lock:
        _counters[("preemption_attempts",)] += n
    if _HAVE_PROM:
        _preempt_total.inc(n)


def update_unschedule_task_count(job_id: str, count: int) -> None:
    with _lock:
        _gauges[("unschedule_tasks", job_id)] = count
    if _HAVE_PROM:
        _unsched_tasks.labels(job_id=job_id).set(count)


def register_unschedule_job() -> None:
    with _lock:
        _counters[("unschedule_jobs",)] += 1
    if _HAVE_PROM:
        _unsched_jobs.inc()


def update_queue_metrics(name: str, allocated_mcpu: float, allocated_mem: float,
                         deserved_mcpu: float = 0.0, deserved_mem: float = 0.0,
                         share: float = 0.0, weight: float = 1.0) -> None:
    with _lock:
        _gauges[("queue_allocated", name)] = allocated_mcpu
        _gauges[("queue_share", name)] = share
    if _HAVE_PROM:
        _q_alloc.labels(queue_name=name).set(allocated_mcpu)
        _q_alloc_mem.labels(queue_name=name).set(allocated_mem)
        _q_deserved.labels(queue_name=name).set(deserved_mcpu)
        _q_deserved_mem.labels(queue_name=name).set(deserved_mem)
        _q_share.labels(queue_name=name).set(share)
        _q_weight.labels(queue_name=name).set(weight)


def serve(port: int = 8080) -> None:
    """Expose /metrics like cmd/scheduler --listen-address (options.go:32,94)."""
    if _HAVE_PROM:
        start_http_server(port)


def local_durations() -> Dict[Tuple[str, ...], list]:
    """The retained window of every duration series (ring-bounded: at most
    the newest VOLCANO_TPU_METRICS_RING observations each)."""
    with _lock:
        return {k: list(v.data) for k, v in _durations.items()}


def local_counters() -> Dict[Tuple[str, ...], float]:
    with _lock:
        return dict(_counters)


def durations_mark() -> Dict[Tuple[str, ...], int]:
    """Snapshot the ALL-TIME observation count of every duration series.
    Pair with durations_since to read only the observations recorded after
    the mark — how the simulator (volcano_tpu/sim) and bench.py attribute
    per-action latency to one run without resetting the global recorder
    under other consumers. Marks are counts, not list indices, so ring
    truncation between mark and read cannot misattribute old samples."""
    with _lock:
        return {k: v.count for k, v in _durations.items()}


def durations_since(mark: Dict[Tuple[str, ...], int]
                    ) -> Dict[Tuple[str, ...], list]:
    """Every duration series' observations recorded after ``mark``
    (series born since the mark are returned whole). Units are as stored:
    ms for ("e2e",)/("task",), us for ("action", name)/("plugin", ...).
    If more observations arrived since the mark than the ring retains,
    the surviving (newest) ones are returned — never pre-mark samples."""
    with _lock:
        out: Dict[Tuple[str, ...], list] = {}
        for k, v in _durations.items():
            new = v.count - mark.get(k, 0)
            if new <= 0:
                out[k] = []
            else:
                data = list(v.data)
                out[k] = data[-new:] if new < len(data) else data
        return out


def reset_local() -> None:
    with _lock:
        _durations.clear()
        _gauges.clear()
        _counters.clear()
        _health_detail.clear()
        _health["state"] = HEALTHY
        _health["consecutive_failures"] = 0
