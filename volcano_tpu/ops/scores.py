"""Node-order scoring as vectorized array expressions.

Each scorer maps (task request ``f32[R]`` or batch ``f32[T,R]``, node state
``f32[N,R]``) → ``f32[N]``/``f32[T,N]``. These replace the per-(task,node)
callback scorers of the reference:

- binpack       /root/reference/pkg/scheduler/plugins/binpack/binpack.go:196-260
- least/most    k8s noderesources plugins wrapped by
                /root/reference/pkg/scheduler/plugins/nodeorder/nodeorder.go:179-269
- balanced      k8s NodeResourcesBalancedAllocation (same wrap)

All scorers are pure and state comes in as arguments, so the placement scan
can re-evaluate them as node usage mutates — the array analogue of the
EventHandler-driven cache updates in the reference.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from .dense import safe_div

MAX_NODE_SCORE = 100.0


class ScoreWeights(NamedTuple):
    """Static weights for the dynamic (state-dependent) scorers.

    binpack_res: f32[R] per-resource binpack weights (binpack.go:89-155;
    defaults cpu=1, memory=1, others 0 unless configured).
    """

    binpack_weight: float = 1.0
    binpack_res: jnp.ndarray = None            # f32[R]
    least_req_weight: float = 1.0
    most_req_weight: float = 0.0
    balanced_weight: float = 1.0


def binpack_score(req: jnp.ndarray, used: jnp.ndarray, allocatable: jnp.ndarray,
                  res_weights: jnp.ndarray, plugin_weight: float) -> jnp.ndarray:
    """Best-fit score (BinPackingScore, binpack.go:196-260).

    req: f32[R] (one task) or f32[T,R]; used/allocatable: f32[N,R];
    res_weights: f32[R]. Returns f32[N] or f32[T,N].

    Per resource r with request>0 and weight>0:
      score_r = (used_r + req_r) * w_r / allocatable_r   (0 if would overflow)
    total = sum_r score_r / sum_r w_r * 100 * plugin_weight
    """
    req_b = req[..., None, :]                      # [..., 1, R]
    used_finally = used + req_b                    # [..., N, R]
    active = (req_b > 0) & (res_weights > 0)       # dims that participate
    fits = used_finally <= allocatable             # inclusive (binpack.go:253)
    per_res = jnp.where(active & fits & (allocatable > 0),
                        safe_div(used_finally * res_weights, allocatable), 0.0)
    weight_sum = jnp.sum(jnp.where(req_b > 0, res_weights, 0.0), axis=-1)
    score = safe_div(jnp.sum(per_res, axis=-1), weight_sum)
    return score * MAX_NODE_SCORE * plugin_weight


def least_allocated_score(req: jnp.ndarray, used: jnp.ndarray,
                          allocatable: jnp.ndarray) -> jnp.ndarray:
    """k8s NodeResourcesLeastAllocated with cpu/memory weight 50/50
    (nodeorder.go:179-190): mean over {cpu,mem} of
    (alloc - used - req) * 100 / alloc."""
    req_b = req[..., None, :]
    frac = safe_div(allocatable - used - req_b, allocatable)
    frac = jnp.clip(frac, 0.0, 1.0)
    return jnp.mean(frac[..., :2], axis=-1) * MAX_NODE_SCORE


def most_allocated_score(req: jnp.ndarray, used: jnp.ndarray,
                         allocatable: jnp.ndarray) -> jnp.ndarray:
    """k8s NodeResourcesMostAllocated, cpu/mem weights 1/1 (nodeorder.go:195-202)."""
    req_b = req[..., None, :]
    frac = safe_div(used + req_b, allocatable)
    frac = jnp.where(frac > 1.0, 0.0, frac)        # over-capacity scores 0
    return jnp.mean(frac[..., :2], axis=-1) * MAX_NODE_SCORE


def balanced_allocation_score(req: jnp.ndarray, used: jnp.ndarray,
                              allocatable: jnp.ndarray) -> jnp.ndarray:
    """k8s NodeResourcesBalancedAllocation (nodeorder.go:204-206):
    (1 - std(resource fractions)) * 100 over cpu/mem."""
    req_b = req[..., None, :]
    frac = jnp.clip(safe_div(used + req_b, allocatable), 0.0, 1.0)[..., :2]
    mean = jnp.mean(frac, axis=-1, keepdims=True)
    std = jnp.sqrt(jnp.mean((frac - mean) ** 2, axis=-1))
    return (1.0 - std) * MAX_NODE_SCORE


def combined_dynamic_score(req: jnp.ndarray, used: jnp.ndarray,
                           allocatable: jnp.ndarray,
                           w: ScoreWeights) -> jnp.ndarray:
    """Weighted sum of all state-dependent scorers, mirroring how the session
    sums NodeOrderFn contributions (session_plugins.go NodeOrderFn)."""
    # weights may be traced scalars under jit — gate with multiplication,
    # never Python branches; XLA drops the zero-weight terms after constant
    # folding when weights are compile-time constants.
    score = binpack_score(req, used, allocatable, w.binpack_res,
                          w.binpack_weight)
    score = score + w.least_req_weight * least_allocated_score(req, used, allocatable)
    score = score + w.most_req_weight * most_allocated_score(req, used, allocatable)
    score = score + w.balanced_weight * balanced_allocation_score(req, used, allocatable)
    return score


def default_weights(num_res: int) -> ScoreWeights:
    """Default plugin weights: binpack cpu/mem = 1, others 0; nodeorder
    least=1, most=0, balanced=1 (nodeorder.go:71-138, binpack.go:89-155)."""
    res = jnp.zeros(num_res, dtype=jnp.float32).at[:2].set(1.0)
    return ScoreWeights(binpack_weight=1.0, binpack_res=res,
                        least_req_weight=1.0, most_req_weight=0.0,
                        balanced_weight=1.0)
