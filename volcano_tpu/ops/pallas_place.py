"""Pallas TPU kernel: the whole allocate loop as ONE on-chip program.

``ops/place.place_scan`` expresses the reference's sequential allocate loop
(/root/reference/pkg/scheduler/actions/allocate/allocate.go:42-277 with
Statement gang atomicity, statement.go:229-395) as a ``lax.scan``. That is
correct but pays XLA loop overhead per task: at 10k tasks the scan's serial
dimension dominates wall-clock.

This kernel removes that overhead by keeping ALL mutable node state
(idle/future_idle/used/ntasks, plus the Statement snapshot copies) resident
in VMEM scratch for the entire solve:

- layout: node state is ``f32[R_pad, N_pad]`` (resources on sublanes, nodes
  on lanes) so every per-task op is a handful of 8x128-lane VPU ops;
- grid: sequential chunks of C tasks; Pallas DMAs the next chunk's
  feasibility+static-score block ``[C, N_pad]`` into VMEM while the current
  chunk computes (automatic double buffering); VMEM scratch persists across
  the sequential TPU grid, so node state never round-trips to HBM;
- per task: fit mask vs future-idle, the dynamic scorers of ops/scores.py
  (binpack / least-allocated / most-allocated / balanced), masked argmax
  with lowest-index tie-break, allocate-vs-pipeline, gang counters;
- per job boundary: gang vote and commit/rollback by copying the saved VMEM
  snapshot back — Statement.Commit/Discard entirely on-chip.

Statically infeasible (task, node) pairs are encoded as ``NEG`` in the
static-score matrix, which fuses the ``feas`` mask and ``static_score``
inputs of place_scan into one f32 array (halves HBM traffic).

Falls back to interpret mode off-TPU so unit tests run on CPU.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import numpy as np

NEG = -1e30          # static-infeasible sentinel (avoids inf arithmetic)
NEG_TEST = -1e29     # anything below this is infeasible
NO_NODE = -1

# out_flags bits
F_PLACE = 1
F_PIPE = 2
F_READY = 4
F_KEEP = 8

# in flags bits
_VALID = 1
_FIRST = 2
_LAST = 4

R_PAD = 8            # resource rows (f32 sublane tile); >8 falls back to scan
LANE = 128


def _kernel(req_s, flags_s, rdy_s, keep_s, ws_s,
            ms_ref, idle0, fidle0, used0, nt0, alloc_ref, maxt_ref, rw_ref,
            out_packed, fin_state,
            t_idle, t_fidle, t_used, t_nt,
            s_idle, s_fidle, s_used, s_nt,
            cnt, row_node, row_flags):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    g = pl.program_id(0)
    C = row_node.shape[1]
    N = t_idle.shape[1]

    @pl.when(g == 0)
    def _():
        t_idle[...] = idle0[...]
        t_fidle[...] = fidle0[...]
        t_used[...] = used0[...]
        t_nt[...] = nt0[...]
        s_idle[...] = idle0[...]
        s_fidle[...] = fidle0[...]
        s_used[...] = used0[...]
        s_nt[...] = nt0[...]
        cnt[0] = 0
        cnt[1] = 0
        cnt[2] = 0

    row_node[...] = jnp.full((1, C), NO_NODE, jnp.int32)
    row_flags[...] = jnp.zeros((1, C), jnp.int32)

    lane_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    lane_c = jax.lax.broadcasted_iota(jnp.int32, (1, C), 1)

    bw = ws_s[0, 0]
    lw = ws_s[0, 1]
    mw = ws_s[0, 2]
    balw = ws_s[0, 3]

    def scal(ref, i):                    # (1,1,X) SMEM chunk-row scalar read
        return ref[0, 0, i]

    alloc = alloc_ref[...]                       # [R,N] constant per solve
    alloc_pos = alloc > 0.0
    alloc_safe = jnp.where(alloc_pos, alloc, 1.0)
    rw = rw_ref[...]
    maxt = maxt_ref[...]

    def body(i, carry):
        f = scal(flags_s, i)
        valid = (f & _VALID) != 0
        firstj = (f & _FIRST) != 0
        lastj = (f & _LAST) != 0

        # Job boundary open: Statement snapshot (statement.go:229 Allocate
        # records ops; here the undo-log is "restore the VMEM copy").
        @pl.when(firstj)
        def _():
            s_idle[...] = t_idle[...]
            s_fidle[...] = t_fidle[...]
            s_used[...] = t_used[...]
            s_nt[...] = t_nt[...]
            cnt[0] = 0
            cnt[1] = 0
            cnt[2] = 0

        attempt = jnp.logical_and(valid, cnt[2] == 0)

        @pl.when(attempt)
        def _():
            # req column: scalars from SMEM broadcast to [R,N]
            reqb = jnp.concatenate(
                [jnp.full((1, N), scal(req_s, i * R_PAD + r), jnp.float32)
                 for r in range(R_PAD)], axis=0)

            idle = t_idle[...]
            fidle = t_fidle[...]
            used = t_used[...]
            ms = ms_ref[pl.ds(i, 1), :]                       # [1,N]

            fit_fut = (jnp.all(reqb <= fidle, axis=0, keepdims=True)
                       & (ms > NEG_TEST) & (t_nt[...] < maxt))
            has = jnp.any(fit_fut)
            # reference breaks the job's task loop when nothing fits
            # (allocate.go:206-210)
            cnt[2] = jnp.where(has, cnt[2], 1)

            @pl.when(has)
            def _():
                req_pos = reqb > 0.0
                used_f = used + reqb
                # binpack (binpack.go:196-260)
                per = jnp.where(req_pos & (rw > 0.0) & (used_f <= alloc)
                                & alloc_pos,
                                used_f * rw / alloc_safe, 0.0)
                wsum = jnp.sum(jnp.where(req_pos, rw, 0.0), axis=0,
                               keepdims=True)
                binp = jnp.where(wsum > 0.0,
                                 jnp.sum(per, axis=0, keepdims=True) / wsum,
                                 0.0) * 100.0 * bw
                # least-allocated (nodeorder.go:179-190), cpu/mem rows
                frac_l = jnp.clip(jnp.where(alloc_pos,
                                            (alloc - used_f) / alloc_safe,
                                            0.0), 0.0, 1.0)
                least = jnp.mean(frac_l[0:2, :], axis=0,
                                 keepdims=True) * 100.0
                # most-allocated (nodeorder.go:195-202)
                frac_m = jnp.where(alloc_pos, used_f / alloc_safe, 0.0)
                frac_m = jnp.where(frac_m > 1.0, 0.0, frac_m)
                most = jnp.mean(frac_m[0:2, :], axis=0, keepdims=True) * 100.0
                # balanced allocation (k8s NodeResourcesBalancedAllocation)
                frac_b = jnp.clip(jnp.where(alloc_pos, used_f / alloc_safe,
                                            0.0), 0.0, 1.0)[0:2, :]
                mean_b = jnp.mean(frac_b, axis=0, keepdims=True)
                std_b = jnp.sqrt(jnp.mean((frac_b - mean_b) ** 2, axis=0,
                                          keepdims=True))
                bal = (1.0 - std_b) * 100.0

                score = ms + binp + lw * least + mw * most + balw * bal
                masked = jnp.where(fit_fut, score, NEG)
                mval = jnp.max(masked)
                best = jnp.min(jnp.where(masked == mval, lane_n, N))

                fit_idle = (jnp.all(reqb <= idle, axis=0, keepdims=True)
                            & fit_fut)
                onehot_i = (lane_n == best).astype(jnp.int32)
                do_alloc = jnp.sum(onehot_i * fit_idle.astype(jnp.int32)) > 0

                onehot = onehot_i.astype(jnp.float32)         # [1,N]
                delta = reqb * onehot                          # [R,N]
                af = jnp.where(do_alloc, 1.0, 0.0)
                t_idle[...] = idle - delta * af
                t_used[...] = used + delta * af
                # pipeline reserves future resources only (node_info.go
                # AddTask Pipelined); allocate consumes idle too
                t_fidle[...] = fidle - delta
                t_nt[...] = t_nt[...] + onehot
                cnt[0] = cnt[0] + jnp.where(do_alloc, 1, 0)
                cnt[1] = cnt[1] + jnp.where(do_alloc, 0, 1)

                here = lane_c == i
                row_node[...] = jnp.where(here, best, row_node[...])
                row_flags[...] = row_flags[...] | jnp.where(
                    here, F_PLACE + jnp.where(do_alloc, 0, F_PIPE), 0)

        # Job boundary close: gang vote (gang.go jobReadyFn) ->
        # Statement.Commit / Discard.
        @pl.when(jnp.logical_and(lastj, valid))
        def _():
            ready = cnt[0] >= scal(rdy_s, i)
            keepv = jnp.logical_or(ready, (cnt[0] + cnt[1]) >= scal(keep_s, i))
            row_flags[...] = row_flags[...] | jnp.where(
                lane_c == i,
                jnp.where(ready, F_READY, 0) | jnp.where(keepv, F_KEEP, 0),
                0)

            @pl.when(jnp.logical_not(keepv))
            def _():
                t_idle[...] = s_idle[...]
                t_fidle[...] = s_fidle[...]
                t_used[...] = s_used[...]
                t_nt[...] = s_nt[...]

        return carry

    import jax.lax
    jax.lax.fori_loop(0, C, body, 0)

    # One packed i32 per task — (node+1)<<4 | flags — so the host retrieves
    # the whole solve in a single device->host fetch (tunnel RTT ~100ms
    # dominates any payload size at these shapes).
    out_packed[0] = ((row_node[...] + 1) << 4) | row_flags[...]
    R = t_idle.shape[0]
    fin_state[0:R, :] = t_idle[...]
    fin_state[R:2 * R, :] = t_fidle[...]
    fin_state[2 * R:3 * R, :] = t_used[...]
    fin_state[3 * R:3 * R + 1, :] = t_nt[...]
    fin_state[3 * R + 1:, :] = jnp.zeros(
        (fin_state.shape[0] - 3 * R - 1, fin_state.shape[1]), jnp.float32)


def use_interpret() -> bool:
    """True when the kernel would run in (slow) interpret mode — callers use
    this to prefer the XLA scan path off-TPU."""
    import jax
    return jax.default_backend() not in ("tpu", "axon")


@functools.lru_cache(maxsize=64)
def _build(G: int, C: int, N_pad: int, interpret: bool):
    """Compile the kernel for (grid, chunk, node) bucket shapes."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T_pad = G * C
    grid = (G,)
    smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
    vmem = functools.partial(pl.BlockSpec, memory_space=pltpu.VMEM)
    full_rn = vmem((R_PAD, N_pad), lambda g: (0, 0))
    full_1n = vmem((1, N_pad), lambda g: (0, 0))
    # per-chunk scalar rows are (G, 1, X) arrays with (1, 1, X) blocks: the
    # trailing two block dims then equal the array dims, which Mosaic requires
    chunk_row = lambda X, space: space((1, 1, X), lambda g: (g, 0, 0))

    call = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            chunk_row(C * R_PAD, smem),                  # req scalars
            chunk_row(C, smem),                          # flags
            chunk_row(C, smem),                          # ready_need
            chunk_row(C, smem),                          # keep_need
            smem((1, 8), lambda g: (0, 0)),              # scorer weights
            vmem((C, N_pad), lambda g: (g, 0)),          # masked static score
            full_rn, full_rn, full_rn, full_1n,          # idle/fidle/used/nt
            full_rn,                                     # allocatable
            full_1n,                                     # max_tasks
            full_rn,                                     # binpack res weights
        ],
        out_specs=[
            chunk_row(C, vmem),                          # packed node|flags
            vmem((3 * R_PAD + 8, N_pad), lambda g: (0, 0)),  # final state
        ],
        out_shape=[
            jax.ShapeDtypeStruct((G, 1, C), jnp.int32),
            jax.ShapeDtypeStruct((3 * R_PAD + 8, N_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((R_PAD, N_pad), jnp.float32),     # tent idle
            pltpu.VMEM((R_PAD, N_pad), jnp.float32),     # tent future idle
            pltpu.VMEM((R_PAD, N_pad), jnp.float32),     # tent used
            pltpu.VMEM((1, N_pad), jnp.float32),         # tent ntasks
            pltpu.VMEM((R_PAD, N_pad), jnp.float32),     # saved idle
            pltpu.VMEM((R_PAD, N_pad), jnp.float32),     # saved future idle
            pltpu.VMEM((R_PAD, N_pad), jnp.float32),     # saved used
            pltpu.VMEM((1, N_pad), jnp.float32),         # saved ntasks
            pltpu.SMEM((4,), jnp.int32),                 # cnt_alloc/pipe/broken
            pltpu.VMEM((1, C), jnp.int32),               # out row: node
            pltpu.VMEM((1, C), jnp.int32),               # out row: flags
        ],
        interpret=interpret,
    )
    return jax.jit(call)


class PallasPlacement(NamedTuple):
    task_node: np.ndarray      # i32[T] chosen node or NO_NODE (kept jobs only)
    task_pipelined: np.ndarray  # bool[T]
    job_ready: np.ndarray      # bool[J]
    job_kept: np.ndarray       # bool[J]
    idle: np.ndarray           # f32[N,R] final committed state (None unless
    future_idle: np.ndarray    # fetch_state — each fetch is a tunnel RTT)
    used: np.ndarray
    ntasks: np.ndarray


def supported(num_resources: int, num_nodes: int) -> bool:
    """VMEM bound: ~9 [8, N] f32 buffers + one [C, N] block must fit 16MB."""
    return num_resources <= R_PAD and num_nodes <= 32768


def _grid(T: int, chunk: int) -> int:
    """Chunk count bucketing: pow2 up to 8 chunks (small solves stay small —
    40 tasks pad to 128, not 1024), then multiples of 8 (10k tasks: 80
    chunks, not the pow2 128). Distinct shapes stay ~bounded at 35 below the
    32k-task ceiling, within _build's lru_cache(64)."""
    g = max(1, -(-T // chunk))
    if g <= 8:
        return 1 << (g - 1).bit_length()
    return -(-g // 8) * 8


def padded_shape(T: int, N: int, chunk: int = 128) -> Tuple[int, int]:
    """(T_pad, N_pad) the kernel buckets (T, N) to — for callers that build
    the masked-static matrix on device."""
    return _grid(T, chunk) * chunk, -(-max(N, LANE) // LANE) * LANE


@functools.lru_cache(maxsize=16)
def neutral_masked_static(T_pad: int, N_pad: int, T: int, N: int):
    """Device-resident all-feasible/zero-score matrix with NEG padding —
    avoids shipping O(T*N) floats over PCIe/tunnel when no plugin registers
    static feasibility or score terms (the default conf)."""
    import jax.numpy as jnp
    ms = jnp.zeros((T_pad, N_pad), jnp.float32)
    ms = ms.at[:, N:].set(NEG)
    ms = ms.at[T:, :].set(NEG)
    ms.block_until_ready()
    return ms


def _invoke(idle, future_idle, used, ntasks, allocatable, max_tasks,
            req, job_ix, masked_static, min_available, base_ready,
            base_pipelined, binpack_res, binpack_weight, least_weight,
            most_weight, balanced_weight, chunk):
    """Shared input assembly + kernel dispatch of place_pallas and
    place_pallas_packed (ONE definition of padding, dtypes and the build
    cache key — what makes a committed speculative pallas solve
    byte-identical to the serial cycle's). Returns the device outputs
    ``(out_packed, fin_state, T_pad, N_pad)`` without fetching."""
    T, R = req.shape
    N = idle.shape[0]
    assert R <= R_PAD, f"{R} resource dims > {R_PAD}; use place_scan"
    G = _grid(T, chunk)
    T_pad = G * chunk
    N_pad = -(-max(N, LANE) // LANE) * LANE

    def padRN(a):                                  # [N,R] -> [R_PAD, N_pad]
        out = np.zeros((R_PAD, N_pad), np.float32)
        out[:R, :N] = a.T
        return out

    req_s = np.zeros((T_pad, R_PAD), np.float32)
    req_s[:T, :R] = req
    job_ix = np.asarray(job_ix, np.int32)
    first = np.zeros(T_pad, bool)
    last = np.zeros(T_pad, bool)
    if T:
        first[0] = True
        first[1:T] = job_ix[1:] != job_ix[:-1]
        last[:T - 1] = job_ix[1:] != job_ix[:-1]
        last[T - 1] = True
    flags = np.zeros(T_pad, np.int32)
    flags[:T] = _VALID
    flags |= first * _FIRST + last * _LAST

    rdy = np.zeros(T_pad, np.int32)
    keep = np.zeros(T_pad, np.int32)
    rdy[:T] = (min_available - base_ready)[job_ix]
    keep[:T] = (min_available - base_ready - base_pipelined)[job_ix]

    if hasattr(masked_static, "devices") \
            and masked_static.shape == (T_pad, N_pad):
        ms = masked_static          # pre-padded device array: no host traffic
    else:
        ms = np.full((T_pad, N_pad), NEG, np.float32)
        ms[:T, :N] = masked_static

    ws = np.zeros((1, 8), np.float32)
    ws[0, :4] = [binpack_weight, least_weight, most_weight, balanced_weight]
    rw = np.zeros((R_PAD, N_pad), np.float32)
    rw[:R, :N] = np.asarray(binpack_res, np.float32)[:R, None]

    nt = np.zeros((1, N_pad), np.float32)
    nt[0, :N] = ntasks
    mt = np.zeros((1, N_pad), np.float32)
    mt[0, :N] = max_tasks

    fn = _build(G, chunk, N_pad, use_interpret())
    out_packed, fin_state = fn(
        req_s.reshape(G, 1, chunk * R_PAD), flags.reshape(G, 1, chunk),
        rdy.reshape(G, 1, chunk), keep.reshape(G, 1, chunk), ws,
        ms, padRN(idle), padRN(future_idle), padRN(used), nt,
        padRN(allocatable), mt, rw)
    return out_packed, fin_state, T_pad, N_pad


def place_pallas(idle: np.ndarray, future_idle: np.ndarray, used: np.ndarray,
                 ntasks: np.ndarray, allocatable: np.ndarray,
                 max_tasks: np.ndarray,
                 req: np.ndarray, job_ix: np.ndarray,
                 masked_static: np.ndarray,
                 min_available: np.ndarray, base_ready: np.ndarray,
                 base_pipelined: np.ndarray,
                 binpack_res: np.ndarray,
                 binpack_weight: float = 1.0, least_weight: float = 1.0,
                 most_weight: float = 0.0, balanced_weight: float = 1.0,
                 chunk: int = 128, fetch_state: bool = True) -> PallasPlacement:
    """Sequential-parity placement, fully on-chip.

    idle/future_idle/used/allocatable: f32[N,R]; ntasks/max_tasks: [N];
    req: f32[T,R]; job_ix: i32[T] (tasks of a job contiguous);
    masked_static: f32[T,N] with NEG where statically infeasible;
    min_available/base_ready/base_pipelined: i32[J].
    """
    T, R = req.shape
    N = idle.shape[0]
    job_ix = np.asarray(job_ix, np.int32)
    out_packed, fin_state, T_pad, _ = _invoke(
        idle, future_idle, used, ntasks, allocatable, max_tasks, req,
        job_ix, masked_static, min_available, base_ready, base_pipelined,
        binpack_res, binpack_weight, least_weight, most_weight,
        balanced_weight, chunk)

    packed = np.asarray(out_packed).reshape(T_pad)[:T]   # the ONE fetch
    out_node = (packed >> 4) - 1
    out_flags = packed & 0xF

    J = len(min_available)
    job_ready = np.zeros(J, bool)
    job_kept = np.zeros(J, bool)
    boundary = (out_flags & (F_READY | F_KEEP)) != 0
    job_ready[job_ix[boundary]] = (out_flags[boundary] & F_READY) != 0
    job_kept[job_ix[boundary]] = (out_flags[boundary] & F_KEEP) != 0

    task_node = np.where(job_kept[job_ix] & ((out_flags & F_PLACE) != 0),
                         out_node, NO_NODE).astype(np.int32)
    pipelined = (out_flags & F_PIPE) != 0
    if fetch_state:
        st = np.asarray(fin_state)                       # one more RTT
        f_idle, f_fidle, f_used = (st[k * R_PAD:k * R_PAD + R, :N].T
                                   for k in range(3))
        f_nt = st[3 * R_PAD, :N]
    else:
        f_idle = f_fidle = f_used = f_nt = None
    return PallasPlacement(
        task_node=task_node, task_pipelined=pipelined,
        job_ready=job_ready, job_kept=job_kept,
        idle=f_idle, future_idle=f_fidle, used=f_used, ntasks=f_nt)


@functools.lru_cache(maxsize=32)
def _packed_decoder(J: int):
    """Jitted device transliteration of place_pallas's host decode into
    the unified packed wire layout. Scatter-by-boundary becomes a
    segment-sum OR: each job has exactly ONE boundary row (its last
    task), so "any boundary row with the bit set" equals the host's
    boundary-row scatter write."""
    import jax
    import jax.numpy as jnp

    # not named ``decode``: the dataflow linter resolves method calls by
    # bare name, and a local def called ``decode`` would alias
    # ``bytes.decode`` repo-wide, device-tainting every string decode
    def decode_packed_wire(packed, job_ix):
        node = (packed >> 4) - 1
        flags = packed & 0xF
        boundary = (flags & (F_READY | F_KEEP)) != 0
        ready = jax.ops.segment_sum(
            (boundary & ((flags & F_READY) != 0)).astype(jnp.int32),
            job_ix, num_segments=J) > 0
        kept = jax.ops.segment_sum(
            (boundary & ((flags & F_KEEP) != 0)).astype(jnp.int32),
            job_ix, num_segments=J) > 0
        place = kept[job_ix] & ((flags & F_PLACE) != 0)
        task_node = jnp.where(place, node, NO_NODE).astype(jnp.int32)
        pipe = (flags & F_PIPE) != 0
        return jnp.concatenate([task_node, pipe.astype(jnp.int32),
                                ready.astype(jnp.int32),
                                kept.astype(jnp.int32)])

    return jax.jit(decode_packed_wire)


def place_pallas_packed(idle: np.ndarray, future_idle: np.ndarray,
                        used: np.ndarray, ntasks: np.ndarray,
                        allocatable: np.ndarray, max_tasks: np.ndarray,
                        req: np.ndarray, job_ix: np.ndarray,
                        masked_static: np.ndarray,
                        min_available: np.ndarray, base_ready: np.ndarray,
                        base_pipelined: np.ndarray,
                        binpack_res: np.ndarray,
                        binpack_weight: float = 1.0,
                        least_weight: float = 1.0,
                        most_weight: float = 0.0,
                        balanced_weight: float = 1.0,
                        chunk: int = 128):
    """place_pallas decoded ON DEVICE into the unified single-fetch wire
    layout ``[task_node | pipelined | ready | kept]`` (i32; task spans of
    length ``padded_shape(T, N)[0]``, job spans of length J). Nothing is
    fetched here — the caller (allocate's dispatch/await split) holds the
    device array and awaits it at the commit boundary through the one
    sanctioned readback (allocate._fetch_packed), which is what lets the
    pallas kernel pipeline end-to-end on real TPU backends."""
    import jax
    T = req.shape[0]
    job_ix = np.asarray(job_ix, np.int32)
    out_packed, _, T_pad, _ = _invoke(
        idle, future_idle, used, ntasks, allocatable, max_tasks, req,
        job_ix, masked_static, min_available, base_ready, base_pipelined,
        binpack_res, binpack_weight, least_weight, most_weight,
        balanced_weight, chunk)
    # pad rows carry zero flags, so job 0 receiving them is inert
    jix = np.zeros(T_pad, np.int32)
    jix[:T] = job_ix
    return _packed_decoder(len(min_available))(
        out_packed.reshape(T_pad), jax.numpy.asarray(jix))
