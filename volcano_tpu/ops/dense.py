"""Dense resource-vector primitives shared by all device kernels.

Every resource quantity is one lane of an ``f32[..., R]`` array (lane layout
fixed by api.ResourceNames). Comparisons carry the reference's 0.1 epsilon
(resource_info.go:36,311-316): ``l <= r`` means ``l < r + 0.1``.
"""

from __future__ import annotations

import jax.numpy as jnp

# Epsilon from resource_info.go:36. `l < r or |l-r| < eps` == `l < r + eps`.
EPS = 0.1


def le_all(l: jnp.ndarray, r: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """LessEqualInAllDimension over the resource axis (resource_info.go:310)."""
    return jnp.all(l < r + EPS, axis=axis)


def le_some(l: jnp.ndarray, r: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Any dimension of l strictly below r (LessInSomeDimension)."""
    return jnp.any(l < r, axis=axis)


def is_empty(v: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """All dimensions below epsilon (resource_info.go:142-155)."""
    return jnp.all(v < EPS, axis=axis)


def safe_div(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """num/den with 0 where den == 0 (scores never divide by zero capacity)."""
    return jnp.where(den > 0, num / jnp.where(den > 0, den, 1.0), 0.0)
