"""Pure-JAX kernels: the device-side math of the scheduler."""

from .dense import EPS, is_empty, le_all, le_some, safe_div
from .scores import (ScoreWeights, balanced_allocation_score, binpack_score,
                     combined_dynamic_score, default_weights,
                     least_allocated_score, most_allocated_score)
from .place import (NO_NODE, JobMeta, NodeState, PlacementResult,
                    PlacementTasks, gang_admission, make_node_state,
                    place_scan)
from .auction import BlockTasks, place_blocks, place_blocks_packed
from .fairness import (ProportionResult, dominant_share, drf_shares,
                       proportion_deserved, queue_overused)

__all__ = [
    "EPS", "is_empty", "le_all", "le_some", "safe_div",
    "ScoreWeights", "balanced_allocation_score", "binpack_score",
    "combined_dynamic_score", "default_weights", "least_allocated_score",
    "most_allocated_score",
    "NO_NODE", "JobMeta", "NodeState", "PlacementResult", "PlacementTasks",
    "gang_admission", "make_node_state", "place_scan",
    "BlockTasks", "place_blocks", "place_blocks_packed",
    "ProportionResult", "dominant_share", "drf_shares", "proportion_deserved",
    "queue_overused",
]
