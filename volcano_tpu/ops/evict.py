"""Device-side victim selection for preempt/reclaim — "negative allocation"
over the same score matrices the allocate kernels use (SURVEY M3).

The reference's eviction hot loop is per (preemptor, node, running-task)
Python callbacks (/root/reference/pkg/scheduler/actions/preempt/
preempt.go:190-269 with the tiered Preemptable dispatch of
session_plugins.go:187-236). Here the search runs on device, including the
FULL tier semantics:

- node scores ``f32[P,N]`` are computed ONCE per action — the dynamic
  scorers (binpack/least/most/balanced) read node ``used``, which eviction
  does not change (an evicted task moves its resources to ``releasing``;
  ``used`` drops only when the pod actually terminates), so the matrix is
  exact for the whole scan;
- tier dispatch is replayed per (preemptor, node): a tier's verdict stands
  only if EVERY participating plugin returns a non-empty candidate set on
  that node; an empty set makes the tier abstain and the next tier rules
  (session_plugins.go: ``if len(candidates) == 0 { victims = nil; break }``).
  Static plugin verdicts (priority/gang guards, conformance critical pods,
  tdm windows) are host-precomputed ``[PJ,V]`` masks; the drf tier is
  DYNAMIC — job dominant shares are tracked in the scan carry exactly as
  drf's event handlers would (allocate on pipeline, deallocate on evict),
  including the within-dispatch sequential subtraction of earlier
  candidates of the same job (drf.go:308-330) via an O(V) segmented
  exclusive cumsum over a host-precomputed (node, job, candidate-order)
  permutation — not a [V,V] matmul, which dominates the scan at 5k
  victims;
- per preemptor: evictable capacity per node via one [V,R]x[V,N] einsum,
  feasibility = future_idle + evictable >= request AND at least one victim
  (validate_victims rejects empty lists), best node by argmax of the masked
  score row, victims evicted lowest-priority-first (host-presorted order)
  while the node does not yet fit — the reference's pop-until-fit loop;
- job boundaries carry gang statement semantics: snapshots on the first
  task of a job, rollback (alive mask, future_idle, shares, victim owners)
  when the job misses its pipeline quota — Statement.Commit/Discard on
  device.

The host replays the returned proposals through real Statements (gang
atomicity, plugin event handlers), so the cache/session end state is
produced by the same machinery as the callback engine.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .dense import EPS

NO_NODE = -1
BIG = 1 << 30
SHARE_DELTA = 1e-6          # plugins/drf.py SHARE_DELTA (drf.go:37)


def _share(alloc, total):
    """calculate_share (drf.go / plugins/drf.py:40-49) vectorized over the
    trailing resource dim: max over dims of alloc/total (1.0 when total==0
    but alloc>0)."""
    ratio = jnp.where(total > 0, alloc / jnp.where(total > 0, total, 1.0),
                      jnp.where(alloc > 0, 1.0, 0.0))
    return jnp.max(ratio, axis=-1)


@functools.lru_cache(maxsize=16)
def build_preempt_scan(tier_kinds: Tuple[str, ...],
                       tier_sizes: Tuple[int, ...],
                       gang_commit: bool):
    """Compile a preempt scan for one tier structure.

    tier_kinds[i] is "static" or "drf"; tier_sizes[i] is the number of
    static plugin masks in tier i (the drf tier may also carry static
    co-plugins). The returned jitted fn takes:

      (future_idle0 [N,R], vreq [V,R], vnode [V], cand_mask [PJ,V],
       tier_masks  — tuple per tier of tuples (mask [PJ,V], part [PJ]),
       preq [P,R], pjob [P], first_of_job [P], score [P,N], needed [PJ],
       vjob [V], pjg [P], jalloc0 [AJ,R], total [R],
       drf_perm [V], drf_inv [V], drf_seg [V], drf_head [V])

    where drf_perm sorts victims by (node, job, candidate-list order),
    drf_inv is its inverse, drf_seg the (node, job) segment id per sorted
    position, and drf_head the sorted position of each segment's first
    element (indexed by segment id, padded to V). Returns (task_node
    i32[P], victim_owner i32[V], job_done bool[PJ]).
    """

    def scan_fn(future_idle0, vreq, vnode, cand_mask, tier_masks,
                preq, pjob, first_of_job, score, needed,
                vjob, pjg, jalloc0, total,
                drf_perm, drf_inv, drf_seg, drf_head):
        N, R = future_idle0.shape
        V = vreq.shape[0]
        P = preq.shape[0]
        PJ = needed.shape[0]
        AJ = jalloc0.shape[0]
        fdtype = preq.dtype
        vreq_sorted = vreq[drf_perm]
        # one-hot matmuls beat segment_sum scatters on TPU by ~an order of
        # magnitude per scan step (scatter lowers to serialized updates;
        # [V,N] x [V,R] dots ride the MXU)
        node_onehot = jax.nn.one_hot(vnode, N, dtype=fdtype)       # [V,N]
        job_onehot = jax.nn.one_hot(vjob, AJ, dtype=fdtype)        # [V,AJ]

        def per_node(x):
            """reduce a [V] or [V,R] quantity onto nodes via the MXU."""
            if x.ndim == 1:
                return x @ node_onehot
            return jnp.einsum("vr,vn->nr", x, node_onehot)

        def eligibility(alive, jalloc, pj, pjg_i, req):
            """Replay the tiered dispatch for this preemptor against every
            node at once; returns the eligible-victim mask [V]."""
            cand = alive & cand_mask[pj]
            decided_n = jnp.zeros(N, bool)
            elig = jnp.zeros(V, bool)
            for kind, masks in zip(tier_kinds, tier_masks):
                tset = cand
                ok_n = jnp.ones(N, bool)
                participated = jnp.zeros((), bool)
                for m, part in masks:
                    row_on = part[pj]
                    pm = m[pj] | ~row_on
                    tset = tset & pm
                    cnt = per_node((cand & m[pj]).astype(fdtype))
                    ok_n = ok_n & ((cnt > 0) | ~row_on)
                    participated = participated | row_on
                if kind == "drf":
                    # drf.go:308-330 — subtract earlier same-job candidates
                    # (in candidate-list order) before comparing shares:
                    # segmented exclusive cumsum in (node, job, order) space
                    cs = jnp.cumsum(
                        vreq_sorted * cand[drf_perm][:, None].astype(fdtype),
                        axis=0)
                    ecs = cs - vreq_sorted \
                        * cand[drf_perm][:, None].astype(fdtype)
                    base = ecs[drf_head[drf_seg]]          # segment starts
                    prior = (ecs - base)[drf_inv]          # back to V order
                    ralloc = jalloc[vjob] - prior - vreq
                    rs = _share(ralloc, total)                   # [V]
                    ls = _share(jalloc[pjg_i] + req, total)      # scalar
                    dset = cand & ((ls < rs)
                                   | (jnp.abs(ls - rs) <= SHARE_DELTA))
                    tset = tset & dset
                    ok_n = ok_n & (per_node(dset.astype(fdtype)) > 0)
                    participated = jnp.ones((), bool)
                ok_n = ok_n & participated
                take_n = ok_n & ~decided_n
                elig = elig | (tset & take_n[vnode])
                decided_n = decided_n | ok_n
            return elig

        class Carry(NamedTuple):
            alive: jnp.ndarray
            fidle: jnp.ndarray
            jalloc: jnp.ndarray
            pipe_cnt: jnp.ndarray
            owner: jnp.ndarray
            stopped: jnp.ndarray
            s_alive: jnp.ndarray
            s_fidle: jnp.ndarray
            s_jalloc: jnp.ndarray
            s_owner: jnp.ndarray

        def step(c: Carry, xs):
            p_ix, req, pj, pjg_i, first, prev_pj = xs

            if gang_commit:
                # close the PREVIOUS job's statement: rollback on missed
                # quota (the final boundary is handled after the scan)
                failed = first & (prev_pj >= 0) & \
                    (c.pipe_cnt[prev_pj] < needed[prev_pj])
                c = c._replace(
                    alive=jnp.where(failed, c.s_alive, c.alive),
                    fidle=jnp.where(failed, c.s_fidle, c.fidle),
                    jalloc=jnp.where(failed, c.s_jalloc, c.jalloc),
                    owner=jnp.where(failed, c.s_owner, c.owner),
                    pipe_cnt=jnp.where(
                        failed, c.pipe_cnt.at[prev_pj].set(-BIG),
                        c.pipe_cnt))
                c = c._replace(
                    s_alive=jnp.where(first, c.alive, c.s_alive),
                    s_fidle=jnp.where(first, c.fidle, c.s_fidle),
                    s_jalloc=jnp.where(first, c.jalloc, c.s_jalloc),
                    s_owner=jnp.where(first, c.owner, c.s_owner))

            active = c.pipe_cnt[pj] < needed[pj]
            if not gang_commit:
                active = active & ~c.stopped[pj]

            elig = eligibility(c.alive, c.jalloc, pj, pjg_i, req)
            elig_f = elig[:, None].astype(fdtype)
            evictable = per_node(vreq * elig_f)
            # a node is only a preemption target if it hosts at least one
            # eligible victim (validate_victims rejects empty victim lists)
            has_victim = per_node(elig.astype(fdtype)) > 0
            fits = (jnp.all(req[None, :] < c.fidle + evictable + EPS,
                            axis=-1) & has_victim)
            row = jnp.where(fits, score[p_ix], -jnp.inf)
            best = jnp.argmax(row)
            ok = active & (row[best] > -jnp.inf)

            # pop-until-fit on the chosen node in host-presorted victim
            # order: victim v is evicted iff the node does not yet fit
            # before it
            on_node = (elig & (vnode == best))[:, None].astype(fdtype)
            cum_excl = jnp.cumsum(vreq * on_node, axis=0) - vreq * on_node
            fit_before = jnp.all(
                req[None, :] < c.fidle[best][None] + cum_excl + EPS, axis=-1)
            evicted = (on_node[:, 0] > 0) & ~fit_before & ok

            freed = jnp.sum(vreq * evicted[:, None].astype(fdtype), axis=0)
            delta = (freed - req) * ok.astype(fdtype)
            jalloc = c.jalloc - jnp.einsum(
                "vr,vj->jr", vreq * evicted[:, None].astype(fdtype),
                job_onehot)
            jalloc = jalloc.at[pjg_i].add(req * ok.astype(fdtype))
            c = c._replace(
                fidle=c.fidle.at[best].add(delta),
                alive=c.alive & ~evicted,
                jalloc=jalloc,
                owner=jnp.where(evicted, p_ix, c.owner),
                pipe_cnt=c.pipe_cnt.at[pj].add(ok.astype(jnp.int32)),
                stopped=c.stopped.at[pj].set(c.stopped[pj]
                                             | (active & ~ok)))
            out_node = jnp.where(ok, best, NO_NODE).astype(jnp.int32)
            return c, out_node

        c0 = Carry(
            alive=jnp.ones(V, bool), fidle=future_idle0, jalloc=jalloc0,
            pipe_cnt=jnp.zeros(PJ, jnp.int32),
            owner=jnp.full(V, -1, jnp.int32), stopped=jnp.zeros(PJ, bool),
            s_alive=jnp.ones(V, bool), s_fidle=future_idle0,
            s_jalloc=jalloc0, s_owner=jnp.full(V, -1, jnp.int32))

        prev_pj = jnp.concatenate([jnp.full(1, -1, jnp.int32), pjob[:-1]])
        xs = (jnp.arange(P), preq, pjob, pjg, first_of_job, prev_pj)
        c, task_node = jax.lax.scan(step, c0, xs)

        if gang_commit:
            last_pj = pjob[-1]
            failed = c.pipe_cnt[last_pj] < needed[last_pj]
            c = c._replace(
                alive=jnp.where(failed, c.s_alive, c.alive),
                owner=jnp.where(failed, c.s_owner, c.owner),
                pipe_cnt=jnp.where(failed,
                                   c.pipe_cnt.at[last_pj].set(-BIG),
                                   c.pipe_cnt))

        job_done = c.pipe_cnt >= needed
        if gang_commit:
            # gang statements: only quota-met jobs keep their placements.
            # The intra-job phase commits every attempt (needed is a BIG
            # sentinel there, so this mask would wrongly discard everything).
            task_node = jnp.where(job_done[pjob], task_node, NO_NODE)
        return task_node, c.owner, job_done

    return jax.jit(scan_fn)


@functools.lru_cache(maxsize=16)
def build_reclaim_scan(tier_kinds: Tuple[str, ...],
                       tier_sizes: Tuple[int, ...]):
    """Compile a reclaim scan for one tier structure (reclaim.go:40-192).

    Node walk takes the FIRST node (index order — the reference iterates
    ssn.Nodes without scoring) where the eligible victims alone cover the
    reclaimer's request; victims are evicted until reclaimed >= resreq;
    evictions are direct (no statement rollback). Rotation quirks are
    reproduced: a job leaves its queue's rotation at its first failed task,
    and a queue leaves the action when some job ran all its tasks without a
    failure (the reference's continue paths skip the queue re-push).

    The "proportion" tier is dynamic: a victim's queue must be allocated
    above deserved in some dimension and still hold the victim's resources
    (proportion.go:246-271), with queue allocations tracked in the carry —
    evictions subtract, reclaimer pipelines add.

    Returned fn takes:
      (future_idle0 [N,R], vreq [V,R], vnode [V], cand_mask [PJ,V],
       tier_masks, preq [P,R], pjob [P], pqueue [P], last_of_job [P],
       vqueue [V], qalloc0 [Q,R], qdeserved [Q,R], n_queues static)
    and returns (task_node i32[P], victim_owner i32[V]).
    """

    def scan_fn(future_idle0, vreq, vnode, cand_mask, tier_masks,
                preq, pjob, pqueue, last_of_job, vqueue, qalloc0, qdeserved):
        N, R = future_idle0.shape
        V = vreq.shape[0]
        P = preq.shape[0]
        PJ = cand_mask.shape[0]
        Q = qalloc0.shape[0]
        fdtype = preq.dtype
        node_onehot = jax.nn.one_hot(vnode, N, dtype=fdtype)
        queue_onehot = jax.nn.one_hot(vqueue, Q, dtype=fdtype)

        def per_node(x):
            if x.ndim == 1:
                return x @ node_onehot
            return jnp.einsum("vr,vn->nr", x, node_onehot)

        def eligibility(alive, qalloc, pj):
            cand = alive & cand_mask[pj]
            decided_n = jnp.zeros(N, bool)
            elig = jnp.zeros(V, bool)
            for kind, masks in zip(tier_kinds, tier_masks):
                tset = cand
                ok_n = jnp.ones(N, bool)
                participated = jnp.zeros((), bool)
                for m, part in masks:
                    row_on = part[pj]
                    pm = m[pj] | ~row_on
                    tset = tset & pm
                    cnt = per_node((cand & m[pj]).astype(fdtype))
                    ok_n = ok_n & ((cnt > 0) | ~row_on)
                    participated = participated | row_on
                if kind == "proportion":
                    over = jnp.any(qalloc > qdeserved + EPS, axis=-1)  # [Q]
                    # skip only when allocated < resreq in EVERY dim
                    # (proportion.go: allocated.Less(reclaimee.Resreq))
                    holds = jnp.any(qalloc[vqueue] - vreq > -EPS, axis=-1)
                    pset = cand & over[vqueue] & holds
                    tset = tset & pset
                    ok_n = ok_n & (per_node(pset.astype(fdtype)) > 0)
                    participated = jnp.ones((), bool)
                ok_n = ok_n & participated
                take_n = ok_n & ~decided_n
                elig = elig | (tset & take_n[vnode])
                decided_n = decided_n | ok_n
            return elig

        def step(c, xs):
            alive, fidle, qalloc, owner, job_stop, queue_stop = c
            p_ix, req, pj, pq, last = xs

            active = ~job_stop[pj] & ~queue_stop[pq]
            elig = eligibility(alive, qalloc, pj)
            elig_f = elig[:, None].astype(fdtype)
            evictable = per_node(vreq * elig_f)
            covers = jnp.all(req[None, :] < fidle + evictable + EPS, axis=-1)
            enough = jnp.all(req[None, :] < evictable + EPS, axis=-1)
            fits = covers & enough
            best = jnp.argmax(fits)              # first feasible node
            ok = active & fits[best]

            on_node = (elig & (vnode == best))[:, None].astype(fdtype)
            cum_excl = jnp.cumsum(vreq * on_node, axis=0) - vreq * on_node
            enough_before = jnp.all(req[None, :] < cum_excl + EPS, axis=-1)
            evicted = (on_node[:, 0] > 0) & ~enough_before & ok

            freed = jnp.sum(vreq * evicted[:, None].astype(fdtype), axis=0)
            fidle = fidle.at[best].add((freed - req) * ok.astype(fdtype))
            qalloc = qalloc - jnp.einsum(
                "vr,vq->qr", vreq * evicted[:, None].astype(fdtype),
                queue_onehot)
            qalloc = qalloc.at[pq].add(req * ok.astype(fdtype))
            alive = alive & ~evicted
            owner = jnp.where(evicted, p_ix, owner)
            job_stop = job_stop.at[pj].set(job_stop[pj] | (active & ~ok))
            queue_stop = queue_stop.at[pq].set(queue_stop[pq] | (ok & last))
            out_node = jnp.where(ok, best, NO_NODE).astype(jnp.int32)
            return (alive, fidle, qalloc, owner, job_stop, queue_stop), \
                out_node

        c0 = (jnp.ones(V, bool), future_idle0, qalloc0,
              jnp.full(V, -1, jnp.int32), jnp.zeros(PJ, bool),
              jnp.zeros(Q, bool))
        xs = (jnp.arange(P), preq, pjob, pqueue, last_of_job)
        (_, _, _, owner, _, _), task_node = jax.lax.scan(step, c0, xs)
        return task_node, owner

    return jax.jit(scan_fn)
