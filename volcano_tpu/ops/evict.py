"""Device-side victim selection for preempt — "negative allocation"
over the same preference machinery the allocate kernels use (SURVEY M3).
(Reclaim has no device kernel since r4 — see actions/evict_tpu.py
_ReclaimScreener for why its rotation stays on host.)

The reference's eviction hot loop is per (preemptor, node, running-task)
Python callbacks (/root/reference/pkg/scheduler/actions/preempt/
preempt.go:190-269 with the tiered Preemptable dispatch of
session_plugins.go:187-236). Here the search runs on device, including the
FULL tier semantics, in a dense per-node victim layout:

- victims live in ``[N, W]`` node-major slots (W = max victims on any node,
  row order = host-presorted eviction order), so every per-node reduction is
  an axis-1 sum over at most W elements instead of a ``[V, N]`` one-hot
  matmul, and the pop-until-fit prefix is a W-length cumsum of the chosen
  node's row only — the v1 kernel's two ``[V, R]`` log-depth cumsums per
  step were the single largest step cost;
- tier dispatch is replayed per (preemptor, node): a tier's verdict stands
  only if EVERY participating plugin returns a non-empty candidate set on
  that node; an empty set makes the tier abstain and the next tier rules
  (session_plugins.go: ``if len(candidates) == 0 { victims = nil; break }``).
  Static plugin verdicts (priority/gang guards, conformance critical pods,
  tdm windows) are host-precomputed ``[PJ, V]`` masks pre-expanded into
  the ``[N, W]`` layout, with the CURRENT job's rows cached in the loop
  carry (refreshed at job boundaries — an in-loop dynamic row gather from
  an HBM-resident table costs ~30us of latency per iteration); the drf
  tier is DYNAMIC — job dominant shares are tracked in the carry exactly
  as drf's event handlers would (allocate on pipeline, deallocate on
  evict), including the within-dispatch sequential subtraction of earlier
  candidates of the same job (drf.go:308-330) as a broadcast-sum against
  the device-expanded ``[N, W, W]`` precedence tensor;
- **same-node runs take a cheap step.** Within one job, consecutive tasks
  with identical requests re-choose the previous node whenever it still
  fits, skipping the full dispatch: scores are static, ``fidle`` changes
  only on the chosen node, and during a same-job run every dynamic verdict
  set only *shrinks* (the preemptor's dominant share grows monotonically;
  victim jobs/queues only lose allocation; static masks are frozen), so the
  fit set can only shrink and the previous argmax remains the argmax while
  it still fits. The cheap step re-evaluates the FULL tier dispatch on the
  chosen node's row (W-sized ops), so the decision is exact, not cached.
  The shrink argument needs the dynamic tier (drf/proportion) to be the
  LAST tier — a mid-stack dynamic tier draining to zero could hand a node
  to a lower tier and *grow* its verdict; the host disables the cheap path
  (``allow_cheap=False``) for such confs. Failed attempts short-circuit the
  same way: an attempt mutates nothing, so the next identical task of the
  job re-fails without re-evaluating (phase 1; phase 2 stops the whole job
  at its first failure);
- job boundaries carry gang statement semantics: snapshots on the first
  task of a job, rollback (alive mask, future_idle, shares, victim owners)
  when the job misses its pipeline quota — Statement.Commit/Discard on
  device.

The host replays the returned proposals through real Statements (gang
atomicity, plugin event handlers), so the cache/session end state is
produced by the same machinery as the callback engine.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .dense import EPS

NO_NODE = -1
BIG = 1 << 30
SHARE_DELTA = 1e-6          # plugins/drf.py SHARE_DELTA (drf.go:37)


def _share(alloc, total):
    """calculate_share (drf.go / plugins/drf.py:40-49) vectorized over the
    trailing resource dim: max over dims of alloc/total (1.0 when total==0
    but alloc>0)."""
    ratio = jnp.where(total > 0, alloc / jnp.where(total > 0, total, 1.0),
                      jnp.where(alloc > 0, 1.0, 0.0))
    return jnp.max(ratio, axis=-1)


class EvictNW(NamedTuple):
    """Static device inputs shared by both walks (the [N, W] victim
    layout). ``vslot`` indexes the compact victim axis (V = pad sentinel,
    so per-victim tables carry one trailing pad entry)."""

    vslot: jnp.ndarray          # i32[N, W] -> victim index (V = pad)
    valid: jnp.ndarray          # bool[N, W]
    vreq: jnp.ndarray           # f32[N, W, R]
    vgroup: jnp.ndarray         # i32[N, W] victim job (preempt) / queue
    #                             (reclaim) index; pad rows point at the
    #                             zeroed extra row of the tracked table
    rank: jnp.ndarray           # i32[N, W] candidate-list rank per slot
    #                             (pads BIG) — the drf tier's
    #                             within-dispatch subtraction order; the
    #                             walk prologue expands it to the [N, W, W]
    #                             ``before`` tensor ON DEVICE, so the host
    #                             never builds or uploads the W^2 array


def _tier_eval(tier_kinds, masks_g, cand, dynamic_fn):
    """Replay the tiered dispatch over a leading node axis of any size.

    cand: bool[n, W] candidates (alive & per-job candidate mask & valid).
    dynamic_fn(cand_x) -> bool[n, W] dynamic verdict (drf share compare /
    proportion over-deserved) or None when the conf has no dynamic tier.
    Returns (elig bool[n, W], dyn_decided bool[n] — node was ruled by a
    tier containing the dynamic plugin; feeds the fill expiry cap —
    dyn_extra, the dynamic plugin's side data: drf returns the victim
    shares rs f32[n, W], else None).
    """
    n = cand.shape[0]
    decided = jnp.zeros(n, bool)
    dyn_decided = jnp.zeros(n, bool)
    dyn_extra = None
    elig = jnp.zeros_like(cand)
    for kind, (m_nw, part) in zip(tier_kinds, masks_g):
        Mt = m_nw.shape[0]
        if Mt:
            pm = m_nw | ~part[:, None, None]
            tset = cand & jnp.all(pm, axis=0)
            cnt = jnp.sum(cand[None] & m_nw, axis=-1)          # [Mt, n]
            ok_n = jnp.all((cnt > 0) | ~part[:, None], axis=0)  # [n]
            participated = jnp.any(part)
        else:
            tset = cand
            ok_n = jnp.ones(n, bool)
            participated = jnp.zeros((), bool)
        if kind != "static":
            dset, dyn_extra = dynamic_fn(cand)
            tset = tset & dset
            ok_n = ok_n & (jnp.sum(dset, axis=-1) > 0)
            participated = jnp.ones((), bool)
        ok_n = ok_n & participated
        take = ok_n & ~decided
        elig = elig | (tset & take[:, None])
        if kind != "static":
            dyn_decided = dyn_decided | take
        decided = decided | ok_n
    return elig, dyn_decided, dyn_extra


def expand_before(nw: EvictNW) -> jnp.ndarray:
    """f32[N, W, W] before[n, u, w] = 1 iff slot u shares w's group and
    precedes it in candidate-list order — computed once per walk call from
    the [N, W] rank/group tables (never uploaded: the host would otherwise
    ship an O(N*W^2) array that blows up on skewed victim distributions)."""
    same_g = nw.vgroup[:, :, None] == nw.vgroup[:, None, :]
    earlier = nw.rank[:, :, None] < nw.rank[:, None, :]
    return (same_g & earlier & nw.valid[:, :, None]).astype(jnp.float32)


def _drf_dynamic(nw: EvictNW, before, jalloc, total, ls, rows=None):
    """drf.go:308-330 — victim stays a candidate iff the preemptor's share
    (with the task) stays <= the victim job's share after losing the victim
    and every earlier same-(node, job) candidate. The within-dispatch
    exclusive prefix is a broadcast-sum against the ``before`` precedence
    tensor: prior[n,w,r] = sum_u before[n,u,w] * cand[n,u] * vreq[n,u,r]
    — replacing the v2 kernels' sort/cumsum/unsort chain (take_along_axis
    costs ~40us per op inside a device loop). ``rows``: optional i32[n]
    node-row restriction."""
    before = before if rows is None else before[rows]
    vreq = nw.vreq if rows is None else nw.vreq[rows]
    vgroup = nw.vgroup if rows is None else nw.vgroup[rows]

    def fn(cand):
        return _drf_keep(vreq, before, vgroup, jalloc, total, ls, cand)
    return fn


def _drf_keep(vreq, before, vgroup, jalloc, total, ls, cand):
    """The drf verdict core over a leading node axis of any size —
    SHARED by the full dispatch and the walk's carry-cached row path so
    the keep-rule can never diverge between them."""
    masked = vreq * cand[..., None]
    # explicit broadcast-sum, NOT a matmul: einsum would go through
    # the MXU (bf16 by default — verdict flips vs the f64 comparator;
    # HIGHEST fixes that but costs ~100us per walk iteration at these
    # tiny shapes). The [n, W, W, R] product is ~150k elements, the
    # operands are gcd-scaled small integers, so pure VPU f32
    # multiply-add is both exact and fast.
    prior = jnp.sum(before[..., None] * masked[..., :, None, :], axis=-3)
    ralloc = jalloc[vgroup] - prior - vreq
    rs = _share(ralloc, total)
    return cand & ((ls < rs) | (jnp.abs(ls - rs) <= SHARE_DELTA)), rs


# fill horizon: a same-request run longer than this re-evaluates once per
# KMAX placements (the [KMAX, W] fill matrices stay tiny)
KMAX = 64


def _fill_schedule(vreq_row, fidle_b, elig_row, rs_row, dyn_dec_b, req,
                   jalloc_p, total, run_left_i, quota_left, has_drf):
    """Closed-form schedule for a whole same-node run — WITH evictions.

    Attempt m of a run places the m-th identical task on the node,
    evicting the minimal row-order prefix of the eligible victims that
    makes it fit (the serial pop-until-fit). Because evictions within the
    run only remove row-order prefixes of a FIXED eligible set, the whole
    schedule is closed-form: victim w (exclusive eligible-prefix capacity
    ``cum_w``) is first wanted at

        t_w = 1 + #{m: all_d(m*r_d < fidle_d + cum_w_d + EPS)}

    and the run length k is the minimum of:
      - k_cap: attempts for which even ALL eligible capacity fits the
        cumulative demand;
      - k_hv: attempts with >=1 eligible unevicted victim at their start
        (has_victim; drf-ruled nodes also drop victims whose share expires
        at m_v, from the monotone ls_m = share(jalloc_p + m*req));
      - k_exp (drf-ruled): the first expiry of an UNEVICTED victim — from
        there the eligible prefix shifts and the schedule is stale;
      - the quota and same-request run length.

    A tier-flip cap is NOT needed: every eligible victim is a member of
    every participating mask of the deciding tier (tset = cand & all
    masks), so a participating mask can only drain after the last
    eligible victim is gone — at which point k_hv has already ended the
    run. Everything after attempt k re-evaluates serially, so truncation
    only costs speed, never exactness. Returns (k i32, evicted bool[W],
    t_w i32[W], K+1 = never wanted)."""
    K = KMAX
    fdtype = req.dtype
    elig_f = elig_row[:, None].astype(fdtype)
    masked = vreq_row * elig_f
    cum_excl = jnp.cumsum(masked, axis=0) - masked           # [W, R]
    cum_total = jnp.sum(masked, axis=0)                      # [R]
    m_req = (jnp.arange(1, K + 1, dtype=fdtype)[:, None]
             * req[None, :])                                 # [K, R]
    m_idx = jnp.arange(1, K + 1, dtype=jnp.int32)
    fit_kw = jnp.all(m_req[:, None, :] < fidle_b[None, None, :]
                     + cum_excl[None, :, :] + EPS, axis=-1)  # [K, W]
    t_w = (1 + jnp.sum(fit_kw.astype(jnp.int32), axis=0))    # [W]
    k_cap = jnp.sum(jnp.all(m_req < fidle_b[None, :] + cum_total[None, :]
                            + EPS, axis=-1).astype(jnp.int32))

    unevicted_km = elig_row[None, :] & (t_w[None, :] >= m_idx[:, None])
    if has_drf:
        ls_vec = _share(jalloc_p[None, :] + m_req, total)    # [K]
        m_v = jnp.sum((ls_vec[:, None] <= rs_row[None, :] + SHARE_DELTA)
                      .astype(jnp.int32), axis=0)            # [W]
        k_exp = jnp.min(jnp.where(elig_row & (m_v < t_w), m_v, K))
        k_exp = jnp.where(dyn_dec_b, k_exp, K).astype(jnp.int32)
        hv_dyn = jnp.sum((unevicted_km
                          & (m_v[None, :] >= m_idx[:, None]))
                         .astype(jnp.int32), axis=1) > 0
        hv_static = jnp.sum(unevicted_km.astype(jnp.int32), axis=1) > 0
        hv_ok = jnp.where(dyn_dec_b, hv_dyn, hv_static)      # [K]
    else:
        k_exp = jnp.asarray(K, jnp.int32)
        hv_ok = jnp.sum(unevicted_km.astype(jnp.int32), axis=1) > 0
    k_hv = jnp.sum(jnp.cumprod(hv_ok.astype(jnp.int32)))

    k = jnp.minimum(jnp.minimum(k_cap, k_hv), k_exp)
    k = jnp.minimum(k, jnp.minimum(run_left_i, quota_left))
    k = jnp.clip(k, 0, K).astype(jnp.int32)
    evicted = elig_row & (t_w <= k)
    return k, evicted, t_w


@functools.lru_cache(maxsize=16)
def build_preempt_walk(tier_kinds: Tuple[str, ...],
                       tier_sizes: Tuple[int, ...],
                       gang_commit: bool,
                       allow_cheap: bool = True):
    """Compile a preempt walk for one tier structure.

    tier_kinds[i] is "static" or "drf"; tier_sizes[i] is the number of
    static plugin masks in tier i (the drf tier may also carry static
    co-plugins). ``allow_cheap`` must be False when a dynamic tier is not
    the last tier (the same-node-run shortcut's monotone-shrink argument
    would not hold).

    The walk is a ``lax.while_loop`` over a TASK CURSOR, not a per-task
    scan: each iteration evaluates ONE dispatch (full or node-local cheap)
    and places a whole same-request CHUNK via the closed-form fill
    schedule, then jumps the cursor — past the chunk on success, past the
    rest of the run on failure (a failed attempt mutates nothing, so every
    identical task re-fails), past the rest of the job when its quota is
    met. Iteration count is therefore the number of dispatch evaluations
    the serial algorithm needs (~jobs x nodes-touched), not the task
    count — at 5k preemptors in ~100 same-request runs that is ~100
    device steps instead of 5k, which is what keeps the whole action
    inside the reference's 1 s cycle budget on a remote-tunnel TPU.

    Decisions are bit-identical to the per-task formulation: the fill
    schedule (``_fill_schedule``) already encoded chunk semantics for the
    scan's free-fill countdown; the walk merely stops paying for the
    pass-through steps.

    ``score_g`` carries one score row per same-request RUN (``run_id``
    indexes it) — runs are maximal stretches with identical (job, request,
    feasibility row, static score row), so the dedup is exact and the
    device never sees the [P, N] matrix."""

    def walk_fn(future_idle0, nw: EvictNW, cand_mask, tier_masks,
                preq, pjob, pjg, first_of_job, run_id, run_end, job_end,
                score_g, needed, jalloc0, total):
        N, W, R = nw.vreq.shape
        P = preq.shape[0]
        fdtype = preq.dtype
        has_drf = any(k == "drf" for k in tier_kinds)
        iota_p = jnp.arange(P, dtype=jnp.int32)
        before = expand_before(nw) if has_drf else None
        # the CURRENT job's candidate/veto rows live in the carry as
        # [N, W] expansions, refreshed only at job boundaries (~PJ times):
        # an in-loop dynamic row gather from an HBM-resident [PJ, V+1]
        # table costs ~25-35us of latency PER ITERATION on TPU. Only the
        # compact [*, PJ, V+1] tables stay resident — expanding ALL jobs
        # to [PJ, N, W] up front would blow up by N*W/(V+1) on skewed
        # victim distributions.

        class Carry(NamedTuple):
            i: jnp.ndarray           # i32[] task cursor
            last_pj: jnp.ndarray     # i32[] job of last visited task
            alive: jnp.ndarray       # bool[N, W]
            fidle: jnp.ndarray       # f32[N, R]
            jalloc: jnp.ndarray      # f32[AJ+1, R]
            pipe_cnt: jnp.ndarray    # i32[PJ]
            owner: jnp.ndarray       # i32[N, W]
            task_node: jnp.ndarray   # i32[P]
            prev_node: jnp.ndarray   # i32[]
            prev_ok: jnp.ndarray     # bool[]
            prev_rid: jnp.ndarray    # i32[] run of the last evaluation
            cur_cand: jnp.ndarray    # bool[N, W] current job's candidates
            cur_masks: tuple         # per tier ([Mt, N, W], [Mt])
            # chosen-node ROW caches (refreshed on node switches in
            # full_eval; mutated alongside the [N, *] arrays): the cheap
            # path reads ONLY these, avoiding per-iteration dynamic row
            # gathers from HBM tables. Stale values are harmless — every
            # read is gated by can_cheap, which is False whenever the run
            # or node changed.
            b_vreq: jnp.ndarray      # f32[W, R]
            b_fidle: jnp.ndarray     # f32[R]
            b_alive: jnp.ndarray     # bool[W]
            b_cand: jnp.ndarray      # bool[W]
            b_before: object         # f32[W, W] (None without a drf tier)
            b_vgroup: jnp.ndarray    # i32[W]
            b_mrow: tuple            # per tier ([Mt, 1, W], [Mt]) mask rows
            s_alive: jnp.ndarray
            s_fidle: jnp.ndarray
            s_jalloc: jnp.ndarray
            s_owner: jnp.ndarray

        def body(c: Carry) -> Carry:
            i = c.i
            req = preq[i]
            pj = pjob[i]
            pjg_i = pjg[i]
            rid = run_id[i]
            rend = run_end[i]
            jend = job_end[i]

            # job boundary: refresh the carry-cached per-job rows, and
            # (gang mode) close the previous job's statement — rollback on
            # missed quota — then snapshot for this one. Every job's first
            # task is visited: cursor jumps only land within the current
            # job or on the next job's first task.
            def job_boundary(c):
                if gang_commit:
                    prev = c.last_pj
                    failed = (prev >= 0) & \
                        (c.pipe_cnt[prev] < needed[prev])
                    c = c._replace(
                        alive=jnp.where(failed, c.s_alive, c.alive),
                        fidle=jnp.where(failed, c.s_fidle, c.fidle),
                        jalloc=jnp.where(failed, c.s_jalloc, c.jalloc),
                        owner=jnp.where(failed, c.s_owner, c.owner),
                        pipe_cnt=jnp.where(
                            failed, c.pipe_cnt.at[prev].set(-BIG),
                            c.pipe_cnt))
                    c = c._replace(s_alive=c.alive, s_fidle=c.fidle,
                                   s_jalloc=c.jalloc, s_owner=c.owner)
                return c._replace(
                    cur_cand=cand_mask[pj][nw.vslot] & nw.valid,
                    cur_masks=tuple(
                        ((stk[:, pj, :][:, nw.vslot] if stk.shape[0]
                          else jnp.zeros((0, N, W), bool)),
                         part[:, pj])
                        for stk, part in tier_masks))
            c = jax.lax.cond(first_of_job[i], job_boundary,
                             lambda c: c, c)

            def inactive_step(c):
                # quota met: every remaining task of the job is inactive
                # too — skip the whole job
                return c._replace(i=jend + 1, last_pj=pj,
                                  prev_ok=jnp.zeros((), bool))

            def active_step(c):
                ls = _share(c.jalloc[pjg_i] + req, total) if has_drf \
                    else None
                quota_left = needed[pj] - c.pipe_cnt[pj]
                run_left_i = rend - i + 1

                def dynamic_for(rows):
                    if not has_drf:
                        return lambda cand_x: (cand_x, None)
                    return _drf_dynamic(nw, before, c.jalloc, total, ls,
                                        rows=rows)

                def dynamic_row_cached(cand_w):
                    # row-restricted drf over the CARRY-CACHED node rows —
                    # no HBM row gathers (the [N, W, (W)] tables live in
                    # HBM; a dynamic row read costs ~25-35us of latency)
                    if not has_drf:
                        return cand_w, None
                    return _drf_keep(c.b_vreq, c.b_before, c.b_vgroup,
                                     c.jalloc, total, ls, cand_w)

                # row-local re-evaluation on the previous node: exact tier
                # dispatch restricted to one row, W-sized carry-cached
                # ops, computed unconditionally (it is tiny next to the
                # [N, W] dispatch) so the full dispatch is traced ONCE
                def dyn_row(cand_x):           # [1, W] -> ([1, W], extra)
                    keep, rs = dynamic_row_cached(cand_x[0])
                    return keep[None], (None if rs is None else rs[None])

                b0 = c.prev_node
                cand_b = c.b_alive & c.b_cand
                elig_b, dyn_dec_b, rs_b = _tier_eval(
                    tier_kinds, c.b_mrow, cand_b[None], dyn_row)
                elig_b = elig_b[0]
                evictable_b = jnp.sum(
                    c.b_vreq * elig_b[:, None].astype(fdtype), axis=0)
                fits_b = jnp.all(req < c.b_fidle + evictable_b
                                 + EPS) & jnp.any(elig_b)
                can_cheap = (jnp.asarray(allow_cheap) & (rid == c.prev_rid)
                             & c.prev_ok & fits_b)

                def full_eval():
                    masks_g = c.cur_masks
                    cand = c.alive & c.cur_cand
                    elig, dyn_dec, rs = _tier_eval(
                        tier_kinds, masks_g, cand, dynamic_for(None))
                    elig_f = elig.astype(fdtype)
                    evictable = jnp.sum(nw.vreq * elig_f[..., None], axis=1)
                    has_victim = jnp.any(elig, axis=1)
                    fits = (jnp.all(
                        req[None, :] < c.fidle + evictable + EPS,
                        axis=-1) & has_victim)
                    row = jnp.where(fits, score_g[rid], -jnp.inf)
                    best = jnp.argmax(row).astype(jnp.int32)
                    found = row[best] > -jnp.inf
                    # node switch: load the chosen node's rows (the only
                    # HBM row gathers on this path, ~#full_evals times)
                    return (best, found, elig[best],
                            rs[best] if has_drf else rs,
                            dyn_dec[best], nw.vreq[best], c.fidle[best],
                            c.alive[best], c.cur_cand[best],
                            before[best] if has_drf else rs,
                            nw.vgroup[best],
                            tuple((m_nw[:, best][:, None], part)
                                  for m_nw, part in c.cur_masks))

                def cheap_eval():
                    return (b0, jnp.ones((), bool), elig_b,
                            rs_b[0] if has_drf else rs_b,
                            dyn_dec_b[0], c.b_vreq, c.b_fidle,
                            c.b_alive, c.b_cand,
                            c.b_before if has_drf else rs_b,
                            c.b_vgroup, c.b_mrow)

                (best, found, elig_row, rs_row, dyn_dec_b0, b_vreq,
                 b_fidle, b_alive, b_cand, b_before, b_vgroup,
                 b_mrow) = jax.lax.cond(can_cheap, cheap_eval, full_eval)
                k, evicted, t_w = _fill_schedule(
                    b_vreq, b_fidle, elig_row, rs_row,
                    dyn_dec_b0, req, c.jalloc[pjg_i], total,
                    run_left_i, quota_left, has_drf)
                if not allow_cheap:
                    # multi-placement fills share the same exactness
                    # precondition as the same-node shortcut (dynamic tier
                    # last): a mid-stack dynamic tier could drain mid-fill
                    # and hand another node to a lower tier
                    k = jnp.minimum(k, 1)
                ok = found
                k = jnp.where(ok, jnp.maximum(k, 1), 0)
                evicted = evicted & (t_w <= k) & ok

                new_alive_row = b_alive & ~evicted

                def apply_evictions(carry):
                    alive, owner, jalloc = carry
                    AJ1 = jalloc.shape[0]
                    job_onehot = jax.nn.one_hot(b_vgroup, AJ1,
                                                dtype=fdtype)
                    jalloc = jalloc - job_onehot.T @ (
                        b_vreq * evicted[:, None].astype(fdtype))
                    alive = alive.at[best].set(new_alive_row)
                    # victims belong to the chunk step of the attempt that
                    # wanted them — the replay groups evictions per task
                    owner = owner.at[best].set(
                        jnp.where(evicted, i + t_w - 1, owner[best]))
                    freed = jnp.sum(
                        b_vreq * evicted[:, None].astype(fdtype),
                        axis=0)
                    return (alive, owner, jalloc), freed

                (alive, owner, jalloc), freed = jax.lax.cond(
                    jnp.any(evicted), apply_evictions,
                    lambda carry: (carry, jnp.zeros(R, fdtype)),
                    (c.alive, c.owner, c.jalloc))
                placed = k.astype(fdtype)
                delta = freed - req * placed
                jalloc = jalloc.at[pjg_i].add(req * placed)
                task_node = jnp.where((iota_p >= i) & (iota_p < i + k),
                                      best, c.task_node)
                # fail: the rest of the run re-fails (skip to rend+1 in
                # phase 1; phase 2 stops the whole job at first failure —
                # jobs are cursor-contiguous, so the jump IS the stop)
                fail_to = rend + 1 if gang_commit else jend + 1
                next_i = jnp.where(ok, i + k, fail_to)
                return c._replace(
                    i=next_i, last_pj=pj,
                    fidle=c.fidle.at[best].add(delta),
                    alive=alive,
                    jalloc=jalloc,
                    owner=owner,
                    task_node=task_node,
                    pipe_cnt=c.pipe_cnt.at[pj].add(k),
                    prev_node=best, prev_ok=ok, prev_rid=rid,
                    # node-row caches track the (possibly new) chosen
                    # node's post-apply state
                    b_vreq=b_vreq, b_fidle=b_fidle + delta,
                    b_alive=new_alive_row, b_cand=b_cand,
                    b_before=b_before, b_vgroup=b_vgroup, b_mrow=b_mrow)

            active = c.pipe_cnt[pj] < needed[pj]
            return jax.lax.cond(active, active_step, inactive_step, c)

        PJ = needed.shape[0]
        c0 = Carry(
            i=jnp.zeros((), jnp.int32),
            last_pj=jnp.full((), -1, jnp.int32),
            alive=jnp.ones((N, W), bool), fidle=future_idle0,
            jalloc=jalloc0, pipe_cnt=jnp.zeros(PJ, jnp.int32),
            owner=jnp.full((N, W), -1, jnp.int32),
            task_node=jnp.full(P, NO_NODE, jnp.int32),
            prev_node=jnp.zeros((), jnp.int32),
            prev_ok=jnp.zeros((), bool),
            prev_rid=jnp.full((), -1, jnp.int32),
            # overwritten at the first job boundary before any read
            cur_cand=jnp.zeros((N, W), bool),
            cur_masks=tuple(
                (jnp.zeros(stk.shape[:1] + (N, W), bool),
                 jnp.zeros(part.shape[:1], bool))
                for stk, part in tier_masks),
            b_vreq=jnp.zeros((W, R), preq.dtype),
            b_fidle=jnp.zeros(R, preq.dtype),
            b_alive=jnp.zeros(W, bool),
            b_cand=jnp.zeros(W, bool),
            b_before=(jnp.zeros((W, W), jnp.float32) if has_drf else None),
            b_vgroup=jnp.zeros(W, jnp.int32),
            b_mrow=tuple(
                (jnp.zeros(stk.shape[:1] + (1, W), bool),
                 jnp.zeros(part.shape[:1], bool))
                for stk, part in tier_masks),
            s_alive=jnp.ones((N, W), bool), s_fidle=future_idle0,
            s_jalloc=jalloc0, s_owner=jnp.full((N, W), -1, jnp.int32))

        c = jax.lax.while_loop(lambda c: c.i < P, body, c0)

        if gang_commit:
            last_pj = c.last_pj
            failed = (last_pj >= 0) & (c.pipe_cnt[last_pj] < needed[last_pj])
            c = c._replace(
                alive=jnp.where(failed, c.s_alive, c.alive),
                owner=jnp.where(failed, c.s_owner, c.owner),
                pipe_cnt=jnp.where(failed,
                                   c.pipe_cnt.at[last_pj].set(-BIG),
                                   c.pipe_cnt))

        job_done = c.pipe_cnt >= needed
        task_node = c.task_node
        if gang_commit:
            # gang statements: only quota-met jobs keep their placements.
            # The intra-job phase commits every attempt (needed is a BIG
            # sentinel there, so this mask would wrongly discard everything).
            task_node = jnp.where(job_done[pjob], task_node, NO_NODE)
        return task_node, c.owner, job_done

    return jax.jit(walk_fn)


