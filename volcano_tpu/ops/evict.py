"""Device-side victim selection for preempt — "negative allocation"
over the same preference machinery the allocate kernels use (SURVEY M3).
(Reclaim has no device kernel since r4 — see actions/evict_tpu.py
_ReclaimScreener for why its rotation stays on host.)

The reference's eviction hot loop is per (preemptor, node, running-task)
Python callbacks (/root/reference/pkg/scheduler/actions/preempt/
preempt.go:190-269 with the tiered Preemptable dispatch of
session_plugins.go:187-236). Here the search runs on device, including the
FULL tier semantics, in a dense per-node victim layout:

- victims live in ``[N, W]`` node-major slots (W = max victims on any node,
  row order = host-presorted eviction order), so every per-node reduction is
  an axis-1 sum over at most W elements instead of a ``[V, N]`` one-hot
  matmul, and the pop-until-fit prefix is a W-length cumsum of the chosen
  node's row only — the v1 kernel's two ``[V, R]`` log-depth cumsums per
  step were the single largest step cost;
- tier dispatch is replayed per (preemptor, node): a tier's verdict stands
  only if EVERY participating plugin returns a non-empty candidate set on
  that node; an empty set makes the tier abstain and the next tier rules
  (session_plugins.go: ``if len(candidates) == 0 { victims = nil; break }``).
  Static plugin verdicts (priority/gang guards, conformance critical pods,
  tdm windows) are host-precomputed ``[PJ, V]`` masks pre-expanded into
  the ``[N, W]`` layout, with the CURRENT job's rows cached in the loop
  carry (refreshed at job boundaries — an in-loop dynamic row gather from
  an HBM-resident table costs ~30us of latency per iteration); the drf
  tier is DYNAMIC — job dominant shares are tracked in the carry exactly
  as drf's event handlers would (allocate on pipeline, deallocate on
  evict), including the within-dispatch sequential subtraction of earlier
  candidates of the same job (drf.go:308-330) as a broadcast-sum against
  the device-expanded ``[N, W, W]`` precedence tensor;
- **same-node runs take a cheap step.** Within one job, consecutive tasks
  with identical requests re-choose the previous node whenever it still
  fits, skipping the full dispatch: scores are static, ``fidle`` changes
  only on the chosen node, and during a same-job run every dynamic verdict
  set only *shrinks* (the preemptor's dominant share grows monotonically;
  victim jobs/queues only lose allocation; static masks are frozen), so the
  fit set can only shrink and the previous argmax remains the argmax while
  it still fits. The cheap step re-evaluates the FULL tier dispatch on the
  chosen node's row (W-sized ops), so the decision is exact, not cached.
  The shrink argument needs the dynamic tier (drf/proportion) to be the
  LAST tier — a mid-stack dynamic tier draining to zero could hand a node
  to a lower tier and *grow* its verdict; the host disables the cheap path
  (``allow_cheap=False``) for such confs. Failed attempts short-circuit the
  same way: an attempt mutates nothing, so the next identical task of the
  job re-fails without re-evaluating (phase 1; phase 2 stops the whole job
  at its first failure);
- job boundaries carry gang statement semantics: snapshots on the first
  task of a job, rollback (alive mask, future_idle, shares, victim owners)
  when the job misses its pipeline quota — Statement.Commit/Discard on
  device.

The host replays the returned proposals through real Statements (gang
atomicity, plugin event handlers), so the cache/session end state is
produced by the same machinery as the callback engine.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .dense import EPS

NO_NODE = -1
BIG = 1 << 30
SHARE_DELTA = 1e-6          # plugins/drf.py SHARE_DELTA (drf.go:37)


def _share(alloc, total):
    """calculate_share (drf.go / plugins/drf.py:40-49) vectorized over the
    trailing resource dim: max over dims of alloc/total (1.0 when total==0
    but alloc>0)."""
    ratio = jnp.where(total > 0, alloc / jnp.where(total > 0, total, 1.0),
                      jnp.where(alloc > 0, 1.0, 0.0))
    return jnp.max(ratio, axis=-1)


class EvictNW(NamedTuple):
    """Static device inputs shared by both walks (the [N, W] victim
    layout). ``vslot`` indexes the compact victim axis (V = pad sentinel,
    so per-victim tables carry one trailing pad entry)."""

    vslot: jnp.ndarray          # i32[N, W] -> victim index (V = pad)
    valid: jnp.ndarray          # bool[N, W]
    vreq: jnp.ndarray           # f32[N, W, R]
    vgroup: jnp.ndarray         # i32[N, W] victim job (preempt) / queue
    #                             (reclaim) index; pad rows point at the
    #                             zeroed extra row of the tracked table
    rank: jnp.ndarray           # i32[N, W] candidate-list rank per slot
    #                             (pads BIG) — the drf tier's
    #                             within-dispatch subtraction order; the
    #                             walk prologue expands it to the [N, W, W]
    #                             ``before`` tensor ON DEVICE, so the host
    #                             never builds or uploads the W^2 array


def _tier_eval(tier_kinds, masks_g, cand, dynamic_fn):
    """Replay the tiered dispatch over a leading node axis of any size.

    cand: bool[n, W] candidates (alive & per-job candidate mask & valid).
    dynamic_fn(cand_x) -> bool[n, W] dynamic verdict (drf share compare /
    proportion over-deserved) or None when the conf has no dynamic tier.
    Returns (elig bool[n, W], dyn_decided bool[n] — node was ruled by a
    tier containing the dynamic plugin; feeds the fill expiry cap —
    dyn_extra, the dynamic plugin's side data: drf returns the victim
    shares rs f32[n, W], else None).
    """
    n = cand.shape[0]
    decided = jnp.zeros(n, bool)
    dyn_decided = jnp.zeros(n, bool)
    dyn_extra = None
    elig = jnp.zeros_like(cand)
    for kind, (m_nw, part) in zip(tier_kinds, masks_g):
        Mt = m_nw.shape[0]
        if Mt:
            pm = m_nw | ~part[:, None, None]
            tset = cand & jnp.all(pm, axis=0)
            cnt = jnp.sum(cand[None] & m_nw, axis=-1)          # [Mt, n]
            ok_n = jnp.all((cnt > 0) | ~part[:, None], axis=0)  # [n]
            participated = jnp.any(part)
        else:
            tset = cand
            ok_n = jnp.ones(n, bool)
            participated = jnp.zeros((), bool)
        if kind != "static":
            dset, dyn_extra = dynamic_fn(cand)
            tset = tset & dset
            ok_n = ok_n & (jnp.sum(dset, axis=-1) > 0)
            participated = jnp.ones((), bool)
        ok_n = ok_n & participated
        take = ok_n & ~decided
        elig = elig | (tset & take[:, None])
        if kind != "static":
            dyn_decided = dyn_decided | take
        decided = decided | ok_n
    return elig, dyn_decided, dyn_extra


def expand_before(nw: EvictNW) -> jnp.ndarray:
    """f32[N, W, W] before[n, u, w] = 1 iff slot u shares w's group and
    precedes it in candidate-list order — computed once per walk call from
    the [N, W] rank/group tables (never uploaded: the host would otherwise
    ship an O(N*W^2) array that blows up on skewed victim distributions)."""
    same_g = nw.vgroup[:, :, None] == nw.vgroup[:, None, :]
    earlier = nw.rank[:, :, None] < nw.rank[:, None, :]
    return (same_g & earlier & nw.valid[:, :, None]).astype(jnp.float32)


def _drf_keep(vreq, before, vgroup, jalloc, total, ls, cand):
    """The drf verdict core (drf.go:308-330) over a leading node axis of
    any size — a victim stays a candidate iff the preemptor's share (with
    the task) stays <= the victim job's share after losing the victim and
    every earlier same-(node, job) candidate, the exclusive prefix being a
    broadcast-sum against the ``before`` precedence tensor. SHARED by the
    run-entry full dispatch and the fill loop's row path so the keep-rule
    can never diverge between them."""
    masked = vreq * cand[..., None]
    # explicit broadcast-sum, NOT a matmul: einsum would go through
    # the MXU (bf16 by default — verdict flips vs the f64 comparator;
    # HIGHEST fixes that but costs ~100us per walk iteration at these
    # tiny shapes). The [n, W, W, R] product is ~150k elements, the
    # operands are gcd-scaled small integers, so pure VPU f32
    # multiply-add is both exact and fast.
    prior = jnp.sum(before[..., None] * masked[..., :, None, :], axis=-3)
    ralloc = jalloc[vgroup] - prior - vreq
    rs = _share(ralloc, total)
    return cand & ((ls < rs) | (jnp.abs(ls - rs) <= SHARE_DELTA)), rs


# fill horizon: a same-request run longer than this re-evaluates once per
# KMAX placements (the [KMAX, W] fill matrices stay tiny)
KMAX = 64


def _fill_schedule(vreq_row, fidle_b, elig_row, rs_row, dyn_dec_b, req,
                   jalloc_p, total, run_left_i, quota_left, has_drf):
    """Closed-form schedule for a whole same-node run — WITH evictions.

    Attempt m of a run places the m-th identical task on the node,
    evicting the minimal row-order prefix of the eligible victims that
    makes it fit (the serial pop-until-fit). Because evictions within the
    run only remove row-order prefixes of a FIXED eligible set, the whole
    schedule is closed-form: victim w (exclusive eligible-prefix capacity
    ``cum_w``) is first wanted at

        t_w = 1 + #{m: all_d(m*r_d < fidle_d + cum_w_d + EPS)}

    and the run length k is the minimum of:
      - k_cap: attempts for which even ALL eligible capacity fits the
        cumulative demand;
      - k_hv: attempts with >=1 eligible unevicted victim at their start
        (has_victim; drf-ruled nodes also drop victims whose share expires
        at m_v, from the monotone ls_m = share(jalloc_p + m*req));
      - k_exp (drf-ruled): the first expiry of an UNEVICTED victim — from
        there the eligible prefix shifts and the schedule is stale;
      - the quota and same-request run length.

    A tier-flip cap is NOT needed: every eligible victim is a member of
    every participating mask of the deciding tier (tset = cand & all
    masks), so a participating mask can only drain after the last
    eligible victim is gone — at which point k_hv has already ended the
    run. Everything after attempt k re-evaluates serially, so truncation
    only costs speed, never exactness. Returns (k i32, evicted bool[W],
    t_w i32[W], K+1 = never wanted)."""
    K = KMAX
    fdtype = req.dtype
    elig_f = elig_row[:, None].astype(fdtype)
    masked = vreq_row * elig_f
    cum_excl = jnp.cumsum(masked, axis=0) - masked           # [W, R]
    cum_total = jnp.sum(masked, axis=0)                      # [R]
    m_req = (jnp.arange(1, K + 1, dtype=fdtype)[:, None]
             * req[None, :])                                 # [K, R]
    m_idx = jnp.arange(1, K + 1, dtype=jnp.int32)
    fit_kw = jnp.all(m_req[:, None, :] < fidle_b[None, None, :]
                     + cum_excl[None, :, :] + EPS, axis=-1)  # [K, W]
    t_w = (1 + jnp.sum(fit_kw.astype(jnp.int32), axis=0))    # [W]
    k_cap = jnp.sum(jnp.all(m_req < fidle_b[None, :] + cum_total[None, :]
                            + EPS, axis=-1).astype(jnp.int32))

    unevicted_km = elig_row[None, :] & (t_w[None, :] >= m_idx[:, None])
    if has_drf:
        ls_vec = _share(jalloc_p[None, :] + m_req, total)    # [K]
        m_v = jnp.sum((ls_vec[:, None] <= rs_row[None, :] + SHARE_DELTA)
                      .astype(jnp.int32), axis=0)            # [W]
        k_exp = jnp.min(jnp.where(elig_row & (m_v < t_w), m_v, K))
        k_exp = jnp.where(dyn_dec_b, k_exp, K).astype(jnp.int32)
        hv_dyn = jnp.sum((unevicted_km
                          & (m_v[None, :] >= m_idx[:, None]))
                         .astype(jnp.int32), axis=1) > 0
        hv_static = jnp.sum(unevicted_km.astype(jnp.int32), axis=1) > 0
        hv_ok = jnp.where(dyn_dec_b, hv_dyn, hv_static)      # [K]
    else:
        k_exp = jnp.asarray(K, jnp.int32)
        hv_ok = jnp.sum(unevicted_km.astype(jnp.int32), axis=1) > 0
    k_hv = jnp.sum(jnp.cumprod(hv_ok.astype(jnp.int32)))

    k = jnp.minimum(jnp.minimum(k_cap, k_hv), k_exp)
    k = jnp.minimum(k, jnp.minimum(run_left_i, quota_left))
    k = jnp.clip(k, 0, K).astype(jnp.int32)
    evicted = elig_row & (t_w <= k)
    return k, evicted, t_w


@functools.lru_cache(maxsize=16)
def build_preempt_walk(tier_kinds: Tuple[str, ...],
                       tier_sizes: Tuple[int, ...],
                       gang_commit: bool,
                       allow_cheap: bool = True,
                       axis: Optional[str] = None):
    """Compile a preempt walk for one tier structure.

    tier_kinds[i] is "static" or "drf"; tier_sizes[i] is the number of
    static plugin masks in tier i (the drf tier may also carry static
    co-plugins). ``allow_cheap`` must be False when a dynamic tier is not
    the last tier (the monotone-shrink argument below would not hold);
    the fill loop then takes one dispatch-fresh placement at a time.

    The walk is a ``lax.while_loop`` over a TASK CURSOR whose iterations
    are same-request RUNS, each run processed as ONE full [N, W] tier
    dispatch followed by an inner fill loop of node-row-local steps:

    - the dispatch computes every node's eligible-victim set and a
      ``fits0`` over-approximation at the run's entry state;
    - each inner step picks the best still-alive scoring node, re-derives
      its verdict row EXACTLY at the current state (shares, evictions),
      places a chunk via the closed-form fill schedule, and applies the
      effects as one fused pack-row + one fused jstate-row scatter;
    - during a same-request run every per-node verdict set only SHRINKS
      (the preemptor's dominant share grows monotonically, victim jobs
      only lose allocation, static masks are frozen — the r4 same-node
      shortcut's argument, now covering node switches too), so a node
      whose stale ``fits0`` no longer holds yields k=0 at its row
      re-evaluation, is marked dead, and the next-best node is probed —
      exactly the node order the serial algorithm visits.

    Device latency is therefore ~#runs full dispatches plus ~#node-fills
    cheap W-sized steps — at 5k preemptors in ~100 runs over ~1.2k node
    fills that is ~100 heavy + ~1.3k light steps instead of 1.3k heavy
    ones, which is what keeps the whole action inside the reference's 1 s
    cycle budget on a remote-tunnel TPU. Decisions are bit-identical to
    the per-task formulation (tests pin eviction parity against the
    callbacks engine; preempt.go:190-269 is the loop being replaced).

    ``score_g`` carries one score row per same-request RUN (``run_id``
    indexes it) — runs are maximal stretches with identical (job, request,
    feasibility row, static score row), so the dedup is exact and the
    device never sees the [P, N] matrix.

    With ``axis`` set the SAME walk runs node-sharded under ``shard_map``
    (build_preempt_walk_sharded): every [N, ...] input/carry becomes the
    device's local shard, the per-task tables and jstate are replicated,
    and each probe adds exactly two collectives — an all_gather of the
    per-shard (score, global-id) maxima to pick the eviction node (lowest
    global index among ties, matching the unsharded argmax), and one psum
    broadcasting the owner shard's node-row bundle so every shard computes
    the identical fill schedule and jstate update (the owner alone writes
    its pack row). Decisions are bit-identical to the single-device walk;
    the gang pipeline-quota column rides the replicated jstate, so the
    psum IS the quota synchronization."""

    def walk_fn(future_idle0, nw: EvictNW, cand_mask, tier_masks,
                preq, pjob, pjg, first_of_job, run_id, run_end, job_end,
                score_g, needed, jalloc0, total):
        # ``needed`` is f32[AJ+1] keyed by ALLOC-GROUP index (pjg), not by
        # kept-job index: the pipeline quota count lives fused as the last
        # column of the jstate matrix (see Carry.jstate), and one index
        # space for both halves keeps the per-iteration update a single
        # row scatter. Pad/victim-only groups carry 0.
        N, W, R = nw.vreq.shape
        P = preq.shape[0]
        fdtype = preq.dtype
        has_drf = any(k == "drf" for k in tier_kinds)
        iota_p = jnp.arange(P, dtype=jnp.int32)
        before = expand_before(nw) if has_drf else None
        # per-task scalar tables fused into one [P, R+6] f32 matrix (all
        # values integral < 2^24, exact in f32): the body reads ONE row per
        # iteration instead of seven scalar gathers (~2-3us each of pure
        # latency per gather inside the device loop)
        tpack = jnp.concatenate([
            preq.astype(fdtype),
            jnp.stack([pjob, pjg, run_id, run_end, job_end,
                       first_of_job.astype(jnp.int32)], axis=1
                      ).astype(fdtype)], axis=1)
        # the CURRENT job's candidate/veto rows live in the carry as
        # [N, W] expansions, refreshed only at job boundaries (~PJ times):
        # an in-loop dynamic row gather from an HBM-resident [PJ, V+1]
        # table costs ~25-35us of latency PER ITERATION on TPU. Only the
        # compact [*, PJ, V+1] tables stay resident — expanding ALL jobs
        # to [PJ, N, W] up front would blow up by N*W/(V+1) on skewed
        # victim distributions.

        class Carry(NamedTuple):
            i: jnp.ndarray           # i32[] task cursor
            iters: jnp.ndarray       # i32[] loop iterations (diagnostics)
            last_g: jnp.ndarray      # i32[] alloc-group of last visited task
            # the per-node mutable state — future_idle f32[N, R], alive
            # bool-as-f32[N, W], eviction owner step f32[N, W] (exact:
            # step indices < 2^24) — lives FUSED in one [N, R+2W] matrix:
            # the walk mutates exactly one node row per iteration, and one
            # fused row scatter costs a third of three (scatter latency
            # ~12us each inside a device loop, measured on v5e)
            pack: jnp.ndarray        # f32[N, R+2W]  fidle | alive | owner
            # per-job tracked state, same fusion trick on the job axis:
            # jalloc f32[AJ+1, R] | pipeline-quota count f32[AJ+1, 1]
            # (counts are small integers, exact in f32; -BIG marks a
            # gang-rolled-back job)
            jstate: jnp.ndarray      # f32[AJ+1, R+1]
            task_node: jnp.ndarray   # i32[P]
            cur_cand: jnp.ndarray    # bool[N, W] current job's candidates
            cur_masks: tuple         # per tier ([Mt, N, W], [Mt])
            s_pack: jnp.ndarray
            s_jstate: jnp.ndarray

        def body(c: Carry) -> Carry:
            c = c._replace(iters=c.iters + 1)
            i = c.i
            trow = tpack[i]
            req = trow[:R]
            pj = trow[R].astype(jnp.int32)
            pjg_i = trow[R + 1].astype(jnp.int32)
            rid = trow[R + 2].astype(jnp.int32)
            rend = trow[R + 3].astype(jnp.int32)
            jend = trow[R + 4].astype(jnp.int32)
            first_i = trow[R + 5] > 0.5

            # job boundary: refresh the carry-cached per-job rows, and
            # (gang mode) close the previous job's statement — rollback on
            # missed quota — then snapshot for this one. Every job's first
            # task is visited: cursor jumps only land within the current
            # job or on the next job's first task.
            def job_boundary(c):
                if gang_commit:
                    prev = c.last_g
                    failed = (prev >= 0) & \
                        (c.jstate[prev, R] < needed[prev])
                    # rollback restores jalloc AND every other group's
                    # count (only prev's changed since the snapshot);
                    # prev's count then takes the -BIG failure sentinel
                    js = jnp.where(failed, c.s_jstate, c.jstate)
                    js = js.at[prev, R].set(
                        jnp.where(failed, jnp.asarray(-BIG, fdtype),
                                  js[prev, R]))
                    c = c._replace(
                        pack=jnp.where(failed, c.s_pack, c.pack),
                        jstate=js)
                    c = c._replace(s_pack=c.pack, s_jstate=c.jstate)
                return c._replace(
                    cur_cand=cand_mask[pj][nw.vslot] & nw.valid,
                    cur_masks=tuple(
                        ((stk[:, pj, :][:, nw.vslot] if stk.shape[0]
                          else jnp.zeros((0, N, W), bool)),
                         part[:, pj])
                        for stk, part in tier_masks))
            c = jax.lax.cond(first_i, job_boundary,
                             lambda c: c, c)

            def inactive_step(c):
                # quota met: every remaining task of the job is inactive
                # too — skip the whole job
                return c._replace(i=jend + 1, last_g=pjg_i)

            def active_step(c):
                run_len = rend - i + 1
                score_row = score_g[rid]             # f32[N], once per run

                # ---- ONE full dispatch at the run's entry state --------
                alive_full = c.pack[:, R:R + W] > 0.5
                cand = alive_full & c.cur_cand
                ls0 = _share(c.jstate[pjg_i, :R] + req, total) \
                    if has_drf else None
                if has_drf:
                    # within-dispatch exclusive prefix at the run's entry
                    # candidate set; for nodes the run never touches this
                    # is INVARIANT (prior changes only through evictions
                    # on the node itself), which is what makes the fill
                    # loop's global refresh below exact
                    masked0 = nw.vreq * cand[..., None].astype(fdtype)
                    prior0 = jnp.sum(
                        before[..., None] * masked0[..., :, None, :],
                        axis=-3)                         # [N, W, R]

                    def dynamic_full(cand_x):
                        ralloc = (c.jstate[:, :R][nw.vgroup]
                                  - prior0 - nw.vreq)
                        rs = _share(ralloc, total)
                        return cand_x & ((ls0 < rs)
                                         | (jnp.abs(ls0 - rs)
                                            <= SHARE_DELTA)), rs
                else:
                    prior0 = None

                    def dynamic_full(cand_x):
                        return cand_x, None

                elig0, dyn_dec0, _ = _tier_eval(
                    tier_kinds, c.cur_masks, cand, dynamic_full)
                if has_drf:
                    # the dynamic tiers' candidate set after their static
                    # co-masks, BEFORE the share verdict — the refresh
                    # re-intersects it with the current-share keep rule.
                    # ACCUMULATE across dynamic tiers: with two of them
                    # (each carrying static co-plugins) overwriting would
                    # keep only the last tier's co-masks and let the fill
                    # loop probe nodes whose extra "eligible" victims the
                    # exact row dispatch then rejects — a k=0 dead end
                    # where the serial walk would have moved on
                    drf_pre0 = cand
                    for kind, (m_nw, part) in zip(tier_kinds,
                                                  c.cur_masks):
                        if kind != "static" and m_nw.shape[0]:
                            pm = m_nw | ~part[:, None, None]
                            drf_pre0 = drf_pre0 & jnp.all(pm, axis=0)

                # ---- inner fill loop: serial node fills over the run ---
                # During a same-request run every per-node verdict set
                # only SHRINKS (the r4 same-node shortcut's monotone
                # argument: the preemptor's dominant share grows, victim
                # jobs only lose allocation, static masks are frozen), and
                # for nodes the run has NOT touched the entry prefix
                # ``prior0`` and tier cascade stay exact — so each probe
                # re-derives the CURRENT global fit picture from a handful
                # of [N, W] ops instead of the full multi-tier dispatch,
                # picks the best node, and evaluates its verdict row
                # exactly. For TOUCHED nodes (evictions change their
                # cascade and prefix) the formula under-approximates, so
                # their fitness is tracked via ``t_fit`` instead: any
                # successful fill leaves its node re-probeable (the
                # closed-form schedule is conservative — its truncation
                # never proves deadness), and only an exact k=0 probe
                # retires a node for the rest of the run. One heavy
                # dispatch per run + light probes per node fill, at
                # decisions bit-identical to the serial algorithm.

                class Fill(NamedTuple):
                    pack: jnp.ndarray
                    jstate: jnp.ndarray
                    task_node: jnp.ndarray
                    m: jnp.ndarray        # i32[] placed so far this visit
                    probes: jnp.ndarray   # i32[] inner iterations
                    touched: jnp.ndarray  # bool[N] filled/probed this run
                    t_fit: jnp.ndarray    # bool[N] exact fit for touched
                    cont: jnp.ndarray     # bool[]

                def fill_cond(s: Fill):
                    return s.cont

                def fill_body(s: Fill) -> Fill:
                    alive_cur = s.pack[:, R:R + W] > 0.5
                    if has_drf:
                        ls_cur = _share(s.jstate[pjg_i, :R] + req, total)
                        ralloc = (s.jstate[:, :R][nw.vgroup]
                                  - prior0 - nw.vreq)
                        rs_all = _share(ralloc, total)
                        keep = drf_pre0 & ((ls_cur < rs_all)
                                           | (jnp.abs(ls_cur - rs_all)
                                              <= SHARE_DELTA))
                        elig_cur = jnp.where(dyn_dec0[:, None], keep,
                                             elig0) & alive_cur
                    else:
                        elig_cur = elig0 & alive_cur
                    evictable = jnp.sum(
                        nw.vreq * elig_cur[..., None].astype(fdtype),
                        axis=1)
                    fits = (jnp.all(
                        req[None, :] < s.pack[:, :R] + evictable + EPS,
                        axis=-1) & jnp.any(elig_cur, axis=1))
                    cand_n = jnp.where(s.touched, s.t_fit, fits)
                    row = jnp.where(cand_n, score_row, -jnp.inf)
                    lbest = jnp.argmax(row).astype(jnp.int32)
                    if axis is None:
                        best = lbest             # global == local
                        li = lbest
                        found = row[lbest] > -jnp.inf
                        is_owner = jnp.ones((), bool)
                    else:
                        # global node pick: one all_gather of per-shard
                        # (score, global-id) maxima; ties resolve to the
                        # lowest global index, matching the unsharded
                        # argmax (per-shard argmax already picks the
                        # lowest local index)
                        Nl = row.shape[0]
                        off = (jax.lax.axis_index(axis) * Nl) \
                            .astype(jnp.int32)
                        all_sc = jax.lax.all_gather(row[lbest], axis)
                        all_id = jax.lax.all_gather(off + lbest, axis)
                        gmax = jnp.max(all_sc)
                        found = gmax > -jnp.inf
                        best = jnp.min(jnp.where(all_sc == gmax, all_id,
                                                 BIG)).astype(jnp.int32)
                        li = jnp.clip(best - off, 0, Nl - 1)
                        is_owner = (best >= off) & (best < off + Nl)
                    prow = s.pack[li]
                    b_vreq = nw.vreq[li]
                    b_vgroup = nw.vgroup[li]
                    b_cand = c.cur_cand[li]
                    mrows = [m_nw[:, li] for m_nw, _ in c.cur_masks]
                    before_row = before[li] if has_drf else None
                    if axis is not None:
                        # broadcast the owner's node-row bundle in ONE
                        # psum (non-owners contribute zeros); every shard
                        # then computes the identical fill schedule and
                        # replicated jstate update. All values are exact
                        # in f32 (GCD-scaled ints, group ids < 2^24).
                        ownf = is_owner.astype(fdtype)
                        parts = [prow, b_vreq.ravel(),
                                 b_vgroup.astype(fdtype),
                                 b_cand.astype(fdtype)]
                        parts += [m.astype(fdtype).ravel() for m in mrows]
                        if has_drf:
                            parts.append(before_row.ravel())
                        sizes = [int(p.shape[0]) for p in parts]
                        bundle = jax.lax.psum(
                            jnp.concatenate(parts) * ownf, axis)
                        pieces = []
                        o = 0
                        for sz in sizes:
                            pieces.append(bundle[o:o + sz])
                            o += sz
                        prow = pieces[0]
                        b_vreq = pieces[1].reshape(W, R)
                        b_vgroup = jnp.round(pieces[2]).astype(jnp.int32)
                        b_cand = pieces[3] > 0.5
                        mrows = [pieces[4 + t].reshape(m.shape) > 0.5
                                 for t, m in enumerate(mrows)]
                        if has_drf:
                            before_row = pieces[-1].reshape(W, W)
                    b_fidle = prow[:R]
                    b_alive = prow[R:R + W] > 0.5
                    b_owner = prow[R + W:]
                    b_mrow = tuple(
                        (mrows[t][:, None, :], part)
                        for t, (_, part) in enumerate(c.cur_masks))
                    jrow = s.jstate[pjg_i]
                    jalloc_p = jrow[:R]
                    quota_left = (needed[pjg_i] - jrow[R]) \
                        .astype(jnp.int32)
                    ls = _share(jalloc_p + req, total) if has_drf else None

                    def dyn_row(cand_x):       # [1, W] -> ([1, W], extra)
                        if not has_drf:
                            return cand_x, None
                        keep, rs = _drf_keep(
                            b_vreq, before_row, b_vgroup,
                            s.jstate[:, :R], total, ls, cand_x[0])
                        return keep[None], rs[None]

                    cand_b = (b_alive & b_cand)[None]
                    elig_b, dyn_dec_b, rs_b = _tier_eval(
                        tier_kinds, b_mrow, cand_b, dyn_row)
                    elig_row = elig_b[0]
                    rs_row = rs_b[0] if has_drf else rs_b
                    k, evicted, t_w = _fill_schedule(
                        b_vreq, b_fidle, elig_row, rs_row,
                        dyn_dec_b[0], req, jalloc_p, total,
                        run_len - s.m, quota_left, has_drf)
                    if not allow_cheap:
                        # without the shrink guarantee (dynamic tier not
                        # last) only the dispatch-fresh first probe is
                        # exact, one placement at a time
                        k = jnp.minimum(k, 1)
                    k = jnp.where(found, k, 0)
                    evicted = evicted & (t_w <= k) & found

                    # apply — all unconditional (empty evicted set is a
                    # mathematical no-op); one fused pack-row scatter +
                    # one fused jstate-row scatter
                    new_alive_row = b_alive & ~evicted
                    evicted_f = evicted[:, None].astype(fdtype)
                    AJ1 = s.jstate.shape[0]
                    job_onehot = jax.nn.one_hot(b_vgroup, AJ1,
                                                dtype=fdtype)
                    evict_delta = job_onehot.T @ (b_vreq * evicted_f)
                    freed = jnp.sum(b_vreq * evicted_f, axis=0)
                    # victims belong to the chunk step of the attempt
                    # that wanted them (replay groups evictions per task)
                    new_owner = jnp.where(
                        evicted, (i + s.m + t_w - 1).astype(fdtype),
                        b_owner)
                    placed = k.astype(fdtype)
                    delta = freed - req * placed
                    new_row = jnp.concatenate([
                        b_fidle + delta, new_alive_row.astype(fdtype),
                        new_owner])
                    jstate = (s.jstate
                              - jnp.pad(evict_delta, ((0, 0), (0, 1)))
                              ).at[pjg_i].add(
                        jnp.concatenate([req * placed, placed[None]]))
                    lo = i + s.m
                    task_node = jnp.where(
                        (iota_p >= lo) & (iota_p < lo + k),
                        best, s.task_node)
                    m = s.m + k
                    # a successful fill leaves its node re-probeable UNLESS
                    # provably capacity-dead: attempt k+1 must fail even
                    # with EVERY still-alive candidate evicted — candidates
                    # (alive & job mask) only shrink during a run, and any
                    # future tier verdict (including a cascade flip after
                    # a mask drains) is a subset of them, so this bound
                    # survives everything the conservative expiry/hv
                    # cutoffs do not. Non-dead truncations defer to a
                    # follow-up exact probe; a k=0 probe retires the node.
                    # Only the OWNER shard's local row takes the writes.
                    cand_post = cand_b[0] & ~evicted
                    cum_cand_post = jnp.sum(
                        b_vreq * cand_post[:, None].astype(fdtype), axis=0)
                    cap_dead = ~jnp.all(
                        req < new_row[:R] + cum_cand_post + EPS)
                    wrote = found & is_owner
                    touched = s.touched.at[li].set(s.touched[li] | wrote)
                    t_fit = s.t_fit.at[li].set(
                        jnp.where(wrote, (k > 0) & ~cap_dead,
                                  s.t_fit[li]))
                    pack = s.pack.at[li].set(
                        jnp.where(wrote, new_row, s.pack[li]))
                    cont = (found & (m < run_len)
                            & (m < quota_left + s.m))
                    if not allow_cheap:
                        cont = jnp.zeros((), bool)
                    return Fill(pack=pack,
                                jstate=jstate, task_node=task_node,
                                m=m, probes=s.probes + 1,
                                touched=touched, t_fit=t_fit,
                                cont=cont)

                s = jax.lax.while_loop(fill_cond, fill_body, Fill(
                    pack=c.pack, jstate=c.jstate, task_node=c.task_node,
                    m=jnp.zeros((), jnp.int32),
                    probes=jnp.zeros((), jnp.int32),
                    touched=jnp.zeros(N, bool),
                    t_fit=jnp.zeros(N, bool),
                    cont=jnp.ones((), bool)))

                ok = s.m > 0
                # fail: the rest of the run re-fails (skip to rend+1 in
                # phase 1; phase 2 stops the whole job at first failure —
                # jobs are cursor-contiguous, so the jump IS the stop).
                # A failed visit (m=0) wrote only identity rows, so the
                # inner-loop state carries over unconditionally.
                fail_to = rend + 1 if gang_commit else jend + 1
                next_i = jnp.where(ok, i + s.m, fail_to)
                return c._replace(
                    i=next_i, last_g=pjg_i, iters=c.iters + s.probes,
                    pack=s.pack, jstate=s.jstate, task_node=s.task_node)

            active = c.jstate[pjg_i, R] < needed[pjg_i]
            return jax.lax.cond(active, active_step, inactive_step, c)

        pack0 = jnp.concatenate([
            future_idle0.astype(fdtype),
            jnp.ones((N, W), fdtype),
            jnp.full((N, W), -1.0, fdtype)], axis=1)
        jstate0 = jnp.pad(jalloc0.astype(fdtype), ((0, 0), (0, 1)))
        c0 = Carry(
            i=jnp.zeros((), jnp.int32),
            iters=jnp.zeros((), jnp.int32),
            last_g=jnp.full((), -1, jnp.int32),
            pack=pack0,
            jstate=jstate0,
            task_node=jnp.full(P, NO_NODE, jnp.int32),
            # overwritten at the first job boundary before any read
            cur_cand=jnp.zeros((N, W), bool),
            cur_masks=tuple(
                (jnp.zeros(stk.shape[:1] + (N, W), bool),
                 jnp.zeros(part.shape[:1], bool))
                for stk, part in tier_masks),
            s_pack=pack0, s_jstate=jstate0)

        c = jax.lax.while_loop(lambda c: c.i < P, body, c0)

        if gang_commit:
            last_g = c.last_g
            failed = (last_g >= 0) & (c.jstate[last_g, R] < needed[last_g])
            js = c.jstate.at[last_g, R].set(
                jnp.where(failed, jnp.asarray(-BIG, fdtype),
                          c.jstate[last_g, R]))
            c = c._replace(
                pack=jnp.where(failed, c.s_pack, c.pack),
                jstate=js)

        # per-GROUP quota verdicts (the caller maps kept jobs via pjg)
        job_done = c.jstate[:, R] >= needed
        task_node = c.task_node
        if gang_commit:
            # gang statements: only quota-met jobs keep their placements.
            # The intra-job phase commits every attempt (needed is a BIG
            # sentinel there, so this mask would wrongly discard everything).
            task_node = jnp.where(job_done[pjg], task_node, NO_NODE)
        owner = jnp.round(c.pack[:, R + W:]).astype(jnp.int32)
        return task_node, owner, job_done, c.iters

    # with an axis the caller (build_preempt_walk_sharded) wraps walk_fn
    # in shard_map + jit; collectives inside require the mesh context
    return walk_fn if axis is not None else jax.jit(walk_fn)


_SHARDED_WALK_CACHE: dict = {}


def build_preempt_walk_sharded(mesh, tier_kinds: Tuple[str, ...],
                               tier_sizes: Tuple[int, ...],
                               gang_commit: bool,
                               allow_cheap: bool = True):
    """The preempt walk node-sharded over ``mesh`` (jax.sharding.Mesh with
    one axis): pack/EvictNW/candidate masks/score rows are sharded on the
    node axis, per-task tables and the jstate quota matrix are replicated,
    and the walk's two per-probe collectives (see build_preempt_walk)
    resolve the global node pick and broadcast the owner's row bundle.
    The caller pads the node axis to a multiple of the mesh size with
    victim-free rows (they can never be chosen). Decisions are
    bit-identical to the single-device walk — tests pin 8-vs-1 parity."""
    from jax.sharding import PartitionSpec as P

    axis = mesh.axis_names[0]
    key = (tuple(d.id for d in mesh.devices.flat), tier_kinds, tier_sizes,
           gang_commit, allow_cheap)
    if key in _SHARDED_WALK_CACHE:
        return _SHARDED_WALK_CACHE[key]
    if len(_SHARDED_WALK_CACHE) >= 16:
        # bound like build_preempt_walk's lru_cache(16): a long-lived
        # scheduler with churning tier structures must not pin compiled
        # shard_map executables forever
        _SHARDED_WALK_CACHE.clear()

    fn = build_preempt_walk(tier_kinds, tier_sizes, gang_commit,
                            allow_cheap, axis=axis)
    node = P(axis)
    repl = P()
    nw_spec = EvictNW(vslot=node, valid=node, vreq=node, vgroup=node,
                      rank=node)
    masks_spec = tuple((repl, repl) for _ in tier_sizes)
    in_specs = (node, nw_spec, repl, masks_spec,
                repl, repl, repl, repl, repl, repl, repl,
                P(None, axis), repl, repl, repl)
    out_specs = (repl, node, repl, repl)
    from ..parallel.mesh import shard_map_compat
    wrapped = jax.jit(shard_map_compat(fn, mesh=mesh, in_specs=in_specs,
                                       out_specs=out_specs))
    _SHARDED_WALK_CACHE[key] = wrapped
    return wrapped


