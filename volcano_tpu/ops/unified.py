"""One shard_map-partitioned placement solver — the device kernel behind
every allocate engine.

Before this module the repo carried four divergent solve paths (scan,
strict, blocks, sharded) with two wire layouts and per-path readback
sites. They are now ONE partitioned solver with two *mode* kernels over
one packed single-fetch layout ``[task_node | pipelined | ready | kept]``:

- mode **blocks**: the chunked block-greedy kernel (the throughput path,
  ops/auction.py semantics) — top-K candidate bidding per chunk, exact
  capacity contention, gang rollback sweeps;
- mode **scan**: the sequential-parity kernel (ops/place.py semantics) —
  the reference's task-by-task loop, also what the strict engine batches.

Both kernels run unsharded (``mesh=None``) or node-sharded over a 1-D
device mesh (axis ``NODE_AXIS``): the node axis is partitioned across
the mesh, the task/job axes are replicated, and per-node state updates
are shard-local. Decisions are **mesh-size invariant by construction**:

- candidate merging keeps the *global* top-K in global-index tie order
  (per-shard stable top-k → shard-major flat concat → stable top-k, so
  equal scores resolve to the lowest global index, exactly what a
  single-device ``top_k``/``argmax`` over the full node axis picks);
- the number of contention rounds is ``min(K_CAND, N_global)`` — a
  *global* quantity, not the per-shard one (the old parallel/mesh.py
  kernel used the local shard size here, which is why it could diverge
  from the single-device oracle on small shards);
- accept verdicts are psums over disjoint owner shards (exact), and all
  remaining arithmetic is element-wise over shard-local rows.

So the 8-device solve is byte-identical to the single-device oracle
(tests/test_unified.py), and ``mesh=None`` vs a 1-device mesh are the
same program modulo the shard_map wrapper — the engine drops the wrapper
at D == 1 to skip its dispatch overhead.

The blocks kernel's sweep/pass budgets are *runtime* scalars driven by a
``lax.while_loop`` with fixed-point early exit: a pass that places
nothing (or a sweep that changes no assignment and kills no job) is a
fixpoint, so exiting early is byte-identical to running the full budget.
This is the 20k-crossover fix: at steady state most of the former
``sweeps x passes`` grid was re-scoring an unchanged cluster, and on
sharded meshes every wasted pass paid cross-shard gather/argmax traffic.

All collectives ride ICI inside one jit program; nothing touches the
host between chunks, and the packed result is fetched by the caller at a
single site (allocate._fetch_packed).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .dense import EPS
from .pallas_place import NEG, NEG_TEST
from .place import (NO_NODE, JobMeta, NodeState, PlacementTasks,
                    place_scan_packed)
from .scores import ScoreWeights, combined_dynamic_score

NODE_AXIS = "nodes"

# Candidate-list width of the blocks kernel's bidding rounds. The round
# count is min(K_CAND, N_global) — global, so it cannot depend on how
# the node axis happens to be partitioned. 32, not 8: the dynamic
# scorers rank nodes near-identically for same-shaped tasks, so a
# narrow candidate list makes every task in a chunk fight over the same
# few nodes — at 20k/5k a K=8 first pass lands only ~27% of tasks and
# the rest re-bid in later full-price passes (measured 16s -> 7s at
# K=32, same full packing). Rounds beyond the last productive one cost
# nothing: the round loop exits at its fixpoint.
K_CAND = 32

_MESH_CACHE: dict = {}


def make_mesh(devices=None, axis: str = NODE_AXIS) -> Mesh:
    """1-D device mesh over ``axis``, cached per device set. Mesh
    construction is not free (it hashes the device list and builds the
    sharding machinery); the preempt/allocate hot paths call this every
    phase, so the cache is what keeps the sharded engines from paying it
    per cycle.

    The cache key is the device-id tuple, so every healthy subset the
    degradation ladder walks through (allocate._mesh_devices) gets its
    own cached Mesh — a heal that drops device 3 and a later probe that
    readmits it alternate between two cache ENTRIES, never rebuilding
    either. Meshes over retired/quarantined device sets are tiny (the
    Mesh holds device handles, not buffers), so no eviction is needed:
    the entry count is bounded by the subsets actually visited."""
    devices = tuple(devices) if devices is not None else tuple(jax.devices())
    key = (tuple(d.id for d in devices), axis)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        mesh = Mesh(np.asarray(devices), (axis,))
        _MESH_CACHE[key] = mesh
    return mesh


def shard_map_compat(fn, *, mesh, in_specs, out_specs):
    """shard_map across jax releases: ``jax.shard_map(..., check_vma=)`` on
    new jax, ``jax.experimental.shard_map.shard_map(..., check_rep=)``
    before the promotion. Without this shim the whole multi-chip engine
    family dies with an AttributeError on one side of the move — a
    toolchain-version fault, not a scheduling fault, so it is absorbed
    here instead of crashing the cycle (docs/robustness.md)."""
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    # the replication/VMA check must stay OFF (the solvers' out_specs are
    # not provably replicated), under whichever keyword this jax spells
    # it. Probe the signature rather than catching TypeError — a genuine
    # TypeError from shard_map's own argument validation must surface as
    # itself, not as a bogus incompatibility retry.
    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        kw = {"check_vma": False}
    elif "check_rep" in params:
        kw = {"check_rep": False}
    else:
        raise TypeError(
            "installed jax's shard_map accepts neither check_vma nor "
            "check_rep; cannot disable the replication check the sharded "
            "solvers require")
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


# --- degenerate collectives -------------------------------------------------
# axis=None means the solver runs unsharded: every collective collapses to
# the identity so one kernel body serves both deployments.

def _axis_index(axis):
    return 0 if axis is None else jax.lax.axis_index(axis)


def _all_gather(x, axis):
    return x[None] if axis is None else jax.lax.all_gather(x, axis)


def _any_shard(x, axis):
    """bool[...] -> "true on any shard" (identity unsharded; psum of
    disjoint owner verdicts sharded)."""
    return x if axis is None else jax.lax.psum(x.astype(jnp.int32), axis) > 0


def _chunk_step(axis: Optional[str], has_ms: bool):
    """One blocks-mode chunk over (possibly node-sharded) state. All array
    args are the per-device shards when ``axis`` is set, the full arrays
    otherwise.

    Top-K bidding: every shard offers its local top-K candidates, one
    all_gather merges them into the exact global top-K per task, then
    ``min(K_CAND, N_global)`` contention rounds let a task rejected at
    its r-th choice fall to its (r+1)-th. Contention for a node is
    resolved on the shard that owns it; one psum per round merges accept
    verdicts."""

    def step(carry, chunk, *, allocatable, max_tasks, weights, shard_offset):
        nodes: NodeState = carry
        if has_ms:
            req, valid, ms = chunk          # req/valid replicated, ms sharded
        else:
            req, valid = chunk
            ms = None
        C, R = req.shape
        Nl = nodes.idle.shape[0]                            # local shard size
        K_loc = min(K_CAND, Nl)

        pods_ok = nodes.ntasks < max_tasks
        # bid eligibility is FutureIdle-based (allocate.go:232-256): a task
        # that does not fit Idle may still pipeline onto releasing capacity;
        # the alloc-vs-pipeline split is resolved per accepted task below
        fit = (jnp.all(req[:, None, :] < nodes.future_idle[None] + EPS,
                       axis=-1) & pods_ok[None])              # [C,Nl]
        score = combined_dynamic_score(req, nodes.used, allocatable, weights)
        if ms is not None:
            fit = fit & (ms > NEG_TEST)
            score = score + ms
        masked = jnp.where(fit, score, -jnp.inf)
        lscore, lidx = jax.lax.top_k(masked, K_loc)          # [C,K_loc] local
        gidx = lidx + shard_offset

        # merge every shard's candidates into the global per-task top-K:
        # one gather of [D,C,K_loc] scores + ids across the mesh. The flat
        # concat is shard-major, and per-shard top_k is stable, so equal
        # scores sit in global-index order and the merged stable top_k
        # keeps exactly the candidates (and tie order) a single-device
        # top_k over the full node axis would — mesh-size invariance.
        all_s = jax.lax.all_gather(lscore, axis) if axis is not None \
            else lscore[None]
        all_i = jax.lax.all_gather(gidx, axis) if axis is not None \
            else gidx[None]
        D = all_s.shape[0]
        K = min(K_CAND, Nl * D)                              # global K
        flat_s = jnp.moveaxis(all_s, 0, 1).reshape(C, D * K_loc)
        flat_i = jnp.moveaxis(all_i, 0, 1).reshape(C, D * K_loc)
        cand_score, pos = jax.lax.top_k(flat_s, K)           # [C,K] global
        cand = jnp.take_along_axis(flat_i, pos, axis=1)

        lower = jnp.arange(C)[:, None] > jnp.arange(C)[None, :]

        def round_body(st):
            _r, _, accept, choice_g, slot = st
            st_in = (accept, choice_g, slot)
            bid_g = jnp.take_along_axis(cand, slot[:, None], 1)[:, 0]
            bscore = jnp.take_along_axis(cand_score, slot[:, None], 1)[:, 0]
            bidding = ~accept & valid & (bscore > -jnp.inf)
            local = (bid_g >= shard_offset) & (bid_g < shard_offset + Nl)
            bid_l = jnp.clip(bid_g - shard_offset, 0, Nl - 1)
            bidding_l = bidding & local

            # claimed capacity on this shard from earlier-round accepts
            choice_l = jnp.clip(choice_g - shard_offset, 0, Nl - 1)
            acc_l = (accept & (choice_g >= shard_offset)
                     & (choice_g < shard_offset + Nl))
            claimed_hot = (jax.nn.one_hot(choice_l, Nl, dtype=req.dtype)
                           * acc_l[:, None])
            claimed = jnp.einsum("cn,cr->nr", claimed_hot, req)
            claimed_cnt = jnp.sum(claimed_hot, axis=0)
            avail_bid = nodes.future_idle[bid_l] - claimed[bid_l]
            base_cnt = nodes.ntasks[bid_l] + claimed_cnt[bid_l]
            maxt_bid = max_tasks[bid_l]

            same = (bid_l[:, None] == bid_l[None, :]) & lower

            def wave(mask):
                live = (mask & bidding_l).astype(req.dtype)
                m = same * live[None, :]
                cum = m.astype(req.dtype) @ req
                room = jnp.all(req + cum < avail_bid + EPS, axis=-1)
                cnt = jnp.sum(m, axis=1)
                return bidding_l & room & (base_cnt + cnt < maxt_bid)

            acc = wave(jnp.ones(C, dtype=bool))
            acc = acc | wave(acc)
            acc = wave(acc)
            # each bid node is owned by exactly one shard: psum broadcasts
            # the owner's verdict to everyone
            acc_any = _any_shard(acc, axis)
            choice_g = jnp.where(acc_any, bid_g, choice_g)
            accept = accept | acc_any
            slot = jnp.where(bidding & ~acc_any,
                             jnp.minimum(slot + 1, K - 1), slot)
            # fixpoint: a round that accepted nothing and advanced no
            # slot leaves the next round with identical inputs (claims
            # only grow with accepts), so every later round is the
            # identity — exiting early is byte-identical to running all
            # K rounds. All three fields are replicated, so the exit is
            # uniform across shards.
            changed = (jnp.any(accept != st_in[0])
                       | jnp.any(choice_g != st_in[1])
                       | jnp.any(slot != st_in[2]))
            return _r + 1, changed, accept, choice_g, slot

        accept0 = jnp.zeros(C, dtype=bool)
        choice0 = jnp.full(C, -1, dtype=jnp.int32)
        slot0 = jnp.zeros(C, dtype=jnp.int32)
        _, _, accept, choice_g, _ = jax.lax.while_loop(
            lambda st: (st[0] < K) & st[1], round_body,
            (jnp.int32(0), jnp.bool_(True), accept0, choice0, slot0))

        # apply deltas on the owning shard
        mine = (accept & (choice_g >= shard_offset)
                & (choice_g < shard_offset + Nl))
        choice_l = jnp.clip(choice_g - shard_offset, 0, Nl - 1)
        placed = jax.nn.one_hot(choice_l, Nl, dtype=req.dtype) * mine[:, None]

        # alloc-vs-pipeline split (allocate.go:232-256 / ops/place.py:119):
        # within the chunk, a task allocates iff it fits the node's Idle
        # after the IDLE consumption of earlier-in-chunk allocs on the same
        # node — pipelined neighbors consume FutureIdle only. Earlier alloc
        # membership is itself the unknown; iterate the antitone fit map F:
        # after t applications the first t same-node tasks carry their
        # exact sequential value, and an ODD iterate is a SUBSET of the
        # true greedy alloc set (S0=all ⊇ true ⇒ S1=F(S0) ⊆ F(true)=true,
        # alternating), so any task still undecided at depth >9 falls on
        # the safe side — pipelined, consuming only the FutureIdle room its
        # acceptance already validated. Idle can never be oversubscribed.
        same_node = (choice_l[:, None] == choice_l[None, :]) \
            & mine[:, None] & mine[None, :] & lower
        idle_bid = nodes.idle[choice_l]

        def alloc_iter(_, alloc):
            cum = (same_node * alloc[None, :].astype(req.dtype)) @ req
            return mine & jnp.all(req + cum < idle_bid + EPS, axis=-1)

        alloc = jax.lax.fori_loop(0, 9, alloc_iter, mine)
        # one psum so every shard sees the global pipelined verdict
        alloc_any = _any_shard(alloc, axis)
        pipe = accept & ~alloc_any

        alloc_hot = placed * alloc[:, None].astype(req.dtype)
        delta_alloc = jnp.einsum("cn,cr->nr", alloc_hot, req)
        delta_all = jnp.einsum("cn,cr->nr", placed, req)
        nodes = NodeState(
            idle=nodes.idle - delta_alloc,
            future_idle=nodes.future_idle - delta_all,
            used=nodes.used + delta_alloc,
            ntasks=nodes.ntasks + jnp.sum(placed, axis=0).astype(jnp.int32))

        out = jnp.where(accept, choice_g, NO_NODE).astype(jnp.int32)
        return nodes, (out, pipe)

    return step


def _make_blocks_solve(axis: Optional[str], has_ms: bool, chunk: int):
    """The blocks-mode solve body. Runs whole-array when ``axis`` is None,
    per-shard inside shard_map otherwise. ``sweeps``/``passes`` are traced
    i32 budget caps: a ``lax.while_loop`` runs up to the cap but exits at
    the first fixpoint pass/sweep — byte-identical to running the full
    budget (an unchanged pass implies every later pass is the identity),
    and one compiled program serves every budget."""

    def solve(nodes, allocatable, max_tasks, req, valid, job_ix, jobs,
              weights, sweeps, passes, *maybe_ms):
        Tp = req.shape[0]
        n_chunks = Tp // chunk
        Nl = allocatable.shape[0]
        J = jobs.min_available.shape[0]
        shard_offset = _axis_index(axis) * Nl
        step = partial(_chunk_step(axis, has_ms),
                       allocatable=allocatable, max_tasks=max_tasks,
                       weights=weights, shard_offset=shard_offset)
        ms = maybe_ms[0] if has_ms else None

        assign0 = jnp.full(Tp, NO_NODE, dtype=jnp.int32)
        pipe0 = jnp.zeros(Tp, dtype=bool)

        def todo_of(assign, job_dead):
            return (assign == NO_NODE) & valid & ~job_dead[job_ix]

        # a chunk whose todo rows are all False is the IDENTITY (nothing
        # bids, deltas are exact zeros, every row comes back NO_NODE), so
        # skipping it is byte-identical — and it is what makes the
        # fixpoint-confirmation passes ~free: on a fully-packed cluster
        # the straggler pass and every later sweep's re-check pay only
        # the chunks that still hold unplaced tasks, not a full [T,N]
        # re-score. The predicate is replicated (assign/valid/job_ix are),
        # so the cond is uniform across shards.
        def guarded_step(carry, chunk_xs):
            todo_c = chunk_xs[1]
            skip_out = (jnp.full(todo_c.shape[0], NO_NODE, dtype=jnp.int32),
                        jnp.zeros(todo_c.shape[0], dtype=bool))
            return jax.lax.cond(
                jnp.any(todo_c),
                lambda c: step(c, chunk_xs),
                lambda c: (c, skip_out),
                carry)

        def one_pass(nodes, assign, pipe, job_dead):
            todo = todo_of(assign, job_dead)
            xs = (req.reshape(n_chunks, chunk, -1),
                  todo.reshape(n_chunks, chunk))
            if has_ms:
                xs = xs + (ms.reshape(n_chunks, chunk, Nl),)
            nodes, (out, out_pipe) = jax.lax.scan(guarded_step, nodes, xs)
            fresh = assign == NO_NODE
            assign = jnp.where(fresh, out.reshape(Tp), assign)
            pipe = jnp.where(fresh, out_pipe.reshape(Tp), pipe)
            return nodes, assign, pipe

        def pass_cond(st):
            k, changed = st[0], st[1]
            return (k < passes) & changed

        def pass_body(st):
            k, _, nodes, assign, pipe, job_dead = st
            nodes, assign2, pipe2 = one_pass(nodes, assign, pipe, job_dead)
            # a pass that assigned nothing left nodes/pipe untouched too
            # (pipe only changes where a fresh assignment landed) — the
            # next pass would see identical inputs: fixpoint, exit early.
            # Likewise a pass that emptied todo: later passes have no
            # bidders, i.e. are the identity, so exit without paying one
            changed = (jnp.any(assign2 != assign)
                       & jnp.any(todo_of(assign2, job_dead)))
            return k + 1, changed, nodes, assign2, pipe2, job_dead

        def sweep_cond(st):
            s, changed = st[0], st[1]
            return (s < sweeps) & changed

        def sweep_body(st):
            s, _, nodes, assign, pipe, job_dead, _, _ = st
            assign_in, dead_in = assign, job_dead
            # seed with any(todo), not True: a re-sweep over a cluster
            # with nothing left to place runs ZERO passes (the gang
            # re-check below is all this sweep needs)
            _, _, nodes, assign, pipe, job_dead = jax.lax.while_loop(
                pass_cond,
                pass_body,
                (jnp.int32(0), jnp.any(todo_of(assign, job_dead)), nodes,
                 assign, pipe, job_dead))

            placed = assign != NO_NODE
            alloc_cnt = jax.ops.segment_sum(
                (placed & ~pipe).astype(jnp.int32), job_ix, num_segments=J)
            pipe_cnt = jax.ops.segment_sum(
                (placed & pipe).astype(jnp.int32), job_ix, num_segments=J)
            # gang votes (gang.go:45-216): ready counts allocations only;
            # a merely-pipelined gang is KEPT (allocate.go:264-270 commits
            # ready jobs, keeps pipelined ones open)
            ready = alloc_cnt + jobs.base_ready >= jobs.min_available
            kept = (alloc_cnt + pipe_cnt + jobs.base_ready
                    + jobs.base_pipelined >= jobs.min_available)
            drop = placed & ~kept[job_ix]
            # free dropped demand on the owning shard (alloc'd drops free
            # Idle too; pipelined drops only reserved future capacity)
            local = (assign >= shard_offset) & (assign < shard_offset + Nl) \
                & drop
            drop_hot = (jax.nn.one_hot(
                jnp.where(local, assign - shard_offset, 0), Nl,
                dtype=req.dtype) * local[:, None])
            alloc_hot = drop_hot * (~pipe)[:, None].astype(req.dtype)
            freed_alloc = jnp.einsum("tn,tr->nr", alloc_hot, req)
            freed_all = jnp.einsum("tn,tr->nr", drop_hot, req)
            nodes = NodeState(
                idle=nodes.idle + freed_alloc,
                future_idle=nodes.future_idle + freed_all,
                used=nodes.used - freed_alloc,
                ntasks=nodes.ntasks
                - jnp.sum(drop_hot, axis=0).astype(jnp.int32))
            assign = jnp.where(drop, NO_NODE, assign)
            job_dead = job_dead | (~kept & (alloc_cnt + pipe_cnt > 0))
            # a sweep that changed no assignment and killed no job is a
            # fixpoint: every later sweep reproduces this ready/kept
            changed = (jnp.any(assign != assign_in)
                       | jnp.any(job_dead != dead_in))
            return s + 1, changed, nodes, assign, pipe, job_dead, ready, kept

        _, _, nodes, assign, pipe, _, ready, kept = jax.lax.while_loop(
            sweep_cond, sweep_body,
            (jnp.int32(0), jnp.bool_(True), nodes, assign0, pipe0,
             jnp.zeros(J, dtype=bool), jnp.zeros(J, dtype=bool),
             jnp.zeros(J, dtype=bool)))
        # pack (assign, pipe, ready, kept) in one i32 row: one host fetch
        packed = jnp.concatenate([assign, pipe.astype(jnp.int32),
                                  ready.astype(jnp.int32),
                                  kept.astype(jnp.int32)])
        return packed, nodes

    return solve


_SOLVER_CACHE: dict = {}


def _blocks_solver(mesh: Optional[Mesh], chunk: int, has_ms: bool):
    """Compiled blocks-mode solve, cached per (mesh, chunk, has_ms).
    jobs/weights/budgets are runtime args (re-tracing per cycle or per
    budget tier would pay a multi-second compile)."""
    key = ("blocks",
           None if mesh is None else tuple(d.id for d in mesh.devices.flat),
           chunk, has_ms)
    fn = _SOLVER_CACHE.get(key)
    if fn is not None:
        return fn

    axis = None if mesh is None else NODE_AXIS
    solve = _make_blocks_solve(axis, has_ms, chunk)
    if mesh is not None:
        node_sharded = P(NODE_AXIS)
        repl = P()
        in_specs = [NodeState(*(node_sharded,) * 4), node_sharded,
                    node_sharded, repl, repl, repl,
                    JobMeta(repl, repl, repl),
                    ScoreWeights(repl, repl, repl, repl, repl), repl, repl]
        if has_ms:
            in_specs.append(P(None, NODE_AXIS))
        solve = shard_map_compat(
            solve, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=(repl, NodeState(*(node_sharded,) * 4)))
    fn = jax.jit(solve)
    _SOLVER_CACHE[key] = fn
    return fn


def padded_task_len(T: int, chunk: int = 256) -> int:
    """Padded task-axis length of the blocks-mode packed layout."""
    return T + (-T) % chunk


def bucket_nodes_for_mesh(n: int, d: int) -> int:
    """Node-axis length after padding to a multiple of the mesh size.
    Callers pad with zero-capacity nodes (max_tasks 0), which the fit
    predicate can never select — inert by construction, so the padded
    solve is byte-identical to the unpadded one."""
    return n + (-n) % d


def place_blocks_unified(mesh: Optional[Mesh], nodes: NodeState,
                         req: jnp.ndarray, valid: jnp.ndarray,
                         job_ix: jnp.ndarray, jobs: JobMeta,
                         weights: ScoreWeights, allocatable: jnp.ndarray,
                         max_tasks: jnp.ndarray, chunk: int = 256,
                         sweeps: int = 3, passes: int = 3,
                         masked_static: Optional[jnp.ndarray] = None,
                         ) -> Tuple[jnp.ndarray, NodeState]:
    """Blocks-mode placement, unsharded (``mesh=None``) or node-sharded.

    nodes/allocatable/max_tasks are (shard-)resident on the node axis;
    tasks (req/valid/job_ix) and JobMeta are replicated; ``masked_static``
    (optional f32[T,N], NEG where statically infeasible) is sharded on
    its node axis. Returns ``(packed, nodes)`` with BOTH left on device —
    ``packed`` is the i32 single-fetch layout
    ``[task_node | pipelined | ready | kept]`` with task spans of length
    ``padded_task_len(T, chunk)``; the caller fetches it at ONE site
    (allocate._fetch_packed). N must be divisible by the mesh size (pad
    with zero-capacity nodes). A 1-device mesh is collapsed to
    ``mesh=None`` — the kernel is mesh-size invariant, so this only skips
    the shard_map dispatch overhead, never changes a decision."""
    if mesh is not None and int(mesh.devices.size) == 1:
        mesh = None
    D = 1 if mesh is None else int(mesh.devices.size)
    N = allocatable.shape[0]
    assert N == bucket_nodes_for_mesh(N, D), \
        f"node count {N} not divisible by mesh size {D}"
    T = req.shape[0]
    pad = (-T) % chunk
    if pad:
        req = jnp.pad(req, ((0, pad), (0, 0)))
        valid = jnp.pad(valid, (0, pad))
        job_ix = jnp.pad(job_ix, (0, pad))
        if masked_static is not None:
            masked_static = jnp.pad(masked_static, ((0, pad), (0, 0)),
                                    constant_values=NEG)

    fn = _blocks_solver(mesh, chunk, masked_static is not None)
    args = [nodes, allocatable, max_tasks, req, valid, job_ix, jobs,
            weights, jnp.int32(sweeps), jnp.int32(passes)]
    if masked_static is not None:
        args.append(masked_static)
    return fn(*args)


def _scan_solver(mesh: Mesh):
    """Compiled node-sharded scan-mode solve for this mesh: the exact
    sequential kernel (ops/place.place_scan) with its per-step argmax
    resolved by one all_gather of per-shard (score, index, fit) maxima —
    ties fall to the lowest shard, i.e. the lowest global node index,
    matching the single-device ``jnp.argmax``."""
    key = ("scan", tuple(d.id for d in mesh.devices.flat))
    fn = _SOLVER_CACHE.get(key)
    if fn is not None:
        return fn

    node_sharded = P(NODE_AXIS)
    repl = P()
    tasks_spec = PlacementTasks(
        req=repl, job_ix=repl, valid=repl,
        feas=P(None, NODE_AXIS), static_score=P(None, NODE_AXIS),
        first_of_job=repl, last_of_job=repl)
    in_specs = (NodeState(*(node_sharded,) * 4), tasks_spec,
                JobMeta(repl, repl, repl),
                ScoreWeights(repl, repl, repl, repl, repl),
                node_sharded, node_sharded)

    @partial(shard_map_compat, mesh=mesh, in_specs=in_specs,
             out_specs=(repl, NodeState(*(node_sharded,) * 4)))
    def solve(nodes, tasks, jobs, weights, allocatable, max_tasks):
        Nl = allocatable.shape[0]
        offset = jax.lax.axis_index(NODE_AXIS) * Nl
        return place_scan_packed(nodes, tasks, jobs, weights, allocatable,
                                 max_tasks, axis=NODE_AXIS,
                                 shard_offset=offset)

    fn = jax.jit(solve)
    _SOLVER_CACHE[key] = fn
    return fn


def place_scan_unified(mesh: Optional[Mesh], nodes: NodeState,
                       tasks: PlacementTasks, jobs: JobMeta,
                       weights: ScoreWeights, allocatable: jnp.ndarray,
                       max_tasks: jnp.ndarray):
    """Scan-mode placement over ``mesh`` (or unsharded when None / one
    device), packed single-fetch layout, everything left on device. N
    must be divisible by the mesh size; decisions are byte-identical to
    the single-device ``place_scan_packed`` at every mesh size."""
    if mesh is not None and int(mesh.devices.size) == 1:
        mesh = None
    if mesh is None:
        key = ("scan", None)
        fn = _SOLVER_CACHE.get(key)
        if fn is None:
            fn = jax.jit(place_scan_packed)
            _SOLVER_CACHE[key] = fn
        return fn(nodes, tasks, jobs, weights, allocatable, max_tasks)
    D = int(mesh.devices.size)
    N = allocatable.shape[0]
    assert N == bucket_nodes_for_mesh(N, D), \
        f"node count {N} not divisible by mesh size {D}"
    return _scan_solver(mesh)(nodes, tasks, jobs, weights, allocatable,
                              max_tasks)
