"""Block-greedy batched placement — the throughput path.

The parity path (ops/place.py) replays the reference's task-by-task loop and
is serial in T. This solver instead processes tasks in chunks of C: one chunk
scores all C tasks against current node state at once (dense [C, N] work that
maps onto the VPU/MXU), resolves intra-chunk capacity contention exactly with
an exclusive cumulative-sum of requests per chosen node, and commits the chunk
in one step. Chunked greedy differs from pure sequential only in that scores
are evaluated at chunk granularity; capacity feasibility is exact.

Gang semantics are restored after placement: a segment-sum gang check
(ops/place.gang_admission) rejects jobs that missed minAvailable, their
resources are returned in one vectorized rollback, and an optional extra
sweep reuses the freed capacity — the batched analogue of
Statement.Commit/Discard (statement.go:352-395).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .dense import EPS
from .place import NO_NODE, JobMeta, NodeState
from .scores import ScoreWeights, combined_dynamic_score


class BlockTasks(NamedTuple):
    """Pending tasks in priority order, padded to a multiple of the chunk."""

    req: jnp.ndarray           # f32[T,R]
    job_ix: jnp.ndarray        # i32[T]
    valid: jnp.ndarray         # bool[T]
    feas: jnp.ndarray          # bool[T,N]
    static_score: jnp.ndarray  # f32[T,N]


K_CAND = 8


def _round_contention(req, bid, bidding, avail_bid, base_cnt, maxt_bid):
    """Exact intra-round capacity contention via a [C,C] same-bid matmul.

    For task i, the demand claimed ahead of it is the sum of req over
    earlier tasks j<i bidding the same node — a lower-triangular same-bid
    mask times req (MXU work, no [C,N,R] cumsum). Three waves: count all
    bidders (conservative), recount with only accepted (recovers tasks
    displaced by rejected bidders), re-validate the merged set.
    """
    C = req.shape[0]
    lower = jnp.arange(C)[:, None] > jnp.arange(C)[None, :]   # j < i
    same = (bid[:, None] == bid[None, :]) & lower             # [C,C]

    def wave(mask):
        live = (mask & bidding).astype(req.dtype)             # [C]
        m = same * live[None, :]
        cum = m.astype(req.dtype) @ req                       # [C,R]
        room = jnp.all(req + cum < avail_bid + EPS, axis=-1)
        cnt = jnp.sum(m, axis=1)
        pods_room = base_cnt + cnt < maxt_bid
        return bidding & room & pods_room

    accept = wave(jnp.ones(C, dtype=bool))
    accept = accept | wave(accept)
    return wave(accept)


def _chunk_step(allocatable, max_tasks, weights):
    def step(nodes: NodeState, chunk):
        req, job_ix, valid, feas, static_score = chunk
        C, R = req.shape
        N = nodes.idle.shape[0]
        K = min(K_CAND, N)

        pods_ok = nodes.ntasks < max_tasks                       # [N]
        # bids are FutureIdle-based (allocate.go:232-256): a task that does
        # not fit Idle may pipeline onto releasing capacity; alloc-vs-pipe
        # is split per accepted task below
        fit = (jnp.all(req[:, None, :] < nodes.future_idle[None] + EPS,
                       axis=-1) & feas & pods_ok[None])           # [C,N]
        score = static_score + combined_dynamic_score(
            req, nodes.used, allocatable, weights)                # [C,N]
        masked = jnp.where(fit, score, -jnp.inf)
        cand_score, cand = jax.lax.top_k(masked, K)               # [C,K]

        # K bidding rounds: a task rejected at its r-th choice (node filled
        # by earlier bidders) falls to its (r+1)-th within the same chunk —
        # without this, homogeneous tasks herd onto one argmax node and each
        # chunk pass fills a single node.
        def round_body(_, st):
            accept, choice, slot = st
            bid = jnp.take_along_axis(cand, slot[:, None], 1)[:, 0]
            bscore = jnp.take_along_axis(cand_score, slot[:, None], 1)[:, 0]
            bidding = ~accept & valid & (bscore > -jnp.inf)
            # claimed state = accepted choices so far, by construction
            claimed_hot = (jax.nn.one_hot(choice, N, dtype=req.dtype)
                           * accept[:, None])
            claimed = jnp.einsum("cn,cr->nr", claimed_hot, req)
            claimed_cnt = jnp.sum(claimed_hot, axis=0)
            avail_bid = nodes.future_idle[bid] - claimed[bid]
            base_cnt = nodes.ntasks[bid] + claimed_cnt[bid]
            acc = _round_contention(req, bid, bidding, avail_bid, base_cnt,
                                    max_tasks[bid])
            choice = jnp.where(acc, bid, choice)
            accept = accept | acc
            slot = jnp.where(bidding & ~acc,
                             jnp.minimum(slot + 1, K - 1), slot)
            return accept, choice, slot

        accept0 = jnp.zeros(C, dtype=bool)
        choice0 = jnp.zeros(C, dtype=jnp.int32)
        slot0 = jnp.zeros(C, dtype=jnp.int32)
        accept, choice, _ = jax.lax.fori_loop(
            0, K, round_body, (accept0, choice0, slot0))

        placed = jax.nn.one_hot(choice, N, dtype=req.dtype) * accept[:, None]

        # alloc-vs-pipeline split (same construction as parallel/mesh.py):
        # a task allocates iff it fits Idle after the IDLE consumption of
        # earlier-in-chunk same-node allocs; iterate the antitone fit map —
        # an ODD iterate under-approximates the true greedy alloc set, so
        # deep same-node ties fall safely to pipeline and Idle can never
        # be oversubscribed (exact for up to 9 same-node contenders)
        C_lower = jnp.arange(C)[:, None] > jnp.arange(C)[None, :]
        same_node = (choice[:, None] == choice[None, :]) \
            & accept[:, None] & accept[None, :] & C_lower
        idle_bid = nodes.idle[choice]

        def alloc_iter(_, alloc):
            cum = (same_node * alloc[None, :].astype(req.dtype)) @ req
            return accept & jnp.all(req + cum < idle_bid + EPS, axis=-1)

        alloc = jax.lax.fori_loop(0, 9, alloc_iter, accept)
        pipe = accept & ~alloc

        alloc_hot = placed * alloc[:, None].astype(req.dtype)
        delta_alloc = jnp.einsum("cn,cr->nr", alloc_hot, req)
        delta_all = jnp.einsum("cn,cr->nr", placed, req)
        nodes = NodeState(
            idle=nodes.idle - delta_alloc,
            future_idle=nodes.future_idle - delta_all,
            used=nodes.used + delta_alloc,
            ntasks=nodes.ntasks + jnp.sum(placed, axis=0).astype(jnp.int32))
        out = jnp.where(accept, choice, NO_NODE).astype(jnp.int32)
        return nodes, (out, pipe)

    return step


def place_blocks(nodes: NodeState, tasks: BlockTasks, jobs: JobMeta,
                 weights: ScoreWeights, allocatable: jnp.ndarray,
                 max_tasks: jnp.ndarray, chunk: int = 256,
                 sweeps: int = 3, passes: int = 3,
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                            jnp.ndarray, NodeState]:
    """Place tasks; returns (task_node i32[T], task_pipelined bool[T],
    job_ready bool[J], job_kept bool[J], nodes).

    Each sweep runs ``passes`` placement passes — a task rejected in pass k
    (its chosen node filled up inside the chunk) retries against updated node
    state in pass k+1 — then one gang check rolls back jobs below
    minAvailable. Later sweeps let other jobs reuse freed capacity.
    """
    T = tasks.req.shape[0]
    pad = (-T) % chunk
    if pad:
        tasks = BlockTasks(
            req=jnp.pad(tasks.req, ((0, pad), (0, 0))),
            job_ix=jnp.pad(tasks.job_ix, (0, pad)),
            valid=jnp.pad(tasks.valid, (0, pad)),
            feas=jnp.pad(tasks.feas, ((0, pad), (0, 0))),
            static_score=jnp.pad(tasks.static_score, ((0, pad), (0, 0))))
    Tp = T + pad
    n_chunks = Tp // chunk

    def reshape(x):
        return x.reshape((n_chunks, chunk) + x.shape[1:])

    J = jobs.min_available.shape[0]
    assign = jnp.full(Tp, NO_NODE, dtype=jnp.int32)
    pipe0 = jnp.zeros(Tp, dtype=bool)

    def place_pass(carry, _):
        nodes, assign, pipe, job_dead = carry
        todo = (assign == NO_NODE) & tasks.valid & ~job_dead[tasks.job_ix]
        xs = (reshape(tasks.req), reshape(tasks.job_ix), reshape(todo),
              reshape(tasks.feas), reshape(tasks.static_score))
        nodes, (out, out_pipe) = jax.lax.scan(
            _chunk_step(allocatable, max_tasks, weights), nodes, xs)
        fresh = assign == NO_NODE
        assign = jnp.where(fresh, out.reshape(Tp), assign)
        pipe = jnp.where(fresh, out_pipe.reshape(Tp), pipe)
        return (nodes, assign, pipe, job_dead), None

    def sweep(carry, _):
        (nodes, new_assign, pipe, job_dead), _ = jax.lax.scan(
            place_pass, carry, jnp.arange(passes))

        # Gang votes + vectorized rollback of non-kept jobs (batched
        # Statement.Discard): ready counts allocations only; a
        # merely-pipelined gang is KEPT open (allocate.go:264-270). A
        # rolled-back job does not retry in later sweeps — the reference
        # pops each job once and discards for good.
        placed = new_assign != NO_NODE
        alloc_cnt = jax.ops.segment_sum((placed & ~pipe).astype(jnp.int32),
                                        tasks.job_ix, num_segments=J)
        pipe_cnt = jax.ops.segment_sum((placed & pipe).astype(jnp.int32),
                                       tasks.job_ix, num_segments=J)
        ready = alloc_cnt + jobs.base_ready >= jobs.min_available
        kept = (alloc_cnt + pipe_cnt + jobs.base_ready
                + jobs.base_pipelined >= jobs.min_available)
        drop = placed & ~kept[tasks.job_ix]
        drop_hot = (jax.nn.one_hot(jnp.where(drop, new_assign, 0),
                                   nodes.idle.shape[0], dtype=tasks.req.dtype)
                    * drop[:, None])
        alloc_hot = drop_hot * (~pipe)[:, None].astype(tasks.req.dtype)
        freed_alloc = jnp.einsum("tn,tr->nr", alloc_hot, tasks.req)
        freed_all = jnp.einsum("tn,tr->nr", drop_hot, tasks.req)
        nodes = NodeState(
            idle=nodes.idle + freed_alloc,
            future_idle=nodes.future_idle + freed_all,
            used=nodes.used - freed_alloc,
            ntasks=nodes.ntasks - jnp.sum(drop_hot, axis=0).astype(jnp.int32))
        new_assign = jnp.where(drop, NO_NODE, new_assign)
        job_dead = job_dead | (~kept & (alloc_cnt + pipe_cnt > 0))
        return (nodes, new_assign, pipe, job_dead), (ready, kept)

    job_dead = jnp.zeros(J, dtype=bool)
    (nodes, assign, pipe, _), (readies, kepts) = jax.lax.scan(
        sweep, (nodes, assign, pipe0, job_dead), jnp.arange(sweeps))
    return assign[:T], pipe[:T], readies[-1], kepts[-1], nodes


def place_blocks_packed(nodes: NodeState, tasks: BlockTasks, jobs: JobMeta,
                        weights: ScoreWeights, allocatable: jnp.ndarray,
                        max_tasks: jnp.ndarray, chunk: int = 256,
                        sweeps: int = 3, passes: int = 3):
    """place_blocks with the place_scan_packed single-fetch layout
    ``[task_node | task_pipelined | job_ready | job_kept]`` (i32, task
    spans length T, job spans length J). One wire format for both fused
    solvers means ONE host readback site (allocate._fetch_packed) serves
    the scan and blocks engines alike; the final NodeState stays on
    device, never fetched."""
    assign, pipe, ready, kept, nodes = place_blocks(
        nodes, tasks, jobs, weights, allocatable, max_tasks,
        chunk=chunk, sweeps=sweeps, passes=passes)
    packed = jnp.concatenate([
        assign,
        pipe.astype(jnp.int32),
        ready.astype(jnp.int32),
        kept.astype(jnp.int32)])
    return packed, nodes
