"""Block-greedy batched placement — the throughput path.

The parity path (ops/place.py) replays the reference's task-by-task loop and
is serial in T. This solver instead processes tasks in chunks of C: one chunk
scores all C tasks against current node state at once (dense [C, N] work that
maps onto the VPU/MXU), resolves intra-chunk capacity contention exactly with
an exclusive cumulative-sum of requests per chosen node, and commits the chunk
in one step. Chunked greedy differs from pure sequential only in that scores
are evaluated at chunk granularity; capacity feasibility is exact.

Gang semantics are restored after placement: a segment-sum gang check
(ops/place.gang_admission) rejects jobs that missed minAvailable, their
resources are returned in one vectorized rollback, and an optional extra
sweep reuses the freed capacity — the batched analogue of
Statement.Commit/Discard (statement.go:352-395).

The kernel itself lives in ops/unified.py — ONE shard_map-partitioned
solver whose unsharded (mesh=None) degenerate form is exactly the chunked
greedy described above. This module keeps the single-device entry points
(BlockTasks with dense feas/static matrices) and folds them into the
unified solver's NEG-masked static-score representation:
``ms = where(feas, static_score, NEG)`` carries the same fit mask
(``ms > NEG_TEST``) and, where feasible, the same score (float addition
is commutative, so ``dynamic + ms == static + dynamic`` bitwise) — the
delegation is byte-identical to the former in-module kernel.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from .pallas_place import NEG
from .place import JobMeta, NodeState
from .scores import ScoreWeights
from .unified import K_CAND, place_blocks_unified  # noqa: F401 (re-export)


class BlockTasks(NamedTuple):
    """Pending tasks in priority order, padded to a multiple of the chunk."""

    req: jnp.ndarray           # f32[T,R]
    job_ix: jnp.ndarray        # i32[T]
    valid: jnp.ndarray         # bool[T]
    feas: jnp.ndarray          # bool[T,N]
    static_score: jnp.ndarray  # f32[T,N]


def place_blocks(nodes: NodeState, tasks: BlockTasks, jobs: JobMeta,
                 weights: ScoreWeights, allocatable: jnp.ndarray,
                 max_tasks: jnp.ndarray, chunk: int = 256,
                 sweeps: int = 3, passes: int = 3,
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                            jnp.ndarray, NodeState]:
    """Place tasks; returns (task_node i32[T], task_pipelined bool[T],
    job_ready bool[J], job_kept bool[J], nodes) — device arrays.

    Each sweep runs up to ``passes`` placement passes — a task rejected in
    pass k (its chosen node filled up inside the chunk) retries against
    updated node state in pass k+1 — then one gang check rolls back jobs
    below minAvailable. Later sweeps let other jobs reuse freed capacity.
    The unified kernel exits early at the first fixpoint pass/sweep, which
    is byte-identical to running the full budget (see ops/unified.py).
    """
    T = tasks.req.shape[0]
    J = jobs.min_available.shape[0]
    ms = jnp.where(tasks.feas, tasks.static_score, NEG)
    packed, out_nodes = place_blocks_unified(
        None, nodes, tasks.req, tasks.valid, tasks.job_ix, jobs, weights,
        allocatable, max_tasks, chunk=chunk, sweeps=sweeps, passes=passes,
        masked_static=ms)
    Tp = T + (-T) % chunk
    return (packed[:T], packed[Tp:Tp + T].astype(bool),
            packed[2 * Tp:2 * Tp + J].astype(bool),
            packed[2 * Tp + J:2 * Tp + 2 * J].astype(bool), out_nodes)


def place_blocks_packed(nodes: NodeState, tasks: BlockTasks, jobs: JobMeta,
                        weights: ScoreWeights, allocatable: jnp.ndarray,
                        max_tasks: jnp.ndarray, chunk: int = 256,
                        sweeps: int = 3, passes: int = 3):
    """place_blocks with the place_scan_packed single-fetch layout
    ``[task_node | task_pipelined | job_ready | job_kept]`` (i32, task
    spans length T, job spans length J). One wire format for every fused
    solver means ONE host readback site (allocate._fetch_packed) serves
    the scan, blocks, and sharded engines alike; the final NodeState
    stays on device, never fetched."""
    assign, pipe, ready, kept, nodes = place_blocks(
        nodes, tasks, jobs, weights, allocatable, max_tasks,
        chunk=chunk, sweeps=sweeps, passes=passes)
    packed = jnp.concatenate([
        assign,
        pipe.astype(jnp.int32),
        ready.astype(jnp.int32),
        kept.astype(jnp.int32)])
    return packed, nodes
