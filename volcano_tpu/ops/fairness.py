"""Fairness math as fixed-point array iterations.

- Proportion's deserved water-filling
  (/root/reference/pkg/scheduler/plugins/proportion/proportion.go:132-196):
  each round grants every unmet queue ``remaining * weight/totalWeight``,
  clamps to capability and request, and stops when nothing moves. Here one
  round is a masked vector update over ``f32[Q,R]`` and the loop is
  ``lax.while_loop``.

- DRF dominant share (/root/reference/pkg/scheduler/plugins/drf/drf.go:202-520):
  ``share_j = max_r allocated_jr / total_r`` — one reduction.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .dense import EPS, le_all


class ProportionResult(NamedTuple):
    deserved: jnp.ndarray   # f32[Q,R]
    share: jnp.ndarray      # f32[Q]


def proportion_deserved(total: jnp.ndarray, weight: jnp.ndarray,
                        request: jnp.ndarray, capability: jnp.ndarray,
                        allocated: jnp.ndarray,
                        max_iters: int = 64) -> ProportionResult:
    """Water-fill cluster resources into per-queue `deserved` vectors.

    total: f32[R]; weight: f32[Q]; request/capability/allocated: f32[Q,R]
    (capability uses +inf for unlimited dimensions).
    """
    Q, R = request.shape

    def cond(state):
        i, deserved, meet, remaining, moved = state
        total_w = jnp.sum(jnp.where(meet, 0.0, weight))
        return (i < max_iters) & (total_w > 0) & moved & jnp.any(remaining >= EPS)

    def body(state):
        i, deserved, meet, remaining, _ = state
        active = ~meet
        total_w = jnp.sum(jnp.where(active, weight, 0.0))
        grant = remaining[None, :] * (weight / jnp.maximum(total_w, 1e-9))[:, None]
        new_deserved = deserved + jnp.where(active[:, None], grant, 0.0)

        # capability clamp: if any dimension exceeds capability, queue is met
        # at min(deserved, capability, request) (proportion.go:163-169)
        over_cap = active & ~le_all(new_deserved, capability)
        # request met: request <= deserved in all dims (proportion.go:170-173)
        req_met = active & ~over_cap & le_all(request, new_deserved)

        capped = jnp.minimum(jnp.minimum(new_deserved, capability), request)
        # still-unmet queues clamp per-dimension to request
        # (MinDimensionResource, proportion.go:174-177)
        clamped = jnp.minimum(new_deserved, request)

        new_deserved = jnp.where(over_cap[:, None], capped,
                                 jnp.where(req_met[:, None],
                                           jnp.minimum(new_deserved, request),
                                           jnp.where(active[:, None], clamped,
                                                     deserved)))
        new_meet = meet | over_cap | req_met

        delta = jnp.sum(new_deserved - deserved, axis=0)   # inc - dec per dim
        new_remaining = remaining - delta
        moved = jnp.any(jnp.abs(delta) >= EPS)
        return i + 1, new_deserved, new_meet, new_remaining, moved

    init = (jnp.int32(0), jnp.zeros_like(request),
            jnp.zeros(Q, dtype=bool), total, jnp.bool_(True))
    _, deserved, _, _, _ = jax.lax.while_loop(cond, body, init)
    share = dominant_share(allocated, jnp.maximum(deserved, 0.0))
    return ProportionResult(deserved=deserved, share=share)


def proportion_deserved_numpy(total, weight, request, capability, allocated,
                              max_iters: int = 64) -> ProportionResult:
    """NumPy twin of proportion_deserved — same fixed-point semantics, zero
    compile cost. The scheduler plugin uses this for small queue counts so
    the first cycle never stalls on a device compile; the JAX kernel remains
    the scale path and both are cross-checked in tests."""
    import numpy as np

    total = np.asarray(total, np.float32).copy()
    weight = np.asarray(weight, np.float32)
    request = np.asarray(request, np.float32)
    capability = np.asarray(capability, np.float32)
    allocated = np.asarray(allocated, np.float32)
    Q, R = request.shape

    deserved = np.zeros_like(request)
    meet = np.zeros(Q, dtype=bool)
    remaining = total
    for _ in range(max_iters):
        active = ~meet
        total_w = weight[active].sum()
        if total_w <= 0 or not (remaining >= EPS).any():
            break
        grant = remaining[None, :] * (weight / max(total_w, 1e-9))[:, None]
        new_deserved = deserved + np.where(active[:, None], grant, 0.0)

        over_cap = active & ~np.all(new_deserved < capability + EPS, axis=-1)
        req_met = active & ~over_cap & np.all(request < new_deserved + EPS,
                                              axis=-1)
        capped = np.minimum(np.minimum(new_deserved, capability), request)
        clamped = np.minimum(new_deserved, request)
        new_deserved = np.where(over_cap[:, None], capped,
                                np.where(req_met[:, None],
                                         np.minimum(new_deserved, request),
                                         np.where(active[:, None], clamped,
                                                  deserved)))
        meet = meet | over_cap | req_met
        delta = (new_deserved - deserved).sum(axis=0)
        remaining = remaining - delta
        deserved = new_deserved
        if not (np.abs(delta) >= EPS).any():
            break

    denom = np.maximum(deserved, 0.0)
    ratio = np.where(denom > 0, allocated / np.where(denom > 0, denom, 1.0),
                     np.where(allocated > 0, 1.0, 0.0))
    return ProportionResult(deserved=deserved, share=ratio.max(axis=-1))


def dominant_share(used: jnp.ndarray, denom: jnp.ndarray) -> jnp.ndarray:
    """max_r used_r/denom_r, dims with denom 0: share=1 if used>0 else 0
    (proportion.go updateShare / drf.go calculateShare)."""
    ratio = jnp.where(denom > 0, used / jnp.where(denom > 0, denom, 1.0),
                      jnp.where(used > 0, 1.0, 0.0))
    return jnp.max(ratio, axis=-1)


def drf_shares(job_allocated: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """DRF dominant share per job: allocated f32[J,R], total f32[R] -> f32[J]."""
    return dominant_share(job_allocated, jnp.broadcast_to(total, job_allocated.shape))


def queue_overused(allocated: jnp.ndarray, deserved: jnp.ndarray) -> jnp.ndarray:
    """proportion OverusedFn (proportion.go:244): allocated exceeds deserved
    in ANY dimension, i.e. NOT allocated <= deserved in all dims."""
    return ~le_all(allocated, deserved)
