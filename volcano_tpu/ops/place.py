"""The placement kernel: Volcano's allocate loop as one jitted scan.

The reference allocates task-by-task (actions/allocate/allocate.go:42-277),
mutating node Idle as it goes, and wraps each job's placements in a Statement
that commits only if the gang is Ready (statement.go:229-289,352-395). Here
the whole loop is a single ``lax.scan`` over the ordered task list:

- carry: tentative node state + the last committed state (the Statement
  undo-log, reduced to "restore the snapshot saved at job start");
- per step: feasibility = dense resource fit vs FutureIdle (allocate.go:111-118)
  AND a host-precomputed static predicate mask; score = static score matrix +
  dynamic state-dependent scorers (ops/scores.py); best node by argmax
  (reference tie-breaks randomly, scheduler_helper.go:210-225 — we tie-break
  by lowest node index for determinism);
- allocate if the task fits Idle, else pipeline onto FutureIdle
  (allocate.go:232-256);
- at a job boundary: gang check (gang.go jobReadyFn: occupied >= MinAvailable)
  decides commit vs rollback, exactly Statement.Commit/Discard — a job that is
  merely Pipelined keeps its session-local state but emits no binds
  (allocate.go:264-270).

Because every step is vector ops over [N, R] arrays, XLA fuses the whole
per-task body into a few kernels; T sequential steps are the only serial
dimension. For batched/parallel placement see ops/auction.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .dense import EPS, le_all
from .scores import ScoreWeights, combined_dynamic_score

NO_NODE = -1


class NodeState(NamedTuple):
    """Mutable per-node accounting (api.NodeInfo reduced to arrays)."""

    idle: jnp.ndarray          # f32[N,R]
    future_idle: jnp.ndarray   # f32[N,R] = idle + releasing - pipelined
    used: jnp.ndarray          # f32[N,R]
    ntasks: jnp.ndarray        # i32[N] current pod count


class PlacementTasks(NamedTuple):
    """Pending tasks in processing order (host decides the order: the
    namespace/queue/job/task priority-queue interleave)."""

    req: jnp.ndarray           # f32[T,R]
    job_ix: jnp.ndarray        # i32[T]
    valid: jnp.ndarray         # bool[T] padding mask
    feas: jnp.ndarray          # bool[T,N] static predicates (affinity/taints/...)
    static_score: jnp.ndarray  # f32[T,N] session-constant score terms
    first_of_job: jnp.ndarray  # bool[T]
    last_of_job: jnp.ndarray   # bool[T]


class JobMeta(NamedTuple):
    min_available: jnp.ndarray   # i32[J]
    base_ready: jnp.ndarray      # i32[J] ReadyTaskNum before this action
    base_pipelined: jnp.ndarray  # i32[J] WaitingTaskNum before this action


class PlacementResult(NamedTuple):
    task_node: jnp.ndarray     # i32[T] chosen node or NO_NODE
    task_pipelined: jnp.ndarray  # bool[T] pipeline (vs allocate)
    job_ready: jnp.ndarray     # bool[J] gang Ready -> Statement committed (bind)
    job_kept: jnp.ndarray      # bool[J] state kept (ready or pipelined)
    nodes: NodeState           # final committed node state


class _Carry(NamedTuple):
    tent: NodeState            # tentative (inside current job's statement)
    saved: NodeState           # committed state at current job's start
    cnt_alloc: jnp.ndarray     # i32 newly-allocated tasks of current job
    cnt_pipe: jnp.ndarray      # i32 newly-pipelined tasks of current job
    broken: jnp.ndarray        # bool: a task of this job had no feasible node


def _select(pred, a: NodeState, b: NodeState) -> NodeState:
    return NodeState(*(jnp.where(pred, x, y) for x, y in zip(a, b)))


def make_node_state(idle, releasing, pipelined, used, ntasks) -> NodeState:
    return NodeState(idle=idle, future_idle=idle + releasing - pipelined,
                     used=used, ntasks=ntasks)


def place_scan(nodes: NodeState, tasks: PlacementTasks, jobs: JobMeta,
               weights: ScoreWeights, allocatable: jnp.ndarray,
               max_tasks: jnp.ndarray, unroll: int = 8,
               axis=None, shard_offset=None) -> PlacementResult:
    """Run the sequential-parity placement over all tasks.

    allocatable: f32[N,R]; max_tasks: i32[N] (pod-count capacity; the
    reference checks it first in the predicate chain, predicates.go:267-290).
    unroll amortizes the TPU while-loop per-iteration overhead over several
    task steps without changing sequential semantics.

    ``axis``/``shard_offset`` make the same kernel run node-sharded inside
    a shard_map (ops/unified.place_scan_unified): per-node arrays are the
    local shards, the per-step argmax is resolved by one all_gather of
    per-shard (score, global index, fit) maxima with ties falling to the
    lowest shard — i.e. the lowest global node index, exactly the
    single-device ``jnp.argmax`` tie-break — and node deltas apply on the
    owning shard only. With ``axis=None`` (the default) the program below
    is literally the unsharded original; task_node indices are global
    either way, so decisions are byte-identical at every mesh size.
    """
    J = jobs.min_available.shape[0]

    def step(carry: _Carry, inp):
        (req, job_ix, valid, feas, static_score,
         first_of_job, last_of_job) = inp

        # Job boundary: snapshot committed state (Statement open).
        saved = _select(first_of_job, carry.tent, carry.saved)
        cnt_alloc = jnp.where(first_of_job, 0, carry.cnt_alloc)
        cnt_pipe = jnp.where(first_of_job, 0, carry.cnt_pipe)
        broken = jnp.where(first_of_job, False, carry.broken)
        tent = carry.tent

        # Predicate: resource fit vs FutureIdle + static mask + pod count
        # (allocate.go:111-118 predicateFn).
        pods_ok = tent.ntasks < max_tasks
        fit_future = le_all(req[None, :], tent.future_idle) & feas & pods_ok
        fit_idle = le_all(req[None, :], tent.idle) & fit_future
        if axis is None:
            has_node = jnp.any(fit_future)
        else:
            has_node = jax.lax.psum(
                jnp.any(fit_future).astype(jnp.int32), axis) > 0

        # Reference breaks out of the job's task loop when no node passes
        # predicates (allocate.go:206-210).
        attempt = valid & ~broken
        broken = broken | (attempt & ~has_node)

        score = static_score + combined_dynamic_score(
            req, tent.used, allocatable, weights)
        # Prefer feasible nodes; among them argmax score, lowest index on tie.
        masked = jnp.where(fit_future, score, -jnp.inf)
        if axis is None:
            best = jnp.argmax(masked)
            fit_idle_best = fit_idle[best]
        else:
            lbest = jnp.argmax(masked)
            g_score = jax.lax.all_gather(masked[lbest], axis)       # [D]
            g_idx = jax.lax.all_gather(lbest + shard_offset, axis)
            g_fit = jax.lax.all_gather(fit_idle[lbest], axis)
            # argmax over shards: first max wins = lowest shard = lowest
            # global index (per-shard argmax already picked the lowest
            # local index), so ties resolve exactly as unsharded
            w = jnp.argmax(g_score)
            best = g_idx[w]
            fit_idle_best = g_fit[w]

        do_place = attempt & has_node
        do_alloc = do_place & fit_idle_best
        do_pipe = do_place & ~fit_idle_best

        if axis is None:
            onehot = (jnp.arange(tent.idle.shape[0])
                      == best)[:, None]                             # [N,1]
        else:
            # global comparison doubles as the owner-shard mask: the
            # one-hot is all-False on every non-owning shard
            onehot = ((jnp.arange(tent.idle.shape[0]) + shard_offset)
                      == best)[:, None]                             # [Nl,1]
        delta = onehot * req[None, :]
        new_idle = tent.idle - jnp.where(do_alloc, delta, 0.0)
        new_used = tent.used + jnp.where(do_alloc, delta, 0.0)
        # allocate consumes idle (so future_idle too); pipeline only reserves
        # future resources (node_info.go AddTask Pipelined case).
        new_fidle = tent.future_idle - jnp.where(do_place, delta, 0.0)
        new_ntasks = tent.ntasks + jnp.where(
            do_place, onehot[:, 0].astype(jnp.int32), 0)
        tent = NodeState(new_idle, new_fidle, new_used, new_ntasks)

        cnt_alloc = cnt_alloc + do_alloc.astype(jnp.int32)
        cnt_pipe = cnt_pipe + do_pipe.astype(jnp.int32)

        # Job boundary close: gang vote (gang.go:45-216) -> commit/keep/rollback.
        min_avail = jobs.min_available[job_ix]
        ready = jobs.base_ready[job_ix] + cnt_alloc >= min_avail
        pipelined_ok = (jobs.base_ready[job_ix] + jobs.base_pipelined[job_ix]
                        + cnt_alloc + cnt_pipe >= min_avail)
        keep = ready | pipelined_ok
        commit_now = last_of_job & valid
        tent = _select(commit_now & ~keep, saved, tent)

        out = (jnp.where(do_place, best, NO_NODE).astype(jnp.int32),
               do_pipe,
               commit_now & ready,
               commit_now & keep)
        return _Carry(tent, saved, cnt_alloc, cnt_pipe, broken), out

    init = _Carry(tent=nodes, saved=nodes,
                  cnt_alloc=jnp.int32(0), cnt_pipe=jnp.int32(0),
                  broken=jnp.bool_(False))
    xs = (tasks.req, tasks.job_ix, tasks.valid, tasks.feas, tasks.static_score,
          tasks.first_of_job, tasks.last_of_job)
    carry, (task_node, task_pipe, job_ready_t, job_kept_t) = jax.lax.scan(
        step, init, xs, unroll=unroll)

    # Scatter per-boundary job verdicts to [J].
    job_ready = jnp.zeros(J, dtype=bool).at[tasks.job_ix].max(job_ready_t)
    job_kept = jnp.zeros(J, dtype=bool).at[tasks.job_ix].max(job_kept_t)

    kept_task = job_kept[tasks.job_ix]
    task_node = jnp.where(kept_task, task_node, NO_NODE)
    return PlacementResult(task_node=task_node, task_pipelined=task_pipe,
                           job_ready=job_ready, job_kept=job_kept,
                           nodes=carry.tent)


def place_scan_packed(nodes: NodeState, tasks: PlacementTasks, jobs: JobMeta,
                      weights: ScoreWeights, allocatable: jnp.ndarray,
                      max_tasks: jnp.ndarray, unroll: int = 8,
                      axis=None, shard_offset=None):
    """place_scan with all host-bound outputs packed into ONE i32 vector
    ``[task_node | task_pipelined | job_ready | job_kept]`` — a single
    device→host fetch. On tunneled backends every fetch costs a full RTT
    (~60ms measured), so result packing matters more than kernel time.
    The final NodeState is returned as device arrays (never fetched)."""
    res = place_scan(nodes, tasks, jobs, weights, allocatable, max_tasks,
                     unroll=unroll, axis=axis, shard_offset=shard_offset)
    packed = jnp.concatenate([
        res.task_node,
        res.task_pipelined.astype(jnp.int32),
        res.job_ready.astype(jnp.int32),
        res.job_kept.astype(jnp.int32)])
    return packed, res.nodes


class _CarryTopo(NamedTuple):
    tent: NodeState            # tentative (inside current job's statement)
    saved: NodeState           # committed state at current job's start
    cnt_alloc: jnp.ndarray     # i32 newly-allocated tasks of current job
    cnt_pipe: jnp.ndarray      # i32 newly-pipelined tasks of current job
    broken: jnp.ndarray        # bool: a task of this job had no feasible node
    anchor: jnp.ndarray        # i32 zone code of the job's first placement (0=none)


def place_scan_topo(nodes: NodeState, tasks: PlacementTasks, jobs: JobMeta,
                    weights: ScoreWeights, allocatable: jnp.ndarray,
                    max_tasks: jnp.ndarray, zone_code: jnp.ndarray,
                    topo_weight: jnp.ndarray,
                    unroll: int = 8) -> PlacementResult:
    """place_scan with a batched gang-compactness term (Tesserae-style
    topology packing as a score term, not a host filter).

    zone_code: i32[N] per-node topology-zone code (0 = unzoned). The
    interconnect-distance matrix is block-constant over zones (intra-zone
    ~0, inter-zone ~1 for rack/NUMA locality), so it factors into this
    per-node axis — the only shape compatible with the persistent
    snapshot's row-wise dirty-set/scatter contract. The job's FIRST
    placement anchors its zone; every later member scores
    ``+topo_weight`` on nodes sharing that zone, steering the argmax
    toward co-location while resource fit and the other score terms
    still dominate infeasible-or-worse choices. topo_weight: f32 scalar
    (traced, so one compiled program serves all weights)."""
    J = jobs.min_available.shape[0]

    def step(carry: _CarryTopo, inp):
        (req, job_ix, valid, feas, static_score,
         first_of_job, last_of_job) = inp

        saved = _select(first_of_job, carry.tent, carry.saved)
        cnt_alloc = jnp.where(first_of_job, 0, carry.cnt_alloc)
        cnt_pipe = jnp.where(first_of_job, 0, carry.cnt_pipe)
        broken = jnp.where(first_of_job, False, carry.broken)
        anchor = jnp.where(first_of_job, 0, carry.anchor)
        tent = carry.tent

        pods_ok = tent.ntasks < max_tasks
        fit_future = le_all(req[None, :], tent.future_idle) & feas & pods_ok
        fit_idle = le_all(req[None, :], tent.idle) & fit_future
        has_node = jnp.any(fit_future)

        attempt = valid & ~broken
        broken = broken | (attempt & ~has_node)

        score = static_score + combined_dynamic_score(
            req, tent.used, allocatable, weights)
        same_zone = (zone_code == anchor) & (anchor != 0)
        score = score + topo_weight * same_zone.astype(score.dtype)
        masked = jnp.where(fit_future, score, -jnp.inf)
        best = jnp.argmax(masked)

        do_place = attempt & has_node
        do_alloc = do_place & fit_idle[best]
        do_pipe = do_place & ~fit_idle[best]
        anchor = jnp.where(do_place & (anchor == 0), zone_code[best], anchor)

        onehot = (jnp.arange(tent.idle.shape[0]) == best)[:, None]  # [N,1]
        delta = onehot * req[None, :]
        new_idle = tent.idle - jnp.where(do_alloc, delta, 0.0)
        new_used = tent.used + jnp.where(do_alloc, delta, 0.0)
        new_fidle = tent.future_idle - jnp.where(do_place, delta, 0.0)
        new_ntasks = tent.ntasks + jnp.where(
            do_place, onehot[:, 0].astype(jnp.int32), 0)
        tent = NodeState(new_idle, new_fidle, new_used, new_ntasks)

        cnt_alloc = cnt_alloc + do_alloc.astype(jnp.int32)
        cnt_pipe = cnt_pipe + do_pipe.astype(jnp.int32)

        min_avail = jobs.min_available[job_ix]
        ready = jobs.base_ready[job_ix] + cnt_alloc >= min_avail
        pipelined_ok = (jobs.base_ready[job_ix] + jobs.base_pipelined[job_ix]
                        + cnt_alloc + cnt_pipe >= min_avail)
        keep = ready | pipelined_ok
        commit_now = last_of_job & valid
        tent = _select(commit_now & ~keep, saved, tent)

        out = (jnp.where(do_place, best, NO_NODE).astype(jnp.int32),
               do_pipe,
               commit_now & ready,
               commit_now & keep)
        return _CarryTopo(tent, saved, cnt_alloc, cnt_pipe, broken,
                          anchor), out

    init = _CarryTopo(tent=nodes, saved=nodes,
                      cnt_alloc=jnp.int32(0), cnt_pipe=jnp.int32(0),
                      broken=jnp.bool_(False), anchor=jnp.int32(0))
    xs = (tasks.req, tasks.job_ix, tasks.valid, tasks.feas, tasks.static_score,
          tasks.first_of_job, tasks.last_of_job)
    carry, (task_node, task_pipe, job_ready_t, job_kept_t) = jax.lax.scan(
        step, init, xs, unroll=unroll)

    job_ready = jnp.zeros(J, dtype=bool).at[tasks.job_ix].max(job_ready_t)
    job_kept = jnp.zeros(J, dtype=bool).at[tasks.job_ix].max(job_kept_t)

    kept_task = job_kept[tasks.job_ix]
    task_node = jnp.where(kept_task, task_node, NO_NODE)
    return PlacementResult(task_node=task_node, task_pipelined=task_pipe,
                           job_ready=job_ready, job_kept=job_kept,
                           nodes=carry.tent)


def place_scan_topo_packed(nodes: NodeState, tasks: PlacementTasks,
                           jobs: JobMeta, weights: ScoreWeights,
                           allocatable: jnp.ndarray, max_tasks: jnp.ndarray,
                           zone_code: jnp.ndarray, topo_weight: jnp.ndarray,
                           unroll: int = 8):
    """place_scan_topo with the place_scan_packed single-fetch layout."""
    res = place_scan_topo(nodes, tasks, jobs, weights, allocatable,
                          max_tasks, zone_code, topo_weight, unroll=unroll)
    packed = jnp.concatenate([
        res.task_node,
        res.task_pipelined.astype(jnp.int32),
        res.job_ready.astype(jnp.int32),
        res.job_kept.astype(jnp.int32)])
    return packed, res.nodes


def unpack_placement(packed: "np.ndarray", T_padded: int, J: int):
    """Split the packed vector back into (task_node, task_pipelined,
    job_ready, job_kept) numpy views."""
    task_node = packed[:T_padded]
    task_pipe = packed[T_padded:2 * T_padded].astype(bool)
    job_ready = packed[2 * T_padded:2 * T_padded + J].astype(bool)
    job_kept = packed[2 * T_padded + J:2 * T_padded + 2 * J].astype(bool)
    return task_node, task_pipe, job_ready, job_kept


def gang_admission(assigned: jnp.ndarray, job_ix: jnp.ndarray,
                   min_needed: jnp.ndarray) -> jnp.ndarray:
    """Gang feasibility reduction: per-job count of assigned tasks vs
    remaining minAvailable (the batched analogue of JobInfo.Ready,
    job_info.go:587-590). assigned: bool[T]; returns bool[J]."""
    counts = jax.ops.segment_sum(assigned.astype(jnp.int32), job_ix,
                                 num_segments=min_needed.shape[0])
    return counts >= min_needed
