"""Backfill action: place best-effort (zero-request) tasks on any node that
passes predicates.

Mirrors /root/reference/pkg/scheduler/actions/backfill/backfill.go:40-92.
"""

from __future__ import annotations

from ..api import FitErrors, PodGroupPhase, TaskStatus
from ..obs import trace as obs_trace
from .base import Action


class BackfillAction(Action):
    NAME = "backfill"

    def execute(self, ssn) -> None:
        with obs_trace.span("backfill_scan"):
            self._execute(ssn)

    def _execute(self, ssn) -> None:
        for job in list(ssn.jobs.values()):
            if job.podgroup.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            pending = list(job.task_status_index.get(TaskStatus.PENDING,
                                                     {}).values())
            for task in pending:
                if not task.init_resreq.is_empty():
                    continue
                fe = FitErrors()
                allocated = False
                for node in ssn.nodes.values():
                    try:
                        ssn.predicate_fn(task, node)
                    except Exception as err:
                        fe.set_node_error(node.name,
                                          getattr(err, "fit_error", err))
                        continue
                    ssn.allocate(task, node)
                    allocated = True
                    break
                if not allocated:
                    job.nodes_fit_errors[task.uid] = fe
