"""Elect action: pick the reservation target job.

Mirrors /root/reference/pkg/scheduler/actions/elect/elect.go:28-51.
"""

from __future__ import annotations

from ..api import PodGroupPhase
from ..obs import trace as obs_trace
from ..utils.reservation import Reservation
from .base import Action


class ElectAction(Action):
    NAME = "elect"

    def execute(self, ssn) -> None:
        if Reservation.target_job is not None:
            return
        with obs_trace.span("elect_target"):
            pending = [job for job in ssn.jobs.values()
                       if job.podgroup.phase == PodGroupPhase.PENDING]
            Reservation.target_job = ssn.target_job(pending)
