"""Action interface (mirrors
/root/reference/pkg/scheduler/framework/interface.go:20-32)."""

from __future__ import annotations


class Action:
    NAME = "action"

    def name(self) -> str:
        return self.NAME

    def initialize(self) -> None:
        pass

    def execute(self, ssn) -> None:
        raise NotImplementedError

    def uninitialize(self) -> None:
        pass
