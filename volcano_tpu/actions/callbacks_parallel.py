"""`callbacks-parallel`: the callbacks engine with its two hot loops fanned
out over a process pool — the faithful mirror of the reference's 16-way
``workqueue.ParallelizeUntil`` in PredicateNodes / PrioritizeNodes
(/root/reference/pkg/scheduler/util/scheduler_helper.go:121,157).

This engine exists to keep the CPU-vs-TPU benchmark honest at the headline
10k-pods/2k-nodes config: the single-threaded Python callbacks loop
overstates the reference's cycle time by ~the worker count, so the bench
compares the device engines against THIS engine's wall-clock while
asserting its decisions equal the serial callbacks engine's.

Design (Go shares memory between its 16 goroutines; Python processes
cannot, so):

- the pool forks AFTER the session opens — each worker inherits the full
  session snapshot (plugins, registered closures, node state) copy-on-write
  and evaluates the same ``predicate_fn`` / ``node_order_fn`` chains its
  parent would;
- in-cycle state divergence is fixed by a placement journal: every
  statement op (allocate/pipeline, and their reverses on gang discard) is
  appended by the main process and shipped to each worker piggybacked on
  its next evaluation request — workers replay the ops against their own
  session copy before scanning, so every evaluation sees exactly the state
  the serial engine would;
- decisions stay bit-identical to the serial engine: the default conf
  scans 100% of nodes (no early-exit nondeterminism), chunk results merge
  in node order, batch scores and best-node selection run in the main
  process.
"""

from __future__ import annotations

import multiprocessing as mp
import os
from typing import Dict, List

from ..api import TaskStatus
from ..api.unschedule_info import FitErrors
from ..utils.scheduler_helper import (calculate_num_feasible_nodes,
                                      select_best_node)

DEFAULT_WORKERS = 16        # scheduler_helper.go:121 workqueue width


def effective_cpus() -> int:
    """CPUs actually available to THIS process (cgroup/affinity aware) —
    os.cpu_count() reports host cores and over-forks in containers."""
    try:
        return max(1, len(os.sched_getaffinity(0)))
    except (AttributeError, OSError):
        return max(1, os.cpu_count() or 1)


def _worker_main(conn, ssn, node_names: List[str]) -> None:
    """Forked worker: owns a COW copy of the session; replays journal ops
    and evaluates predicate/score chunks on request."""
    nodes = ssn.nodes

    def apply_ops(ops) -> None:
        for op, job_uid, task_uid, hostname in ops:
            job = ssn.jobs[job_uid]
            task = job.tasks[task_uid]
            if op == "alloc" or op == "pipe":
                status = (TaskStatus.ALLOCATED if op == "alloc"
                          else TaskStatus.PIPELINED)
                job.update_task_status(task, status)
                task.node_name = hostname
                nodes[hostname].add_task(task)
            else:                              # un-alloc / un-pipe
                job.update_task_status(task, TaskStatus.PENDING)
                node = nodes.get(task.node_name)
                if node is not None:
                    node.remove_task(task)
                task.node_name = ""

    while True:
        msg = conn.recv()
        cmd = msg[0]
        if cmd == "stop":
            return
        ops, job_uid, task_uid, lo, hi = msg[1], msg[2], msg[3], msg[4], msg[5]
        apply_ops(ops)
        task = ssn.jobs[job_uid].tasks[task_uid]
        if cmd == "pred":
            feasible: List[str] = []
            errors: List = []
            for name in node_names[lo:hi]:
                node = nodes[name]
                try:
                    if not task.init_resreq.less_equal(node.future_idle()):
                        from .allocate import _fit_error
                        raise _fit_error(task, node)
                    ssn.predicate_fn(task, node)
                except Exception as err:       # noqa: BLE001 — mirrors serial
                    errors.append((name, getattr(err, "fit_error", str(err))))
                    continue
                feasible.append(name)
            conn.send((feasible, errors))
        elif cmd == "score":
            cand = msg[6]
            scores = [ssn.node_order_fn(task, nodes[name]) for name in cand]
            conn.send(scores)


class _ScanPool:
    def __init__(self, ssn, workers: int):
        self.node_names = list(ssn.nodes)
        self.workers = workers
        self.pipes = []
        self.procs = []
        self.journal: List[tuple] = []
        self.cursor = [0] * workers
        ctx = mp.get_context("fork")
        for w in range(workers):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_worker_main,
                            args=(child, ssn, self.node_names), daemon=True)
            p.start()
            child.close()
            self.pipes.append(parent)
            self.procs.append(p)

    def _send(self, w: int, cmd: str, job_uid, task_uid, lo, hi, extra=None):
        ops = self.journal[self.cursor[w]:]
        self.cursor[w] = len(self.journal)
        msg = [cmd, ops, job_uid, task_uid, lo, hi]
        if extra is not None:
            msg.append(extra)
        self.pipes[w].send(tuple(msg))

    def _chunks(self, n: int):
        per = -(-n // self.workers)
        return [(w, w * per, min(n, (w + 1) * per))
                for w in range(self.workers) if w * per < n]

    def predicate(self, task):
        N = len(self.node_names)
        chunks = self._chunks(N)
        for w, lo, hi in chunks:
            self._send(w, "pred", task.job, task.uid, lo, hi)
        feasible: List[str] = []
        errors = FitErrors()
        for w, lo, hi in chunks:
            names, errs = self.pipes[w].recv()
            feasible.extend(names)
            for name, fe in errs:
                errors.set_node_error(name, fe)
        return feasible, errors

    def score(self, task, candidates: List[str]) -> Dict[str, float]:
        n = len(candidates)
        chunks = self._chunks(n)
        for w, lo, hi in chunks:
            self._send(w, "score", task.job, task.uid, lo, hi,
                       extra=candidates[lo:hi])
        out: Dict[str, float] = {}
        for w, lo, hi in chunks:
            scores = self.pipes[w].recv()
            for name, s in zip(candidates[lo:hi], scores):
                out[name] = s
        return out

    def record(self, op: str, task) -> None:
        self.journal.append((op, task.job, task.uid, task.node_name))

    def record_reverts(self, ops) -> None:
        from ..framework.statement import ALLOCATE, PIPELINE
        for op in reversed(ops):
            kind = "un-alloc" if op.name == ALLOCATE else "un-pipe"
            self.journal.append((kind, op.task.job, op.task.uid, ""))

    def stop(self) -> None:
        for pipe in self.pipes:
            try:
                pipe.send(("stop",))
            except (BrokenPipeError, OSError):
                pass
        for p in self.procs:
            p.join(timeout=2)
            if p.is_alive():
                p.terminate()


class ParallelCallbackJobPlacer:
    """Drop-in for _CallbackJobPlacer with pooled node scans. Requires the
    default full-node scan (percentage 100) — an adaptive early-exit scan
    is order-dependent and stays on the serial engine."""

    def __init__(self, ssn, workers: int = 0):
        self.ssn = ssn
        self.workers = workers or min(DEFAULT_WORKERS, effective_cpus())
        self.pool = _ScanPool(ssn, self.workers)

    def place(self, job, tasks, stmt, jobs_pq) -> bool:
        ssn = self.ssn
        pool = self.pool
        node_map = ssn.nodes

        while tasks:
            task = tasks.pop(0)
            to_find = calculate_num_feasible_nodes(len(pool.node_names))
            feasible_names, fit_errors = pool.predicate(task)
            feasible = [node_map[n] for n in feasible_names[:to_find]]
            if not feasible:
                job.nodes_fit_errors[task.uid] = fit_errors
                break

            candidates = [n for n in feasible
                          if task.init_resreq.less_equal(n.idle)
                          or task.init_resreq.less_equal(n.future_idle())]
            if not candidates:
                continue

            name_scores = pool.score(task, [n.name for n in candidates])
            for name, s in (ssn.batch_node_order_fn(
                    task, candidates) or {}).items():
                if name in name_scores:
                    name_scores[name] += s
            grouped: Dict[float, List] = {}
            for n in candidates:
                grouped.setdefault(name_scores[n.name], []).append(n)
            node = ssn.best_node_fn(task, grouped) or select_best_node(grouped)

            if task.init_resreq.less_equal(node.idle):
                stmt.allocate(task, node)
                pool.record("alloc", task)
            elif task.init_resreq.less_equal(node.future_idle()):
                stmt.pipeline(task, node.name)
                pool.record("pipe", task)

            if ssn.job_ready(job) and tasks:
                jobs_pq.push(job)
                return True
        return False

    def statement_closed(self, job, committed: bool, ops) -> None:
        """Called by the action when the job's statement commits or
        discards; a discard must be replayed into the worker journals."""
        if not committed:
            self.pool.record_reverts(ops)

    def close(self) -> None:
        self.pool.stop()
