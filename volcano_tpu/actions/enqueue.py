"""Enqueue action: gates PodGroup Pending -> Inqueue on plugin votes.

Mirrors /root/reference/pkg/scheduler/actions/enqueue/enqueue.go:43-102.
"""

from __future__ import annotations

from ..api import PodGroupPhase
from ..obs import trace as obs_trace
from ..utils import PriorityQueue
from .base import Action


class EnqueueAction(Action):
    NAME = "enqueue"

    def execute(self, ssn) -> None:
        with obs_trace.span("enqueue_gate"):
            self._execute(ssn)

    def _execute(self, ssn) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_set = set()
        jobs_map = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            if job.podgroup.phase == PodGroupPhase.PENDING:
                jobs_map.setdefault(queue.uid, PriorityQueue(ssn.job_order_fn)
                                    ).push(job)

        while not queues.empty():
            queue = queues.pop()
            jobs = jobs_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            if job.podgroup.min_resources is None or ssn.job_enqueueable(job):
                job.podgroup.phase = PodGroupPhase.INQUEUE
                ssn.job_enqueued(job)
                # write the phase through immediately (not just at session
                # close): the job controller's syncTask gate and the store's
                # bind gate both key off the STORE phase, and allocate may
                # bind this gang later in the same cycle
                ssn.cache.update_job_status(job)
            queues.push(queue)
