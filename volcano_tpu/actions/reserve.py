"""Reserve action: lock nodes for the elected target job until it schedules.

Mirrors /root/reference/pkg/scheduler/actions/reserve/reserve.go:40-77.
"""

from __future__ import annotations

from ..obs import trace as obs_trace
from ..utils.reservation import Reservation
from .base import Action


class ReserveAction(Action):
    NAME = "reserve"

    def execute(self, ssn) -> None:
        if Reservation.target_job is None:
            return
        with obs_trace.span("reserve_nodes"):
            target = ssn.jobs.get(Reservation.target_job.uid)
            if target is None:
                Reservation.reset()
                return
            Reservation.target_job = target
            if not target.ready():
                ssn.reserved_nodes()
            else:
                Reservation.reset()
