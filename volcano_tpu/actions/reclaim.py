"""Reclaim action: cross-queue eviction for non-overused queues.

Mirrors /root/reference/pkg/scheduler/actions/reclaim/reclaim.go:40-192 —
victims come from OTHER queues that are reclaimable, via the tiered
Reclaimable dispatch; eviction is direct (ssn.evict, no statement).
"""

from __future__ import annotations

from typing import Optional

from ..api import PodGroupPhase, Resource, TaskStatus
from ..obs import trace as obs_trace
from ..utils import PriorityQueue
from .base import Action


class ReclaimAction(Action):
    NAME = "reclaim"
    DEFAULT_ENGINE = "callbacks"

    def __init__(self, engine: Optional[str] = None):
        self.engine = engine or self.DEFAULT_ENGINE

    def execute(self, ssn) -> None:
        engine = self.engine
        for conf in ssn.configurations:
            if conf.name == self.NAME:
                engine = conf.arguments.get("engine", engine)
        if engine == "tpu":
            from .evict_tpu import execute_reclaim_tpu
            return execute_reclaim_tpu(ssn)
        with obs_trace.span("reclaim_rotation", engine=engine):
            return self._execute_callbacks(ssn)

    def _execute_callbacks(self, ssn, screener=None) -> None:
        """The reference rotation verbatim. ``screener`` (optional) is a
        conservative node pre-filter — it must return a SUPERSET of the
        nodes whose per-node body could succeed, in ssn.nodes order; the
        exact per-node logic below is what decides, so a screener can only
        skip work, never change a decision (evict_tpu._ReclaimScreener
        proves the superset property from the invariant that an eviction
        moves exactly its resreq from the evictable pool into future-idle).
        note_pipeline keeps the screener's headroom conservative."""
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_set = set()
        preemptors_map = {}
        preemptor_tasks = {}

        for job in ssn.jobs.values():
            if job.podgroup.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_set:
                queue_set.add(queue.uid)
                queues.push(queue)
            pending = job.task_status_index.get(TaskStatus.PENDING, {})
            if pending:
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                pq = PriorityQueue(ssn.task_order_fn)
                for task in pending.values():
                    pq.push(task)
                preemptor_tasks[job.uid] = pq

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            assigned = False
            node_iter = (screener.nodes_for(task) if screener is not None
                         else ssn.nodes.values())
            for node in node_iter:
                try:
                    ssn.predicate_fn(task, node)
                except Exception:
                    continue

                reclaimees = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is None or j.queue == job.queue:
                        continue
                    victim_queue = ssn.queues.get(j.queue)
                    if victim_queue is None or not victim_queue.reclaimable:
                        continue
                    reclaimees.append(t.clone())
                victims = ssn.reclaimable(task, reclaimees)
                if not victims:
                    continue
                future_idle = node.future_idle()
                for v in victims:
                    future_idle.add(v.resreq)
                if not task.init_resreq.less_equal(future_idle):
                    continue

                resreq = task.init_resreq.clone()
                reclaimed = Resource()
                for reclaimee in victims:
                    ssn.evict(ssn.jobs[reclaimee.job].tasks[reclaimee.uid],
                              "reclaim")
                    if screener is not None:
                        screener.note_evict(reclaimee)
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimed):
                        break
                if task.init_resreq.less_equal(reclaimed):
                    ssn.pipeline(task, node.name)
                    if screener is not None:
                        screener.note_pipeline(task, node)
                    assigned = True
                    break

            if assigned:
                jobs.push(job)
            queues.push(queue)
