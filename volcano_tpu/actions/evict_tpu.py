"""Host side of the eviction engines (SURVEY M3).

PREEMPT assembles victim/preemptor tensors, precomputes per-tier
per-plugin veto masks through the REAL plugin callbacks, runs the
ops/evict.py cursor walk (which replays the tier dispatch per
(preemptor, node) including drf's dynamic dominant-share tier), and
replays the proposals on the host — through genuine Statements with
live-chain re-validation for custom-plugin confs, or the batched fast
replay (aggregated deltas + the live gang job_pipelined gate) for stock
confs. Victims ship to the device in a dense node-major ``[N, W]`` slot
layout (ops/evict.py EvictNW); every resource quantity is gcd-scaled to
exact small integers and node preferences travel as dense ranks of
host-f64 scores, which is what makes the device decisions bit-identical
to the callback engine at full benchmark scale (r4).

Preempt's fixed-order caveat: queue/job order is precomputed once per
action on the opening snapshot — exact for the reference's preempt,
whose per-queue loop processes each starving job's tasks contiguously.

RECLAIM runs the LITERAL callback rotation (reclaim.py) through the
conservative vectorized node screener below (_ReclaimScreener): the
reference's one-task-per-queue-pop rotation re-orders jobs/queues
between pops, which no fixed-order device batching reproduces at scale,
so reclaim keeps the rotation on host and vectorizes only the per-attempt
node walk. Exact by construction.
"""

from __future__ import annotations

from functools import cmp_to_key
from typing import Dict, List, Optional

import numpy as np

from ..api import PodGroupPhase, Resource, TaskInfo, TaskStatus
from ..cache.snapshot import (NodeTensors, assemble_feasibility,
                              assemble_static_score, assemble_weights,
                              discover_resource_names)
from ..framework.session import ABSTAIN
from ..utils import PriorityQueue

NO_NODE = -1
BIG = 1 << 30

# below this many victims the whole action is latency-bound (one device
# round trip costs more than the CPU callbacks path end-to-end on remote
# TPU backends), so the tpu engine delegates to the callbacks engine —
# decisions are identical by the parity contract either way. Preempt's
# callbacks path does per-(task, node) predicate+score loops and loses to
# the device even at a few hundred victims, so it never delegates by
# default. Override with the action configuration key
# ``device-min-victims``. (Reclaim has no device kernel anymore — its
# exact screened rotation runs on host at every scale.)
DEVICE_MIN_VICTIMS = {"preempt": 0}

# above this many victims on ONE node the dense [N, W] slot layout
# degenerates (mostly pads; with a drf tier the walk also materializes an
# [N, W, W] prefix tensor on device), so the engine delegates the cycle to
# the callbacks path — decisions are identical by the parity contract
MAX_W = 64

# transient-HBM budget for the walk's largest intermediates: the [N, W, W]
# ``before`` tensor and the drf dispatch's [N, W, W, R] broadcast product
# (f32 elements). MAX_W alone does not bound them — 10k+ nodes with
# near-MAX_W victim skew would allocate GBs per full_eval. ~256M f32
# elements ≈ 1 GiB of transient HBM, comfortable on a 16 GiB chip.
MAX_NWWR_ELEMS = 256 << 20


def _slot_bucket(w: int) -> int:
    """Pad the [N, W] victim-slot axis to a pow2 bucket: W tracks the
    max victims on any one node, which shifts cycle to cycle under churn
    — unbucketed it keys a fresh XLA walk compile per distinct width
    (the VT006 exposure this closes). Pad slots carry the pad-victim
    sentinel (valid=False), so they can never be chosen."""
    b = 8
    while b < max(w, 1):
        b *= 2
    return b


def _ptask_bucket(p: int) -> int:
    """Pad the preemptor-task axis to a pow2 bucket (the walk's other
    data-dependent jit axis). Pad tasks form one trailing pad job whose
    pipeline quota is already met, so the task cursor skips them in one
    inactive step — they can never place or evict."""
    b = 8
    while b < max(p, 1):
        b *= 2
    return b


def _device_shape_ok(n_nodes: int, victims, n_res: int) -> bool:
    # budget with the BUCKETED width — the padded [N, W, W] tensors are
    # what actually allocates
    w = _slot_bucket(_max_per_node(victims))
    return w <= MAX_W and n_nodes * w * w * max(n_res, 1) <= MAX_NWWR_ELEMS


def _device_min_victims(ssn, action_name: str) -> int:
    default = DEVICE_MIN_VICTIMS[action_name]
    for conf in ssn.configurations:
        if conf.name == action_name:
            return int(conf.arguments.get("device-min-victims", default))
    return default


def _res_rows_f64(resources, rnames) -> np.ndarray:
    """[M, R] float64 straight from the Resource doubles (to_vector would
    round through f32 first, destroying the integer-exactness the scaled
    device arithmetic depends on). Column-wise comprehensions — the naive
    per-(resource, name) .get() costs ~100ms per eviction cycle at 10k
    tasks on the 1-CPU bench host."""
    out = np.empty((len(resources), len(rnames)), np.float64)
    for k, n in enumerate(rnames.names):
        if n == "cpu":
            out[:, k] = [r.cpu for r in resources]
        elif n == "memory":
            out[:, k] = [r.memory for r in resources]
        else:
            out[:, k] = [r.scalars.get(n, 0.0) for r in resources]
    return out


def _dim_scale(vals: np.ndarray) -> np.ndarray:
    """Per-dimension GCD of every quantity the device will see.

    Dividing by it turns memory-scale values (~1e11 bytes, f32 ULP ~8e3 —
    far above the 0.1 epsilon the host Resource comparisons use) into
    SMALL EXACT f32 integers, so every in-kernel sum and fit comparison is
    exact rational arithmetic and decisions match the callback engine's
    f64 bit-for-bit. Dimensions with non-integral or overflowing values
    keep scale 1 (no worse than the unscaled engine)."""
    R = vals.shape[1]
    scale = np.ones(R, np.float64)
    for r in range(R):
        v = vals[:, r]
        v = v[np.isfinite(v) & (v != 0)]
        if v.size == 0:
            continue
        if not np.all(v == np.floor(v)) or np.any(np.abs(v) >= 2 ** 62):
            continue
        g = float(np.gcd.reduce(np.abs(v).astype(np.int64)))
        if g > 1:
            scale[r] = g
    return scale


class _EvictTensors:
    """Shared device-side inputs for one eviction action, including the
    [N, W] node-major victim slot layout (ops/evict.py EvictNW).

    All resource quantities are divided by the per-dimension GCD
    (``self.scale``) so the device works in small exact integers — see
    _dim_scale. Shares and scores are scale-invariant ratios; fit
    comparisons become exact."""

    def __init__(self, ssn, victims: List[TaskInfo],
                 preemptors: List[TaskInfo]):
        self.victims = victims
        self.rnames = discover_resource_names(
            list(ssn.nodes.values()), victims + preemptors)
        nodes = list(ssn.nodes.values())
        self.node_t = NodeTensors(nodes, self.rnames)
        idle64 = _res_rows_f64([n.idle for n in nodes], self.rnames)
        rel64 = _res_rows_f64([n.releasing for n in nodes], self.rnames)
        pip64 = _res_rows_f64([n.pipelined for n in nodes], self.rnames)
        alloc64 = _res_rows_f64([n.allocatable for n in nodes], self.rnames)
        vreq64 = _res_rows_f64([t.resreq for t in victims], self.rnames)
        preq64 = _res_rows_f64([t.init_resreq for t in preemptors],
                               self.rnames)
        self._jobs_order = list(ssn.jobs)
        jalloc64 = _res_rows_f64(
            [j.allocated for j in ssn.jobs.values()], self.rnames)
        self.scale = _dim_scale(np.vstack(
            [idle64, rel64, pip64, alloc64, vreq64, preq64, jalloc64]))
        self._fidle0 = ((idle64 + rel64 - pip64) / self.scale) \
            .astype(np.float32)
        self.alloc_total = (alloc64 / self.scale).sum(axis=0) \
            .astype(np.float32)
        self.jalloc_scaled = (jalloc64 / self.scale).astype(np.float32)
        self.preq = (preq64 / self.scale).astype(np.float32)
        self.vreq = (vreq64 / self.scale).astype(np.float32)
        self.vnode = np.asarray(
            [self.node_t.index[t.node_name] for t in victims], np.int32)
        V = len(victims)
        N = len(self.node_t.names)
        counts = np.bincount(self.vnode, minlength=N) if V else \
            np.zeros(N, np.int64)
        # pow2-bucketed slot width (VT006): pad columns hold the sentinel
        # V below (valid False), decisions cannot touch them
        W = _slot_bucket(max(1, int(counts.max()) if V else 1))
        self.W = W
        # slot table: victims grouped per node, preserving list (eviction)
        # order within each row; V is the pad sentinel. Vectorized: stable
        # sort by node keeps relative order, column index = rank within
        # the node's group
        self.vslot = np.full((N, W), V, np.int32)
        if V:
            order = np.argsort(self.vnode, kind="stable")
            starts = np.r_[0, np.cumsum(counts)[:-1]]
            col = np.arange(V) - starts[self.vnode[order]]
            self.vslot[self.vnode[order], col] = order.astype(np.int32)
        self.valid_nw = self.vslot < V
        vreq_pad = np.vstack([self.vreq,
                              np.zeros((1, len(self.rnames)), np.float32)])
        self.vreq_nw = vreq_pad[self.vslot]

    def future_idle0(self):
        return self._fidle0

    def nw_inputs(self, vgroup: np.ndarray, n_groups: int,
                  vrank: Optional[np.ndarray]):
        """Build the EvictNW namedtuple (host numpy — the caller ships the
        whole input pytree in ONE jax.device_put, which batches transfers;
        per-array uploads pay a tunnel round trip each on remote
        backends). ``vgroup``: per-victim tracked-table index (job for
        preempt, queue for reclaim); pads point at the zeroed extra row
        ``n_groups``. ``vrank``: per-victim candidate-list rank for the
        dynamic tier's within-dispatch subtraction order; None -> no
        dynamic tier, the rank table is never read (the walk only expands
        it to the [N, W, W] ``before`` tensor when a drf tier exists)."""
        from ..ops.evict import EvictNW

        N, W = self.vslot.shape
        group_pad = np.r_[vgroup.astype(np.int64), n_groups]
        group_nw = group_pad[self.vslot].astype(np.int32)
        if vrank is None:
            rank_nw = np.zeros((N, W), np.int32)
        else:
            rank_pad = np.r_[np.minimum(vrank.astype(np.int64), BIG), BIG]
            rank_nw = rank_pad[self.vslot].astype(np.int32)
        return EvictNW(
            vslot=self.vslot, valid=self.valid_nw, vreq=self.vreq_nw,
            vgroup=group_nw, rank=rank_nw)

    def owner_nw_to_victims(self, owner_nw: np.ndarray) -> Dict[int, list]:
        """owner [N, W] (step index or -1) -> step -> victims."""
        out: Dict[int, list] = {}
        N, W = self.vslot.shape
        flat_owner = owner_nw.reshape(-1)
        flat_slot = self.vslot.reshape(-1)
        V = len(self.victims)
        for k in np.flatnonzero(flat_owner >= 0):
            v = flat_slot[k]
            if v < V:
                out.setdefault(int(flat_owner[k]), []).append(
                    self.victims[v])
        return out


def _max_per_node(victims: List[TaskInfo]) -> int:
    """Largest victim count on any one node — the W of the [N, W] layout."""
    counts: Dict[str, int] = {}
    for t in victims:
        counts[t.node_name] = counts.get(t.node_name, 0) + 1
    return max(counts.values(), default=0)


def _segment_ends(is_last: np.ndarray) -> np.ndarray:
    """For each position, the index of its segment's LAST element, given a
    bool[P] marking segment-final positions — the walk kernels' cursor-jump
    targets (run_end / job_end / queue_end)."""
    ends = np.flatnonzero(is_last)
    return ends[np.searchsorted(ends, np.arange(len(is_last)))] \
        .astype(np.int32)


def _task_order_chain(ssn) -> List[str]:
    return [name for tier in ssn.tiers for opt in tier.plugins
            if opt.is_enabled("enabledTaskOrder")
            and (name := opt.name) in ssn.task_order_fns]


def _eviction_order(ssn, victims: List[TaskInfo]) -> List[TaskInfo]:
    """Reversed TaskOrderFn — lowest priority first (preempt.go:237-244).
    Key sort when only the priority plugin orders tasks (the default conf;
    Python's reverse=True is stable, so tie order matches the stable
    comparator sort); comparator sort otherwise."""
    chain = _task_order_chain(ssn)
    if chain == ["priority"]:
        return _elastic_victims_first(ssn, sorted(
            victims, key=lambda t: (-t.priority, t.creation_timestamp,
                                    t.uid), reverse=True))
    if not chain:
        return _elastic_victims_first(ssn, list(victims))

    def cmp(l, r):
        if ssn.task_order_fn(l, r):
            return 1
        if ssn.task_order_fn(r, l):
            return -1
        return 0
    return _elastic_victims_first(ssn, sorted(victims, key=cmp_to_key(cmp)))


def _elastic_victims_first(ssn, ordered: List[TaskInfo]) -> List[TaskInfo]:
    """The elastic-gang victim tier: above-min members of elastic gangs
    are the cheapest victims in the cluster, so they move to the FRONT
    of the eviction order — the walk spends them before touching any
    rigid gang or any elastic gang's core. Each gang designates its
    highest-uid victims, capped at its shrink allowance (the count-based
    surplus; never a path below min — the live tiered chain re-validates
    allowances per attempt on top of this ordering). Exact no-op — same
    list object order — when no elastic gang is present, which is what
    keeps pre-elastic scenarios byte-identical."""
    from ..elastic_gang.membership import is_elastic, shrink_allowance
    allow: Dict[str, int] = {}
    for t in ordered:
        if t.job in allow:
            continue
        job = ssn.jobs.get(t.job)
        allow[t.job] = shrink_allowance(job) \
            if job is not None and is_elastic(job) else 0
    if not any(allow.values()):
        return ordered
    surplus = set()
    by_job: Dict[str, List[TaskInfo]] = {}
    for t in ordered:
        by_job.setdefault(t.job, []).append(t)
    for uid, ts in by_job.items():
        a = allow[uid]
        if a <= 0:
            continue
        for t in sorted(ts, key=lambda x: x.uid, reverse=True)[:a]:
            surplus.add(t.uid)
    front = [t for t in ordered if t.uid in surplus]
    rest = [t for t in ordered if t.uid not in surplus]
    return front + rest


def _collect_victims(ssn) -> List[TaskInfo]:
    """RUNNING victim candidates in node-iteration x node.tasks order — the
    candidate-list order every plugin dispatch sees."""
    out = []
    for node in ssn.nodes.values():
        for t in node.tasks.values():
            if t.status != TaskStatus.RUNNING or t.resreq.is_empty():
                continue
            if t.job in ssn.jobs and t.uid in ssn.jobs[t.job].tasks:
                out.append(ssn.jobs[t.job].tasks[t.uid])
    return out


def _rep_task(job) -> Optional[TaskInfo]:
    pend = job.task_status_index.get(TaskStatus.PENDING, {})
    for t in pend.values():
        if not t.resreq.is_empty():
            return t
    return None


def _is_critical(task) -> bool:
    from ..plugins.conformance import _is_critical as crit
    return crit(task)


class _TierStack:
    """Per-tier plugin veto masks for the device dispatch replay.

    kinds[i]: "static" | "drf" | "proportion". masks[i]: tuple of
    (mask [PJ,V] bool, part [PJ] bool) for the STATIC plugins of tier i —
    dynamic plugins (drf dominant shares, proportion deserved) are computed
    in-kernel from tracked state.

    The stock priority/gang/conformance callbacks have vectorized fast
    paths (they filter on per-victim attributes only: owning-job priority,
    critical-pod annotations — priority.py:28, gang.py:43, conformance.py);
    unknown plugins run the generic per-job dispatch through the real
    registered callback.

    cand_kind selects the candidate filter: "inter-queue" (preempt phase 1:
    same queue, different job — preempt.go:120), "intra-job" (phase 2), or
    "cross-queue" (reclaim: other queues marked reclaimable,
    reclaim.go:112-120).
    """

    FAST = {"priority", "gang", "conformance"}

    def __init__(self, ssn, pjobs, victims, registry, flag, dynamic_name,
                 cand_kind: str):
        PJ, V = len(pjobs), len(victims)
        vjob_prio = np.asarray(
            [ssn.jobs[t.job].priority for t in victims], np.int64)
        jprio = np.asarray([j.priority for j in pjobs], np.int64)
        qnames = {name: i for i, name in enumerate(ssn.queues)}
        vqueue = np.asarray(
            [qnames.get(ssn.jobs[t.job].queue, -1) for t in victims],
            np.int64)
        jqueue = np.asarray([qnames.get(j.queue, -2) for j in pjobs],
                            np.int64)
        juids = {uid: i for i, uid in
                 enumerate(dict.fromkeys([t.job for t in victims]))}
        vjob_code = np.asarray([juids[t.job] for t in victims], np.int64)
        jjob_code = np.asarray([juids.get(j.uid, -1) for j in pjobs],
                               np.int64)

        if cand_kind == "inter-queue":
            self.cand_mask = ((vqueue[None, :] == jqueue[:, None])
                              & (vjob_code[None, :] != jjob_code[:, None]))
        elif cand_kind == "intra-job":
            self.cand_mask = vjob_code[None, :] == jjob_code[:, None]
        elif cand_kind == "cross-queue":
            vq_ok = np.asarray(
                [(q := ssn.queues.get(ssn.jobs[t.job].queue)) is not None
                 and q.reclaimable for t in victims], bool)
            self.cand_mask = ((vqueue[None, :] != jqueue[:, None])
                              & vq_ok[None, :])
        else:
            raise ValueError(cand_kind)

        reps = [_rep_task(j) for j in pjobs]
        has_rep = np.asarray([r is not None for r in reps], bool)

        def is_fast(name: str) -> bool:
            """Fast path only for the STOCK callbacks — a custom plugin
            registered under the same conf name must go through its real
            callback (identity check via the defining module)."""
            if name not in self.FAST:
                return False
            fn = registry.get(name)
            return getattr(fn, "__module__", "") == \
                f"volcano_tpu.plugins.{name}"

        # generic plugins need the materialized candidate lists
        generic_names = [
            opt.name for tier in ssn.tiers for opt in tier.plugins
            if opt.is_enabled(flag) and opt.name in registry
            and opt.name != dynamic_name and not is_fast(opt.name)]
        cands_per_job = None
        vix = {t.uid: i for i, t in enumerate(victims)}
        if generic_names:
            cands_per_job = [
                [victims[v] for v in np.flatnonzero(self.cand_mask[j])]
                for j in range(PJ)]

        kinds: List[str] = []
        masks: List[tuple] = []
        for tier in ssn.tiers:
            entries = []
            has_dynamic = False
            for opt in tier.plugins:
                if not opt.is_enabled(flag):
                    continue
                if opt.name not in registry:
                    continue
                if opt.name == dynamic_name:
                    has_dynamic = True
                else:
                    entries.append(opt.name)
            if not entries and not has_dynamic:
                continue
            tier_masks = []
            for name in entries:
                if not is_fast(name):
                    fn = registry[name]
                    m = np.zeros((PJ, V), bool)
                    part = np.zeros(PJ, bool)
                    for j in range(PJ):
                        if reps[j] is None:
                            continue
                        returned, vote = fn(reps[j], cands_per_job[j])
                        if vote == ABSTAIN:
                            continue
                        part[j] = True
                        for v in returned:
                            if v.uid in vix:
                                m[j, vix[v.uid]] = True
                elif name == "priority" or name == "gang":
                    # victims only from lower-priority jobs
                    # (priority.go:44-117, gang.go:83-101)
                    m = (vjob_prio[None, :] < jprio[:, None]) \
                        & has_rep[:, None]
                    part = has_rep.copy()
                else:                       # conformance
                    crit = np.asarray([_is_critical(t) for t in victims],
                                      bool)
                    m = np.broadcast_to(~crit[None, :], (PJ, V)).copy() \
                        & has_rep[:, None]
                    part = has_rep.copy()
                tier_masks.append((m, part))
            # identical masks in one tier merge exactly: tset folds
            # (m | ~p1) & (m | ~p2) = m | ~(p1 | p2) and the per-plugin
            # non-empty counts coincide — the default conf's priority and
            # gang callbacks produce the same lower-priority-job filter
            merged: List[tuple] = []
            for m, part in tier_masks:
                for i, (m2, part2) in enumerate(merged):
                    if m2.shape == m.shape and np.array_equal(m2, m):
                        merged[i] = (m2, part2 | part)
                        break
                else:
                    merged.append((m, part))
            kinds.append(dynamic_name if has_dynamic else "static")
            masks.append(tuple(merged))
        self.kinds = tuple(kinds)
        self.sizes = tuple(len(m) for m in masks)
        self.masks = tuple(masks)
        self.has_dynamic = dynamic_name in self.kinds
        # custom (non-stock) plugins participated: their live callbacks must
        # re-validate every proposal at replay (no batched fast replay)
        self.generic = bool(generic_names)
        # the same-node-run shortcut is exact only when every dynamic tier
        # is the last tier (see ops/evict.py docstring)
        self.allow_cheap = all(k == "static" for k in self.kinds[:-1])

    def device_masks(self):
        """-> tuple per tier of (stacked [Mt, PJ, V+1] bool,
        part [Mt, PJ] bool) — V+1 carries the pad column (always False).
        Host numpy; uploaded with the rest of the input pytree."""
        out = []
        for tier_masks in self.masks:
            if tier_masks:
                stk = np.stack([np.pad(m, ((0, 0), (0, 1)))
                                for m, _ in tier_masks])
                part = np.stack([p for _, p in tier_masks])
            else:
                PJ, V = self.cand_mask.shape
                stk = np.zeros((0, PJ, V + 1), bool)
                part = np.zeros((0, PJ), bool)
            out.append((stk, part))
        return tuple(out)

    def padded_cand_mask(self):
        return np.pad(self.cand_mask, ((0, 0), (0, 1)))


def _drf_inputs(ssn, tensors: _EvictTensors, victims, need_group: bool):
    """(vjob, jalloc0 [AJ+1,R], total, vrank, job_index): global job table
    for the in-kernel drf share tracking; jalloc carries a zeroed pad row
    for [N,W] pad slots. vrank is the candidate-list order rank
    (drf.go:308-330 within-dispatch subtraction order). ``job.allocated``
    is maintained as exactly the sum of allocated-status task resreqs
    (api/job_info.py update_task_status), so one to_vector per job replaces
    the per-task accumulation."""
    job_index = {uid: i for i, uid in enumerate(tensors._jobs_order)}
    R = len(tensors.rnames)
    jalloc = np.vstack([tensors.jalloc_scaled,
                        np.zeros((1, R), np.float32)])
    total = tensors.alloc_total
    vjob = np.asarray([job_index[t.job] for t in victims], np.int32)
    vrank = None
    if need_group and victims:
        rank = {t.uid: i for i, t in enumerate(_collect_victims(ssn))}
        vrank = np.asarray([rank.get(t.uid, 0) for t in victims],
                           np.int64)
    return vjob, jalloc, total, vrank, job_index


def _stock_node_order_chain(ssn):
    """The enabled node-order chain when EVERY entry is a stock scorer with
    an exact f64 vectorization below — [(kind, plugin), ...] in tier order,
    or None when an unknown scorer participates."""
    out = []
    for _, fn in ssn._enabled_fns(ssn.node_order_fns, "enabledNodeOrder"):
        mod = getattr(fn, "__module__", "")
        qn = getattr(fn, "__qualname__", "")
        owner = getattr(fn, "__self__", None)
        if mod == "volcano_tpu.plugins.nodeorder" and \
                qn.endswith("._score") and owner is not None:
            out.append(("nodeorder", owner))
        elif mod == "volcano_tpu.plugins.binpack" and \
                qn.endswith(".score") and owner is not None:
            out.append(("binpack", owner))
        else:
            return None
    return out


def _f64_rank_scores(ssn, rep_tasks, node_t) -> Optional[np.ndarray]:
    """f32[G, N] DENSE RANKS of the exact f64 node scores the callback
    engine computes.

    The callback path scores per (task, node) in Python doubles; shipping
    f32 scores to the device flips near-ties, which picks a different
    (equal-fitness) node and therefore different victim identities — the
    only full-scale preempt divergence r4 found. Ranks sidestep precision
    entirely: the host replicates the stock scorers' arithmetic in f64
    (same expressions, same accumulation order, straight from the Resource
    doubles — NOT the f32 NodeTensors), adds the live batch-scorer
    contributions, and dense-ranks each row; the device argmax over ranks
    then reproduces the exact f64 ordering with the same first-index
    tie-break as sort_nodes/select_best_node. Ranks < 2^24 are exact in
    f32. Returns None when a non-stock scorer or per-node preferred
    node-affinity term participates (callers fall back to f32 scores)."""
    total = _f64_scores(ssn, rep_tasks, node_t)
    if total is None:
        return None
    G, N = total.shape
    ranks = np.empty((G, N), np.float32)
    for g in range(G):
        _, inv = np.unique(total[g], return_inverse=True)
        ranks[g] = inv.astype(np.float32)
    return ranks


def _f64_scores(ssn, rep_tasks, node_t) -> Optional[np.ndarray]:
    """f64[G, N] bit-exact replica of the callback scorer chain (see
    _f64_rank_scores; tests pin bit-identity against ssn.node_order_fn)."""
    chain = _stock_node_order_chain(ssn)
    if chain is None:
        return None
    for task in rep_tasks:
        if (task.affinity.get("nodeAffinity", {})
                .get("preferredDuringSchedulingIgnoredDuringExecution")):
            return None            # per-node python term; no exact replica
    from ..plugins.podaffinity import session_has_pod_affinity
    if session_has_pod_affinity(ssn):
        # the batch pod-affinity scorer normalizes over the candidate
        # LIST, which differs per attempt — no exact replica
        return None
    nodes = [ssn.nodes[name] for name in node_t.names]
    N, G = len(nodes), len(rep_tasks)
    # a non-stock batch scorer may depend on the node LIST it is handed
    # (the callback comparator scores the per-attempt feasible subset, we
    # would score all nodes once) — no exact replica, like the pod-affinity
    # bail-out above. The stock taint scorer is per-node independent.
    stock_batch = all(
        getattr(fn, "__module__", "") == "volcano_tpu.plugins.nodeorder"
        for _, fn in ssn._enabled_fns(ssn.batch_node_order_fns,
                                      "enabledNodeOrder"))
    if not stock_batch:
        return None
    need_batch = any(n.taints for n in nodes)
    alloc_c = np.asarray([n.allocatable.cpu for n in nodes], np.float64)
    alloc_m = np.asarray([n.allocatable.memory for n in nodes], np.float64)
    used_c0 = np.asarray([n.used.cpu for n in nodes], np.float64)
    used_m0 = np.asarray([n.used.memory for n in nodes], np.float64)
    sc_safe = np.where(alloc_c != 0, alloc_c, 1.0)
    sm_safe = np.where(alloc_m != 0, alloc_m, 1.0)
    MAXS = 100.0                   # MAX_NODE_SCORE

    res_cache: Dict[str, tuple] = {}

    def res_vecs(rname):
        if rname not in res_cache:
            res_cache[rname] = (
                np.asarray([n.allocatable.get(rname) for n in nodes],
                           np.float64),
                np.asarray([n.used.get(rname) for n in nodes], np.float64))
        return res_cache[rname]

    total = np.zeros((G, N), np.float64)
    for g, task in enumerate(rep_tasks):
        row = np.zeros(N, np.float64)
        for kind, plugin in chain:
            if kind == "nodeorder":
                # exact replica of NodeOrderPlugin._score (f64, same op
                # order); the node-affinity term is identically 0.0 here
                # (preferred-affinity tasks bailed above), and x + 0.0
                # preserves every f64 bit
                uc = used_c0 + task.resreq.cpu
                um = used_m0 + task.resreq.memory
                s = np.zeros(N, np.float64)
                if plugin.least_req_weight:
                    fc = np.where(alloc_c != 0,
                                  np.maximum(0.0, (alloc_c - uc) / sc_safe),
                                  0.0)
                    fm = np.where(alloc_m != 0,
                                  np.maximum(0.0, (alloc_m - um) / sm_safe),
                                  0.0)
                    s = s + plugin.least_req_weight * (fc + fm) / 2 * MAXS
                if plugin.most_req_weight:
                    fc = np.where(alloc_c != 0, uc / sc_safe, 0.0)
                    fm = np.where(alloc_m != 0, um / sm_safe, 0.0)
                    fc = np.where(fc > 1, 0.0, fc)
                    fm = np.where(fm > 1, 0.0, fm)
                    s = s + plugin.most_req_weight * (fc + fm) / 2 * MAXS
                if plugin.balanced_weight:
                    fc = np.where(alloc_c != 0,
                                  np.minimum(1.0, uc / sc_safe), 0.0)
                    fm = np.where(alloc_m != 0,
                                  np.minimum(1.0, um / sm_safe), 0.0)
                    mean = (fc + fm) / 2
                    std = (((fc - mean) ** 2 + (fm - mean) ** 2) / 2) ** 0.5
                    s = s + plugin.balanced_weight * (1.0 - std) * MAXS
                row = row + s
            else:                  # binpack — exact replica of .score
                s = np.zeros(N, np.float64)
                weight_sum = 0
                for rname in task.resreq.resource_names():
                    request = task.resreq.get(rname)
                    if request == 0:
                        continue
                    w = plugin.res_weights.get(rname)
                    if w is None:
                        continue
                    allocatable, used = res_vecs(rname)
                    ok = ((allocatable != 0) & bool(w != 0)
                          & (used + request <= allocatable))
                    safe = np.where(allocatable != 0, allocatable, 1.0)
                    s = s + np.where(ok, (used + request) * w / safe, 0.0)
                    weight_sum += w
                if weight_sum > 0:
                    s = s / weight_sum
                row = row + s * MAXS * plugin.weight
        # batch scorers (taint toleration) run as the live python fns —
        # already f64, one call per representative; per-node independent,
        # so scoring all nodes equals scoring the feasible subset on every
        # feasible entry (infeasible rows are masked -inf by the caller).
        # Skipped entirely when provably rank-constant: the stock batch
        # scorer adds the same taint score to every node of a taint-free
        # cluster, and a constant row shift cannot change dense ranks —
        # calling it would cost ~1000 python calls per representative.
        if need_batch:
            for name, s in (ssn.batch_node_order_fn(task, nodes)
                            or {}).items():
                ix = node_t.index.get(name)
                if ix is not None:
                    row[ix] = row[ix] + s
        total[g] = row
    return total


def _score_rows(ssn, ptasks, tensors: _EvictTensors, pjob_arr: np.ndarray):
    """One score row per same-request RUN instead of the full [P,N] matrix.

    Tasks are grouped into maximal runs with identical (job, request,
    feasibility row, static-score row) — the exactness precondition of the
    walk kernels' same-node-run shortcut AND of the row dedup: within a
    run every task's score row (dynamic + static, -inf where infeasible)
    is identical, so the device only needs ``score_g`` f32[G,N] plus the
    ``run_id`` i32[P] indirection. At 5k preemptors in ~100 runs that cuts
    the per-cycle host->device transfer from ~20MB+ (the [P,N] f32 plus a
    [P,N] bool whose upload conversion alone costs >100ms on a remote
    tunnel) to ~0.5MB. Returns (preq, score_g device array, run_id,
    run_end)."""
    import jax.numpy as jnp
    from ..ops.scores import combined_dynamic_score

    node_t = tensors.node_t
    preq = tensors.preq               # gcd-scaled exact integers
    feas = assemble_feasibility(ssn, ptasks, node_t)
    static = assemble_static_score(ssn, ptasks, node_t)
    weights = assemble_weights(ssn, tensors.rnames)

    P = len(ptasks)
    same = np.zeros(P, bool)
    if P > 1:
        same[1:] = np.all(preq[1:] == preq[:-1], axis=-1)
        same[1:] &= pjob_arr[1:] == pjob_arr[:-1]
        for arr in (feas, static):
            if arr is not None:
                same[1:] &= np.all(arr[1:] == arr[:-1], axis=-1)
    run_id = (np.cumsum(~same) - 1).astype(np.int32)
    rep = np.flatnonzero(~same)                      # run-start indices
    run_end = _segment_ends(np.r_[~same[1:], True])

    ranks = None
    if static is None:
        # f64-exact path: host ranks reproduce the callback engine's exact
        # f64 score ordering (see _f64_rank_scores) — f32 scores flip
        # near-ties and pick different equal-fitness nodes
        ranks = _f64_rank_scores(ssn, [ptasks[i] for i in rep], node_t)
    if ranks is not None:
        if feas is not None:
            ranks = np.where(feas[rep], ranks, -np.inf).astype(np.float32)
        return preq, jnp.asarray(ranks), run_id, run_end

    ms = None
    if feas is not None or static is not None:
        N = len(node_t.names)
        s = (np.zeros((len(rep), N), np.float32) if static is None
             else static[rep].astype(np.float32))
        ms = s if feas is None else np.where(feas[rep], s, -np.inf) \
            .astype(np.float32)
    # fallback scorers want the ORIGINAL units (node_t is unscaled)
    preq_units = (preq[rep].astype(np.float64)
                  * tensors.scale[None, :]).astype(np.float32)
    score_g = combined_dynamic_score(jnp.asarray(preq_units),
                                     jnp.asarray(node_t.used),
                                     jnp.asarray(node_t.allocatable), weights)
    if ms is not None:
        score_g = score_g + jnp.asarray(ms)
    return preq, score_g, run_id, run_end


def _starving_jobs(ssn):
    """(phase1_order, under_request): starving jobs grouped per queue in job
    order for the inter-job phase, plus the same jobs in plain ssn.jobs
    iteration order — the reference's ``underRequest`` list that drives the
    intra-job pass (preempt.go:46-81,146)."""
    per_queue: Dict[str, PriorityQueue] = {}
    under_request = []
    for job in ssn.jobs.values():
        if job.podgroup.phase == PodGroupPhase.PENDING:
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.passed:
            continue
        if job.queue not in ssn.queues:
            continue
        if ssn.job_starving(job):
            per_queue.setdefault(job.queue,
                                 PriorityQueue(ssn.job_order_fn)).push(job)
            under_request.append(job)
    ordered = []
    for q in per_queue.values():
        while not q.empty():
            ordered.append(q.pop())
    return ordered, under_request


def _pending_in_order(ssn, job) -> List[TaskInfo]:
    """Pending tasks in TaskOrderFn order — same fast paths as the allocate
    engine's _pending_tasks (actions/allocate.py)."""
    from .allocate import _pending_tasks
    return _pending_tasks(ssn, job)


def execute_preempt_tpu(ssn, sharded: bool = False) -> None:
    """Device preempt: phase 1 inter-job (gang statements), phase 2
    intra-job, then the host victim_tasks pass. ``sharded`` runs the walk
    node-sharded over the full device mesh (ops/evict.py
    build_preempt_walk_sharded) — decisions are bit-identical."""
    victims = _eviction_order(ssn, _collect_victims(ssn))
    # R for the budget gate is the UNION of resource names the kernel will
    # see (discover_resource_names over nodes + victims + preemptors), not
    # a per-node max — undercounting R here would defeat the OOM guard on
    # heterogeneous clusters. Pending tasks over-approximate preemptors.
    names = set()
    for n in ssn.nodes.values():
        names.update(n.allocatable.resource_names())
    for v in victims:
        names.update(v.resreq.resource_names())
    for job in ssn.jobs.values():
        for t in job.task_status_index.get(TaskStatus.PENDING, {}).values():
            names.update(t.resreq.resource_names())
    if len(victims) < _device_min_victims(ssn, "preempt") \
            or not _device_shape_ok(len(ssn.nodes), victims, len(names)):
        from .preempt import PreemptAction
        return PreemptAction(engine="callbacks")._execute_callbacks(ssn)
    pjobs, under_request = _starving_jobs(ssn)
    # a job with NO same-queue foreign victim can never preempt: its
    # candidate row is empty for every tier (drf verdicts are subsets of
    # the candidate list), so pruning it is exact
    vq_count: Dict[str, int] = {}
    vq_own: Dict[tuple, int] = {}
    for v in victims:
        q = ssn.jobs[v.job].queue
        vq_count[q] = vq_count.get(q, 0) + 1
        vq_own[(q, v.job)] = vq_own.get((q, v.job), 0) + 1
    pjobs = [j for j in pjobs
             if vq_count.get(j.queue, 0)
             - vq_own.get((j.queue, j.uid), 0) > 0]
    if pjobs and victims:
        _preempt_phase(ssn, pjobs, victims, inter_job=True,
                       sharded=sharded)
    # phase 2: within-job preemption, one pass in underRequest order
    # (preempt.go:146-183) — only jobs that still have pending tasks AND
    # own running victims can act (victims re-collected only then: the
    # phase-1 statements may have flipped RUNNING tasks to RELEASING)
    pjobs2 = [j for j in under_request
              if j.task_status_index.get(TaskStatus.PENDING)
              and j.task_status_index.get(TaskStatus.RUNNING)]
    if pjobs2:
        victims2 = _eviction_order(ssn, _collect_victims(ssn))
        if victims2:
            _preempt_phase(ssn, pjobs2, victims2, inter_job=False,
                           sharded=sharded)
    _victim_tasks_host(ssn)


def prewarm_preempt(ssn, sharded: bool = False) -> int:
    """Compile the preempt walk at the pow2 (preemptor, victim-slot)
    buckets the CURRENT session implies — the prewarm mirror of the
    bucketing in _preempt_phase/_EvictTensors, so the steady state's
    walk compiles pay at startup like the allocate solver's
    (allocate.prewarm_shapes calls this when the conf runs a device
    preempt). Runs both phases end-to-end through the REAL shape
    assembly but discards the device outputs (dry_run) — read-only on
    session state. Returns the number of walk shapes compiled."""
    victims = _eviction_order(ssn, _collect_victims(ssn))
    if not victims:
        return 0
    names = set()
    for n in ssn.nodes.values():
        names.update(n.allocatable.resource_names())
    for v in victims:
        names.update(v.resreq.resource_names())
    for job in ssn.jobs.values():
        for t in job.task_status_index.get(TaskStatus.PENDING, {}).values():
            names.update(t.resreq.resource_names())
    if len(victims) < _device_min_victims(ssn, "preempt") \
            or not _device_shape_ok(len(ssn.nodes), victims, len(names)):
        return 0
    pjobs, under_request = _starving_jobs(ssn)
    vq_count: Dict[str, int] = {}
    vq_own: Dict[tuple, int] = {}
    for v in victims:
        q = ssn.jobs[v.job].queue
        vq_count[q] = vq_count.get(q, 0) + 1
        vq_own[(q, v.job)] = vq_own.get((q, v.job), 0) + 1
    pjobs = [j for j in pjobs
             if vq_count.get(j.queue, 0)
             - vq_own.get((j.queue, j.uid), 0) > 0]
    warmed = 0
    if pjobs:
        _preempt_phase(ssn, pjobs, victims, inter_job=True,
                       sharded=sharded, dry_run=True)
        warmed += 1
    pjobs2 = [j for j in under_request
              if j.task_status_index.get(TaskStatus.PENDING)
              and j.task_status_index.get(TaskStatus.RUNNING)]
    if pjobs2:
        _preempt_phase(ssn, pjobs2, victims, inter_job=False,
                       sharded=sharded, dry_run=True)
        warmed += 1
    return warmed


# Per-cycle phase timers of the last device preempt (seconds) — the
# host/device breakdown bench.py reports, keyed per phase.
LAST_STATS: Dict[str, float] = {}


def _preempt_phase(ssn, pjobs, victims, inter_job: bool,
                   sharded: bool = False, dry_run: bool = False) -> None:
    import jax.numpy as jnp
    from ..ops.evict import build_preempt_walk, build_preempt_walk_sharded

    ptasks: List[TaskInfo] = []
    pjob_ix: List[int] = []
    first: List[bool] = []
    kept_jobs = []
    for job in pjobs:
        tasks = _pending_in_order(ssn, job)
        if not tasks:
            continue
        jx = len(kept_jobs)
        kept_jobs.append(job)
        for k, t in enumerate(tasks):
            ptasks.append(t)
            pjob_ix.append(jx)
            first.append(k == 0)
    if not ptasks:
        return

    if inter_job:
        cand_kind = "inter-queue"
        needed_j = np.asarray(
            [max(0, j.min_available - j.ready_task_num()
                 - j.waiting_task_num()) for j in kept_jobs], np.int32)
    else:
        cand_kind = "intra-job"
        needed_j = np.full(len(kept_jobs), BIG, np.int32)

    stack = _TierStack(ssn, kept_jobs, victims, ssn.preemptable_fns,
                       "enabledPreemptable", "drf", cand_kind)
    tensors = _EvictTensors(ssn, victims, ptasks)
    pjob_arr = np.asarray(pjob_ix, np.int32)
    preq, score_g, run_id, run_end = _score_rows(ssn, ptasks, tensors,
                                                 pjob_arr)
    first_np = np.asarray(first, bool)
    job_end = _segment_ends(np.r_[first_np[1:], True])
    vjob, jalloc0, total, vrank, job_index = _drf_inputs(
        ssn, tensors, victims, need_group=stack.has_dynamic)
    nw = tensors.nw_inputs(vjob, len(job_index), vrank)
    pjg_job = np.asarray([job_index[j.uid] for j in kept_jobs], np.int32)
    pjg = pjg_job[pjob_arr]
    # pipeline quota keyed by ALLOC-GROUP index — the walk tracks it as
    # the fused last column of its jstate matrix (ops/evict.py)
    needed = np.zeros(len(job_index) + 1, np.float32)
    needed[pjg_job] = needed_j

    # pow2-bucket the preemptor-task axis (VT006, the churn-recompile
    # contract): pad tasks form ONE trailing pad job — pjob points at a
    # fresh all-False candidate row, pjg at the zeroed jalloc pad group
    # whose quota (0) is already met, so the walk's first pad visit runs
    # the job boundary (closing the last real job exactly as the
    # unpadded after-loop close would) and then skips straight past the
    # pad block in a single inactive step. Decisions are untouched.
    P_live = len(ptasks)
    Pp = _ptask_bucket(P_live)
    cand_mask_np = stack.padded_cand_mask()
    tier_masks_np = stack.device_masks()
    if Pp > P_live:
        pad = Pp - P_live
        PJ = len(kept_jobs)
        pad_group = len(job_index)           # the zeroed jalloc pad row
        preq = np.pad(preq, ((0, pad), (0, 0)))
        pjob_arr = np.pad(pjob_arr, (0, pad), constant_values=PJ)
        pjg = np.pad(pjg, (0, pad), constant_values=pad_group)
        first_np = np.pad(first_np, (0, pad))
        first_np[P_live] = True
        run_id = np.pad(run_id, (0, pad),
                        constant_values=int(run_id[P_live - 1]))
        run_end = np.pad(run_end, (0, pad), constant_values=Pp - 1)
        job_end = np.pad(job_end, (0, pad), constant_values=Pp - 1)
        cand_mask_np = np.pad(cand_mask_np, ((0, 1), (0, 0)))
        tier_masks_np = tuple(
            (np.pad(stk, ((0, 0), (0, 1), (0, 0))),
             np.pad(part, ((0, 0), (0, 1))))
            for stk, part in tier_masks_np)

    # intra-job preemption breaks the same-node-run shrink argument when a
    # dynamic tier is present: the victim job IS the preemptor's job, so
    # its allocation (and the victims' shares) GROWS with each placement —
    # a non-chosen node's drf verdict can grow mid-run. Inter-job excludes
    # own-job victims, so only phase 1 keeps the shortcut with drf.
    allow_cheap = stack.allow_cheap and (inter_job or not stack.has_dynamic)
    import jax
    fidle0 = tensors.future_idle0()
    score_arr = score_g
    if sharded:
        from ..device_health import DEVICE_HEALTH
        from ..parallel.mesh import make_mesh
        # preempt rides the SAME health-filtered mesh as allocate: a
        # quarantined device is out of the walk until its probe readmits
        # it (allocate._probe_quarantined). Zero healthy devices drops to
        # the single-device program on the default device — the walk is
        # bit-identical at every D, so no decision changes either way.
        devices = jax.devices()
        live = set(DEVICE_HEALTH.healthy_devices([d.id for d in devices]))
        healthy = [d for d in devices if d.id in live]
        mesh = make_mesh(healthy or devices[:1])
        D = int(mesh.devices.size)
        if D == 1:
            # a 1-device mesh runs the single-device program: the sharded
            # walk is bit-identical to it by construction (ops/evict.py),
            # so collapsing only skips the shard_map/psum plumbing — this
            # is what closed the 527ms-vs-387ms sharded preempt gap on
            # single-device hosts
            sharded = False
    if sharded:
        from ..ops.evict import EvictNW
        N0 = tensors.vslot.shape[0]
        n_pad = (-N0) % D
        if n_pad:
            # pad the node axis with victim-free rows: vslot points at the
            # pad victim (valid False), so they can never be chosen
            V = len(tensors.victims)
            fidle0 = np.pad(fidle0, ((0, n_pad), (0, 0)))
            nw = EvictNW(
                vslot=np.pad(nw.vslot, ((0, n_pad), (0, 0)),
                             constant_values=V),
                valid=np.pad(nw.valid, ((0, n_pad), (0, 0))),
                vreq=np.pad(nw.vreq, ((0, n_pad), (0, 0), (0, 0))),
                vgroup=np.pad(nw.vgroup, ((0, n_pad), (0, 0)),
                              constant_values=jalloc0.shape[0] - 1),
                rank=np.pad(nw.rank, ((0, n_pad), (0, 0)),
                            constant_values=BIG))
            # jnp.pad, NOT np.pad: score_g is device-resident (the
            # combined-score path computes it in-kernel), and np.pad
            # would force a hidden device->host fetch plus re-upload —
            # an implicit sync in the middle of the solve hot path
            # (VT010); jnp.pad dispatches the pad on device
            score_arr = jnp.pad(score_g, ((0, 0), (0, n_pad)),
                                constant_values=-1e30)
        fn = build_preempt_walk_sharded(mesh, stack.kinds, stack.sizes,
                                        inter_job, allow_cheap)
    else:
        fn = build_preempt_walk(stack.kinds, stack.sizes, inter_job,
                                allow_cheap)
    key = "p1" if inter_job else "p2"
    from ..obs import trace as obs_trace
    with obs_trace.span("upload", phase=key) as sp:
        inputs = jax.device_put((
            fidle0, nw, cand_mask_np,
            tier_masks_np, preq, pjob_arr, pjg, first_np,
            run_id, run_end, job_end,
            needed, jalloc0, total))                        # one upload
        (fidle_d, nw_d, cand_d, masks_d, preq_d, pjob_d, pjg_d, first_d,
         rid_d, rend_d, jend_d, needed_d, jalloc_d, total_d) = inputs
    LAST_STATS[key + "_upload_s"] = sp.dur_s
    with obs_trace.span("solve", phase=key) as sp:
        task_node, owner_nw, job_done, iters = fn(
            fidle_d, nw_d, cand_d, masks_d, preq_d, pjob_d, pjg_d, first_d,
            rid_d, rend_d, jend_d, score_arr, needed_d, jalloc_d, total_d)
        N, W = tensors.vslot.shape        # pre-mesh-pad dims for replay
        Np = fidle0.shape[0]              # includes any mesh padding
        packed = np.asarray(jnp.concatenate([
            task_node, owner_nw.reshape(-1),
            job_done.astype(jnp.int32), iters[None]]))      # one fetch
    LAST_STATS[key + "_solve_s"] = sp.dur_s
    task_node = packed[:P_live]           # pad-task rows are NO_NODE
    owner_nw = packed[Pp:Pp + Np * W].reshape(Np, W)[:N]
    # per-group verdicts -> per kept job via its alloc-group index
    job_done = packed[Pp + Np * W:-1].astype(bool)[pjg_job]
    LAST_STATS[key + "_iters"] = int(packed[-1])

    if dry_run:
        return
    with obs_trace.span("replay", phase=key) as sp:
        _replay_preempt(ssn, ptasks, pjob_ix, kept_jobs, tensors,
                        task_node, owner_nw, job_done, inter_job, stack)
    LAST_STATS[key + "_replay_s"] = sp.dur_s


def _fast_evict_ok(ssn, stack: "_TierStack") -> bool:
    """Batched eviction replay skips the per-task Statement machinery and
    the live preemptable/reclaimable re-validation. Sound only when every
    participating eviction plugin is a stock fast-path one — the kernel
    replays exactly their semantics, including the dynamic tier's tracked
    state, in the same order the replay applies them, so the live chain
    could never veto a kernel verdict — plus allocate's batched-replay
    conditions (no stateful predicates, additive handlers, gang-owned
    readiness/pipelining, no GPU card state)."""
    from .allocate import _fast_replay_ok
    return not stack.generic and _fast_replay_ok(ssn)


def _fast_pipeline(ssn, task: TaskInfo, host: str) -> None:
    """PENDING -> PIPELINED bookkeeping, identical end-state to
    Statement.pipeline minus the per-task event fire (aggregated by the
    caller; handlers are additive under _fast_evict_ok)."""
    ssn.jobs[task.job].update_task_status(task, TaskStatus.PIPELINED)
    task.node_name = host
    node = ssn.nodes[host]
    node._touched = True       # direct mutation: incremental-snapshot witness
    ti = task.shallow_clone()
    node.tasks[task.uid] = ti
    for port in ti.host_ports:
        node.used_ports[port] = node.used_ports.get(port, 0) + 1
    node.pipelined.add(task.resreq)


def _fast_unpipeline(ssn, task: TaskInfo) -> None:
    """Exact reverse of _fast_pipeline (Statement._unpipeline analogue)."""
    ssn.jobs[task.job].update_task_status(task, TaskStatus.PENDING)
    node = ssn.nodes.get(task.node_name)
    if node is not None:
        node._touched = True
        node.tasks.pop(task.uid, None)
        for port in task.host_ports:
            left = node.used_ports.get(port, 0) - 1
            if left > 0:
                node.used_ports[port] = left
            else:
                node.used_ports.pop(port, None)
        node.pipelined.sub(task.resreq)
    task.node_name = ""


def _fast_evict(ssn, vt: TaskInfo) -> TaskInfo:
    """RUNNING -> RELEASING bookkeeping, identical end-state to
    Statement.evict minus the fire and the cache side effect (both done by
    the caller after the gang gate): job status index + allocated, node
    mirror status + releasing accounting (update_task's remove/add nets to
    releasing.add for RUNNING -> RELEASING)."""
    job = ssn.jobs[vt.job]
    own = job.tasks[vt.uid]
    job.update_task_status(own, TaskStatus.RELEASING)
    node = ssn.nodes.get(own.node_name)
    if node is not None:
        node._touched = True
        mirror = node.tasks.get(own.uid)
        if mirror is not None:
            mirror.status = TaskStatus.RELEASING
            node.releasing.add(own.resreq)
    return own


def _fast_unevict(ssn, own: TaskInfo) -> None:
    """Exact reverse of _fast_evict (Statement._unevict analogue)."""
    ssn.jobs[own.job].update_task_status(own, TaskStatus.RUNNING)
    node = ssn.nodes.get(own.node_name)
    if node is not None:
        node._touched = True
        mirror = node.tasks.get(own.uid)
        if mirror is not None:
            mirror.status = TaskStatus.RUNNING
            node.releasing.sub(own.resreq)


def _replay_preempt_fast(ssn, ptasks, pjob_ix, kept_jobs, tensors,
                         task_node, victims_by_step,
                         inter_job: bool) -> None:
    """Batched preempt replay (the eviction analogue of allocate's
    _replay_fused_fast): dict bookkeeping + aggregated event fires, no
    Statements. The kernel already enforced gang atomicity (task_node is
    NO_NODE for rolled-back jobs) and fit (fidle tracked in-kernel), and
    _fast_evict_ok guaranteed the live chain could not veto placements.

    One live gate survives from the slow path: a preemptor job can itself
    LOSE RUNNING tasks to an earlier same-queue preemptor in this very
    action, dropping its ready count below what the kernel's snapshot-time
    quota assumed — so phase 1 re-checks gang's job_pipelined vote after
    applying each job and rolls that job back (pipelines AND its
    evictions) exactly as Statement.discard would. Event fires and
    cache.evict side effects happen only for committed jobs, which is why
    the per-op helpers defer both."""
    from .allocate import _AggTask
    from .. import metrics

    names = tensors.node_t.names
    per_job: Dict[int, List[int]] = {}
    for i, jx in enumerate(pjob_ix):
        per_job.setdefault(jx, []).append(i)

    alloc_agg: Dict[int, Resource] = {}
    dealloc_agg: Dict[str, Resource] = {}
    cache_evicts: List[TaskInfo] = []
    rolled_back = False
    n_attempts = last_victims = 0
    for jx, ids in per_job.items():
        job = kept_jobs[jx]
        applied_p: List[TaskInfo] = []
        applied_v: List[TaskInfo] = []
        for i in ids:
            if task_node[i] == NO_NODE:
                continue
            evicted = victims_by_step.get(i, [])
            for vt in evicted:
                applied_v.append(_fast_evict(ssn, vt))
            n_attempts += 1
            last_victims = len(evicted)
            host = names[task_node[i]]
            # Until a host-side rollback happens, live future_idle matches
            # the kernel's in-device fidle exactly, so fit holds by kernel
            # invariant. After one, an earlier job's un-done evictions can
            # leave a node below what the kernel assumed — re-check the
            # slow path's pre-pipeline fit gate (preempt.go:263-267) and
            # skip the pipeline (evictions stand, as in the slow path).
            if rolled_back and not ptasks[i].init_resreq.less_equal(
                    ssn.nodes[host].future_idle()):
                continue
            _fast_pipeline(ssn, ptasks[i], host)
            applied_p.append(ptasks[i])
        if not applied_p and not applied_v:
            continue
        if inter_job and not ssn.job_pipelined(job):
            for t in reversed(applied_p):
                _fast_unpipeline(ssn, t)
            for v in reversed(applied_v):
                _fast_unevict(ssn, v)
            rolled_back = True
            continue
        for t in applied_p:
            alloc_agg.setdefault(jx, Resource()).add(t.resreq)
        for v in applied_v:
            dealloc_agg.setdefault(v.job, Resource()).add(v.resreq)
            cache_evicts.append(v)

    # last-attempt gauge semantics, matching the per-attempt set of the
    # slow replay and the callbacks engine (last write wins); no attempts
    # -> gauge untouched, exactly as the per-attempt formulation behaves
    if n_attempts:
        metrics.update_preemption_victims(last_victims)
        metrics.register_preemption_attempt(n_attempts)
    for jx, r in alloc_agg.items():
        ssn._fire_allocate(_AggTask(kept_jobs[jx].uid, r))
    for uid, r in dealloc_agg.items():
        ssn._fire_deallocate(_AggTask(uid, r))
    for v in cache_evicts:
        ssn._audit_event("evict", v, "preempt")
        ssn.cache.evict(v, "preempt")


def _replay_preempt(ssn, ptasks, pjob_ix, kept_jobs, tensors,
                    task_node, owner_nw, job_done, inter_job: bool,
                    stack: "_TierStack") -> None:
    from .. import metrics

    victims_by_step = tensors.owner_nw_to_victims(owner_nw)

    if _fast_evict_ok(ssn, stack):
        _replay_preempt_fast(ssn, ptasks, pjob_ix, kept_jobs, tensors,
                             task_node, victims_by_step, inter_job)
        return

    per_job: Dict[int, List[int]] = {}
    for i, jx in enumerate(pjob_ix):
        per_job.setdefault(jx, []).append(i)

    for jx, ids in per_job.items():
        job = kept_jobs[jx]
        if inter_job and not job_done[jx]:
            continue
        stmt = ssn.statement()
        for i in ids:
            n = int(task_node[i])
            if n == NO_NODE:
                continue
            node_name = tensors.node_t.names[n]
            evicted = victims_by_step.get(i, [])
            # final live validation through the real tiered chain
            validated = {t.uid for t in ssn.preemptable(ptasks[i], evicted)} \
                if evicted else set()
            for vt in evicted:
                if vt.uid in validated and vt.uid in ssn.jobs[vt.job].tasks:
                    stmt.evict(ssn.jobs[vt.job].tasks[vt.uid], "preempt")
            metrics.update_preemption_victims(len(validated))
            metrics.register_preemption_attempt()
            # pipeline only if the node actually fits after the validated
            # evictions (preempt.go:263-267) — a live-chain veto must not
            # overcommit future_idle
            node = ssn.nodes[node_name]
            if ptasks[i].init_resreq.less_equal(node.future_idle()):
                stmt.pipeline(ptasks[i], node_name)
        if inter_job:
            if ssn.job_pipelined(job):
                stmt.commit()
            else:
                stmt.discard()
        else:
            stmt.commit()


def _victim_tasks_host(ssn) -> None:
    """Plugin-driven eviction pass (tdm VictimTasksFn, preempt.go:272-284)."""
    stmt = ssn.statement()
    for victim in ssn.victim_tasks():
        job = ssn.jobs.get(victim.job)
        if job is None or victim.uid not in job.tasks:
            continue
        stmt.evict(job.tasks[victim.uid], "evict")
    stmt.commit()




class _ReclaimScreener:
    """Conservative node pre-filter for the EXACT reclaim rotation.

    The reference's reclaim is a serial one-task-per-queue-pop rotation
    whose job/queue ordering re-evaluates between pops
    (reclaim.go:128-185) — queue-contiguous batching (the r3/r4 device
    kernel) fires the "queue leaves when a job exhausts its tasks" exit
    far too early at scale, and a per-attempt device round trip would pay
    the ~100ms tunnel RTT each. So reclaim runs the LITERAL callback
    rotation (ReclaimAction._execute_callbacks — live PriorityQueues, live
    comparators, the real per-node body) and this screener only shrinks
    the per-attempt node walk from O(N) python to a vectorized f64 mask.

    Superset proof (the body can only ACT on a screened node — evict or
    pipeline — so screening never changes a decision):
    - the body needs at least one cross-queue reclaimable-queue RUNNING
      victim and future_idle + all victims to cover init_resreq
      (reclaim.py:92-99); it evicts even when the victims alone cannot
      cover the request (only the pipeline is skipped then), so the
      screen must NOT require pool-alone coverage;
    - the screen tests exactly that necessary condition, widened by
      MIN_RESOURCE per dimension, against LIVE totals: the rotation body
      reports every eviction (victim leaves the pool, its resreq joins
      future-idle — the same releasing bump session.evict applies) and
      every pipeline (future-idle drops) through note_evict /
      note_pipeline, so head + pool equals the body's own
      future_idle-plus-victims test at every attempt. A stale-totals
      screen would NOT be a superset: an eviction by one queue's
      reclaimer frees head capacity that another SAME-queue-as-victim
      reclaimer could use, which static totals undercount;
    - feasibility rows come from the same plugin feasibility fns every
      device engine uses as predicate-equivalents (cache/snapshot.py),
      assembled once per job.
    """

    def __init__(self, ssn):
        self.ssn = ssn
        self.nodes = list(ssn.nodes.values())
        self.names = [n.name for n in self.nodes]
        self.node_index = {n: i for i, n in enumerate(self.names)}
        tasks = [t for j in ssn.jobs.values() for t in j.tasks.values()]
        self.rnames = discover_resource_names(self.nodes, tasks)
        self.node_t = NodeTensors(self.nodes, self.rnames)
        N, R = len(self.nodes), len(self.rnames)
        self.queue_ix = {uid: i for i, uid in enumerate(ssn.queues)}
        Q = len(self.queue_ix)
        self.head = _res_rows_f64(
            [n.future_idle() for n in self.nodes], self.rnames)
        # victim pools binned by (node, queue, victim-job priority): the
        # default conf's first reclaimable tier (priority + gang) rules
        # with exactly the lower-priority victim set whenever that set is
        # non-empty, so the screen can test coverage against the
        # lower-priority pool when one exists on the node and the full
        # pool otherwise — exact-necessary either way. Non-stock tier-1
        # confs fall back to the full pool (still a superset: tiers only
        # shrink eligibility).
        self.tier1_priority = self._tier1_is_priority(ssn)
        self.tier2_proportion = self._tier2_is_proportion(ssn)
        # live queue allocations mirror proportion's attrs: evictions
        # subtract (deallocate event), pipelines add to the reclaimer's
        # queue (allocate event) — so the tier-2 over-deserved gate below
        # tracks exactly what proportion.reclaimable will see
        self.qalloc = np.zeros((Q, R), np.float64)
        for job in ssn.jobs.values():
            qx = self.queue_ix.get(job.queue)
            if qx is not None:
                self.qalloc[qx] += _res_rows_f64([job.allocated],
                                                 self.rnames)[0]
        self.qdeserved = np.full((Q, R), np.inf, np.float64)
        self.q_has_attr = np.zeros(Q, bool)
        for name, r in ssn.queue_deserved.items():
            qx = self.queue_ix.get(name)
            if qx is not None:
                self.qdeserved[qx] = _res_rows_f64([r], self.rnames)[0]
                self.q_has_attr[qx] = True
        # the BODY's candidate filter, not _collect_victims: the rotation
        # includes empty-resreq RUNNING tasks too (reclaim.py:81-91), so
        # they must keep nodes in the walk (they contribute 0 resources
        # but satisfy the victim-exists gate)
        victims = [t for node in self.nodes for t in node.tasks.values()
                   if t.status == TaskStatus.RUNNING and t.job in ssn.jobs]
        prios = sorted({ssn.jobs[t.job].priority for t in victims} | {0})
        self.pr_vals = np.asarray(prios, np.int64)
        self.pr_ix = {p: i for i, p in enumerate(prios)}
        P = len(prios)
        vrows = _res_rows_f64([t.resreq for t in victims], self.rnames)
        self._row_cache: Dict[str, np.ndarray] = {
            t.uid: vrows[i] for i, t in enumerate(victims)}
        pools = np.zeros((N, Q, P, R), np.float64)
        counts = np.zeros((N, Q, P), np.float64)
        for i, t in enumerate(victims):
            vq = ssn.jobs[t.job].queue
            queue = ssn.queues.get(vq)
            if queue is None or not queue.reclaimable:
                continue
            qx = self.queue_ix.get(vq)
            n = self.node_index.get(t.node_name)
            if qx is None or n is None:
                continue
            px = self.pr_ix[ssn.jobs[t.job].priority]
            pools[n, qx, px] += vrows[i]
            counts[n, qx, px] += 1
        # aggregates maintained INCREMENTALLY (a per-attempt einsum over
        # [N, Q, P, R] costs ~1ms x hundreds of attempts; these slices
        # cost ~20us each): cumulative-over-priority pools for the tier-1
        # lower-priority test, over-deserved-queue pools for tier 2
        self.cumP = np.concatenate(
            [np.zeros((N, Q, 1, R)), np.cumsum(pools, axis=2)], axis=2)
        self.cumP_all = self.cumP.sum(axis=1)            # [N, P+1, R]
        self.ccntP = np.concatenate(
            [np.zeros((N, Q, 1)), np.cumsum(counts, axis=2)], axis=2)
        self.ccntP_all = self.ccntP.sum(axis=1)          # [N, P+1]
        self.pool_q = pools.sum(axis=2)                  # [N, Q, R]
        self.cnt_q = counts.sum(axis=2)                  # [N, Q]
        self.pool_all = self.pool_q.sum(axis=1)          # [N, R]
        self.cnt_all = self.cnt_q.sum(axis=1)            # [N]
        self.over = self._over_now()
        overf = self.over.astype(np.float64)
        self.pool_over = np.einsum("nqr,q->nr", self.pool_q, overf)
        self.cnt_over = self.cnt_q @ overf
        self._feas_cache: Dict[str, np.ndarray] = {}
        self._all_true = np.ones(N, bool)

    def _over_now(self) -> np.ndarray:
        """Queues possibly allocated above deserved (conservative: only a
        queue with EVERY dimension below deserved - eps is certainly not
        over, proportion.py:164-171)."""
        return self.q_has_attr & np.any(
            self.qalloc >= self.qdeserved - self.MINR, axis=-1)

    def _refresh_over(self, qx: int) -> None:
        now = bool(self.q_has_attr[qx] and np.any(
            self.qalloc[qx] >= self.qdeserved[qx] - self.MINR))
        if now == bool(self.over[qx]):
            return
        sign = 1.0 if now else -1.0
        self.pool_over += sign * self.pool_q[:, qx]
        self.cnt_over += sign * self.cnt_q[:, qx]
        self.over[qx] = now

    @staticmethod
    def _tier1_is_priority(ssn) -> bool:
        """True when the FIRST tier with reclaimable participants consists
        only of the stock priority/gang lower-priority filters."""
        for tier in ssn.tiers:
            entries = [opt.name for opt in tier.plugins
                       if opt.is_enabled("enabledReclaimable")
                       and opt.name in ssn.reclaimable_fns]
            if not entries:
                continue
            return all(
                name in ("priority", "gang")
                and getattr(ssn.reclaimable_fns[name], "__module__", "")
                == f"volcano_tpu.plugins.{name}" for name in entries)
        return False

    @staticmethod
    def _tier2_is_proportion(ssn) -> bool:
        """True when the SECOND tier with reclaimable participants is
        exactly the stock proportion plugin AND no later tier
        participates — its over-deserved gate then bounds everything a
        tier-1 abstention can reach."""
        per_tier = []
        for tier in ssn.tiers:
            entries = [opt.name for opt in tier.plugins
                       if opt.is_enabled("enabledReclaimable")
                       and opt.name in ssn.reclaimable_fns]
            if entries:
                per_tier.append(entries)
        return (len(per_tier) == 2 and per_tier[1] == ["proportion"]
                and getattr(ssn.reclaimable_fns["proportion"],
                            "__module__", "")
                == "volcano_tpu.plugins.proportion")

    def _feas_row(self, task) -> np.ndarray:
        if self.ssn.stateful_predicates:
            # stateful predicates (pod affinity, gpu cards, ports) can
            # LOOSEN as the rotation pipelines/evicts, so a cached static
            # row is not a superset — skip feasibility screening entirely
            # (the body's live predicate_fn still decides)
            return self._all_true
        row = self._feas_cache.get(task.uid)
        if row is not None:
            return row
        job = self.ssn.jobs.get(task.job)
        pend = list(job.task_status_index.get(TaskStatus.PENDING,
                                              {}).values()) if job else []
        if task.uid not in {t.uid for t in pend}:
            pend.append(task)
        feas = assemble_feasibility(self.ssn, pend, self.node_t)
        for i, t in enumerate(pend):
            self._feas_cache[t.uid] = (self._all_true if feas is None
                                       else feas[i])
        return self._feas_cache[task.uid]

    MINR = 0.1      # api/resource.py MIN_RESOURCE — widens the screen

    def note_evict(self, victim) -> None:
        """Rotation callback: victim left the pool, its resreq joined the
        node's future-idle (session.evict's releasing bump)."""
        n = self.node_index.get(victim.node_name)
        qx = self.queue_ix.get(self.ssn.jobs[victim.job].queue)
        if n is None:
            return
        r = self._row_cache.get(victim.uid)
        if r is None:
            r = _res_rows_f64([victim.resreq], self.rnames)[0]
        self.head[n] += r
        px = self.pr_ix.get(self.ssn.jobs[victim.job].priority)
        if qx is not None and px is not None:
            self.cumP[n, qx, px + 1:] -= r
            self.cumP_all[n, px + 1:] -= r
            self.ccntP[n, qx, px + 1:] -= 1
            self.ccntP_all[n, px + 1:] -= 1
            self.pool_q[n, qx] -= r
            self.cnt_q[n, qx] -= 1
            self.pool_all[n] -= r
            self.cnt_all[n] -= 1
            if self.over[qx]:
                self.pool_over[n] -= r
                self.cnt_over[n] -= 1
        if qx is not None:
            self.qalloc[qx] -= r
            self._refresh_over(qx)

    def note_pipeline(self, task, node) -> None:
        """Rotation callback: the pipelined reclaimer reserves the node's
        future-idle (node_info.add_task PIPELINED) and grows its queue's
        allocation (proportion's allocate handler)."""
        n = self.node_index.get(node.name)
        r = self._row_cache.get(task.uid)
        if r is None:
            r = _res_rows_f64([task.resreq], self.rnames)[0]
            self._row_cache[task.uid] = r
        if n is not None:
            self.head[n] -= r
        qx = self.queue_ix.get(self.ssn.jobs[task.job].queue)
        if qx is not None:
            self.qalloc[qx] += r
            self._refresh_over(qx)

    def nodes_for(self, task) -> List:
        qx = self.queue_ix.get(self.ssn.jobs[task.job].queue)
        if qx is None:
            return self.nodes
        req = self._row_cache.get(task.uid)
        if req is None:
            req = _res_rows_f64([task.init_resreq], self.rnames)[0]
            self._row_cache[task.uid] = req
        pool_full = self.pool_all - self.pool_q[:, qx]
        cnt_full = self.cnt_all - self.cnt_q[:, qx]
        # NO pool-alone-covers clause: the reference body evicts even when
        # the victims cannot cover the request (it only skips the PIPELINE
        # then, reclaim.py:101-112), so such nodes must stay in the walk
        if self.tier1_priority:
            p = self.ssn.jobs[task.job].priority
            pix = int(np.searchsorted(self.pr_vals, p))  # #priorities < p
            pool_lp = self.cumP_all[:, pix] - self.cumP[:, qx, pix]
            cnt_lp = self.ccntP_all[:, pix] - self.ccntP[:, qx, pix]
            if self.tier2_proportion:
                # tier 2 (proportion) only ever accepts victims of queues
                # currently allocated above deserved; a queue certainly
                # NOT over-deserved contributes nothing to tier 2
                if self.over[qx]:
                    pool_t2 = self.pool_over - self.pool_q[:, qx]
                    cnt_t2 = self.cnt_over - self.cnt_q[:, qx]
                else:
                    pool_t2, cnt_t2 = self.pool_over, self.cnt_over
            else:
                pool_t2, cnt_t2 = pool_full, cnt_full
            # lower-priority victims present -> tier 1 RULES with exactly
            # that set; otherwise tier 1 abstains and tier 2 rules
            pool = np.where((cnt_lp > 0)[:, None], pool_lp, pool_t2)
            cnt = np.where(cnt_lp > 0, cnt_lp, cnt_t2)
        else:
            pool, cnt = pool_full, cnt_full
        ok = ((cnt > 0)
              & np.all(self.head + pool + self.MINR >= req, axis=-1)
              & self._feas_row(task))
        return [self.nodes[i] for i in np.flatnonzero(ok)]


def execute_reclaim_tpu(ssn) -> None:
    """Reclaim engine: the exact reference rotation through the screener
    (see _ReclaimScreener). Decisions are the callback engine's by
    construction; the screener only removes provably-hopeless nodes from
    each attempt's walk."""
    from .reclaim import ReclaimAction
    ReclaimAction(engine="callbacks")._execute_callbacks(
        ssn, screener=_ReclaimScreener(ssn))
