"""Host side of the device preempt/reclaim engines (SURVEY M3, VERDICT r1
#3): assemble victim/preemptor tensors, precompute per-tier per-plugin veto
masks through the REAL plugin callbacks, run the ops/evict.py scans (which
replay the tier dispatch per (preemptor, node) including drf's dynamic
dominant-share tier), and replay the proposals through genuine Statements
so gang atomicity and plugin event handlers see exactly what the callback
engine would produce.

Fixed-order caveat (same stance as the fused allocate engine): queue/job
order is precomputed once per action on the opening snapshot instead of per
pop; every proposal is re-validated through the live plugin chain at
replay, so a divergence can only skip work, never evict a vetoed victim.
"""

from __future__ import annotations

from functools import cmp_to_key
from typing import Dict, List, Optional

import numpy as np

from ..api import PodGroupPhase, TaskInfo, TaskStatus
from ..cache.snapshot import (NodeTensors, assemble_feasibility,
                              assemble_static_score, assemble_weights,
                              discover_resource_names, task_requests)
from ..framework.session import ABSTAIN
from ..utils import PriorityQueue

NO_NODE = -1
BIG = 1 << 30


class _EvictTensors:
    """Shared device-side inputs for one eviction action."""

    def __init__(self, ssn, victims: List[TaskInfo],
                 preemptors: List[TaskInfo]):
        self.victims = victims
        self.rnames = discover_resource_names(
            list(ssn.nodes.values()), victims + preemptors)
        self.node_t = NodeTensors(list(ssn.nodes.values()), self.rnames)
        self.vreq = task_requests_of(victims, self.rnames, init=False)
        self.vnode = np.asarray(
            [self.node_t.index[t.node_name] for t in victims], np.int32)

    def future_idle0(self):
        return (self.node_t.idle + self.node_t.releasing
                - self.node_t.pipelined)


def task_requests_of(tasks, rnames, init=True) -> np.ndarray:
    req = np.zeros((len(tasks), len(rnames)), np.float32)
    for i, t in enumerate(tasks):
        r = t.init_resreq if init else t.resreq
        req[i] = r.to_vector(rnames)
    return req


def _task_order_chain(ssn) -> List[str]:
    return [name for tier in ssn.tiers for opt in tier.plugins
            if opt.is_enabled("enabledTaskOrder")
            and (name := opt.name) in ssn.task_order_fns]


def _eviction_order(ssn, victims: List[TaskInfo]) -> List[TaskInfo]:
    """Reversed TaskOrderFn — lowest priority first (preempt.go:237-244).
    Key sort when only the priority plugin orders tasks (the default conf;
    Python's reverse=True is stable, so tie order matches the stable
    comparator sort); comparator sort otherwise."""
    chain = _task_order_chain(ssn)
    if chain == ["priority"]:
        return sorted(victims,
                      key=lambda t: (-t.priority, t.creation_timestamp,
                                     t.uid), reverse=True)
    if not chain:
        return list(victims)

    def cmp(l, r):
        if ssn.task_order_fn(l, r):
            return 1
        if ssn.task_order_fn(r, l):
            return -1
        return 0
    return sorted(victims, key=cmp_to_key(cmp))


def _collect_victims(ssn) -> List[TaskInfo]:
    """RUNNING victim candidates in node-iteration x node.tasks order — the
    candidate-list order every plugin dispatch sees."""
    out = []
    for node in ssn.nodes.values():
        for t in node.tasks.values():
            if t.status != TaskStatus.RUNNING or t.resreq.is_empty():
                continue
            if t.job in ssn.jobs and t.uid in ssn.jobs[t.job].tasks:
                out.append(ssn.jobs[t.job].tasks[t.uid])
    return out


def _rep_task(job) -> Optional[TaskInfo]:
    pend = job.task_status_index.get(TaskStatus.PENDING, {})
    for t in pend.values():
        if not t.resreq.is_empty():
            return t
    return None


class _TierStack:
    """Per-tier plugin veto masks for the device dispatch replay.

    kinds[i]: "static" | "drf" | "proportion". masks[i]: tuple of
    (mask [PJ,V] bool, part [PJ] bool) for the STATIC plugins of tier i —
    dynamic plugins (drf dominant shares, proportion deserved) are computed
    in-kernel from tracked state.
    """

    def __init__(self, ssn, pjobs, victims, registry, flag, dynamic_name,
                 cand_filter):
        PJ, V = len(pjobs), len(victims)
        vix = {t.uid: i for i, t in enumerate(victims)}
        cands_per_job = [
            [v for v in victims if cand_filter(job, v)] for job in pjobs]
        self.cand_mask = np.zeros((PJ, V), bool)
        for j, cands in enumerate(cands_per_job):
            for v in cands:
                self.cand_mask[j, vix[v.uid]] = True

        kinds: List[str] = []
        masks: List[tuple] = []
        for tier in ssn.tiers:
            entries = []
            has_dynamic = False
            for opt in tier.plugins:
                if not opt.is_enabled(flag):
                    continue
                fn = registry.get(opt.name)
                if fn is None:
                    continue
                if opt.name == dynamic_name:
                    has_dynamic = True
                else:
                    entries.append(fn)
            if not entries and not has_dynamic:
                continue
            tier_masks = []
            for fn in entries:
                m = np.zeros((PJ, V), bool)
                part = np.zeros(PJ, bool)
                for j, job in enumerate(pjobs):
                    rep = _rep_task(job)
                    if rep is None:
                        continue
                    returned, vote = fn(rep, cands_per_job[j])
                    if vote == ABSTAIN:
                        continue
                    part[j] = True
                    for v in returned:
                        if v.uid in vix:
                            m[j, vix[v.uid]] = True
                tier_masks.append((m, part))
            kinds.append(dynamic_name if has_dynamic else "static")
            masks.append(tuple(tier_masks))
        self.kinds = tuple(kinds)
        self.sizes = tuple(len(m) for m in masks)
        self.masks = tuple(masks)
        self.has_dynamic = dynamic_name in self.kinds


def _drf_inputs(ssn, tensors: _EvictTensors, victims, need_group: bool):
    """(vjob, jalloc0, total, perm_inputs, job_index): global job table for
    the in-kernel drf share tracking. perm_inputs = (perm, inv, seg, head):
    a (node, job, candidate-list order) sort of the victims and its segment
    structure, so the kernel's within-dispatch exclusive prefix is one O(V)
    segmented cumsum instead of a [V,V] matmul."""
    job_index = {uid: i for i, uid in enumerate(ssn.jobs)}
    AJ = len(job_index)
    R = len(tensors.rnames)
    jalloc = np.zeros((AJ, R), np.float32)
    from ..api.types import allocated_status
    for uid, job in ssn.jobs.items():
        jx = job_index[uid]
        for t in job.tasks.values():
            if allocated_status(t.status):
                jalloc[jx] += t.resreq.to_vector(tensors.rnames)
    total = tensors.node_t.allocatable.sum(axis=0)
    vjob = np.asarray([job_index[t.job] for t in victims], np.int32)
    V = max(1, len(victims))
    if need_group and victims:
        # drf candidate-list order = _collect_victims order
        rank = {t.uid: i for i, t in enumerate(_collect_victims(ssn))}
        vrank = np.asarray([rank.get(t.uid, 0) for t in victims])
        vnode = tensors.vnode
        perm = np.lexsort((vrank, vjob, vnode)).astype(np.int32)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(len(perm), dtype=np.int32)
        key = vnode[perm].astype(np.int64) * (vjob.max() + 1) + vjob[perm]
        seg = np.zeros(len(perm), np.int32)
        seg[1:] = np.cumsum(key[1:] != key[:-1]).astype(np.int32)
        head = np.zeros(V, np.int32)
        first = np.r_[True, key[1:] != key[:-1]]
        head[seg[first]] = np.flatnonzero(first).astype(np.int32)
    else:
        perm = np.arange(V, dtype=np.int32)
        inv = perm.copy()
        seg = np.zeros(V, np.int32)
        head = np.zeros(V, np.int32)
    return vjob, jalloc, total, (perm, inv, seg, head), job_index


def _score_matrix(ssn, ptasks, tensors: _EvictTensors):
    """f32[P,N] node scores with static feasibility folded in as -inf —
    the same assembly the fused allocate engine uses. Returned as a DEVICE
    array: at 5k preemptors x 1k nodes the matrix is ~20MB, and fetching it
    just to re-upload into the scan costs seconds on a remote backend."""
    import jax.numpy as jnp
    from ..ops.scores import combined_dynamic_score

    node_t = tensors.node_t
    preq = task_requests(ptasks, tensors.rnames)
    feas = assemble_feasibility(ssn, ptasks, node_t)
    static = assemble_static_score(ssn, ptasks, node_t)
    weights = assemble_weights(ssn, tensors.rnames)
    score = combined_dynamic_score(jnp.asarray(preq),
                                   jnp.asarray(node_t.used),
                                   jnp.asarray(node_t.allocatable), weights)
    if static is not None:
        score = score + jnp.asarray(static)
    if feas is not None:
        score = jnp.where(jnp.asarray(feas), score, -jnp.inf)
    return preq, score


def _starving_jobs(ssn):
    """(phase1_order, under_request): starving jobs grouped per queue in job
    order for the inter-job phase, plus the same jobs in plain ssn.jobs
    iteration order — the reference's ``underRequest`` list that drives the
    intra-job pass (preempt.go:46-81,146)."""
    per_queue: Dict[str, PriorityQueue] = {}
    under_request = []
    for job in ssn.jobs.values():
        if job.podgroup.phase == PodGroupPhase.PENDING:
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.passed:
            continue
        if job.queue not in ssn.queues:
            continue
        if ssn.job_starving(job):
            per_queue.setdefault(job.queue,
                                 PriorityQueue(ssn.job_order_fn)).push(job)
            under_request.append(job)
    ordered = []
    for q in per_queue.values():
        while not q.empty():
            ordered.append(q.pop())
    return ordered, under_request


def _pending_in_order(ssn, job) -> List[TaskInfo]:
    """Pending tasks in TaskOrderFn order — same fast paths as the allocate
    engine's _pending_tasks (actions/allocate.py)."""
    from .allocate import _pending_tasks
    return _pending_tasks(ssn, job)


def execute_preempt_tpu(ssn) -> None:
    """Device preempt: phase 1 inter-job (gang statements), phase 2
    intra-job, then the host victim_tasks pass."""
    victims = _eviction_order(ssn, _collect_victims(ssn))
    pjobs, under_request = _starving_jobs(ssn)
    # a job with NO same-queue foreign victim can never preempt: its
    # candidate row is empty for every tier (drf verdicts are subsets of
    # the candidate list), so pruning it is exact
    vq_count: Dict[str, int] = {}
    vq_own: Dict[tuple, int] = {}
    for v in victims:
        q = ssn.jobs[v.job].queue
        vq_count[q] = vq_count.get(q, 0) + 1
        vq_own[(q, v.job)] = vq_own.get((q, v.job), 0) + 1
    pjobs = [j for j in pjobs
             if vq_count.get(j.queue, 0)
             - vq_own.get((j.queue, j.uid), 0) > 0]
    if pjobs and victims:
        _preempt_phase(ssn, pjobs, victims, inter_job=True)
    # phase 2: within-job preemption, one pass in underRequest order
    # (preempt.go:146-183) — only jobs that still have pending tasks AND
    # own running victims can act
    pjobs2 = [j for j in under_request
              if j.task_status_index.get(TaskStatus.PENDING)
              and j.task_status_index.get(TaskStatus.RUNNING)]
    victims2 = _eviction_order(ssn, _collect_victims(ssn))
    if pjobs2 and victims2:
        _preempt_phase(ssn, pjobs2, victims2, inter_job=False)
    _victim_tasks_host(ssn)


def _preempt_phase(ssn, pjobs, victims, inter_job: bool) -> None:
    import jax.numpy as jnp
    from ..ops.evict import build_preempt_scan

    ptasks: List[TaskInfo] = []
    pjob_ix: List[int] = []
    first: List[bool] = []
    kept_jobs = []
    for job in pjobs:
        tasks = _pending_in_order(ssn, job)
        if not tasks:
            continue
        jx = len(kept_jobs)
        kept_jobs.append(job)
        for k, t in enumerate(tasks):
            ptasks.append(t)
            pjob_ix.append(jx)
            first.append(k == 0)
    if not ptasks:
        return

    if inter_job:
        def cand_filter(job, v):
            vj = ssn.jobs.get(v.job)
            return (vj is not None and vj.queue == job.queue
                    and v.job != job.uid)
        needed = np.asarray(
            [max(0, j.min_available - j.ready_task_num()
                 - j.waiting_task_num()) for j in kept_jobs], np.int32)
    else:
        def cand_filter(job, v):
            return v.job == job.uid
        needed = np.full(len(kept_jobs), BIG, np.int32)

    stack = _TierStack(ssn, kept_jobs, victims, ssn.preemptable_fns,
                       "enabledPreemptable", "drf", cand_filter)
    tensors = _EvictTensors(ssn, victims, ptasks)
    preq, score = _score_matrix(ssn, ptasks, tensors)
    vjob, jalloc0, total, (perm, inv, seg, head), job_index = _drf_inputs(
        ssn, tensors, victims, need_group=stack.has_dynamic)
    pjg = np.asarray([job_index[j.uid] for j in kept_jobs], np.int32)[
        np.asarray(pjob_ix, np.int32)]

    fn = build_preempt_scan(stack.kinds, stack.sizes, inter_job)
    task_node, owner, job_done = fn(
        jnp.asarray(tensors.future_idle0()),
        jnp.asarray(tensors.vreq), jnp.asarray(tensors.vnode),
        jnp.asarray(stack.cand_mask),
        tuple(tuple((jnp.asarray(m), jnp.asarray(p)) for m, p in tm)
              for tm in stack.masks),
        jnp.asarray(preq), jnp.asarray(np.asarray(pjob_ix, np.int32)),
        jnp.asarray(np.asarray(first, bool)), jnp.asarray(score),
        jnp.asarray(needed), jnp.asarray(vjob), jnp.asarray(pjg),
        jnp.asarray(jalloc0), jnp.asarray(total),
        jnp.asarray(perm), jnp.asarray(inv), jnp.asarray(seg),
        jnp.asarray(head))
    packed = np.asarray(jnp.concatenate([
        task_node, owner, job_done.astype(jnp.int32)]))     # one fetch
    P, V = len(ptasks), len(victims)
    task_node = packed[:P]
    owner = packed[P:P + V]
    job_done = packed[P + V:].astype(bool)

    _replay_preempt(ssn, ptasks, pjob_ix, kept_jobs, victims, tensors,
                    task_node, owner, job_done, inter_job)


def _replay_preempt(ssn, ptasks, pjob_ix, kept_jobs, victims, tensors,
                    task_node, owner, job_done, inter_job: bool) -> None:
    from .. import metrics

    victims_by_step: Dict[int, List[TaskInfo]] = {}
    for v, own in enumerate(owner):
        if own >= 0:
            victims_by_step.setdefault(int(own), []).append(victims[v])

    per_job: Dict[int, List[int]] = {}
    for i, jx in enumerate(pjob_ix):
        per_job.setdefault(jx, []).append(i)

    for jx, ids in per_job.items():
        job = kept_jobs[jx]
        if inter_job and not job_done[jx]:
            continue
        stmt = ssn.statement()
        for i in ids:
            n = int(task_node[i])
            if n == NO_NODE:
                continue
            node_name = tensors.node_t.names[n]
            evicted = victims_by_step.get(i, [])
            # final live validation through the real tiered chain
            validated = {t.uid for t in ssn.preemptable(ptasks[i], evicted)} \
                if evicted else set()
            for vt in evicted:
                if vt.uid in validated and vt.uid in ssn.jobs[vt.job].tasks:
                    stmt.evict(ssn.jobs[vt.job].tasks[vt.uid], "preempt")
            metrics.update_preemption_victims(len(validated))
            metrics.register_preemption_attempt()
            # pipeline only if the node actually fits after the validated
            # evictions (preempt.go:263-267) — a live-chain veto must not
            # overcommit future_idle
            node = ssn.nodes[node_name]
            if ptasks[i].init_resreq.less_equal(node.future_idle()):
                stmt.pipeline(ptasks[i], node_name)
        if inter_job:
            if ssn.job_pipelined(job):
                stmt.commit()
            else:
                stmt.discard()
        else:
            stmt.commit()


def _victim_tasks_host(ssn) -> None:
    """Plugin-driven eviction pass (tdm VictimTasksFn, preempt.go:272-284)."""
    stmt = ssn.statement()
    for victim in ssn.victim_tasks():
        job = ssn.jobs.get(victim.job)
        if job is None or victim.uid not in job.tasks:
            continue
        stmt.evict(job.tasks[victim.uid], "evict")
    stmt.commit()


def execute_reclaim_tpu(ssn) -> None:
    """Device reclaim: victims from other, reclaimable queues; direct
    evictions (reclaim.go semantics, no statement)."""
    import jax.numpy as jnp
    from ..ops.evict import build_reclaim_scan

    # reclaim evicts in candidate-list order — node.tasks insertion order,
    # NOT the reversed TaskOrderFn that preempt uses (reclaim.go walks the
    # Reclaimable result as-is)
    victims = _collect_victims(ssn)

    # reclaimers: pending tasks of valid jobs in non-overused queues, in
    # (queue share, job order, task order) interleave — fixed per action
    per_queue: Dict[str, PriorityQueue] = {}
    queues = {}
    for job in ssn.jobs.values():
        if job.podgroup.phase == PodGroupPhase.PENDING:
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.passed:
            continue
        queue = ssn.queues.get(job.queue)
        if queue is None or ssn.overused(queue):
            continue
        if not job.task_status_index.get(TaskStatus.PENDING):
            continue
        queues[queue.uid] = queue
        per_queue.setdefault(job.queue,
                             PriorityQueue(ssn.job_order_fn)).push(job)

    kept_jobs: List = []
    ptasks: List[TaskInfo] = []
    pjob_ix: List[int] = []
    pqueue_ix: List[int] = []
    last_of_job: List[bool] = []
    qorder = sorted(queues.values(),
                    key=cmp_to_key(lambda l, r: -1 if ssn.queue_order_fn(l, r)
                                   else 1))
    queue_index = {q.uid: i for i, q in enumerate(qorder)}
    for qx, queue in enumerate(qorder):
        jobs_pq = per_queue.get(queue.uid)
        while jobs_pq is not None and not jobs_pq.empty():
            job = jobs_pq.pop()
            tasks = _pending_in_order(ssn, job)
            if not tasks:
                continue
            jx = len(kept_jobs)
            kept_jobs.append(job)
            for k, t in enumerate(tasks):
                ptasks.append(t)
                pjob_ix.append(jx)
                pqueue_ix.append(qx)
                last_of_job.append(k == len(tasks) - 1)
    if not ptasks or not victims:
        return

    def cand_filter(job, v):
        vj = ssn.jobs.get(v.job)
        if vj is None or vj.queue == job.queue:
            return False
        vq = ssn.queues.get(vj.queue)
        return vq is not None and vq.reclaimable

    stack = _TierStack(ssn, kept_jobs, victims, ssn.reclaimable_fns,
                       "enabledReclaimable", "proportion", cand_filter)
    tensors = _EvictTensors(ssn, victims, ptasks)
    preq = task_requests(ptasks, tensors.rnames)

    # proportion state: queue allocated/deserved vectors (proportion.go)
    Q = len(qorder)
    all_queues = {q.uid: i for i, q in enumerate(ssn.queues.values())}
    Qall = len(all_queues)
    qalloc = np.zeros((Qall, len(tensors.rnames)), np.float32)
    qdeserved = np.full((Qall, len(tensors.rnames)), np.float32(1e30))
    from ..api.types import allocated_status
    for job in ssn.jobs.values():
        if job.queue in all_queues:
            qx = all_queues[job.queue]
            for t in job.tasks.values():
                if allocated_status(t.status):
                    qalloc[qx] += t.resreq.to_vector(tensors.rnames)
    for name, r in ssn.queue_deserved.items():
        if name in all_queues:
            qdeserved[all_queues[name]] = r.to_vector(tensors.rnames)
    vqueue = np.asarray(
        [all_queues.get(ssn.jobs[t.job].queue, 0) for t in victims],
        np.int32)
    pqueue_all = np.asarray(
        [all_queues[qorder[qx].uid] for qx in pqueue_ix], np.int32)

    fn = build_reclaim_scan(stack.kinds, stack.sizes)
    task_node, owner = fn(
        jnp.asarray(tensors.future_idle0()),
        jnp.asarray(tensors.vreq), jnp.asarray(tensors.vnode),
        jnp.asarray(stack.cand_mask),
        tuple(tuple((jnp.asarray(m), jnp.asarray(p)) for m, p in tm)
              for tm in stack.masks),
        jnp.asarray(preq), jnp.asarray(np.asarray(pjob_ix, np.int32)),
        jnp.asarray(pqueue_all),
        jnp.asarray(np.asarray(last_of_job, bool)),
        jnp.asarray(vqueue), jnp.asarray(qalloc), jnp.asarray(qdeserved))
    packed = np.asarray(jnp.concatenate([task_node, owner]))    # one fetch
    P = len(ptasks)
    task_node, owner = packed[:P], packed[P:]

    victims_by_step: Dict[int, List[TaskInfo]] = {}
    for v, own in enumerate(owner):
        if own >= 0:
            victims_by_step.setdefault(int(own), []).append(victims[v])

    from ..api import Resource
    for i, task in enumerate(ptasks):
        n = int(task_node[i])
        if n == NO_NODE:
            continue
        evicted = victims_by_step.get(i, [])
        validated = {t.uid for t in ssn.reclaimable(task, evicted)} \
            if evicted else set()
        reclaimed = Resource()
        for vt in evicted:
            if vt.uid in validated and vt.uid in ssn.jobs[vt.job].tasks:
                ssn.evict(ssn.jobs[vt.job].tasks[vt.uid], "reclaim")
                reclaimed.add(vt.resreq)
        # pipeline only when the validated evictions alone cover the
        # request (reclaim.go:93-96) — a live-chain veto must not
        # overcommit the node
        if task.init_resreq.less_equal(reclaimed):
            ssn.pipeline(task, tensors.node_t.names[n])
