"""Actions (mirrors /root/reference/pkg/scheduler/actions). Importing this
package registers the in-tree actions."""

from ..framework.registry import register_action
from .allocate import AllocateAction, AllocateTPUAction
from .backfill import BackfillAction
from .base import Action
from .elect import ElectAction
from .enqueue import EnqueueAction
from .preempt import PreemptAction
from .reclaim import ReclaimAction
from .reserve import ReserveAction

register_action(EnqueueAction())
register_action(AllocateAction())
register_action(AllocateTPUAction())
register_action(BackfillAction())
register_action(PreemptAction())
register_action(ReclaimAction())
register_action(ElectAction())
register_action(ReserveAction())

__all__ = ["Action", "AllocateAction", "AllocateTPUAction", "BackfillAction",
           "ElectAction", "EnqueueAction", "PreemptAction", "ReclaimAction",
           "ReserveAction"]
