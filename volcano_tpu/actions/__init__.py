"""Actions (mirrors /root/reference/pkg/scheduler/actions). Importing this
package registers the in-tree actions."""

import sys as _sys

from ..framework.registry import register_action
from .allocate import AllocateAction, AllocateTPUAction
from .backfill import BackfillAction
from .base import Action
from .elect import ElectAction
from .enqueue import EnqueueAction
from .preempt import PreemptAction
from .reclaim import ReclaimAction
from .reserve import ReserveAction

register_action(EnqueueAction())
register_action(AllocateAction())
register_action(AllocateTPUAction())
register_action(BackfillAction())
register_action(PreemptAction())
register_action(ReclaimAction())
register_action(ElectAction())
register_action(ReserveAction())

# grow-shrink lives in the elastic_gang package (it is the elastic stage,
# not a generic action) and SELF-registers at the end of its module. The
# sys.modules guard breaks the import cycle: grow_shrink imports
# actions.base, so when ITS import triggered this package the module is
# mid-flight here — skipping it is safe because its own tail registers.
if "volcano_tpu.elastic_gang.grow_shrink" not in _sys.modules:
    from ..elastic_gang import grow_shrink as _grow_shrink  # noqa: F401

__all__ = ["Action", "AllocateAction", "AllocateTPUAction", "BackfillAction",
           "ElectAction", "EnqueueAction", "GrowShrinkAction",
           "PreemptAction", "ReclaimAction", "ReserveAction"]


def __getattr__(name):
    if name == "GrowShrinkAction":
        from ..elastic_gang.grow_shrink import GrowShrinkAction
        return GrowShrinkAction
    raise AttributeError(name)
