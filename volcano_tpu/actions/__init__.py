"""Actions (mirrors /root/reference/pkg/scheduler/actions). Importing this
package registers the in-tree actions."""

from ..framework.registry import register_action
from .allocate import AllocateAction, AllocateTPUAction
from .backfill import BackfillAction
from .base import Action
from .enqueue import EnqueueAction

register_action(EnqueueAction())
register_action(AllocateAction())
register_action(AllocateTPUAction())
register_action(BackfillAction())

__all__ = ["Action", "AllocateAction", "AllocateTPUAction", "BackfillAction",
           "EnqueueAction"]
