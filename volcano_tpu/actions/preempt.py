"""Preempt action: within-queue job-vs-job and within-job preemption for
starving jobs.

Mirrors /root/reference/pkg/scheduler/actions/preempt/preempt.go:41-284.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .. import metrics
from ..api import PodGroupPhase, Resource, TaskInfo, TaskStatus
from ..obs import trace as obs_trace
from ..utils import PriorityQueue
from ..utils.scheduler_helper import (predicate_nodes, prioritize_nodes,
                                      select_best_node)
from .base import Action


def validate_victims(preemptor: TaskInfo, node, victims: List[TaskInfo]) -> bool:
    """scheduler_helper.go ValidateVictims: enough future-idle after evicting
    all victims."""
    if not victims:
        return False
    future_idle = node.future_idle()
    for v in victims:
        future_idle.add(v.resreq)
    return preemptor.init_resreq.less_equal(future_idle)


def sort_nodes(node_scores) -> List:
    out = []
    for score in sorted(node_scores, reverse=True):
        out.extend(node_scores[score])
    return out


class PreemptAction(Action):
    NAME = "preempt"
    DEFAULT_ENGINE = "callbacks"

    def __init__(self, engine: Optional[str] = None):
        self.engine = engine or self.DEFAULT_ENGINE

    def execute(self, ssn) -> None:
        engine = self.engine
        for conf in ssn.configurations:
            if conf.name == self.NAME:
                engine = conf.arguments.get("engine", engine)
        if engine == "tpu":
            from .evict_tpu import execute_preempt_tpu
            return execute_preempt_tpu(ssn)
        if engine == "tpu-sharded":
            from .evict_tpu import execute_preempt_tpu
            return execute_preempt_tpu(ssn, sharded=True)
        return self._execute_callbacks(ssn)

    def _execute_callbacks(self, ssn) -> None:
        preemptors_map = {}
        preemptor_tasks = {}
        under_request = []
        queues = {}

        for job in ssn.jobs.values():
            if job.podgroup.phase == PodGroupPhase.PENDING:
                continue
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            queues[queue.uid] = queue

            if ssn.job_starving(job):
                preemptors_map.setdefault(
                    job.queue, PriorityQueue(ssn.job_order_fn)).push(job)
                under_request.append(job)
                pq = PriorityQueue(ssn.task_order_fn)
                for task in job.task_status_index.get(TaskStatus.PENDING,
                                                      {}).values():
                    pq.push(task)
                preemptor_tasks[job.uid] = pq

        # Preemption between jobs within a queue (preempt.go:83-144).
        with obs_trace.span("preempt_inter_job"):
            self._inter_job_pass(ssn, queues, preemptors_map,
                                 preemptor_tasks)

        # Preemption between tasks within one job — ONE pass after the
        # per-queue loop (preempt.go:146-183 sits outside it).
        with obs_trace.span("preempt_intra_job"):
            self._intra_job_pass(ssn, under_request, preemptor_tasks)

        with obs_trace.span("victim_tasks"):
            self._victim_tasks(ssn)

    def _inter_job_pass(self, ssn, queues, preemptors_map,
                        preemptor_tasks) -> None:
        for queue in queues.values():
            while True:
                preemptors = preemptors_map.get(queue.uid)
                if preemptors is None or preemptors.empty():
                    break
                preemptor_job = preemptors.pop()

                stmt = ssn.statement()
                assigned = False
                while True:
                    if not ssn.job_starving(preemptor_job):
                        break
                    if preemptor_tasks[preemptor_job.uid].empty():
                        break
                    preemptor = preemptor_tasks[preemptor_job.uid].pop()

                    def job_filter(task: TaskInfo) -> bool:
                        if task.status != TaskStatus.RUNNING:
                            return False
                        if task.resreq.is_empty():
                            return False
                        victim_job = ssn.jobs.get(task.job)
                        if victim_job is None:
                            return False
                        return (victim_job.queue == preemptor_job.queue
                                and preemptor.job != task.job)

                    if self._preempt(ssn, stmt, preemptor, job_filter):
                        assigned = True

                if ssn.job_pipelined(preemptor_job):
                    stmt.commit()
                else:
                    stmt.discard()
                    continue
                if assigned:
                    preemptors.push(preemptor_job)

    def _intra_job_pass(self, ssn, under_request, preemptor_tasks) -> None:
        for job in under_request:
            pq = PriorityQueue(ssn.task_order_fn)
            for task in job.task_status_index.get(TaskStatus.PENDING,
                                                  {}).values():
                pq.push(task)
            preemptor_tasks[job.uid] = pq
            while not preemptor_tasks[job.uid].empty():
                preemptor = preemptor_tasks[job.uid].pop()
                stmt = ssn.statement()
                assigned = self._preempt(
                    ssn, stmt, preemptor,
                    lambda task: (task.status == TaskStatus.RUNNING
                                  and not task.resreq.is_empty()
                                  and preemptor.job == task.job))
                stmt.commit()
                if not assigned:
                    break

    def _preempt(self, ssn, stmt, preemptor: TaskInfo,
                 task_filter: Callable[[TaskInfo], bool]) -> bool:
        """preempt.go:190-269: evict lowest-priority victims on the best
        node until FutureIdle fits, then Pipeline the preemptor."""
        assigned = False
        nodes = list(ssn.nodes.values())

        def pred(task, node):
            ssn.predicate_fn(task, node)

        feasible, _ = predicate_nodes(preemptor, nodes, pred)
        scores = prioritize_nodes(preemptor, feasible,
                                  ssn.batch_node_order_fn, ssn.node_order_fn)
        for node in sort_nodes(scores):
            preemptees = [t.clone() for t in node.tasks.values()
                          if task_filter(t)]
            victims = ssn.preemptable(preemptor, preemptees)
            metrics.update_preemption_victims(len(victims))
            if not validate_victims(preemptor, node, victims):
                continue

            # lowest priority first (reversed TaskOrderFn)
            vq = PriorityQueue(lambda l, r: not ssn.task_order_fn(l, r))
            for v in victims:
                vq.push(v)
            preempted = Resource()
            while not vq.empty():
                if preemptor.init_resreq.less_equal(node.future_idle()):
                    break
                preemptee = vq.pop()
                stmt.evict(ssn.jobs[preemptee.job].tasks[preemptee.uid],
                           "preempt")
                preempted.add(preemptee.resreq)
            metrics.register_preemption_attempt()

            if preemptor.init_resreq.less_equal(node.future_idle()):
                stmt.pipeline(preemptor, node.name)
                assigned = True
                break
        return assigned

    def _victim_tasks(self, ssn) -> None:
        """Plugin-driven eviction pass (tdm's VictimTasksFn etc.,
        preempt.go:272-284)."""
        stmt = ssn.statement()
        for victim in ssn.victim_tasks():
            job = ssn.jobs.get(victim.job)
            if job is None or victim.uid not in job.tasks:
                continue
            stmt.evict(job.tasks[victim.uid], "evict")
        stmt.commit()
