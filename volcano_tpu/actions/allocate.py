"""Allocate: the core scheduling action, in three engines.

Control flow mirrors /root/reference/pkg/scheduler/actions/allocate/
allocate.go:42-277 — namespace → queue (overused-filtered, share-ordered) →
job → task priority interleave, per-task predicate/score/select, Statement
commit iff the gang is Ready.

Engines:

- ``callbacks``  the reference architecture verbatim: per-(task,node) plugin
  callbacks through PredicateNodes/PrioritizeNodes. The CPU baseline.
- ``tpu-strict`` identical interleave — the same _pop_next against the live
  session decides every job — with the device solves BATCHED: the next B
  pops are predicted by clone-simulating the interleave, solved in one
  carried-state device program, and verified pop-by-pop at replay; a
  mispredicted pop discards the rest of the batch and re-solves the
  verified prefix. Decision-parity mode at ~B jobs per device round trip
  (``tpu-strict-perjob`` keeps the r3 one-RTT-per-job formulation).
- ``tpu-fused``  the whole action is ONE device program: job order is fixed
  up front (same priority rules, without mid-cycle queue re-ordering), all
  pending tasks solve in a single place_scan, results replay through
  Statements. Highest throughput; gang admissions may differ from strict
  only when mid-cycle share updates would reorder queues.

The action name ``allocate`` defaults to callbacks; ``allocate-tpu``
(registered separately) defaults to tpu-fused — so the conf swap
``actions: "enqueue, allocate-tpu, backfill"`` is exactly the north-star
drop-in.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import (FitError, FitErrors, NodeInfo, PodGroupPhase, Resource, TaskInfo,
                   TaskStatus)
from ..obs import trace as obs_trace
from ..cache.snapshot import (NodeTensors, assemble_feasibility,
                              assemble_static_score, assemble_weights,
                              discover_resource_names, task_requests)
from ..utils import PriorityQueue
from ..utils.scheduler_helper import (predicate_nodes, prioritize_nodes,
                                      select_best_node)
from .base import Action

log = logging.getLogger(__name__)

NO_NODE = -1


class SolverFault(RuntimeError):
    """A batched device solve produced an unusable result (non-finite
    scores propagated into garbage placements, shape mismatch, compile
    failure surfaced as a value error). Raised so the degradation chain
    in AllocateAction.execute can complete the cycle sequentially."""


class ReplayFault(RuntimeError):
    """A failure inside the BATCHED (statement-free) replay: its
    incremental aggregate mutations are not statement-tracked, so the
    session cannot be proven consistent and the sequential fallback must
    NOT run. ``poisons_session`` makes the scheduler shell abort the
    REST of the cycle too (later actions would schedule against the
    phantom aggregates); the next cycle opens a fresh snapshot."""

    poisons_session = True


# What the last degradation event did, for bench/ops introspection:
# {"engine": failed engine, "error": repr} — empty when the last cycle
# ran its configured engine end to end.
LAST_FALLBACK: Dict[str, str] = {}

# Pre-solve hook for device-fault injection (chaos.DeviceFaultInjector /
# chaos.MeshFaultInjector): called with the engine name before every
# device solve attempt (and with "<engine>:probe:<id>" before a
# quarantined device's dry-run probe); raising
# device_health.DeviceFaultError simulates an XLA OOM/device-lost at
# exactly the point the real XlaRuntimeError would surface.
DEVICE_FAULT_HOOK = None

# Device ids of the mesh the CURRENT sharded solve attempt runs over,
# refreshed before each attempt (including mid-cycle heal retries).
# MeshFaultInjector reads it to target a live shard; the heal path reads
# it to validate fault attribution against the devices that were solving.
CURRENT_MESH_DEVICES: tuple = ()


def _device_available() -> bool:
    """Is the FLEET cool-down window closed (device_health)? The fleet
    window opens only on unattributed faults — attributed ones
    quarantine a single device and heal the mesh instead."""
    from ..device_health import DEVICE_HEALTH
    return DEVICE_HEALTH.available()


def _mesh_devices(ssn):
    """The sharded engine's device selection with the health lattice
    applied: ``(capped, healthy)`` where ``capped`` is jax.devices()
    truncated by the ``sharded-devices`` conf argument and ``healthy``
    is the non-quarantined subset in the same order. The degradation
    ladder falls out of ``healthy``: the full capped set is rung 0, a
    strict subset re-forms the mesh (rung 1 — byte-identical decisions
    by the mesh-size-invariance contract, ops/unified.py), one survivor
    collapses to the single-device program (rung 2), and empty is rung 3
    (the CPU placer, taken only here)."""
    import jax
    from ..device_health import DEVICE_HEALTH
    devices = jax.devices()
    k = _sharded_device_count(ssn)
    if k:
        devices = devices[:k]
    live = set(DEVICE_HEALTH.healthy_devices([d.id for d in devices]))
    return devices, [d for d in devices if d.id in live]


def current_mesh_ids(ssn) -> tuple:
    """Device-id tuple the sharded engine would solve over right now —
    the pipelined shell compares this against the tuple recorded at
    speculative dispatch: any difference (quarantine OR readmission)
    means the packed result may live on a lost device or a stale
    layout, and the commit classifies it as a conflict."""
    return tuple(d.id for d in _mesh_devices(ssn)[1])


def _degradation_rung(total: int, healthy: int) -> int:
    """0 full mesh, 1 shrunken mesh, 2 single device (degraded from a
    larger mesh), 3 CPU placer. A deliberately 1-device configuration
    (total == healthy == 1) is rung 0 — nothing degraded."""
    if healthy == 0:
        return 3
    if healthy == 1 and total > 1:
        return 2
    if healthy < total:
        return 1
    return 0


def _dry_run_probe_solve(device) -> None:
    """A throwaway micro-solve pinned to ``device`` — the quarantined
    device's PROBE. Runs the unified blocks kernel (the same program
    family a readmitted device will serve) over dummy 1-node/1-task
    tensors and blocks on the result; the output is discarded, so a
    probe can NEVER leak into a live decision. Raises whatever the
    device raises — the caller classifies and doubles the window."""
    import jax
    import jax.numpy as jnp
    from ..ops import JobMeta, default_weights, make_node_state
    from ..ops.unified import place_blocks_unified
    # the probe's await IS its point — a scheduled readback of a real
    # solve, so it rides the sanctioned solve span (vlint VT010)
    with obs_trace.span("solve", probe=True), jax.default_device(device):
        state = make_node_state(
            jnp.ones((1, 1), jnp.float32), jnp.zeros((1, 1), jnp.float32),
            jnp.zeros((1, 1), jnp.float32), jnp.zeros((1, 1), jnp.float32),
            jnp.zeros(1, jnp.int32))
        meta = JobMeta(min_available=jnp.ones(1, jnp.int32),
                       base_ready=jnp.zeros(1, jnp.int32),
                       base_pipelined=jnp.zeros(1, jnp.int32))
        packed, _ = place_blocks_unified(
            None, state, jnp.full((1, 1), 0.5, jnp.float32),
            jnp.ones(1, bool), jnp.zeros(1, jnp.int32), meta,
            default_weights(1), jnp.ones((1, 1), jnp.float32),
            jnp.ones(1, jnp.int32))
        jax.block_until_ready(packed)


def _probe_quarantined(ssn) -> int:
    """Probe every PROBE-state device (quarantine window expired) with a
    throwaway dry-run solve and readmit the ones that pass. Readmission
    grows the device set, so the tensor epoch is retired
    (``invalidate_device_state`` — vlint VT021) and the next layout
    re-pads/re-uploads at the larger D. A probe fault doubles the
    device's window; probes are skipped entirely while the FLEET window
    is open (an unattributed outage means hands off the device)."""
    from ..device_health import DEVICE_HEALTH, classify_device_fault
    if not DEVICE_HEALTH.available():
        return 0
    import jax
    devices = jax.devices()
    k = _sharded_device_count(ssn)
    if k:
        devices = devices[:k]
    by_id = {d.id: d for d in devices}
    readmitted = 0
    for dev_id in DEVICE_HEALTH.probe_candidates(list(by_id)):
        try:
            if DEVICE_FAULT_HOOK is not None:
                DEVICE_FAULT_HOOK(f"tpu-sharded:probe:{dev_id}")
            _dry_run_probe_solve(by_id[dev_id])
        except Exception as exc:
            kind = classify_device_fault(exc) or "probe"
            DEVICE_HEALTH.quarantine(dev_id, kind)
            log.warning("device %s failed its probe dry-run (%s): "
                        "quarantine window doubled", dev_id, kind)
            continue
        DEVICE_HEALTH.readmit(dev_id)
        ssn.cache.invalidate_device_state()
        readmitted += 1
        log.info("device %s readmitted after probe dry-run: mesh "
                 "re-forms over %d device(s), epoch retired", dev_id,
                 len(_mesh_devices(ssn)[1]))
    return readmitted


def _node_tensors(ssn, rnames) -> NodeTensors:
    """Node-state tensors for a device solve: the cache's persistent,
    incrementally scatter-updated arrays when the session can prove its
    snapshot untouched (session.snapshot_node_tensors), else a from-scratch
    build — the two are row-identical by the oracle test
    (tests/test_incremental_snapshot.py). Time spent here is reported as
    bench.py's tensor_assembly_ms and traced as the ``tensor_assembly``
    span."""
    with obs_trace.span("tensor_assembly") as sp:
        get = getattr(ssn, "snapshot_node_tensors", None)
        node_t = get(rnames) if get is not None else None
        incremental = node_t is not None
        if node_t is None:
            node_t = NodeTensors(list(ssn.nodes.values()), rnames)
    LAST_STATS["tensor_s"] = LAST_STATS.get("tensor_s", 0.0) + sp.dur_s
    LAST_STATS["tensor_incremental"] = incremental
    return node_t


class _AggTask:
    """Lightweight task stand-in carrying a summed resreq, used to fire one
    aggregated allocate event per job during order simulation."""

    __slots__ = ("job", "resreq")

    def __init__(self, job: str, resreq):
        self.job = job
        self.resreq = resreq


class AllocateAction(Action):
    NAME = "allocate"
    DEFAULT_ENGINE = "callbacks"

    def __init__(self, engine: Optional[str] = None):
        self.engine = engine or self.DEFAULT_ENGINE

    def execute(self, ssn) -> None:
        engine = self.engine
        fallback = True
        for conf in ssn.configurations:
            if conf.name in (self.NAME, "allocate"):
                engine = conf.arguments.get("engine", engine)
                fallback = conf.arguments.get_bool("solver-fallback", True)
        LAST_FALLBACK.clear()
        LAST_STATS.pop("tensor_s", None)      # accumulates within one cycle
        LAST_STATS.pop("tensor_incremental", None)
        degraded = engine.startswith("tpu-") and not _device_available()
        if engine == "tpu-sharded":
            # Per-device lattice path (docs/robustness.md): quarantined
            # devices whose window expired get a throwaway probe solve
            # (readmission bumps the tensor epoch), then the degradation
            # ladder picks the rung — the CPU placer is rung 3, taken
            # only when the FLEET window is open (an unattributed fault
            # suspects everything) or zero devices survive quarantine.
            from .. import metrics
            if _device_available():
                _probe_quarantined(ssn)
            capped, healthy = _mesh_devices(ssn)
            rung = 3 if not _device_available() else \
                _degradation_rung(len(capped), len(healthy))
            metrics.set_degradation_rung(rung)
            degraded = rung == 3
        if degraded:
            # device-fault cool-down (docs/robustness.md): a recent XLA
            # OOM/device-lost opened a cool-down window — run this cycle
            # on the CPU placer without touching the device; the window's
            # expiry re-probes the device engine automatically. With
            # ``solver-fallback: false`` (parity benches want raw
            # errors, never a silent engine swap) the cycle raises
            # instead, same as the original fault did.
            from ..device_health import DEVICE_HEALTH
            if not fallback:
                raise RuntimeError(
                    f"device cool-down active "
                    f"({DEVICE_HEALTH.cooldown_remaining():.1f}s "
                    f"remaining) and solver-fallback is disabled")
            log.warning("device cool-down active (%.1fs remaining): "
                        "allocate degraded to the sequential placer",
                        DEVICE_HEALTH.cooldown_remaining())
            from .. import metrics
            metrics.register_device_degraded_cycle()
            LAST_FALLBACK.update(engine=engine, error="device cool-down")
            _execute_interleaved(ssn, _CallbackJobPlacer(ssn))
            return
        if engine == "callbacks":
            _execute_interleaved(ssn, _CallbackJobPlacer(ssn))
        elif engine == "callbacks-parallel":
            # scheduler_helper.go:121,157 16-way mirror — the honest CPU
            # comparator at benchmark scale (callbacks_parallel.py)
            from .callbacks_parallel import ParallelCallbackJobPlacer
            placer = ParallelCallbackJobPlacer(ssn)
            try:
                _execute_interleaved(ssn, placer)
            finally:
                placer.close()
        elif engine == "tpu-strict":
            batch = 16
            for conf in ssn.configurations:
                if conf.name in (self.NAME, "allocate"):
                    batch = int(conf.arguments.get("strict-batch", batch))
            self._with_fallback(
                ssn, engine, fallback,
                lambda: _execute_strict_batched(ssn, batch=batch))
        elif engine == "tpu-strict-perjob":
            self._with_fallback(
                ssn, engine, fallback,
                lambda: _execute_interleaved(ssn, _DeviceJobPlacer(ssn)))
        elif engine in ("tpu-fused", "tpu-blocks", "tpu-scan", "tpu-pallas",
                        "tpu-sharded"):
            self._with_fallback(
                ssn, engine, fallback,
                lambda: _execute_fused(
                    ssn, blocks=(engine == "tpu-blocks"),
                    sharded=(engine == "tpu-sharded"),
                    kernel={"tpu-scan": "scan",
                            "tpu-pallas": "pallas"}.get(engine, "auto")))
        else:
            raise ValueError(f"unknown allocate engine {engine!r}")

    def _with_fallback(self, ssn, engine: str, enabled: bool, run) -> None:
        """Graceful degradation (docs/robustness.md): if the batched JAX
        solve raises — compile error, shape mismatch, SolverFault on
        non-finite/garbage output — finish the SAME cycle with the
        sequential per-task placer. The fused/strict engines only mutate
        session state when replaying a completed solve through Statements,
        and every replay loop discards its open Statement on a raise — so
        at the point of failure every un-replayed task is still PENDING
        and the interleave loop picks them all up; tasks an earlier
        committed statement already placed are no longer PENDING and stay
        placed. The one statement-free path (_replay_fused_fast) raises
        ReplayFault instead, which is NOT absorbed here. Disable with the
        action configuration key ``solver-fallback: false`` (parity
        benches want the raw error).

        DEVICE faults (XLA OOM / device-lost — see device_health) are
        additionally contained before falling back. When the fault
        ATTRIBUTES to a single device (the XLA error names the chip, or
        the injector tagged it), the sharded engine HEALS mid-cycle
        instead of degrading: the failing device is quarantined, the
        tensor epoch retired (a lost device's buffers are gone, and an
        OOM'd one must not be fed the same resident arrays straight
        back), and the SAME solve re-dispatches over the surviving
        devices — re-formed mesh, node layout re-padded at the new D,
        persistent tensors re-uploaded through the scatter path. The
        decisions are byte-identical across the heal by the mesh-size
        invariance contract (ops/unified.py). Only an UNATTRIBUTED
        fault opens the fleet-wide cool-down (suspect everything) and
        drops the cycle to the sequential placer."""
        from ..device_health import (DEVICE_HEALTH, attribute_device_fault,
                                     classify_device_fault)
        global CURRENT_MESH_DEVICES
        sharded = engine == "tpu-sharded"
        while True:
            mesh_ids = current_mesh_ids(ssn) if sharded else ()
            CURRENT_MESH_DEVICES = mesh_ids
            try:
                if DEVICE_FAULT_HOOK is not None:
                    DEVICE_FAULT_HOOK(engine)
                run()
                DEVICE_HEALTH.record_ok()
                return
            except ReplayFault:
                raise        # session not provably consistent — no fallback
            except Exception as exc:
                from .. import metrics
                kind = classify_device_fault(exc)
                device = attribute_device_fault(exc, mesh_ids) \
                    if kind is not None and sharded else None
                if device is not None:
                    # Attributed device fault: quarantine ONE device and
                    # heal the mesh in the same cycle. The epoch bump
                    # forces the next attempt to re-pad/re-upload for
                    # the shrunken device set (VT021 witness).
                    window = DEVICE_HEALTH.quarantine(device, kind)
                    ssn.cache.invalidate_device_state()
                    capped, healthy = _mesh_devices(ssn)
                    survivors = tuple(d.id for d in healthy)
                    if survivors:
                        # the ladder descended mid-cycle: the gauge
                        # tracks the rung the re-dispatch runs on
                        metrics.set_degradation_rung(
                            _degradation_rung(len(capped), len(healthy)))
                        metrics.register_mesh_heal(kind)
                        log.warning(
                            "device %s fault (%s): quarantined for "
                            "%.1fs; healing mesh over %d surviving "
                            "device(s) and re-dispatching the solve",
                            device, kind, window, len(survivors))
                        continue
                    log.error("device %s fault (%s): quarantined for "
                              "%.1fs and no devices survive — ladder "
                              "bottoms out at the sequential placer",
                              device, kind, window)
                elif kind is not None:
                    window = DEVICE_HEALTH.record_fault(kind)
                    ssn.cache.invalidate_device_state()
                    log.error("device fault (%s) in allocate engine %s: "
                              "cooling down for %.1fs, device tensor state "
                              "invalidated", kind, engine, window)
                if not enabled:
                    raise
                log.exception("allocate engine %s failed; completing the "
                              "cycle with the sequential placer", engine)
                metrics.register_solver_fallback(self.NAME)
                LAST_FALLBACK.update(engine=engine, error=repr(exc))
                _execute_interleaved(ssn, _CallbackJobPlacer(ssn))
                return


class AllocateTPUAction(AllocateAction):
    NAME = "allocate-tpu"
    DEFAULT_ENGINE = "tpu-fused"


# ---------------------------------------------------------------------------
# shared interleave loop (allocate.go:123-274)
# ---------------------------------------------------------------------------

def _eligible_jobs(ssn):
    for job in ssn.jobs.values():
        if job.podgroup.phase == PodGroupPhase.PENDING:
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.passed:
            continue
        if job.queue not in ssn.queues:
            continue
        yield job


def _pending_tasks(ssn, job) -> List[TaskInfo]:
    """Pending, non-best-effort tasks in TaskOrderFn order. When only the
    priority plugin registers a task order (the default conf), a key sort
    replaces the comparator heap — same order, ~10x cheaper at 10k tasks."""
    tasks = [t for t in job.task_status_index.get(TaskStatus.PENDING,
                                                  {}).values()
             if not t.resreq.is_empty()]
    # elastic decision class (elastic_gang): when the elastic plugin is in
    # the conf it narrows an elastic gang's allocate-visible pending set —
    # core members until admission (the solver sees the MIN-sized gang),
    # nothing after (grow-shrink owns expansion toward desired). Absent
    # the plugin this attribute does not exist and the path is unchanged.
    flt = getattr(ssn, "elastic_pending_filter", None)
    if flt is not None:
        tasks = flt(job, tasks)
    # the ENABLED comparator chain decides whether a key sort is equivalent
    enabled = [name for tier in ssn.tiers for opt in tier.plugins
               if opt.is_enabled("enabledTaskOrder")
               and (name := opt.name) in ssn.task_order_fns]
    if enabled == ["priority"]:
        tasks.sort(key=lambda t: (-t.priority, t.creation_timestamp, t.uid))
        return tasks
    if not enabled:
        tasks.sort(key=lambda t: (t.creation_timestamp, t.uid))
        return tasks
    pq = PriorityQueue(ssn.task_order_fn)
    for task in tasks:
        pq.push(task)
    out = []
    while not pq.empty():
        out.append(pq.pop())
    return out


def _build_interleave(ssn):
    """The namespace -> queue -> job PQ structures the popping loop
    mutates (allocate.go:123-142)."""
    namespaces = PriorityQueue(ssn.namespace_order_fn)
    jobs_map: Dict[str, Dict[str, PriorityQueue]] = {}
    for job in _eligible_jobs(ssn):
        ns = job.namespace
        if ns not in jobs_map:
            namespaces.push(ns)
            jobs_map[ns] = {}
        if job.queue not in jobs_map[ns]:
            jobs_map[ns][job.queue] = PriorityQueue(ssn.job_order_fn)
        jobs_map[ns][job.queue].push(job)
    return namespaces, jobs_map


def _pop_next(ssn, namespaces, jobs_map):
    """ONE pop of the reference interleave (allocate.go:143-180), with its
    queue-deletion side effects; returns (job, jobs_pq, ns) or
    (None, None, None) when drained. Shared verbatim by the live loop and
    the strict engine's verification, so 'the job the loop would pop next'
    has one definition.

    The namespace is NOT re-pushed here: the reference re-inserts it only
    after the popped job's statement closes, so a state-dependent
    namespace order (drf's share-based comparator) sees POST-placement
    shares at re-insert time. Callers must push ``ns`` back after
    processing the job."""
    while not namespaces.empty():
        ns = namespaces.pop()
        queue_jobs = jobs_map[ns]
        queue = None
        for qid in list(queue_jobs):
            q = ssn.queues[qid]
            if ssn.overused(q):
                del queue_jobs[qid]
                continue
            if queue_jobs[qid].empty():
                continue
            if queue is None or ssn.queue_order_fn(q, queue):
                queue = q
        if queue is None:
            if queue_jobs:
                # only empty PQs remain; drop namespace
                if all(pq.empty() for pq in queue_jobs.values()):
                    continue
                namespaces.push(ns)
            continue
        jobs = queue_jobs[queue.uid]
        if jobs.empty():
            del queue_jobs[queue.uid]
            namespaces.push(ns)
            continue
        job = jobs.pop()
        return job, jobs, ns
    return None, None, None


def _execute_interleaved(ssn, placer) -> None:
    with obs_trace.span("interleave",
                        placer=type(placer).__name__.lstrip("_")):
        _run_interleaved(ssn, placer)


def _run_interleaved(ssn, placer) -> None:
    namespaces, jobs_map = _build_interleave(ssn)
    pending: Dict[str, List[TaskInfo]] = {}

    while True:
        job, jobs, ns = _pop_next(ssn, namespaces, jobs_map)
        if job is None:
            break

        if job.uid not in pending:
            pending[job.uid] = _pending_tasks(ssn, job)
        tasks = pending[job.uid]

        stmt = ssn.statement()
        try:
            readded = placer.place(job, tasks, stmt, jobs)
        except Exception:
            # keep the session consistent for the caller's degradation
            # chain: every op of the failed job rolls back
            stmt.discard()
            raise

        ops = list(stmt.operations)
        if ssn.job_ready(job):
            stmt.commit()
            committed = True
        elif not ssn.job_pipelined(job):
            stmt.discard()
            committed = False
        else:
            committed = True               # kept open: pipelined gang
        if hasattr(placer, "statement_closed"):
            placer.statement_closed(job, committed, ops)
        namespaces.push(ns)                # post-placement, like allocate.go


class _CallbackJobPlacer:
    """Per-(task,node) callback placement — the reference hot loop
    (allocate.go:186-262)."""

    def __init__(self, ssn):
        self.ssn = ssn

    def place(self, job, tasks, stmt, jobs_pq) -> bool:
        ssn = self.ssn
        nodes = list(ssn.nodes.values())

        def pred(task, node):
            if not task.init_resreq.less_equal(node.future_idle()):
                raise _fit_error(task, node)
            ssn.predicate_fn(task, node)

        while tasks:
            task = tasks.pop(0)
            feasible, fit_errors = predicate_nodes(task, nodes, pred)
            if not feasible:
                job.nodes_fit_errors[task.uid] = fit_errors
                break

            candidates = [n for n in feasible
                          if task.init_resreq.less_equal(n.idle)
                          or task.init_resreq.less_equal(n.future_idle())]
            if not candidates:
                continue

            scores = prioritize_nodes(task, candidates,
                                      ssn.batch_node_order_fn,
                                      ssn.node_order_fn)
            node = ssn.best_node_fn(task, scores) or select_best_node(scores)

            if task.init_resreq.less_equal(node.idle):
                stmt.allocate(task, node)
            elif task.init_resreq.less_equal(node.future_idle()):
                stmt.pipeline(task, node.name)

            if ssn.job_ready(job) and tasks:
                jobs_pq.push(job)
                return True
        return False


class _DeviceJobPlacer:
    """Per-job device solve with device-resident node state (tpu-strict).

    The kernel replays the same per-task loop (ops/place.place_scan), so
    within a job the decisions match the callback engine; across jobs the
    interleave is identical because this placer is driven by the same loop.
    """

    def __init__(self, ssn):
        import jax.numpy as jnp
        self.ssn = ssn
        self.jnp = jnp
        tasks_all = [t for j in ssn.jobs.values() for t in j.tasks.values()]
        self.rnames = discover_resource_names(list(ssn.nodes.values()), tasks_all)
        self.node_t = _node_tensors(ssn, self.rnames)
        self.state = self.node_t.node_state()
        # _d suffix: device-resident mirrors. NodeTensors exposes HOST
        # arrays under .allocatable/.max_tasks — reusing those names here
        # would alias a device value into every node_t.<field> read in
        # this module (the vlint dataflow engine tracks attribute taint
        # per module by name, and readers deserve the same clarity)
        self.allocatable_d = self.node_t.device_allocatable()
        self.max_tasks_d = self.node_t.device_max_tasks()
        self.weights = assemble_weights(ssn, self.rnames)
        self._solve = _job_solver()

    def place(self, job, tasks, stmt, jobs_pq) -> bool:
        if not tasks or not self.node_t.names:
            tasks.clear()
            return False
        from ..ops.place import unpack_placement

        T = len(tasks)
        # the per-job fetch is this engine's contract (one RTT per job,
        # decision parity) — run it under the sanctioned solve span so
        # VT010 sees the scheduled readback, not a stray sync
        with obs_trace.span("solve", batch=1):
            packed, new_state, bucket, J, _ = _solve_job_batch(
                self.ssn, [(job, tasks)], self.state, self.node_t,
                self.rnames, self.weights, self.allocatable_d,
                self.max_tasks_d, self._solve, j_pad=1)
            task_node, pipelined, _, job_kept = unpack_placement(
                np.asarray(packed), bucket, J)
        task_node, pipelined = task_node[:T], pipelined[:T]
        if bool(job_kept[0]):
            self.state = new_state

        # Replay picks through the Statement for host bookkeeping. All tasks
        # are consumed — the reference pops each task from its queue exactly
        # once per cycle whether or not it placed (allocate.go:187-223).
        recheck = bool(self.ssn.stateful_predicates)
        for i, task in enumerate(tasks):
            n = int(task_node[i])
            if n == NO_NODE:
                continue
            node_name = self.node_t.names[n]
            node = self.ssn.nodes[node_name]
            if recheck and not _stateful_recheck(self.ssn, task, node):
                continue
            if pipelined[i]:
                stmt.pipeline(task, node_name)
            else:
                stmt.allocate(task, node)
        tasks.clear()
        return False


def _bucket(n: int) -> int:
    """Pad task counts to power-of-two buckets to bound jit recompiles."""
    b = 8
    while b < n:
        b *= 2
    return b


def _job_bucket(j: int) -> int:
    """Pad the JOB axis to power-of-two buckets too: the scan/blocks/
    sharded solvers' jit keys include the [J] gang-meta arrays
    (min_available/base_ready/base_pipelined), so an un-bucketed J mints
    a fresh XLA program whenever the pending-JOB count shifts — the
    churn warm-up hole (BENCH_r05 cycle 1: 6.5 s, 8 compiles) in its
    remaining form. Pad gangs own no tasks and never affect state (the
    same contract _solve_job_batch's j_pad relies on); prewarm_shapes
    pads identically so startup compiles cover the whole bucket."""
    b = 4
    while b < j:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# batched strict engine (VERDICT r3 #5)
# ---------------------------------------------------------------------------

def _predict_pops(ssn, namespaces, jobs_map, n: int, first=None) -> List:
    """Simulate the next ``n`` pops of the interleave WITHOUT touching the
    live structures: clone the PQs (sequence-faithful — PriorityQueue.clone)
    and fire the fused engine's aggregated assume-all-allocated events per
    popped job so overused gating and share-driven ordering evolve the way
    the live loop usually will, undoing every event before returning. The
    prediction is OPTIMISTIC, never authoritative: the caller verifies each
    entry against the live _pop_next during replay. ``first`` force-seeds a
    job the live loop already popped (a prior batch's mismatch carry)."""
    sim_ns = namespaces.clone()
    sim_map = {ns: {qid: pq.clone() for qid, pq in qmap.items()}
               for ns, qmap in jobs_map.items()}
    predicted: List = [] if first is None else [first]
    simulated: List[_AggTask] = []
    try:
        for job in predicted:
            agg = _assume_allocated(ssn, job)
            if agg is not None:
                simulated.append(agg)
            # the live loop popped this carried job's namespace and will
            # push it back once the job's statement closes — mirror that,
            # or a single-namespace sim PQ drains after one carry and
            # every later batch degenerates to the carried job alone
            sim_ns.push(job.namespace)
        while len(predicted) < n:
            job, _, ns = _pop_next(ssn, sim_ns, sim_map)
            if job is None:
                break
            predicted.append(job)
            agg = _assume_allocated(ssn, job)
            if agg is not None:
                simulated.append(agg)
            sim_ns.push(ns)          # post-placement, like the live loop
    finally:
        for agg in reversed(simulated):
            ssn._fire_deallocate(agg)
    return predicted


def _assume_allocated(ssn, job) -> Optional[_AggTask]:
    """One aggregated allocate-event as if every pending task placed
    (the _fixed_job_order simulation, per job)."""
    total = Resource()
    count = 0
    for task in job.task_status_index.get(TaskStatus.PENDING, {}).values():
        if task.resreq.is_empty():
            continue
        total.add(task.resreq)
        count += 1
    if not count:
        return None
    agg = _AggTask(job.uid, total)
    ssn._fire_allocate(agg)
    return agg


def _solve_job_batch(ssn, jobs_with_tasks, state, node_t, rnames, weights,
                     allocatable_d, max_tasks_d, solver, j_pad: int):
    """One device program over a batch of jobs' pending tasks, node state
    carried in-kernel across jobs with per-job gang snapshots (the same
    place_scan the fused engine uses). The job axis pads to ``j_pad`` and
    the task axis to a pow2 bucket so every batch hits the same compiled
    program (pad jobs own no tasks and never affect state). Returns
    (packed_np, new_state, bucket, J_padded, task_slices)."""
    import jax.numpy as jnp
    from ..ops.place import JobMeta, PlacementTasks

    tasks: List[TaskInfo] = []
    job_ix: List[int] = []
    slices: List[tuple] = []
    for jx, (_, jtasks) in enumerate(jobs_with_tasks):
        slices.append((len(tasks), len(tasks) + len(jtasks)))
        tasks.extend(jtasks)
        job_ix.extend([jx] * len(jtasks))
    T = len(tasks)
    J = max(len(jobs_with_tasks), 1)
    J = max(J, j_pad)
    jpad = J - len(jobs_with_tasks)
    req = task_requests(tasks, rnames)
    feas = assemble_feasibility(ssn, tasks, node_t)
    static = assemble_static_score(ssn, tasks, node_t)
    N = len(node_t.names)
    bucket = _bucket(T)
    pad = bucket - T
    job_ix_np = np.asarray(job_ix, np.int32)
    first = np.zeros(T, bool)
    last = np.zeros(T, bool)
    first[0] = True
    first[1:] = job_ix_np[1:] != job_ix_np[:-1]
    last[:-1] = job_ix_np[1:] != job_ix_np[:-1]
    last[-1] = True
    pt = PlacementTasks(
        req=jnp.asarray(np.pad(req, ((0, pad), (0, 0)))),
        job_ix=jnp.asarray(np.pad(job_ix_np, (0, pad))),
        valid=jnp.asarray(np.r_[np.ones(T, bool), np.zeros(pad, bool)]),
        feas=(jnp.ones((bucket, N), bool) if feas is None
              else jnp.asarray(np.pad(feas, ((0, pad), (0, 0))))),
        static_score=(jnp.zeros((bucket, N), jnp.float32) if static is None
                      else jnp.asarray(np.pad(static, ((0, pad), (0, 0))))),
        first_of_job=jnp.asarray(np.pad(first, (0, pad))),
        last_of_job=jnp.asarray(np.pad(last, (0, pad))))
    jobs_meta = JobMeta(
        min_available=jnp.asarray(
            [j.min_available for j, _ in jobs_with_tasks]
            + [1] * jpad, jnp.int32),
        base_ready=jnp.asarray(
            [j.ready_task_num() for j, _ in jobs_with_tasks]
            + [0] * jpad, jnp.int32),
        base_pipelined=jnp.asarray(
            [j.waiting_task_num() for j, _ in jobs_with_tasks]
            + [0] * jpad, jnp.int32))
    packed, new_state = solver(state, pt, jobs_meta, weights,
                               allocatable_d, max_tasks_d)
    return packed, new_state, bucket, J, slices


def _execute_strict_batched(ssn, batch: int = 16) -> None:
    """The strict oracle with batched device solves (VERDICT r3 #5).

    Pop-by-pop the engine is IDENTICAL to the callbacks loop — the same
    _pop_next against the live session decides every job, and every
    placement replays through a live Statement with the same
    commit/discard votes. The device round trips are what's batched:
    the next B pops are PREDICTED (clone-simulated interleave under the
    assume-all-allocated events), solved in one carried-state device
    program, and each prediction is verified against the live pop during
    replay. A mismatch discards the remaining solves, rebuilds the device
    state by re-solving the verified prefix from the batch-start state
    (dispatch only — no fetch), and restarts prediction from the job the
    live loop actually popped. Worst case (every prediction wrong) this
    degrades to one job per RTT — the r3 per-job engine; typically it is
    ~B jobs per RTT, which is what brings tpu_strict under the CPU
    comparator it replays.

    The batch size is ADAPTIVE (VERDICT r5 #8): it doubles after every
    fully-verified batch (up to 32x the configured floor) and halves on a
    mispredict — on a well-predicted cycle the RTT count shrinks
    geometrically, which is the whole cost model on a ~100ms-RTT tunnel.
    Shape buckets stay bounded: the job axis pads to the CURRENT batch
    size, so at most log2(32)+1 job-axis shapes per task bucket exist."""
    if not ssn.nodes:
        return
    tasks_all = [t for j in ssn.jobs.values() for t in j.tasks.values()]
    rnames = discover_resource_names(list(ssn.nodes.values()), tasks_all)
    node_t = _node_tensors(ssn, rnames)
    state = node_t.node_state()
    allocatable_d = node_t.device_allocatable()
    max_tasks_d = node_t.device_max_tasks()
    weights = assemble_weights(ssn, rnames)
    solver = _job_solver()
    recheck = bool(ssn.stateful_predicates)
    if recheck:
        # stateful predicates (hostPorts, gpu cards, pod affinity) change
        # as replay proceeds, and a batch's feasibility is assembled
        # BEFORE its jobs replay — a later job in the batch would miss an
        # earlier job's claims and get vetoed at recheck instead of
        # re-solved. One job per batch reassembles feasibility after
        # every replay, which is exactly the per-job engine's behavior.
        batch = 1

    namespaces, jobs_map = _build_interleave(ssn)
    pending: Dict[str, List[TaskInfo]] = {}
    carry = None        # (job, ns) a mismatch live-popped but left unprocessed
    # 32x ceiling (was 8x): on a well-predicted saturated cycle the RTT
    # count keeps shrinking geometrically for two more doublings; the
    # shape-bucket bound grows to log2(32)+1 job-axis shapes per task
    # bucket, all warmed through the same _job_bucket ladder
    b_cur, b_max = batch, batch * 32 if batch > 1 else 1

    def live_tasks(job):
        if job.uid not in pending:
            pending[job.uid] = _pending_tasks(ssn, job)
        return pending[job.uid]

    while True:
        carried_job, carried_ns = carry if carry is not None else (None, None)
        predicted = _predict_pops(ssn, namespaces, jobs_map, b_cur,
                                  first=carried_job)
        carry = None
        if not predicted:
            break
        with_tasks = [(j, live_tasks(j)) for j in predicted]
        solvable = [(j, t) for j, t in with_tasks if t]
        if solvable:
            with obs_trace.span("solve", batch=len(solvable)):
                packed_d, new_state, bucket, J, slices = _solve_job_batch(
                    ssn, solvable, state, node_t, rnames, weights,
                    allocatable_d, max_tasks_d, solver, j_pad=b_cur)
                # the batch's ONE fetch, through the same sanctioned
                # readback site as every other fused engine
                task_node, pipelined, _, job_kept = _fetch_packed(
                    packed_d, bucket, J, bucket)
        solved_ix = {id(j): k for k, (j, _) in enumerate(solvable)}

        verified_prefix: List[tuple] = []
        ok = True
        for idx, job in enumerate(predicted):
            if idx == 0 and carried_job is job:
                actual, ns = job, carried_ns  # popped by the previous batch
            else:
                actual, _, ns = _pop_next(ssn, namespaces, jobs_map)
            if actual is not job:
                # live loop diverged (or drained: actual None)
                carry = None if actual is None else (actual, ns)
                ok = False
                break
            tasks = live_tasks(job)
            stmt = ssn.statement()
            k = solved_ix.get(id(job))
            if k is not None:
                lo, hi = slices[k]
                try:
                    for i, task in enumerate(tasks):
                        n = int(task_node[lo + i])
                        if n == NO_NODE:
                            continue
                        name = node_t.names[n]
                        node = ssn.nodes[name]
                        if recheck and not _stateful_recheck(ssn, task,
                                                             node):
                            continue
                        if pipelined[lo + i]:
                            stmt.pipeline(task, name)
                        else:
                            stmt.allocate(task, node)
                except Exception:
                    stmt.discard()      # session stays fallback-safe
                    raise
                verified_prefix.append((job, list(tasks)))
                tasks.clear()
            if ssn.job_ready(job):
                stmt.commit()
            elif not ssn.job_pipelined(job):
                stmt.discard()
            namespaces.push(ns)      # post-placement, like allocate.go
        if ok and solvable:
            state = new_state
        elif verified_prefix:
            # rebuild device state: re-solve just the verified prefix from
            # the batch-start state (deterministic -> same placements); the
            # dispatch is async and never fetched
            _, state, _, _, _ = _solve_job_batch(
                ssn, verified_prefix, state, node_t, rnames, weights,
                allocatable_d, max_tasks_d, solver, j_pad=b_cur)
        # adapt: a SATURATED verified batch earns a doubling (an
        # under-filled one is the queue draining — growing the pad would
        # only compile a fresh solver shape for no work), a mispredict
        # halves. b_max respects the recheck clamp: batch==1 there, so
        # adaptation never reintroduces stale-feasibility batching.
        if ok and len(predicted) == b_cur:
            b_cur = min(b_cur * 2, b_max)
        elif not ok:
            b_cur = max(batch, b_cur // 2)
        if carry is None and not ok:
            break                            # live loop drained mid-batch


_SOLVER_CACHE: dict = {}


def _job_solver():
    """Jitted packed solver: one device→host fetch per solve (tunnel RTTs
    dominate on remote TPU backends)."""
    import jax
    if "solve" not in _SOLVER_CACHE:
        from ..ops.place import place_scan_packed
        _SOLVER_CACHE["solve"] = jax.jit(place_scan_packed)
    return _SOLVER_CACHE["solve"]


def _job_solver_topo():
    """Jitted packed solver WITH the gang-compactness term
    (ops/place.place_scan_topo): selected only when the allocate action's
    ``topology-weight`` argument is positive, so weight-0 confs dispatch
    the exact pre-existing program (byte-identity with the topology term
    disabled). The weight is a traced scalar — one compile serves every
    weight at a given shape bucket."""
    import jax
    if "solve_topo" not in _SOLVER_CACHE:
        from ..ops.place import place_scan_topo_packed
        _SOLVER_CACHE["solve_topo"] = jax.jit(place_scan_topo_packed)
    return _SOLVER_CACHE["solve_topo"]


def _topology_weight(ssn) -> float:
    """The allocate action's ``topology-weight`` argument (0 = term off).
    Rides the scan kernel only: pallas/blocks/sharded formulations carry
    no per-job anchor state, so a positive weight steers kernel selection
    to the scan path in _solve_fused."""
    w = 0.0
    for conf in ssn.configurations:
        if conf.name in ("allocate", "allocate-tpu"):
            try:
                w = float(conf.arguments.get("topology-weight", w))
            except (TypeError, ValueError):
                w = 0.0
    return max(w, 0.0)


def _sharded_device_count(ssn) -> int:
    """The allocate action's ``sharded-devices`` argument: cap the unified
    sharded engine's mesh to the FIRST k devices (0 = the full device
    set). The sim's ``--verify-sharded-equivalence`` runs the same engine
    at k=1 as the single-device oracle — mesh-size invariance
    (ops/unified.py) is what makes that comparison byte-exact."""
    k = 0
    for conf in ssn.configurations:
        if conf.name in ("allocate", "allocate-tpu"):
            try:
                k = int(conf.arguments.get("sharded-devices", k))
            except (TypeError, ValueError):
                k = 0
    return max(k, 0)


# ---------------------------------------------------------------------------
# fused engine: one device program per cycle
# ---------------------------------------------------------------------------

def _fixed_job_order(ssn, assumed_admitted: Optional[set] = None,
                     only_jobs: Optional[set] = None) -> List:
    """Precompute the namespace→queue→job interleave for the fused solve.

    Runs the reference's popping loop (allocate.go:123-180) with one
    assumption: every popped job in ``assumed_admitted`` (all jobs when None)
    allocates all of its pending tasks. Plugin allocate-events fire during
    the simulation so mid-cycle share updates and overused gating order
    queues exactly as the live loop would; all events are undone before
    returning. The fused executor iterates this to a fixed point on the
    actually-admitted set, so gang failures feed back into the ordering.
    ``only_jobs`` restricts the interleave to that uid set — the
    pipelined commit's SUFFIX solve uses it to order exactly the jobs a
    committed speculation did not cover.
    """
    namespaces = PriorityQueue(ssn.namespace_order_fn)
    jobs_map: Dict[str, Dict[str, PriorityQueue]] = {}
    for job in _eligible_jobs(ssn):
        if only_jobs is not None and job.uid not in only_jobs:
            continue
        ns = job.namespace
        if ns not in jobs_map:
            namespaces.push(ns)
            jobs_map[ns] = {}
        if job.queue not in jobs_map[ns]:
            jobs_map[ns][job.queue] = PriorityQueue(ssn.job_order_fn)
        jobs_map[ns][job.queue].push(job)

    ordered: List = []
    simulated: List[TaskInfo] = []
    try:
        while not namespaces.empty():
            ns = namespaces.pop()
            queue_jobs = jobs_map[ns]
            queue = None
            for qid in list(queue_jobs):
                q = ssn.queues[qid]
                if ssn.overused(q):
                    del queue_jobs[qid]
                    continue
                if queue_jobs[qid].empty():
                    continue
                if queue is None or ssn.queue_order_fn(q, queue):
                    queue = q
            if queue is None:
                continue
            jobs = queue_jobs[queue.uid]
            if jobs.empty():
                del queue_jobs[queue.uid]
                namespaces.push(ns)
                continue
            job = jobs.pop()
            ordered.append(job)
            if assumed_admitted is None or job.uid in assumed_admitted:
                # one aggregated pseudo-event per job: allocate-event
                # handlers (drf/proportion) are additive in task.resreq,
                # so summing the job's pending requests into a single
                # event is equivalent and O(jobs) instead of O(tasks)
                total = Resource()
                count = 0
                for task in job.task_status_index.get(TaskStatus.PENDING,
                                                      {}).values():
                    if task.resreq.is_empty():
                        continue
                    total.add(task.resreq)
                    count += 1
                if count:
                    agg = _AggTask(job.uid, total)
                    ssn._fire_allocate(agg)
                    simulated.append(agg)
            namespaces.push(ns)
    finally:
        # always undo the simulated events — the sequential fallback runs
        # in this same session and must not see phantom queue shares
        # (same contract as _predict_pops)
        for task in reversed(simulated):
            ssn._fire_deallocate(task)
    return ordered


# Per-cycle phase timers of the last fused execution (seconds) — the
# host/device breakdown bench.py reports (VERDICT r1 next-round #1).
LAST_STATS: Dict[str, float] = {}


def _execute_fused(ssn, blocks: bool = False, max_order_iters: int = 4,
                   kernel: str = "auto", sharded: bool = False,
                   first_solution: Optional["_FusedSolution"] = None,
                   first_ordered: Optional[List] = None,
                   first_assumed: Optional[set] = None,
                   only_jobs: Optional[set] = None) -> None:
    """Fused executor: iterate (order simulation → one device solve) until
    the admitted-job set stabilizes, then replay the final solve through
    Statements. Convergence is usually immediate; gang rollbacks trigger one
    extra iteration because a failed job must stop influencing queue shares
    and overused gating.

    With stateful predicates (gpu card packing, pod affinity) a device
    proposal can fail the live re-check at replay because an earlier replay
    placement changed the state the mask was computed from. Those tasks
    stay pending; extra rounds re-solve them against the fresh session
    state — the batched analogue of the callback engine's per-task
    re-evaluation.

    ``first_solution``/``first_ordered`` seed the fixpoint with an
    already-solved first iteration — how the pipelined shell commits a
    speculative solve: the speculation IS iteration 1 (same snapshot
    values, same order, same kernel as the serial path would have run),
    and when its admitted set does not match its premise the loop
    continues with the normal assumed=kept re-solve, exactly as the
    serial cycle would. ``first_assumed`` is the seeded iteration's
    premise: None for the all-admitted start, or the EMPTY set when the
    speculation warm-started at the serial fixpoint's converged point (a
    saturated backlog whose fixpoint is ∅→∅ — solving there directly
    reproduces the serial trajectory's FINAL solution, skipping its
    in-cycle re-solve). ``only_jobs`` restricts the whole execution to
    that uid set (the pipelined suffix solve for jobs the speculation
    did not cover)."""
    t_order = t_solve = t_replay = 0.0
    max_rounds = 3 if ssn.stateful_predicates else 1
    seeded = first_solution
    kept_uids: Optional[set] = None
    for _ in range(max_rounds):
        assumed: Optional[set] = None
        solution = None
        for _ in range(max_order_iters):
            if seeded is not None:
                # iteration 1 happened in the speculate window; its
                # order/solve time was paid there (span "speculate")
                ordered_jobs, solution = first_ordered, seeded
                assumed = first_assumed
                seeded = None
            else:
                with obs_trace.span("order") as sp:
                    ordered_jobs = _fixed_job_order(ssn, assumed,
                                                    only_jobs=only_jobs)
                t_order += sp.dur_s
                if not ordered_jobs:
                    solution = None
                    break
                from .. import metrics
                with obs_trace.span("solve", kernel=kernel) as sp:
                    with metrics.solver_trace("allocate-solve"):
                        solution = _solve_fused(ssn, ordered_jobs, blocks,
                                                kernel, sharded)
                t_solve += sp.dur_s
            if solution is None:
                break
            kept_uids = {solution.jobs_list[jx].uid
                         for jx in range(len(solution.jobs_list))
                         if solution.job_kept[jx]}
            # assumed=None simulated "all jobs admitted" — if the solve
            # indeed kept every job the premise held; no re-solve needed.
            if kept_uids == assumed or (
                    assumed is None
                    and kept_uids == {j.uid for j in ordered_jobs}):
                break
            assumed = kept_uids
        if solution is None:
            break
        with obs_trace.span("replay") as sp:
            rejected = _replay_fused(ssn, solution)
        t_replay += sp.dur_s
        if not rejected:
            break
    LAST_STATS.update(order_s=t_order, solve_s=t_solve, replay_s=t_replay)
    # warm-start witness for the pipelined dispatch: True iff the fixpoint
    # CONVERGED at the empty admitted set (a saturated backlog) — the one
    # case where next cycle's speculation may start at assumed=∅ and still
    # reproduce the serial trajectory's final solution byte-for-byte
    LAST_STATS["final_kept_empty"] = bool(solution is not None
                                          and kept_uids is not None
                                          and not kept_uids)


def _collect_pending_ordered(ssn, ordered_jobs):
    """Flatten the ordered jobs' pending tasks into the solver's task
    axis: (tasks, per-task job index, jobs_list). Shared by the serial
    solve and the speculative dispatch so the two assemble bit-identical
    inputs."""
    tasks: List[TaskInfo] = []
    job_ix: List[int] = []
    job_index: Dict[str, int] = {}
    jobs_list: List = []
    for job in ordered_jobs:
        jtasks = _pending_tasks(ssn, job)
        if not jtasks:
            continue
        if job.uid not in job_index:
            job_index[job.uid] = len(jobs_list)
            jobs_list.append(job)
        tasks.extend(jtasks)
        job_ix.extend([job_index[job.uid]] * len(jtasks))
    return tasks, job_ix, jobs_list


class _FusedSolution:
    def __init__(self, tasks, job_ix, jobs_list, node_t, task_node,
                 pipelined, job_ready, job_kept):
        # garbage-output guard: an out-of-range node index here would
        # corrupt host accounting at replay — classify it as a solver
        # fault so the degradation chain takes over
        tn = np.asarray(task_node)
        if tn.size and (int(tn.min()) < NO_NODE
                        or int(tn.max()) >= len(node_t.names)):
            raise SolverFault(
                f"device solve returned node indices outside "
                f"[{NO_NODE}, {len(node_t.names)})")
        self.tasks = tasks
        self.job_ix = job_ix
        self.jobs_list = jobs_list
        self.node_t = node_t
        self.task_node = task_node
        self.pipelined = pipelined
        self.job_ready = job_ready
        self.job_kept = job_kept


def _solve_fused(ssn, ordered_jobs, blocks: bool, kernel: str = "auto",
                 sharded: bool = False):
    # KEEP IN SYNC WITH prewarm_shapes (below): it mirrors this function's
    # kernel selection, tensor dtypes/padding and sweeps/passes budgets so
    # startup compiles hit the same jit cache keys as live cycles — a
    # dispatch change here that skips prewarm_shapes resurfaces the
    # cold-bucket stall (bench.py churn's 2x-median assert catches it).
    import jax.numpy as jnp
    from ..ops.place import JobMeta, NodeState, PlacementTasks
    from ..ops.auction import BlockTasks

    tasks, job_ix, jobs_list = _collect_pending_ordered(ssn, ordered_jobs)
    if not tasks or not ssn.nodes:
        return None

    rnames = discover_resource_names(list(ssn.nodes.values()), tasks)
    node_t = _node_tensors(ssn, rnames)
    req = task_requests(tasks, rnames)
    feas = assemble_feasibility(ssn, tasks, node_t)
    static = assemble_static_score(ssn, tasks, node_t)
    weights = assemble_weights(ssn, rnames)
    # Non-finite values in the assembled tensors flow through the kernels
    # into silently wrong placements (an inf score times a zero weight is
    # NaN, and argmax over NaN rows returns in-range indices) — surface
    # them as a SolverFault so the sequential fallback completes the
    # cycle instead. Feasibility masking applies NEG separately, so the
    # raw static scores and weights are finite by construction.
    if not np.isfinite(req).all() or (
            static is not None
            and not np.isfinite(np.asarray(static)).all()):
        raise SolverFault("non-finite task requests or static scores")
    if not (np.isfinite(weights.binpack_res).all()
            and all(np.isfinite(w) for w in (
                weights.binpack_weight, weights.least_req_weight,
                weights.most_req_weight, weights.balanced_weight))):
        raise SolverFault("non-finite score weights")

    T = len(tasks)
    N = len(node_t.names)
    job_ix_np = np.asarray(job_ix, np.int32)
    # numpy first: the pallas path consumes these host-side, and converting
    # jnp->np costs one ~100ms tunnel RTT per array on remote TPU backends.
    jobs_meta, min_av_np, base_r_np, base_p_np, Jp = _gang_meta(jobs_list)

    if sharded:
        # multi-chip engine: the unified solver (ops/unified.py) with the
        # node axis sharded over the device mesh. Decisions are mesh-size
        # invariant, so the 1-device run of this very engine IS the oracle
        # for any D — and a 1-device mesh collapses to the plain jit
        # program inside place_blocks_unified, skipping shard_map overhead.
        from ..cache.snapshot import sharded_node_layout
        from ..ops.pallas_place import NEG as MNEG
        from ..ops.unified import (make_mesh, padded_task_len,
                                   place_blocks_unified)
        # the health lattice filters quarantined devices out of the mesh
        # — a shrunken mesh is degradation-ladder rung 1 and decisions
        # stay byte-identical (sharded-devices: 1 is the oracle for
        # every D). Zero healthy devices never reaches here: execute()
        # routes rung 3 to the sequential placer.
        _, devices = _mesh_devices(ssn)
        mesh = make_mesh(devices)
        D = int(mesh.devices.size)
        state, alloc_d, maxt_d, n_pad = sharded_node_layout(node_t, D)
        ms = None
        if feas is not None or static is not None:
            f = np.ones((T, N), bool) if feas is None else feas
            s = np.zeros((T, N), np.float32) if static is None else static
            ms = np.pad(np.where(f, s, MNEG).astype(np.float32),
                        ((0, 0), (0, n_pad)), constant_values=MNEG)
            ms = jnp.asarray(ms)
        # contention grows with the task count: at the 20k/5k long-axis
        # config the default sweeps=3/passes=3 budget leaves ~1.5% of a
        # full packing on the table (19700/20000); raising BOTH to
        # sweeps=5/passes=4 recovers the full packing (measured together —
        # the split between the two knobs was not isolated). The budgets
        # are while_loop CAPS with fixpoint early exit, so the big tier
        # costs extra passes only while they still change something.
        big = T > 12000
        packed, _ = place_blocks_unified(
            mesh, state, jnp.asarray(req), jnp.ones(T, bool),
            jnp.asarray(job_ix_np), jobs_meta, weights, alloc_d, maxt_d,
            sweeps=5 if big else 3, passes=4 if big else 3,
            masked_static=ms)
        # same packed single-fetch wire layout as every fused engine: the
        # former separate 4-array device_get readback is gone, and the one
        # sanctioned site (_fetch_packed) serves this engine too
        task_node, pipelined, ready, kept = _fetch_packed(
            packed, padded_task_len(T), Jp, T)
        return _FusedSolution(tasks, job_ix_np, jobs_list, node_t, task_node,
                              pipelined, ready, kept)

    topo_w = _topology_weight(ssn)
    from ..ops import pallas_place
    use_pallas = (not blocks and kernel != "scan" and topo_w == 0.0
                  and pallas_place.supported(len(rnames), N)
                  and (kernel == "pallas"
                       or not pallas_place.use_interpret()))
    # auto mode picks the pallas kernel only on a real TPU backend (interpret
    # mode would run the fori_loop in pure python); an unsupported shape
    # (>8 resource dims, >32k nodes) falls back to the scan kernel even when
    # pallas is forced.
    if use_pallas:
        # VMEM-resident placement kernel (ops/pallas_place.py): the whole
        # sequential loop in one pallas_call, node state never leaving VMEM.
        if feas is None and static is None:
            ms = pallas_place.neutral_masked_static(
                *pallas_place.padded_shape(T, N), T, N)
        else:
            f = np.ones((T, N), bool) if feas is None else feas
            s = np.zeros((T, N), np.float32) if static is None else static
            ms = np.where(f, s, pallas_place.NEG).astype(np.float32)
        res = pallas_place.place_pallas(
            node_t.idle,
            node_t.idle + node_t.releasing - node_t.pipelined,
            node_t.used, node_t.ntasks.astype(np.float32),
            node_t.allocatable, node_t.max_tasks.astype(np.float32),
            req, job_ix_np, ms,
            min_av_np, base_r_np, base_p_np,
            np.asarray(weights.binpack_res),
            binpack_weight=float(weights.binpack_weight),
            least_weight=float(weights.least_req_weight),
            most_weight=float(weights.most_req_weight),
            balanced_weight=float(weights.balanced_weight),
            fetch_state=False)
        return _FusedSolution(tasks, job_ix_np, jobs_list, node_t,
                              res.task_node, res.task_pipelined,
                              res.job_ready, res.job_kept)

    feas_np = np.ones((T, N), bool) if feas is None else np.asarray(feas)
    static_np = (np.zeros((T, N), np.float32) if static is None
                 else np.asarray(static, np.float32))
    if blocks:
        bt = BlockTasks(req=jnp.asarray(req), job_ix=jnp.asarray(job_ix_np),
                        valid=jnp.ones(T, bool),
                        feas=jnp.asarray(feas_np),
                        static_score=jnp.asarray(static_np))
        # same size-scaled sweep budget as the sharded engine above, so
        # the two block-auction paths keep identical admissions at any T
        big_b = T > 12000
        packed, _ = _fused_blocks_solver()(
            node_t.node_state(), bt, jobs_meta, weights,
            node_t.device_allocatable(), node_t.device_max_tasks(),
            sweeps=5 if big_b else 3, passes=4 if big_b else 3)
        # same single-fetch wire format as the scan solver (place_blocks
        # packs [task_node | pipelined | ready | kept] on device), so the
        # inventory's one sanctioned readback site serves both engines
        task_node, pipelined, job_ready, job_kept = _fetch_packed(
            packed, T, Jp, T)
    else:
        pt, bucket = _scan_placement_tasks(req, job_ix_np, feas_np,
                                           static_np)
        if topo_w > 0.0:
            packed, _ = _job_solver_topo()(
                node_t.node_state(), pt, jobs_meta, weights,
                node_t.device_allocatable(), node_t.device_max_tasks(),
                node_t.device_zone_code(), jnp.float32(topo_w))
        else:
            packed, _ = _job_solver()(node_t.node_state(), pt, jobs_meta,
                                      weights, node_t.device_allocatable(),
                                      node_t.device_max_tasks())
        task_node, pipelined, job_ready, job_kept = _fetch_packed(
            packed, bucket, Jp, T)

    return _FusedSolution(tasks, job_ix_np, jobs_list, node_t, task_node,
                          pipelined, job_ready, job_kept)


def _gang_meta(jobs_list):
    """Pow2-padded gang-meta arrays for the fused solvers. The job axis
    pads to its pow2 bucket (_job_bucket): pad gangs with min_available 1
    and no tasks are inert in-kernel, and the [J] arrays stop keying a
    fresh compile every time the pending-job count moves. ONE definition,
    shared by the serial solve and the speculative dispatch — their
    byte-for-byte agreement is what the pipelined equivalence rests on.
    Returns (JobMeta, min_av, base_ready, base_pipelined, Jp)."""
    from ..ops.place import JobMeta
    J = len(jobs_list)
    Jp = _job_bucket(J)
    jpad = Jp - J
    min_av = np.asarray([j.min_available for j in jobs_list]
                        + [1] * jpad, np.int32)
    base_r = np.asarray([j.ready_task_num() for j in jobs_list]
                        + [0] * jpad, np.int32)
    base_p = np.asarray([j.waiting_task_num() for j in jobs_list]
                        + [0] * jpad, np.int32)
    return (JobMeta(min_available=min_av, base_ready=base_r,
                    base_pipelined=base_p), min_av, base_r, base_p, Jp)


def _scan_placement_tasks(req, job_ix_np, feas_np, static_np):
    """The scan solver's padded PlacementTasks — ONE definition of the
    bucket/pad/dtype/boundary rules, shared by the serial solve, the
    speculative dispatch and prewarm (byte-for-byte agreement again).
    Masks are padded in NUMPY: an eager jnp.ones/jnp.pad would key a
    fresh XLA micro-program on the RAW task count T, which (unlike the
    pow2 bucket) changes every cycle under churn. Returns (pt, bucket)."""
    import jax.numpy as jnp
    from ..ops.place import PlacementTasks
    T = len(job_ix_np)
    bucket = _bucket(T)
    pad = bucket - T
    first = np.zeros(T, bool)
    last = np.zeros(T, bool)
    first[0] = True
    first[1:] = job_ix_np[1:] != job_ix_np[:-1]
    last[:-1] = job_ix_np[1:] != job_ix_np[:-1]
    last[-1] = True
    pt = PlacementTasks(
        req=jnp.asarray(np.pad(req, ((0, pad), (0, 0)))),
        job_ix=jnp.asarray(np.pad(job_ix_np, (0, pad))),
        valid=jnp.asarray(np.r_[np.ones(T, bool), np.zeros(pad, bool)]),
        feas=jnp.asarray(np.pad(feas_np, ((0, pad), (0, 0)))),
        static_score=jnp.asarray(np.pad(static_np, ((0, pad), (0, 0)))),
        first_of_job=jnp.asarray(np.pad(first, (0, pad))),
        last_of_job=jnp.asarray(np.pad(last, (0, pad))))
    return pt, bucket


def _fetch_packed(packed_d, bucket: int, jp: int, T: int):
    """The scan solver's ONE device→host fetch + unpack, shared by the
    serial solve and the speculative finalize so the inventory carries a
    single readback site. Callers run it under the sanctioned ``solve``
    span (VT010)."""
    from ..ops.place import unpack_placement
    task_node, pipelined, job_ready, job_kept = unpack_placement(
        np.asarray(packed_d), bucket, jp)
    return task_node[:T], pipelined[:T], job_ready, job_kept


def _stateful_recheck(ssn, task, node) -> bool:
    """Re-validate a device proposal through the stateful predicate chain
    (gpu card packing, numa cpusets — anything that mutates as the cycle
    allocates). The static feasibility mask shipped to the device is
    necessary but not sufficient for these; the callbacks engine evaluates
    them per placement, so batched engines must too. Only called when a
    plugin registered itself in ssn.stateful_predicates."""
    try:
        ssn.predicate_fn(task, node)
        return True
    except Exception:
        return False


def _fast_replay_ok(ssn) -> bool:
    """The batched replay skips the per-task Statement machinery; it is
    sound only when (a) no stateful predicates need re-checking, (b) every
    event handler declared itself additive-per-job (drf/proportion), (c) the
    gang plugin alone decides job readiness/pipelining — so the kernel's
    gang verdicts (bit-identical to gang.go's formula) are authoritative —
    and (d) no node carries GPU card state."""
    if ssn.stateful_predicates:
        return False
    if any(not eh.aggregatable for eh in ssn.event_handlers):
        return False
    for reg, flag in ((ssn.job_ready_fns, "enabledJobReady"),
                      (ssn.job_pipelined_fns, "enabledJobPipelined")):
        owners = [opt.name for tier in ssn.tiers for opt in tier.plugins
                  if opt.name in reg and (flag is None or opt.is_enabled(flag))]
        if any(name != "gang" for name in owners):
            return False
    if any(n.gpu_devices for n in ssn.nodes.values()):
        return False
    return True


def _replay_fused_fast(ssn, sol: "_FusedSolution") -> None:
    """Batched replay: identical end-state to the Statement path. The Python
    loop does only dict bookkeeping (status-index bucket moves + node task
    mirrors) and exact Resource aggregation per node/job — aggregates use
    task.resreq doubles, NOT the solve's f32 req matrix, so node accounting
    stays bit-identical to the Statement path (an f32-rounded delta can
    fail Resource.sub's sufficiency assert on exactly-packed nodes). Status
    flips match the slow path exactly: committed tasks end BINDING on the
    session model and BOUND on the live cache (session.dispatch ->
    cache.bind), pipelined tasks end PIPELINED session-only."""
    from ..api import Resource

    task_node = np.asarray(sol.task_node)
    pipelined = np.asarray(sol.pipelined, bool)
    job_ix = np.asarray(sol.job_ix)
    kept_t = np.asarray(sol.job_kept, bool)[job_ix]
    placed = (task_node != NO_NODE) & kept_t
    pipe_m = placed & pipelined

    # Vectorized accounting plan: every per-task decision (status, bind
    # membership) is precomputed as index arrays so the Python loop is pure
    # dict bookkeeping — and node identity is resolved through a row-indexed
    # object table instead of a per-task name hash.
    ready_j = np.asarray(sol.job_ready, bool)
    placed_ix = np.flatnonzero(placed)
    hosts_row = task_node[placed_ix]
    jx_arr = job_ix[placed_ix]
    pipe_arr = pipe_m[placed_ix]
    bind_arr = ~pipe_arr & ready_j[jx_arr]

    alloc_agg: Dict[int, Resource] = {}
    pipe_agg: Dict[int, Resource] = {}
    job_agg: Dict[int, Resource] = {}
    job_alloc: Dict[int, Resource] = {}
    binds: List[TaskInfo] = []
    names = sol.node_t.names
    node_objs = [ssn.nodes.get(nm) if nm else None for nm in names]
    tasks_l = sol.tasks
    jobs_list = sol.jobs_list
    PIPELINED, BINDING, ALLOCATED = (TaskStatus.PIPELINED,
                                     TaskStatus.BINDING,
                                     TaskStatus.ALLOCATED)
    for k in range(len(placed_ix)):
        i = placed_ix[k]
        task = tasks_l[i]
        jx = int(jx_arr[k])
        job = jobs_list[jx]
        row = hosts_row[k]
        if pipe_arr[k]:
            status = PIPELINED
            pipe_agg.setdefault(row, Resource()).add(task.resreq)
        else:
            if bind_arr[k]:
                status = BINDING
                binds.append(task)
            else:
                status = ALLOCATED
            alloc_agg.setdefault(row, Resource()).add(task.resreq)
            job_alloc.setdefault(jx, Resource()).add(task.resreq)
        # inline update_task_status minus the per-task Resource math
        # (aggregated above): old status is PENDING by construction of
        # _pending_tasks
        job._del_index(task)
        task.status = status
        job._add_index(task)
        task.node_name = names[row]
        ti = task.shallow_clone()
        if status is BINDING:
            ti.status = ALLOCATED
        node_objs[row].tasks[task.uid] = ti
        job_agg.setdefault(jx, Resource()).add(task.resreq)

    for jx, agg in job_agg.items():
        job = jobs_list[jx]
        if jx in job_alloc:
            job.allocated.add(job_alloc[jx])
        ssn._fire_allocate(_AggTask(job.uid, agg))
    for row, r in alloc_agg.items():
        node = node_objs[row]
        node._touched = True          # direct aggregate mutation below
        node.idle.sub(r)
        node.used.add(r)
    for row, r in pipe_agg.items():
        node = node_objs[row]
        node._touched = True
        node.pipelined.add(r)
    # the statement-free path never goes through session.dispatch, so it
    # feeds the decision audit here (a no-op unless the audit is on)
    for task in binds:
        ssn._audit_event("bind", task, task.node_name)
    # bind_batch records every bound task/node in the cache's dirty set, so
    # the NEXT cycle's snapshot+tensor delta is exactly this cycle's binds
    with obs_trace.span("bind_commit", binds=len(binds)):
        ssn.cache.bind_batch(binds)


def _replay_fused(ssn, sol: _FusedSolution) -> int:
    """Replay device decisions through Statements, job by job, preserving
    gang atomicity on the host model (statement.go semantics). Returns the
    number of proposals rejected by the live stateful re-check (callers
    re-solve those tasks against fresh state)."""
    if _fast_replay_ok(ssn):
        try:
            _replay_fused_fast(ssn, sol)
        except Exception as exc:
            # the fast replay's aggregate mutations are not
            # statement-tracked: a mid-replay raise leaves state the
            # fallback cannot reason about — classify so the degradation
            # chain re-raises instead of running on phantom allocations
            raise ReplayFault(
                f"batched replay failed mid-apply: {exc!r}") from exc
        return 0
    per_job_tasks: Dict[int, List[int]] = {}
    for i, jx in enumerate(sol.job_ix):
        per_job_tasks.setdefault(int(jx), []).append(i)
    recheck = bool(ssn.stateful_predicates)

    rejected = 0
    for jx, task_ids in per_job_tasks.items():
        if not sol.job_kept[jx]:
            continue
        job = sol.jobs_list[jx]
        stmt = ssn.statement()
        try:
            for i in task_ids:
                n = int(sol.task_node[i])
                if n == NO_NODE:
                    continue
                name = sol.node_t.names[n]
                node = ssn.nodes[name]
                if recheck and not _stateful_recheck(ssn, sol.tasks[i],
                                                     node):
                    rejected += 1
                    continue
                if sol.pipelined[i]:
                    stmt.pipeline(sol.tasks[i], name)
                else:
                    stmt.allocate(sol.tasks[i], node)
        except Exception:
            stmt.discard()              # session stays fallback-safe
            raise
        if ssn.job_ready(job):
            stmt.commit()
        elif not ssn.job_pipelined(job):
            stmt.discard()
    return rejected


# ---------------------------------------------------------------------------
# speculative dispatch/await split (docs/performance.md pipelining)
# ---------------------------------------------------------------------------

class PendingFusedSolution:
    """A dispatched-but-unfetched fused solve: the device-resident packed
    result plus everything needed to finalize and replay it at the
    pipelined commit boundary. Holding this object IS the overlap — jax
    async dispatch means the device computes while the host runs cycle
    N's replay/bind/close and the inter-cycle wait."""

    __slots__ = ("ordered_jobs", "tasks", "job_ix", "jobs_list", "node_t",
                 "packed_d", "bucket", "jp", "eligible_uids",
                 "assumed_hint", "mesh_devices")

    def __init__(self, ordered_jobs, tasks, job_ix, jobs_list, node_t,
                 packed_d, bucket, jp, eligible_uids, assumed_hint=None,
                 mesh_devices=None):
        self.ordered_jobs = ordered_jobs
        self.tasks = tasks
        self.job_ix = job_ix
        self.jobs_list = jobs_list
        self.node_t = node_t
        self.packed_d = packed_d
        self.bucket = bucket
        self.jp = jp
        # every job eligible at speculation time (covered or not — the
        # ordering's overused gating may have excluded some): the commit
        # suffix-solves exactly the jobs eligible at commit time that are
        # NOT in this set, which is what the speculation could not know
        self.eligible_uids = eligible_uids
        # None: all-admitted premise (the serial trajectory's iteration
        # 1). set(): warm-started at the ∅ fixpoint — the commit must
        # verify kept==∅ and otherwise discard (conflict), never continue
        self.assumed_hint = assumed_hint
        # tpu-sharded only: device-id tuple the speculative packed result
        # was dispatched over. A mesh change before commit (quarantine or
        # readmission) means packed_d may live on a lost device / stale
        # layout — the commit classifies it as a conflict and retires the
        # pinned epoch pair. None for single-device engines.
        self.mesh_devices = mesh_devices


def dispatch_speculative_solve(ssn, engine: str = "tpu-fused",
                               assumed_hint: Optional[set] = None
                               ) -> Optional[PendingFusedSolution]:
    """Order + assemble + DISPATCH one fused scan solve with no
    host↔device synchronization: the call returns as soon as XLA enqueues
    the program, so the device solves cycle N+1's speculative placement
    while the host is still committing cycle N.
    ``finalize_speculative_dispatch`` performs the batch's one fetch at
    the commit boundary.

    The assembly IS ``_solve_fused``'s scan-branch input (the shared
    ``_collect_pending_ordered``/``_gang_meta``/``_scan_placement_tasks``
    helpers — one definition of collection, padding, dtypes and the jit
    cache key), which is what makes a committed speculation
    byte-equivalent to the serial cycle.
    Every fused kernel dispatches: scan, the pallas VMEM kernel (device
    decode into the same packed layout — place_pallas_packed), and the
    unified sharded engine, so multi-chip backends pipeline end-to-end.
    Returns None whenever speculation cannot run this cycle: nothing
    pending, stateful predicates (the mask would go stale mid-replay),
    device cool-down, or non-finite inputs (the serial path's
    SolverFault degradation owns those)."""
    if ssn.stateful_predicates or not ssn.nodes:
        return None
    if engine not in ("tpu-fused", "tpu-scan", "tpu-pallas", "tpu-sharded"):
        return None
    if not _device_available():
        return None
    with obs_trace.span("order", speculative=True) as sp:
        # assumed_hint=set() warm-starts the order at the ∅ fixpoint (the
        # previous cycle's converged admitted set on a saturated
        # backlog); None is the serial trajectory's all-admitted start
        ordered_jobs = _fixed_job_order(ssn, assumed_hint)
    if not ordered_jobs:
        return None
    tasks, job_ix, jobs_list = _collect_pending_ordered(ssn, ordered_jobs)
    if not tasks:
        return None
    rnames = discover_resource_names(list(ssn.nodes.values()), tasks)
    node_t = _node_tensors(ssn, rnames)
    N = len(node_t.names)
    req = task_requests(tasks, rnames)
    feas = assemble_feasibility(ssn, tasks, node_t)
    static = assemble_static_score(ssn, tasks, node_t)
    weights = assemble_weights(ssn, rnames)
    if not np.isfinite(req).all() or (
            static is not None
            and not np.isfinite(np.asarray(static)).all()):
        return None
    if not (np.isfinite(weights.binpack_res).all()
            and all(np.isfinite(w) for w in (
                weights.binpack_weight, weights.least_req_weight,
                weights.most_req_weight, weights.balanced_weight))):
        return None

    T = len(tasks)
    job_ix_np = np.asarray(job_ix, np.int32)
    jobs_meta, min_av_np, base_r_np, base_p_np, Jp = _gang_meta(jobs_list)
    topo_w = _topology_weight(ssn)
    from ..ops import pallas_place
    # mirror of _solve_fused's kernel selection (tpu-fused = auto): the
    # committed speculation must run the SAME kernel the serial cycle
    # would have — byte-equivalence is the contract, not just parity
    use_pallas = (engine in ("tpu-fused", "tpu-pallas") and topo_w == 0.0
                  and pallas_place.supported(len(rnames), N)
                  and (engine == "tpu-pallas"
                       or not pallas_place.use_interpret()))
    if engine == "tpu-sharded":
        # unified sharded solve — same assembly as _solve_fused's sharded
        # branch, dispatch only: the packed result stays on device until
        # finalize_speculative_dispatch's one fetch
        import jax.numpy as jnp
        from ..cache.snapshot import sharded_node_layout
        from ..ops.pallas_place import NEG as MNEG
        from ..ops.unified import (make_mesh, padded_task_len,
                                   place_blocks_unified)
        # same health-filtered mesh as the serial branch; an empty
        # healthy set means no device to speculate on
        _, devices = _mesh_devices(ssn)
        if not devices:
            return None
        mesh_ids = tuple(d.id for d in devices)
        mesh = make_mesh(devices)
        state, alloc_d, maxt_d, n_pad = sharded_node_layout(
            node_t, int(mesh.devices.size))
        ms = None
        if feas is not None or static is not None:
            f = np.ones((T, N), bool) if feas is None else np.asarray(feas)
            s = (np.zeros((T, N), np.float32) if static is None
                 else np.asarray(static, np.float32))
            ms = jnp.asarray(np.pad(
                np.where(f, s, MNEG).astype(np.float32),
                ((0, 0), (0, n_pad)), constant_values=MNEG))
        big = T > 12000
        packed, _ = place_blocks_unified(
            mesh, state, jnp.asarray(req), jnp.ones(T, bool),
            jnp.asarray(job_ix_np), jobs_meta, weights, alloc_d, maxt_d,
            sweeps=5 if big else 3, passes=4 if big else 3,
            masked_static=ms)
        bucket = padded_task_len(T)
    elif use_pallas:
        if feas is None and static is None:
            ms = pallas_place.neutral_masked_static(
                *pallas_place.padded_shape(T, N), T, N)
        else:
            f = np.ones((T, N), bool) if feas is None else np.asarray(feas)
            s = (np.zeros((T, N), np.float32) if static is None
                 else np.asarray(static, np.float32))
            ms = np.where(f, s, pallas_place.NEG).astype(np.float32)
        packed = pallas_place.place_pallas_packed(
            node_t.idle,
            node_t.idle + node_t.releasing - node_t.pipelined,
            node_t.used, node_t.ntasks.astype(np.float32),
            node_t.allocatable, node_t.max_tasks.astype(np.float32),
            req, job_ix_np, ms, min_av_np, base_r_np, base_p_np,
            np.asarray(weights.binpack_res),
            binpack_weight=float(weights.binpack_weight),
            least_weight=float(weights.least_req_weight),
            most_weight=float(weights.most_req_weight),
            balanced_weight=float(weights.balanced_weight))
        bucket = pallas_place.padded_shape(T, N)[0]
    else:
        feas_np = np.ones((T, N), bool) if feas is None else np.asarray(feas)
        static_np = (np.zeros((T, N), np.float32) if static is None
                     else np.asarray(static, np.float32))
        pt, bucket = _scan_placement_tasks(req, job_ix_np, feas_np,
                                           static_np)
        if topo_w > 0.0:
            import jax.numpy as jnp
            packed, _ = _job_solver_topo()(
                node_t.node_state(), pt, jobs_meta, weights,
                node_t.device_allocatable(), node_t.device_max_tasks(),
                node_t.device_zone_code(), jnp.float32(topo_w))
        else:
            packed, _ = _job_solver()(node_t.node_state(), pt, jobs_meta,
                                      weights, node_t.device_allocatable(),
                                      node_t.device_max_tasks())
    LAST_STATS["speculate_order_s"] = sp.dur_s
    return PendingFusedSolution(ordered_jobs, tasks, job_ix_np, jobs_list,
                                node_t, packed, bucket, Jp,
                                {j.uid for j in _eligible_jobs(ssn)},
                                assumed_hint=assumed_hint,
                                mesh_devices=(mesh_ids if engine
                                              == "tpu-sharded" else None))


def finalize_speculative_dispatch(pending: PendingFusedSolution
                                  ) -> _FusedSolution:
    """The dispatched solve's ONE fetch, under the sanctioned solve span
    (VT010): at the commit boundary the device finished during cycle N's
    host commit, so this await costs transfer time, not solve time.
    Raises SolverFault on garbage output (the ``_FusedSolution`` guard);
    the pipelined shell counts that as a conflict and re-solves."""
    with obs_trace.span("solve", speculative=True):
        task_node, pipelined, job_ready, job_kept = _fetch_packed(
            pending.packed_d, pending.bucket, pending.jp,
            len(pending.tasks))
    return _FusedSolution(pending.tasks, pending.job_ix, pending.jobs_list,
                          pending.node_t, task_node, pipelined,
                          job_ready, job_kept)


def remap_speculative_solution(sol: _FusedSolution, ordered_jobs, ssn):
    """Re-anchor a speculative solution onto the COMMIT session's objects
    by uid — sound because the shell's conflict check already proved the
    covered jobs' and placed-on nodes' decision inputs unchanged since
    the speculative snapshot. On the promote path the session is the
    speculative session itself and this is the identity map. Returns
    ``(solution, ordered)`` or ``(None, None)`` when any covered object
    vanished (the shell counts a conflict)."""
    jobs_list = []
    for job in sol.jobs_list:
        live = ssn.jobs.get(job.uid)
        if live is None:
            return None, None
        jobs_list.append(live)
    ordered = []
    for job in ordered_jobs:
        live = ssn.jobs.get(job.uid)
        if live is None:
            return None, None
        ordered.append(live)
    tasks = []
    for t in sol.tasks:
        job = ssn.jobs.get(t.job)
        live = job.tasks.get(t.uid) if job is not None else None
        if live is None or live.status != TaskStatus.PENDING:
            return None, None
        tasks.append(live)
    tn = np.asarray(sol.task_node)
    for n in np.unique(tn[tn != NO_NODE]):
        if sol.node_t.names[int(n)] not in ssn.nodes:
            return None, None
    mapped = _FusedSolution(tasks, sol.job_ix, jobs_list, sol.node_t,
                            sol.task_node, sol.pipelined, sol.job_ready,
                            sol.job_kept)
    return mapped, ordered


def _fused_blocks_solver():
    import jax
    if "blocks" not in _SOLVER_CACHE:
        from ..ops.auction import place_blocks_packed
        # chunk is shape-static; sweeps/passes are runtime while_loop caps
        # in the unified kernel (fixpoint early exit), so ONE compile per
        # task bucket serves every budget tier — the big-tier budget bump
        # at T > 12000 no longer mints a second program
        _SOLVER_CACHE["blocks"] = jax.jit(
            place_blocks_packed, static_argnames=("chunk",))
    return _SOLVER_CACHE["blocks"]


def prewarm_shapes(ssn, shape_configs=None, engine: str = "tpu-fused",
                   preempt_engine: Optional[str] = None) -> int:
    """Compile the device solver at the given cycle shapes before the
    scheduling loop needs them (Scheduler.prewarm). Each config is a
    ``(tasks, jobs)`` pair; dummy zero-valued tensors with the session's
    REAL node count, resource dimensionality and score weights are
    dispatched through the same kernel-selection logic as _solve_fused —
    shape and dtype (not values) key the XLA compile cache, so the later
    live solve of the same bucket is a cache hit. Returns the number of
    shapes warmed (0 for host engines / empty clusters)."""
    import jax
    import jax.numpy as jnp
    from ..ops.place import JobMeta, PlacementTasks

    def _warm_preempt() -> int:
        if preempt_engine not in ("tpu", "tpu-sharded"):
            return 0
        # mirror of the preempt walk's pow2 (preemptor, victim-slot)
        # bucketing (evict_tpu._ptask_bucket/_slot_bucket): compile the
        # walk at the buckets the current session implies so steady-state
        # preempt cycles hit the XLA cache like allocate does
        from .evict_tpu import prewarm_preempt
        return prewarm_preempt(ssn, sharded=preempt_engine == "tpu-sharded")

    if engine.startswith("callbacks"):
        return _warm_preempt()
    nodes = list(ssn.nodes.values())
    if not nodes:
        return 0
    tasks_all = [t for j in ssn.jobs.values() for t in j.tasks.values()]
    rnames = discover_resource_names(nodes, tasks_all)
    # route through the persistent tensor cache so the cold full build —
    # AND the delta-scatter programs the steady-state cycles will dispatch
    # — are both paid here, not inside a scheduling cycle
    node_t = _node_tensors(ssn, rnames)
    weights = assemble_weights(ssn, rnames)
    N, R = len(node_t.names), len(rnames)
    if shape_configs is None:
        T = J = 0
        for job in ssn.jobs.values():
            pend = [t for t in job.task_status_index.get(
                TaskStatus.PENDING, {}).values() if not t.resreq.is_empty()]
            if pend:
                T += len(pend)
                J += 1
        shape_configs = [(T, J)] if T else []

    from ..ops import pallas_place
    use_pallas = (engine in ("tpu-fused", "tpu-pallas")
                  and pallas_place.supported(R, N)
                  and (engine == "tpu-pallas"
                       or not pallas_place.use_interpret()))
    warmed = 0
    prewarm_delta = getattr(node_t, "prewarm_delta", None)
    if prewarm_delta is not None and shape_configs:
        # the per-cycle dirty-row count varies cycle to cycle, so warm the
        # WHOLE pow2 scatter-bucket ladder up to the node count — each
        # program is a tiny scatter, and a cold one inside the loop is
        # exactly the recompile churn_steady_ok forbids. Not counted in the
        # return value, which stays "solver shapes warmed". The ladder is
        # derived through _delta_bucket so it tracks the live policy.
        from ..cache.snapshot import _delta_bucket
        ladder, n = [], 1
        while n <= N:
            ladder.append(_delta_bucket(n))
            n = ladder[-1] + 1
        prewarm_delta(ladder)
    for T, J in shape_configs:
        T, J = int(T), max(int(J), 1)
        if T <= 0:
            continue
        # dummy task tensors: J contiguous equal job blocks over T rows;
        # the gang-meta arrays pad to the SAME pow2 job bucket as
        # _solve_fused, so one warmed entry covers every live J in its
        # bucket (shape — not values — keys the XLA compile cache)
        job_ix = np.minimum(np.arange(T) * J // T, J - 1).astype(np.int32)
        req = np.zeros((T, R), np.float32)
        Jp = _job_bucket(J)
        min_av = np.ones(Jp, np.int32)
        base_z = np.zeros(Jp, np.int32)
        if use_pallas:
            ms = pallas_place.neutral_masked_static(
                *pallas_place.padded_shape(T, N), T, N)
            out = pallas_place.place_pallas(
                node_t.idle,
                node_t.idle + node_t.releasing - node_t.pipelined,
                node_t.used, node_t.ntasks.astype(np.float32),
                node_t.allocatable, node_t.max_tasks.astype(np.float32),
                req, job_ix, ms, min_av, base_z, base_z,
                np.asarray(weights.binpack_res),
                binpack_weight=float(weights.binpack_weight),
                least_weight=float(weights.least_req_weight),
                most_weight=float(weights.most_req_weight),
                balanced_weight=float(weights.balanced_weight),
                fetch_state=False)
        elif engine == "tpu-blocks":
            from ..ops.auction import BlockTasks
            bt = BlockTasks(req=jnp.asarray(req), job_ix=jnp.asarray(job_ix),
                            valid=jnp.ones(T, bool),
                            feas=jnp.ones((T, N), bool),
                            static_score=jnp.zeros((T, N), jnp.float32))
            big = T > 12000
            out = _fused_blocks_solver()(
                node_t.node_state(), bt,
                JobMeta(min_available=min_av, base_ready=base_z,
                        base_pipelined=base_z),
                weights, jnp.asarray(node_t.allocatable),
                jnp.asarray(node_t.max_tasks),
                sweeps=5 if big else 3, passes=4 if big else 3)
        elif engine == "tpu-sharded":
            from ..cache.snapshot import sharded_node_layout
            from ..ops.unified import make_mesh, place_blocks_unified
            # warm the program at the CURRENT healthy mesh size — after a
            # quarantine/readmission the next live solve runs at the new
            # D and this is the bucket it will hit
            _, devices = _mesh_devices(ssn)
            if not devices:
                continue
            mesh = make_mesh(devices)
            state, alloc_d, maxt_d, _ = sharded_node_layout(
                node_t, int(mesh.devices.size))
            big = T > 12000
            out = place_blocks_unified(
                mesh, state, jnp.asarray(req), jnp.ones(T, bool),
                jnp.asarray(job_ix),
                JobMeta(min_available=jnp.asarray(min_av),
                        base_ready=jnp.asarray(base_z),
                        base_pipelined=jnp.asarray(base_z)),
                weights, alloc_d, maxt_d, masked_static=None,
                sweeps=5 if big else 3, passes=4 if big else 3)
        else:
            # scan solver: the fused engine's CPU/interpret path and the
            # strict engines' batched program (same place_scan_packed
            # jit), assembled through the SAME helper as the live paths
            # so prewarm compiles exactly the cache keys they will hit
            pt, _ = _scan_placement_tasks(
                req, job_ix, np.ones((T, N), bool),
                np.zeros((T, N), np.float32))
            meta = JobMeta(min_available=min_av, base_ready=base_z,
                           base_pipelined=base_z)
            if _topology_weight(ssn) > 0.0:
                out = _job_solver_topo()(
                    node_t.node_state(), pt, meta, weights,
                    jnp.asarray(node_t.allocatable),
                    jnp.asarray(node_t.max_tasks),
                    jnp.asarray(node_t.zone_code),
                    jnp.float32(_topology_weight(ssn)))
            else:
                out = _job_solver()(
                    node_t.node_state(), pt, meta,
                    weights, jnp.asarray(node_t.allocatable),
                    jnp.asarray(node_t.max_tasks))
        jax.block_until_ready(out)
        warmed += 1
    warmed += _warm_preempt()
    return warmed


def _fit_error(task, node):
    from ..api.types import NODE_RESOURCE_FIT_FAILED
    err = ValueError(f"task {task.key()} on node {node.name}: resource fit failed")
    err.fit_error = FitError(task, node, [NODE_RESOURCE_FIT_FAILED])
    return err
