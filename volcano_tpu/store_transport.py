"""The hostile store boundary (docs/robustness.md, store failure model).

In production the scheduler talks to the Kubernetes API server; every
verb can be slow, fail transiently (500/etcd timeout), conflict (409),
or — for watches — silently die mid-stream. This module puts that
reality between the scheduler and the in-process :class:`ObjectStore`:

- :class:`FaultyStoreTransport` injects seeded faults per verb (driven
  by :class:`volcano_tpu.chaos.StoreFaultInjector`) and owns the
  tearable watch-stream handles — the chaos half;
- :class:`RetryingStoreTransport` is the production-side funnel every
  scheduler write rides: bounded retry with exponential backoff +
  seeded jitter on transient errors, under a per-cycle time budget.
  Exhaustion re-raises, and the cache funnels degrade to the existing
  rollback → resync → dead-letter machinery instead of crashing the
  cycle. vlint rule VT016 statically pins scheduler-side store verbs to
  this funnel (docs/static-analysis.md).

Composition (the production stack, faulty layer only in chaos rigs)::

    store = RetryingStoreTransport(FaultyStoreTransport(ObjectStore(),
                                                        injector))
    cache = wire_cache_to_store(store)

Both wrappers are duck-typed to the ObjectStore verb surface; anything
not intercepted (events, admission hooks) delegates to the inner store.
"""

from __future__ import annotations

import random
import time
from typing import Callable, List, Optional

from .store import BOOKMARK, ConflictError

# verbs the retry funnel wraps; reads retry too (a relist that dies on a
# transient must not wedge the informer)
WRITE_VERBS = ("create", "create_batch", "update", "update_status",
               "delete", "bind_pod", "evict_pod", "finish_pod")
READ_VERBS = ("get", "list", "list_with_rv")


class TransientStoreError(RuntimeError):
    """A store verb failed in a way a retry may fix — the HTTP 500 /
    etcd-timeout analogue (client-go's IsServerTimeout class)."""

    def __init__(self, verb: str, seed: int, attempt: int):
        super().__init__(f"store: injected transient {verb} failure "
                         f"(seed={seed}, attempt={attempt})")
        self.verb = verb


class StreamHandle:
    """One watch stream through the faulty transport. ``torn`` flips when
    the injector kills the stream — events stop flowing until the owner
    (cache/watches.ResumableWatch) resumes or relists. ``cancel`` ends
    the stream for good (normal informer shutdown)."""

    __slots__ = ("kind", "transport", "handler", "torn", "_watcher")

    def __init__(self, kind: str, transport: "FaultyStoreTransport",
                 handler: Callable):
        self.kind = kind
        self.transport = transport
        self.handler = handler
        self.torn = False
        self._watcher = None

    def cancel(self) -> None:
        if self._watcher is not None:
            self.transport.store.unwatch(self.kind, self._watcher)
            self._watcher = None

    def tear(self) -> None:
        """Kill the stream (the transport's injector calls this on a
        seeded roll; the sim also tears streams wholesale at seeded
        cycles). Idempotent."""
        if not self.torn:
            self.torn = True
            self.cancel()


class FaultyStoreTransport:
    """Seeded fault injection over an ObjectStore-shaped inner store.
    Verb faults come from the injector's per-call roll; watch streams
    are delivered through tearable :class:`StreamHandle`s whose events
    additionally roll the injector's tear rate."""

    def __init__(self, store, injector, name: str = "store"):
        self.store = store
        self.injector = injector
        self.name = name
        self.streams: List[StreamHandle] = []

    # -- verb faulting -------------------------------------------------------

    def _roll(self, verb: str, kind_hint: str = "", key: str = "") -> None:
        fault = self.injector.roll(verb)
        if fault is None:
            return
        from . import metrics
        metrics.register_store_fault(verb, fault)
        if fault == "transient":
            raise TransientStoreError(verb, self.injector.seed,
                                      self.injector.attempts)
        if fault == "conflict":
            raise ConflictError(kind_hint or verb, key or "?",
                                observed=self.store.current_rv(),
                                expected=-1)
        # "latency": the injector already slept; the verb proceeds

    def create(self, obj):
        self._roll("create", obj.KIND, obj.metadata.key())
        return self.store.create(obj)

    def create_batch(self, objs, admit: bool = True):
        objs = list(objs)
        hint = objs[0].KIND if objs else "?"
        self._roll("create_batch", hint)
        return self.store.create_batch(objs, admit=admit)

    def update(self, obj, expect_rv=None):
        self._roll("update", obj.KIND, obj.metadata.key())
        return self.store.update(obj, expect_rv=expect_rv)

    def update_status(self, obj):
        self._roll("update_status", obj.KIND, obj.metadata.key())
        return self.store.update_status(obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._roll("delete", kind, f"{namespace}/{name}")
        return self.store.delete(kind, namespace, name)

    def get(self, kind: str, namespace: str, name: str):
        self._roll("get", kind, f"{namespace}/{name}")
        return self.store.get(kind, namespace, name)

    def list(self, kind: str, namespace=None):
        self._roll("list", kind)
        return self.store.list(kind, namespace)

    def list_with_rv(self, kind: str, namespace=None):
        self._roll("list", kind)
        return self.store.list_with_rv(kind, namespace)

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        self._roll("bind_pod", "Pod", f"{namespace}/{name}")
        return self.store.bind_pod(namespace, name, node_name)

    def evict_pod(self, namespace: str, name: str, reason: str) -> None:
        self._roll("evict_pod", "Pod", f"{namespace}/{name}")
        return self.store.evict_pod(namespace, name, reason)

    def finish_pod(self, namespace: str, name: str, succeeded: bool = True,
                   exit_code=None) -> None:
        # kubelet-side helper: not a scheduler verb; no fault roll
        return self.store.finish_pod(namespace, name, succeeded, exit_code)

    # -- tearable watch streams ----------------------------------------------

    def watch(self, kind: str, handler: Callable,
              since_rv: Optional[int] = None,
              with_rv: bool = False) -> StreamHandle:
        """Open a watch stream through the transport. The returned handle
        tears on the injector's seeded per-event roll (and on explicit
        ``tear()``); a torn stream delivers nothing more — exactly a
        died apiserver connection — until its owner re-watches."""
        hs = StreamHandle(kind, self, handler)

        def forward(event, obj, old, rv):
            if hs.torn:
                return
            if event != BOOKMARK and self.injector.roll_tear():
                from . import metrics
                metrics.register_store_fault("watch", "torn")
                hs.tear()
                return
            if with_rv:
                handler(event, obj, old, rv)
            else:
                handler(event, obj, old)

        hs._watcher = self.store.watch(kind, forward, since_rv=since_rv,
                                       with_rv=True)
        self.streams.append(hs)
        return hs

    def unwatch(self, kind: str, handle: StreamHandle) -> None:
        handle.cancel()
        if handle in self.streams:
            self.streams.remove(handle)

    def tear_streams(self, n: int, rng: Optional[random.Random] = None
                     ) -> List[str]:
        """Tear ``n`` live streams chosen by the (seeded) rng — the sim's
        whole-stream tear drill. Returns the torn kinds."""
        live = [s for s in self.streams if not s.torn]
        if not live:
            return []
        rng = rng or self.injector._rng
        torn = []
        for _ in range(min(n, len(live))):
            s = live.pop(rng.randrange(len(live)))
            s.tear()
            torn.append(s.kind)
            from . import metrics
            metrics.register_store_fault("watch", "torn")
        return torn

    # -- delegation ----------------------------------------------------------

    def __getattr__(self, name):
        return getattr(self.store, name)


DEFAULT_MAX_ATTEMPTS = 5
DEFAULT_BASE_DELAY = 0.02
DEFAULT_MAX_DELAY = 0.5
DEFAULT_JITTER = 0.25
DEFAULT_CYCLE_BUDGET_S = 2.0


class RetryingStoreTransport:
    """The scheduler-side store write funnel: bounded retry with
    exponential backoff + seeded jitter on :class:`TransientStoreError`,
    under a per-cycle time budget.

    Only transients retry — a :class:`ConflictError` is a semantic
    verdict its caller owns (CAS loops re-read; plain writers surface
    it), and admission denials are final. When the attempt budget or the
    cycle's time budget runs out the last error re-raises: the cache
    funnels then roll back and hand the side effect to the resync
    queue → dead-letter machinery, so a sick apiserver degrades the
    scheduler instead of crashing its cycle (docs/robustness.md).

    ``sleep_fn``/``time_fn``/``rng`` are injectable (vlint VT002/VT003):
    the sim pins them to the virtual clock and a seeded RNG so faulted
    runs replay byte-deterministically; production defaults are wall
    time and per-process entropy (a fleet retrying a sick apiserver
    must not retry in lockstep)."""

    def __init__(self, store, max_attempts: int = DEFAULT_MAX_ATTEMPTS,
                 base_delay: float = DEFAULT_BASE_DELAY,
                 max_delay: float = DEFAULT_MAX_DELAY,
                 jitter: float = DEFAULT_JITTER,
                 cycle_budget_s: float = DEFAULT_CYCLE_BUDGET_S,
                 sleep_fn=time.sleep, time_fn=time.monotonic,
                 rng: Optional[random.Random] = None):
        self.store = store
        self.max_attempts = max(int(max_attempts), 1)
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self.cycle_budget_s = cycle_budget_s
        self.sleep_fn = sleep_fn
        self.time_fn = time_fn
        self._rng = rng if rng is not None else random.Random()
        self._budget_spent = 0.0
        self.retries = 0
        self.exhausted = 0

    def new_cycle(self) -> None:
        """Reset the per-cycle retry time budget (the scheduler shell's
        epilogue calls this; the sim calls it per virtual cycle)."""
        self._budget_spent = 0.0

    def _call(self, verb: str, fn: Callable, *args, **kwargs):
        from . import metrics
        attempt = 0
        while True:
            try:
                out = fn(*args, **kwargs)
                metrics.register_store_retry(verb, "ok")
                return out
            except TransientStoreError:
                attempt += 1
                delay = min(self.base_delay * (2 ** (attempt - 1)),
                            self.max_delay)
                delay *= 1.0 + self._rng.uniform(0.0, self.jitter)
                if attempt >= self.max_attempts \
                        or self._budget_spent + delay > self.cycle_budget_s:
                    self.exhausted += 1
                    metrics.register_store_retry(verb, "exhausted")
                    raise
                self.retries += 1
                metrics.register_store_retry(verb, "retry")
                self._budget_spent += delay
                self.sleep_fn(delay)

    # -- wrapped verbs -------------------------------------------------------

    def create(self, obj):
        return self._call("create", self.store.create, obj)

    def create_batch(self, objs, admit: bool = True):
        objs = list(objs)
        return self._call("create_batch", self.store.create_batch, objs,
                          admit=admit)

    def update(self, obj, expect_rv=None):
        return self._call("update", self.store.update, obj,
                          expect_rv=expect_rv)

    def update_status(self, obj):
        return self._call("update_status", self.store.update_status, obj)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        return self._call("delete", self.store.delete, kind, namespace,
                          name)

    def get(self, kind: str, namespace: str, name: str):
        return self._call("get", self.store.get, kind, namespace, name)

    def list(self, kind: str, namespace=None):
        return self._call("list", self.store.list, kind, namespace)

    def list_with_rv(self, kind: str, namespace=None):
        return self._call("list", self.store.list_with_rv, kind, namespace)

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        return self._call("bind_pod", self.store.bind_pod, namespace, name,
                          node_name)

    def evict_pod(self, namespace: str, name: str, reason: str) -> None:
        return self._call("evict_pod", self.store.evict_pod, namespace,
                          name, reason)

    def finish_pod(self, namespace: str, name: str, succeeded: bool = True,
                   exit_code=None) -> None:
        return self._call("finish_pod", self.store.finish_pod, namespace,
                          name, succeeded, exit_code)

    def watch(self, kind: str, handler: Callable,
              since_rv: Optional[int] = None, with_rv: bool = False):
        # stream recovery belongs to the resumable-watch layer
        # (cache/watches.py), not to verb retry. A v1 store (the native
        # backend) only speaks the legacy signature — current_rv is the
        # watch-v2 capability probe store_wiring uses too.
        if since_rv is None and not with_rv \
                and not hasattr(self.store, "current_rv"):
            return self.store.watch(kind, handler)
        return self.store.watch(kind, handler, since_rv=since_rv,
                                with_rv=with_rv)

    def unwatch(self, kind: str, handle) -> None:
        return self.store.unwatch(kind, handle)

    def detail(self) -> dict:
        """The /healthz?detail "store" fragment this funnel owns."""
        return {"retries": self.retries, "exhausted": self.exhausted,
                "max_attempts": self.max_attempts,
                "cycle_budget_s": self.cycle_budget_s}

    def __getattr__(self, name):
        return getattr(self.store, name)
