"""Conformance plugin: vetoes eviction of critical system pods (mirrors
/root/reference/pkg/scheduler/plugins/conformance/conformance.go:45-66)."""

from __future__ import annotations

from ..framework.session import PERMIT
from .base import Plugin

CRITICAL_PRIORITY_CLASSES = ("system-cluster-critical", "system-node-critical")


def _is_critical(task) -> bool:
    if task.namespace == "kube-system":
        return True
    pc = task.annotations.get("priorityClassName", "") or \
        getattr(task, "priority_class_name", "")
    return pc in CRITICAL_PRIORITY_CLASSES


class ConformancePlugin(Plugin):
    NAME = "conformance"

    def on_session_open(self, ssn) -> None:
        def evictable(evictor, evictees):
            victims = [t for t in evictees if not _is_critical(t)]
            return victims, PERMIT

        ssn.add_preemptable_fn(self.NAME, evictable)
        ssn.add_reclaimable_fn(self.NAME, evictable)


def New(arguments):
    return ConformancePlugin(arguments)
