"""Binpack plugin: best-fit node scoring.

Mirrors /root/reference/pkg/scheduler/plugins/binpack/binpack.go:60-260.
Contributes (a) a host NodeOrderFn for the callback path and (b) its
per-resource weights to the in-kernel dynamic scorer
(ops/scores.binpack_score), which the TPU placement kernels re-evaluate as
node usage mutates.
"""

from __future__ import annotations

from typing import Dict

from ..api import CPU, MEMORY
from .base import Plugin

MAX_NODE_SCORE = 100.0


class BinpackPlugin(Plugin):
    NAME = "binpack"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        args = self.arguments
        self.weight = args.get_int("binpack.weight", 1)
        # negative per-resource weights reset to 1 (binpack.go:123-147)
        self.res_weights: Dict[str, int] = {
            CPU: args.get_int("binpack.cpu", 1),
            MEMORY: args.get_int("binpack.memory", 1),
        }
        for rname in (CPU, MEMORY):
            if self.res_weights[rname] < 0:
                self.res_weights[rname] = 1
        # binpack.resources: "nvidia.com/gpu, example.com/foo" with
        # binpack.resources.<name> weights (binpack.go:89-155)
        for rname in str(args.get("binpack.resources", "")).split(","):
            rname = rname.strip()
            if rname:
                w = args.get_int(f"binpack.resources.{rname}", 1)
                self.res_weights[rname] = w if w >= 0 else 1

    def score(self, task, node) -> float:
        """BinPackingScore (binpack.go:196-244)."""
        score, weight_sum = 0.0, 0
        for rname in task.resreq.resource_names():
            request = task.resreq.get(rname)
            if request == 0:
                continue
            w = self.res_weights.get(rname)
            if w is None:
                continue
            allocatable = node.allocatable.get(rname)
            used = node.used.get(rname)
            if allocatable != 0 and w != 0 and used + request <= allocatable:
                score += (used + request) * w / allocatable
            weight_sum += w
        if weight_sum > 0:
            score /= weight_sum
        return score * MAX_NODE_SCORE * self.weight

    def on_session_open(self, ssn) -> None:
        if self.weight != 0:
            ssn.add_node_order_fn(self.NAME, self.score)
            ssn.set_dynamic_score_weights(
                self.NAME, binpack_weight=float(self.weight),
                binpack_res={k: float(v) for k, v in self.res_weights.items()})


def New(arguments):
    return BinpackPlugin(arguments)
