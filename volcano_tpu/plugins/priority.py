"""Priority plugin (mirrors
/root/reference/pkg/scheduler/plugins/priority/priority.go:44-117)."""

from __future__ import annotations

from ..framework.session import PERMIT
from .base import Plugin


class PriorityPlugin(Plugin):
    NAME = "priority"

    def on_session_open(self, ssn) -> None:
        def task_order(l, r) -> int:
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_task_order_fn(self.NAME, task_order)

        def job_order(l, r) -> int:
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_job_order_fn(self.NAME, job_order)

        def preemptable(preemptor, preemptees):
            p_job = ssn.jobs[preemptor.job]
            victims = [t for t in preemptees
                       if ssn.jobs[t.job].priority < p_job.priority]
            return victims, PERMIT

        ssn.add_preemptable_fn(self.NAME, preemptable)


def New(arguments):
    return PriorityPlugin(arguments)
