"""TDM (time-division multiplexing) plugin: revocable nodes usable by
preemptable workloads inside active time windows, drained outside them.

Mirrors /root/reference/pkg/scheduler/plugins/tdm/tdm.go:58-372.
"""

from __future__ import annotations

import weakref
from datetime import datetime, timedelta, timezone
from typing import Dict, List, Optional

from ..api import TaskStatus
from ..framework.session import PERMIT, REJECT
from .base import Plugin

REVOCABLE_ZONE_ARG_PREFIX = "tdm.revocable-zone."
EVICT_PERIOD_ARG = "tdm.evict.period"
MAX_NODE_SCORE = 100.0
DEFAULT_POD_EVICT_NUM = 1


def _parse_hhmm(text: str):
    h, m = text.strip().split(":")
    return int(h), int(m)


def parse_revocable_zone(raw: str, now: datetime):
    """'10:00-21:00' -> (start, end) datetimes on ``now``'s day (end rolls
    to tomorrow when end <= start) (tdm.go:89-117). ``now`` comes from the
    session clock (vlint VT002) so zone decisions replay deterministically
    under the sim's virtual time."""
    lo, hi = raw.strip().split("-")
    h1, m1 = _parse_hhmm(lo)
    h2, m2 = _parse_hhmm(hi)
    start = now.replace(hour=h1, minute=m1, second=0, microsecond=0)
    end = now.replace(hour=h2, minute=m2, second=0, microsecond=0)
    if (h1, m1) >= (h2, m2):
        end += timedelta(days=1)
    return start, end


def _parse_int_or_percent(text: str, total: int) -> int:
    text = str(text).strip()
    if text.endswith("%"):
        return round(float(text[:-1]) / 100.0 * total)
    try:
        return int(text)
    except ValueError:
        return 0


class TDMPlugin(Plugin):
    NAME = "tdm"

    # Last periodic-drain timestamp per scheduler cache, in the session
    # clock's timebase. Plugins are REBUILT from New() on every
    # open_session (framework.open_session), so throttle state on the
    # instance would reset each cycle and the drain would run every
    # cycle; keying by the cache keeps concurrent schedulers independent
    # (the pre-PR-6 module-level global shared them) and the weakref
    # lets a torn-down scheduler's entry collect.
    _last_evict_at: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.revocable_zone: Dict[str, str] = {}
        for k, v in self.arguments.items():
            if REVOCABLE_ZONE_ARG_PREFIX in k:
                self.revocable_zone[k.replace(REVOCABLE_ZONE_ARG_PREFIX, "", 1)] = v
        from .sla import parse_duration
        self.evict_period = parse_duration(
            self.arguments.get(EVICT_PERIOD_ARG, "")) or 60.0

    def _zone_active(self, rz: str, now: datetime) -> Optional[str]:
        """None if the zone is active at ``now``, else an error string.
        ``now`` is the session clock's datetime (_session_now)."""
        raw = self.revocable_zone.get(rz)
        if raw is None:
            return f"revocable zone {rz} not support"
        try:
            start, end = parse_revocable_zone(raw, now)
        except ValueError:
            return f"revocable zone {raw} format error"
        if now < start or now > end:
            return f"current time beyond revocable zone {rz}:{raw}"
        return None

    @staticmethod
    def _session_now(ssn) -> datetime:
        """The session clock as a UTC datetime: wall time live, virtual
        seconds (anchored at the epoch) under sim replay — either way
        the zone verdict is a pure function of the session's clock.
        Zone windows ('10:00-21:00') are interpreted in UTC: a local-tz
        conversion here would make the same trace replay to different
        eviction decisions on hosts in different timezones."""
        return datetime.fromtimestamp(ssn.now(), tz=timezone.utc)

    def _max_victims(self, job, victims: List) -> List:
        return victims[: min(self._max_evict_num(job), len(victims))]

    def _max_evict_num(self, job) -> int:
        """Disruption-budget-bounded eviction count (tdm.go:306-333)."""
        running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
        budget = job.budget
        if budget is not None and budget.max_unavailable not in (None, ""):
            max_unavail = _parse_int_or_percent(budget.max_unavailable,
                                                len(job.tasks))
            final = (len(job.task_status_index.get(TaskStatus.SUCCEEDED, {}))
                     + len(job.task_status_index.get(TaskStatus.FAILED, {})))
            real_unavail = len(job.tasks) - final - running
            if real_unavail >= max_unavail:
                return 0
            return max_unavail - real_unavail
        if budget is not None and budget.min_available not in (None, ""):
            min_avail = _parse_int_or_percent(budget.min_available,
                                              len(job.tasks))
            if running >= min_avail:
                return running - min_avail
        return DEFAULT_POD_EVICT_NUM

    def on_session_open(self, ssn) -> None:
        def predicate(task, node):
            if not node.revocable_zone:
                return
            err = self._zone_active(node.revocable_zone,
                                    self._session_now(ssn))
            if err:
                raise ValueError(f"plugin {self.NAME} predicates {err}")
            if not task.revocable_zone:
                raise ValueError(
                    f"plugin {self.NAME} predicates task {task.key()} is not "
                    f"allow to dispatch to revocable node {node.name}")

        ssn.add_predicate_fn(self.NAME, predicate)

        def feasibility(ssn_, tasks, node_t):
            import numpy as np
            from ..cache.snapshot import node_infos_for
            node_infos = node_infos_for(ssn_, node_t)
            if not any(n.revocable_zone for n in node_infos):
                return None
            mask = np.ones((len(tasks), len(node_infos)), dtype=bool)
            now = self._session_now(ssn_)
            for ni, node in enumerate(node_infos):
                if not node.revocable_zone:
                    continue
                active = self._zone_active(node.revocable_zone, now) is None
                for ti, task in enumerate(tasks):
                    mask[ti, ni] = active and bool(task.revocable_zone)
            return mask

        ssn.add_feasibility_fn(self.NAME, feasibility)

        def node_order(task, node) -> float:
            if not node.revocable_zone:
                return 0.0
            if self._zone_active(node.revocable_zone,
                                 self._session_now(ssn)):
                return 0.0
            if not task.revocable_zone:
                return 0.0
            return MAX_NODE_SCORE

        ssn.add_node_order_fn(self.NAME, node_order)

        def preemptable(preemptor, preemptees):
            """Non-preemptable workloads may evict preemptable tasks running
            on NON-revocable nodes (tdm.go:193-230)."""
            if preemptor.preemptable or preemptor.revocable_zone:
                return None, REJECT
            tasks_map: Dict[str, List] = {}
            for task in preemptees:
                if not task.preemptable or task.status != TaskStatus.RUNNING:
                    continue
                node = ssn.nodes.get(task.node_name)
                if node is None or node.revocable_zone:
                    continue
                tasks_map.setdefault(task.job, []).append(task)
            victims = []
            for job_id, tasks in tasks_map.items():
                job = ssn.jobs.get(job_id)
                if job is not None:
                    victims.extend(self._max_victims(job, tasks))
            return victims, PERMIT

        ssn.add_preemptable_fn(self.NAME, preemptable)

        def victims_fn():
            """Periodic drain of preemptable tasks on inactive revocable
            nodes (tdm.go:232-260)."""
            last = self._last_evict_at.get(ssn.cache, 0.0)
            if last + self.evict_period > ssn.now():
                return None
            now = self._session_now(ssn)
            victims = []
            for rz in self.revocable_zone:
                if self._zone_active(rz, now) is None:
                    continue
                tasks_map: Dict[str, List] = {}
                for node in ssn.nodes.values():
                    if node.revocable_zone != rz:
                        continue
                    for task in node.tasks.values():
                        if task.preemptable and task.status == TaskStatus.RUNNING:
                            tasks_map.setdefault(task.job, []).append(task)
                for job_id, tasks in tasks_map.items():
                    job = ssn.jobs.get(job_id)
                    if job is not None:
                        victims.extend(self._max_victims(job, tasks))
            self._last_evict_at[ssn.cache] = ssn.now()
            return victims

        ssn.add_victim_tasks_fn(self.NAME, victims_fn)

        def job_order(l, r) -> int:
            if l.preemptable == r.preemptable:
                return 0
            return -1 if not l.preemptable else 1

        ssn.add_job_order_fn(self.NAME, job_order)

        def job_pipelined(job) -> int:
            occupied = job.waiting_task_num() + job.ready_task_num()
            return PERMIT if occupied >= job.min_available else REJECT

        ssn.add_job_pipelined_fn(self.NAME, job_pipelined)

        def job_starving(job) -> bool:
            if job.preemptable:
                return False
            return bool(job.task_status_index.get(TaskStatus.PENDING))

        ssn.add_job_starving_fn(self.NAME, job_starving)


def New(arguments):
    return TDMPlugin(arguments)
