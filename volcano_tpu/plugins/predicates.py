"""Predicates plugin: hard feasibility filters.

Mirrors /root/reference/pkg/scheduler/plugins/predicates/predicates.go:80-362
(task-count limit, node-unschedulable, node affinity/selector, taints,
optional GPU-sharing predicate gpu.go:1-56, proportional scarce-resource
guard proportional.go:1-44, predicate cache cache.go:1-88) —
re-architected for the device path: every static filter contributes to one
``bool[T,N]`` feasibility mask (assembled in cache/snapshot.py) so the
placement kernels never call back to the host. The host PredicateFn remains
for callback-path actions (preempt/reclaim/backfill).

Resource fit itself (vs FutureIdle, with pod-count capacity) is checked
in-kernel because it depends on mutable node state.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..api import FitError
from ..api.device_info import (devices_idle_matrix, gpu_memory_of_task,
                               predicate_gpu)
from ..api.types import (NODE_AFFINITY_FAILED, NODE_POD_NUMBER_EXCEEDED,
                         NODE_PORTS_FAILED, NODE_UNSCHEDULABLE,
                         TAINTS_UNTOLERATED)
from .base import Plugin
from .nodeorder import _toleration_matches, match_node_selector_terms
from .podaffinity import get_pod_affinity_index, session_has_pod_affinity

GPU_SHARING_FAILED = "node(s) didn't have a gpu card with enough memory"
PROPORTIONAL_FAILED = "proportional resource check failed"
POD_AFFINITY_FAILED = "pod affinity/anti-affinity check failed"


def node_selector_ok(task, node) -> bool:
    for k, v in task.node_selector.items():
        if node.labels.get(k) != v:
            return False
    required = (task.affinity.get("nodeAffinity", {})
                .get("requiredDuringSchedulingIgnoredDuringExecution"))
    if required:
        terms = required.get("nodeSelectorTerms", []) or []
        if not match_node_selector_terms(node.labels, terms):
            return False
    return True


def taints_tolerated(task, node) -> bool:
    """NoSchedule/NoExecute taints must be tolerated (PreferNoSchedule is
    scoring-only)."""
    for taint in node.taints:
        if taint.get("effect") in ("NoSchedule", "NoExecute"):
            if not any(_toleration_matches(tol, taint)
                       for tol in task.tolerations):
                return False
    return True


def proportional_ok(task, node, rates: Dict[str, Tuple[float, float]]) -> bool:
    """predicates/proportional.go checkNodeResourceIsProportional — refuse
    placements that would starve the CPU/memory needed to drive the node's
    idle scarce resource (e.g. GPUs). ``rates`` maps resource name ->
    (milli-cpu per unit, bytes per unit)."""
    for rname in rates:
        if task.resreq.get(rname) > 0:
            return True
    for rname, (cpu_rate, mem_rate) in rates.items():
        idle_scalar = node.idle.get(rname)
        if idle_scalar <= 0:
            continue
        units = idle_scalar / 1000.0        # scalars are stored milli-scaled
        cpu_reserved = units * cpu_rate
        mem_reserved = units * mem_rate
        remaining_cpu = node.idle.cpu - task.resreq.cpu
        remaining_mem = node.idle.memory - task.resreq.memory
        if remaining_cpu < cpu_reserved or remaining_mem < mem_reserved:
            return False
    return True


class PredicatesPlugin(Plugin):
    NAME = "predicates"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        args = self.arguments
        self.node_affinity_enable = args.get_bool("predicate.NodeAffinityEnable", True)
        self.taint_enable = args.get_bool("predicate.TaintTolerationEnable", True)
        self.pod_number_enable = args.get_bool("predicate.PodNumberEnable", True)
        # optional sub-predicates (predicates.go:88-110), off by default like
        # the reference
        self.gpu_sharing_enable = args.get_bool("predicate.GPUSharingEnable", False)
        self.cache_enable = args.get_bool("predicate.CacheEnable", False)
        self.proportional_enable = args.get_bool("predicate.ProportionalEnable", False)
        # predicate.proportional.resources: "nvidia.com/gpu" with
        # .cpu (cores per unit) and .memory (Gi per unit) sub-keys
        # (proportional.go rates; stored here as milli-cpu/bytes per unit)
        self.proportional: Dict[str, Tuple[float, float]] = {}
        for rname in str(args.get("predicate.proportional.resources", "")).split(","):
            rname = rname.strip()
            if rname:
                cpu_rate = args.get_float(f"predicate.proportional.resources.{rname}.cpu", 0.0)
                mem_rate = args.get_float(f"predicate.proportional.resources.{rname}.memory", 0.0)
                self.proportional[rname] = (cpu_rate * 1000.0,
                                            mem_rate * 1024 ** 3)
        # per-session predicate cache: (node, task equivalence sig) -> reason
        # or None (predicates/cache.go PredicateWithCache)
        self._cache: Dict[Tuple[str, Tuple], object] = {}
        self._ssn = None

    @staticmethod
    def _task_signature(task) -> Tuple:
        """Equivalence class of a task for predicate caching — only what the
        CACHEABLE (node-static) predicates read (cache.go caches per
        pod-template). GPU-share and proportional checks read mutable node
        state and are never cached."""
        return (tuple(sorted(task.node_selector.items())),
                repr(task.affinity) if task.affinity else "",
                tuple(repr(t) for t in task.tolerations))

    def predicate(self, task, node) -> None:
        if self.pod_number_enable and node.max_task_num:
            if len(node.tasks) >= node.max_task_num:
                raise PredicateError(task, node, NODE_POD_NUMBER_EXCEEDED)
        if node.unschedulable:
            raise PredicateError(task, node, NODE_UNSCHEDULABLE)
        # InterPodAffinity filter (predicates.go:330-338): required terms
        # plus existing pods' symmetric anti-affinity, over the live index
        if self._ssn is not None and session_has_pod_affinity(self._ssn):
            idx = get_pod_affinity_index(self._ssn)
            mask = idx.node_mask_cached(task)
            if mask is not None:
                ni = idx.node_index.get(node.name)
                if ni is not None and not mask[ni]:
                    raise PredicateError(task, node, POD_AFFINITY_FAILED)

        if self.cache_enable:
            key = (node.name, self._task_signature(task))
            cached = self._cache.get(key)
            if cached is None:
                try:
                    self._static_predicates(task, node)
                except PredicateError as err:
                    self._cache[key] = err.fit_error.reasons[0]
                    raise
                self._cache[key] = True
            elif cached is not True:
                raise PredicateError(task, node, cached)
        else:
            self._static_predicates(task, node)
        self._stateful_predicates(task, node)

    def _static_predicates(self, task, node) -> None:
        """Predicates over immutable node/task attributes — safe to cache."""
        if self.node_affinity_enable and not node_selector_ok(task, node):
            raise PredicateError(task, node, NODE_AFFINITY_FAILED)
        if self.taint_enable and not taints_tolerated(task, node):
            raise PredicateError(task, node, TAINTS_UNTOLERATED)

    def _stateful_predicates(self, task, node) -> None:
        """Predicates over mutable node usage — evaluated every call."""
        # NodePorts (predicates.go:321 nodePortFilter.Filter): hostPort
        # claims change as the cycle allocates, so never cached
        if node.has_port_conflict(task):
            raise PredicateError(task, node, NODE_PORTS_FAILED)
        if self.gpu_sharing_enable and gpu_memory_of_task(task) > 0:
            # gpu.go checkNodeGPUSharingPredicate: some single card must fit
            if not node.gpu_devices or predicate_gpu(task, node.gpu_devices) is None:
                raise PredicateError(task, node, GPU_SHARING_FAILED)
        if self.proportional_enable and self.proportional:
            if not proportional_ok(task, node, self.proportional):
                raise PredicateError(task, node, PROPORTIONAL_FAILED)

    def feasibility_mask(self, ssn, tasks, node_t):
        from ..cache.snapshot import node_infos_for
        node_infos = node_infos_for(ssn, node_t)
        T, N = len(tasks), len(node_infos)
        any_taints = any(n.taints for n in node_infos)   # O(N), once
        any_unsched = any(n.unschedulable for n in node_infos)
        gpu_reqs = None
        if self.gpu_sharing_enable:
            gpu_reqs = np.asarray([gpu_memory_of_task(t) for t in tasks],
                                  np.float32)
            if not gpu_reqs.any():
                gpu_reqs = None
        prop_needed = bool(self.proportional_enable and self.proportional)
        pod_aff = session_has_pod_affinity(ssn)
        any_ports = any(t.host_ports for t in tasks)
        if (not any_taints and not any_unsched and gpu_reqs is None
                and not prop_needed and not pod_aff and not any_ports
                and not any(t.node_selector or t.affinity for t in tasks)):
            return None                                  # all-true mask
        mask = np.ones((T, N), dtype=bool)
        if any_ports:
            for ni, node in enumerate(node_infos):
                if not node.used_ports:
                    continue
                for ti, task in enumerate(tasks):
                    if task.host_ports and node.has_port_conflict(task):
                        mask[ti, ni] = False
        if pod_aff:
            idx = get_pod_affinity_index(ssn)
            for ti, task in enumerate(tasks):
                row = idx.node_mask_cached(task)
                if row is not None:
                    mask[ti] &= row
        sched = np.asarray([not n.unschedulable for n in node_infos], dtype=bool)
        mask &= sched[None, :]
        for ti, task in enumerate(tasks):
            if not task.node_selector and not task.affinity and not any_taints:
                continue
            for ni, node in enumerate(node_infos):
                if not mask[ti, ni]:
                    continue
                if self.node_affinity_enable and not node_selector_ok(task, node):
                    mask[ti, ni] = False
                elif self.taint_enable and not taints_tolerated(task, node):
                    mask[ti, ni] = False
        if gpu_reqs is not None:
            # feasible iff the node's best card fits the request (gpu.go)
            best_card = devices_idle_matrix(node_infos).max(axis=1)  # f32[N]
            gpu_mask = (gpu_reqs[:, None] <= 0) | \
                (best_card[None, :] >= gpu_reqs[:, None])
            mask &= gpu_mask
        if prop_needed:
            for ni, node in enumerate(node_infos):
                for ti, task in enumerate(tasks):
                    if mask[ti, ni] and not proportional_ok(
                            task, node, self.proportional):
                        mask[ti, ni] = False
        return mask

    def on_session_open(self, ssn) -> None:
        self._cache = {}
        self._ssn = ssn
        ssn.add_predicate_fn(self.NAME, self.predicate)
        ssn.add_feasibility_fn(self.NAME, self.feasibility_mask)
        if self.gpu_sharing_enable or (self.proportional_enable
                                       and self.proportional):
            # card packing / idle ratios mutate as the cycle allocates: the
            # static feasibility mask is necessary but not sufficient, so
            # batched engines re-check proposals through predicate_fn
            ssn.stateful_predicates.add(self.NAME)
        if session_has_pod_affinity(ssn):
            # in-cycle placements change the existing-pod set the affinity
            # terms match against
            ssn.stateful_predicates.add(self.NAME)
        if any(t.host_ports
               for job in ssn.jobs.values() for t in job.tasks.values()):
            # each in-cycle placement claims its hostPorts on the node, so
            # batched proposals must be re-checked through predicate_fn
            ssn.stateful_predicates.add(self.NAME)


class PredicateError(ValueError):
    def __init__(self, task, node, reason: str):
        super().__init__(f"task {task.key()} on node {node.name}: {reason}")
        self.fit_error = FitError(task, node, [reason])


def New(arguments):
    return PredicatesPlugin(arguments)
