"""Predicates plugin: hard feasibility filters.

Mirrors /root/reference/pkg/scheduler/plugins/predicates/predicates.go:80-362
(task-count limit, node-unschedulable, node affinity/selector, taints) —
re-architected for the device path: every static filter contributes to one
``bool[T,N]`` feasibility mask (assembled in cache/snapshot.py) so the
placement kernels never call back to the host. The host PredicateFn remains
for callback-path actions (preempt/reclaim/backfill).

Resource fit itself (vs FutureIdle, with pod-count capacity) is checked
in-kernel because it depends on mutable node state.
"""

from __future__ import annotations

import numpy as np

from ..api import FitError
from ..api.types import (NODE_AFFINITY_FAILED, NODE_POD_NUMBER_EXCEEDED,
                         NODE_UNSCHEDULABLE, TAINTS_UNTOLERATED)
from .base import Plugin
from .nodeorder import _toleration_matches, match_node_selector_terms


def node_selector_ok(task, node) -> bool:
    for k, v in task.node_selector.items():
        if node.labels.get(k) != v:
            return False
    required = (task.affinity.get("nodeAffinity", {})
                .get("requiredDuringSchedulingIgnoredDuringExecution"))
    if required:
        terms = required.get("nodeSelectorTerms", []) or []
        if not match_node_selector_terms(node.labels, terms):
            return False
    return True


def taints_tolerated(task, node) -> bool:
    """NoSchedule/NoExecute taints must be tolerated (PreferNoSchedule is
    scoring-only)."""
    for taint in node.taints:
        if taint.get("effect") in ("NoSchedule", "NoExecute"):
            if not any(_toleration_matches(tol, taint)
                       for tol in task.tolerations):
                return False
    return True


class PredicatesPlugin(Plugin):
    NAME = "predicates"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        args = self.arguments
        self.node_affinity_enable = args.get_bool("predicate.NodeAffinityEnable", True)
        self.taint_enable = args.get_bool("predicate.TaintTolerationEnable", True)
        self.pod_number_enable = args.get_bool("predicate.PodNumberEnable", True)

    def predicate(self, task, node) -> None:
        if self.pod_number_enable and node.max_task_num:
            if len(node.tasks) >= node.max_task_num:
                raise PredicateError(task, node, NODE_POD_NUMBER_EXCEEDED)
        if node.unschedulable:
            raise PredicateError(task, node, NODE_UNSCHEDULABLE)
        if self.node_affinity_enable and not node_selector_ok(task, node):
            raise PredicateError(task, node, NODE_AFFINITY_FAILED)
        if self.taint_enable and not taints_tolerated(task, node):
            raise PredicateError(task, node, TAINTS_UNTOLERATED)

    def feasibility_mask(self, ssn, tasks, node_t):
        node_infos = [ssn.nodes[name] for name in node_t.names]
        T, N = len(tasks), len(node_infos)
        any_taints = any(n.taints for n in node_infos)   # O(N), once
        any_unsched = any(n.unschedulable for n in node_infos)
        if (not any_taints and not any_unsched
                and not any(t.node_selector or t.affinity for t in tasks)):
            return None                                  # all-true mask
        mask = np.ones((T, N), dtype=bool)
        sched = np.asarray([not n.unschedulable for n in node_infos], dtype=bool)
        mask &= sched[None, :]
        for ti, task in enumerate(tasks):
            if not task.node_selector and not task.affinity and not any_taints:
                continue
            for ni, node in enumerate(node_infos):
                if not mask[ti, ni]:
                    continue
                if self.node_affinity_enable and not node_selector_ok(task, node):
                    mask[ti, ni] = False
                elif self.taint_enable and not taints_tolerated(task, node):
                    mask[ti, ni] = False
        return mask

    def on_session_open(self, ssn) -> None:
        ssn.add_predicate_fn(self.NAME, self.predicate)
        ssn.add_feasibility_fn(self.NAME, self.feasibility_mask)


class PredicateError(ValueError):
    def __init__(self, task, node, reason: str):
        super().__init__(f"task {task.key()} on node {node.name}: {reason}")
        self.fit_error = FitError(task, node, [reason])


def New(arguments):
    return PredicatesPlugin(arguments)
