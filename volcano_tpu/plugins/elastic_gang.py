"""elastic-gang plugin: session policy for min/desired gangs.

Installs the four host-side hooks that make elastic membership a policy
every engine respects (the tensor-path victim tier lives in
actions/evict_tpu.py; the lifecycle funnel in elastic_gang/commands.py):

- ``ssn.elastic_pending_filter`` — narrows the pending set the allocate
  engines (and preempt's pending collection) see, so elastic gangs bid
  for exactly ``min`` at admission and never preempt on behalf of
  surplus members (allocate._pending_tasks reads the attribute);
- job_valid — a suspended gang is not schedulable this cycle;
- preemptable/reclaimable — above-min members of elastic gangs are
  offered as victims ONLY up to the per-job shrink allowance (highest
  uid first), so no host preempt/reclaim decision can drag a gang below
  min without a full-gang decision;
- node_order — a compactness bonus for nodes in a zone where the task's
  gang already holds members: the host mirror of the batched solver's
  anchor term (ops/place.py place_scan_topo), and what steers the
  grow-shrink placer into the gang's anchor zone.

Arguments: ``topology-weight`` (float, default 10.0) scales the
node_order bonus; 0 disables it.
"""

from __future__ import annotations

from ..api import TaskStatus
from ..elastic_gang.membership import (allocate_pending_filter, is_elastic,
                                       is_suspended, shrink_allowance)
from ..framework.session import PERMIT, ValidateResult
from .base import Plugin

SUSPENDED = "Suspended"


def _member_zones(ssn, job) -> set:
    """Zones where the gang currently holds capacity — its anchor set."""
    zones = set()
    for status in (TaskStatus.BOUND, TaskStatus.RUNNING,
                   TaskStatus.BINDING, TaskStatus.ALLOCATED):
        for t in job.task_status_index.get(status, {}).values():
            node = ssn.nodes.get(t.node_name)
            if node is not None and node.topology_zone:
                zones.add(node.topology_zone)
    return zones


class ElasticGangPlugin(Plugin):
    NAME = "elastic-gang"

    def on_session_open(self, ssn) -> None:
        args = self.arguments or {}
        try:
            topo_weight = float(args.get("topology-weight", 10.0))
        except (TypeError, ValueError):
            topo_weight = 10.0

        # the allocate-engine hook: THE decision-class switch. Absent
        # (plugin disabled) every engine is byte-identical to pre-elastic.
        ssn.elastic_pending_filter = allocate_pending_filter

        def job_valid(job):
            if is_elastic(job) and is_suspended(job):
                return ValidateResult(
                    False, SUSPENDED,
                    "gang is suspended by lifecycle command")
            return None

        ssn.add_job_valid_fn(self.NAME, job_valid)

        def preemptable(preemptor, preemptees):
            """Cap elastic victims at each gang's shrink allowance so no
            preempt/reclaim decision evicts below min. Victims per gang
            are its highest-uid members — the same order grow-shrink
            sheds them — keeping host and device paths convergent."""
            by_job = {}
            for t in preemptees:
                by_job.setdefault(t.job, []).append(t)
            victims = []
            for uid, tasks in by_job.items():
                job = ssn.jobs.get(uid)
                if job is None or not is_elastic(job):
                    victims.extend(tasks)
                    continue
                if is_suspended(job):
                    # a suspended gang is already draining through the
                    # full-gang funnel; don't double-claim its members
                    continue
                allow = shrink_allowance(job)
                if allow <= 0:
                    continue
                tasks = sorted(tasks, key=lambda t: t.uid, reverse=True)
                victims.extend(tasks[:allow])
            return victims, PERMIT

        ssn.add_preemptable_fn(self.NAME, preemptable)
        ssn.add_reclaimable_fn(self.NAME, preemptable)

        if topo_weight > 0.0:
            # binpack-style scaling: the bonus rides the MAX_NODE_SCORE
            # scale (nodeorder's terms each span ~0-100), so the default
            # weight 10 yields a 1000-point anchor pull that dominates
            # spread/packing preferences without silencing predicates
            bonus = topo_weight * 100.0

            def node_order(task, node):
                if not node.topology_zone:
                    return 0.0
                job = ssn.jobs.get(task.job)
                if job is None:
                    return 0.0
                zones = _member_zones(ssn, job)
                if not zones:
                    return 0.0
                return bonus if node.topology_zone in zones else 0.0

            ssn.add_node_order_fn(self.NAME, node_order)


def New(arguments):
    return ElasticGangPlugin(arguments)
