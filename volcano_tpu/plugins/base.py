"""Plugin base interface (mirrors
/root/reference/pkg/scheduler/framework/interface.go:34-41)."""

from __future__ import annotations

from ..framework.arguments import Arguments


class Plugin:
    NAME = "base"

    def __init__(self, arguments: Arguments = None):
        self.arguments = arguments or Arguments()

    def name(self) -> str:
        return self.NAME

    def on_session_open(self, ssn) -> None:
        raise NotImplementedError

    def on_session_close(self, ssn) -> None:
        pass
