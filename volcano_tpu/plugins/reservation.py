"""Reservation plugin: target-job election + node locking.

Mirrors /root/reference/pkg/scheduler/plugins/reservation/reservation.go:44-141.
"""

from __future__ import annotations

import time

from ..utils.reservation import Reservation
from .base import Plugin


class ReservationPlugin(Plugin):
    NAME = "reservation"

    def on_session_open(self, ssn) -> None:
        def target_job_fn(jobs):
            """Highest priority, then the longest-waiting job by
            ScheduleStartTimestamp (reservation.go:66-117 getTargetJob:
            max now-minus-start = min start; ties keep the earlier
            candidate in list order like the reference's strict > compare)."""
            if not jobs:
                return None
            highest = max(j.priority for j in jobs)
            candidates = [j for j in jobs if j.priority == highest]
            return min(candidates,
                       key=lambda j: (j.schedule_start_timestamp
                                      if j.schedule_start_timestamp
                                      is not None else j.creation_timestamp))

        ssn.add_target_job_fn(self.NAME, target_job_fn)

        def reserved_nodes_fn():
            """Lock the unlocked node with the most idle resources
            (reservation.go:120-141)."""
            best = None
            for node in ssn.nodes.values():
                if node.name in Reservation.locked_nodes:
                    continue
                if best is None or best.idle.less_equal(node.idle):
                    best = node
            if best is not None:
                Reservation.locked_nodes[best.name] = best

        ssn.add_reserved_nodes_fn(self.NAME, reserved_nodes_fn)


def New(arguments):
    return ReservationPlugin(arguments)
