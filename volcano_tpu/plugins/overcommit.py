"""Overcommit plugin: admit jobs into the queue beyond physical capacity by
an overcommit factor.

Mirrors /root/reference/pkg/scheduler/plugins/overcommit/overcommit.go:50-125.
"""

from __future__ import annotations

from ..api import PodGroupPhase, Resource
from ..framework.session import PERMIT, REJECT
from .base import Plugin

DEFAULT_OVERCOMMIT_FACTOR = 1.2


class OvercommitPlugin(Plugin):
    NAME = "overcommit"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.factor = self.arguments.get_float("overcommit-factor",
                                               DEFAULT_OVERCOMMIT_FACTOR)
        if self.factor < 1.0:
            self.factor = DEFAULT_OVERCOMMIT_FACTOR
        self.idle = Resource()
        self.inqueue = Resource()

    def on_session_open(self, ssn) -> None:
        total, used = Resource(), Resource()
        for node in ssn.nodes.values():
            total.add(node.allocatable)
            used.add(node.used)
        self.idle = total.clone().multi(self.factor).sub(used)

        self.inqueue = Resource()
        for job in ssn.jobs.values():
            if (job.podgroup.phase == PodGroupPhase.INQUEUE
                    and job.podgroup.min_resources is not None):
                self.inqueue.add(job.get_min_resources())

        def job_enqueueable(job) -> int:
            if job.podgroup.min_resources is None:
                return PERMIT
            job_min = job.get_min_resources()
            if self.inqueue.clone().add(job_min).less_equal(self.idle):
                self.inqueue.add(job_min)
                return PERMIT
            return REJECT

        ssn.add_job_enqueueable_fn(self.NAME, job_enqueueable)

    def on_session_close(self, ssn) -> None:
        self.idle = Resource()
        self.inqueue = Resource()


def New(arguments):
    return OvercommitPlugin(arguments)
