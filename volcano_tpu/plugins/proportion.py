"""Proportion plugin: weighted queue fair-share (deserved) via water-filling.

Mirrors /root/reference/pkg/scheduler/plugins/proportion/proportion.go:69-325.
The deserved computation runs as the ops.fairness.proportion_deserved JAX
kernel over f32[Q,R] arrays — the vectorized form of the reference's
iterate-until-stable loop (proportion.go:132-196).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .. import metrics
from ..api import (PodGroupPhase, Resource, ResourceNames, TaskStatus,
                   allocated_status)
from ..framework.session import PERMIT, REJECT, EventHandler
from .base import Plugin


class _QueueAttr:
    """share is recomputed lazily: allocate/deallocate events are hot (one
    per task per cycle) while share is only read when queues are ordered."""

    def __init__(self, uid: str, name: str, weight: int):
        self.uid = uid
        self.name = name
        self.weight = weight
        self.deserved = Resource()
        self.allocated = Resource()
        self.request = Resource()
        self.inqueue = Resource()
        self.capability: Resource = None
        self._share = 0.0
        self._share_dirty = True

    @property
    def share(self) -> float:
        if self._share_dirty:
            self._share = _share(self.allocated, self.deserved)
            self._share_dirty = False
        return self._share


def _share(allocated: Resource, deserved: Resource) -> float:
    res = 0.0
    for name in deserved.resource_names():
        d, a = deserved.get(name), allocated.get(name)
        if d > 0:
            res = max(res, a / d)
        elif a > 0:
            res = max(res, 1.0)
    return res


class ProportionPlugin(Plugin):
    NAME = "proportion"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.total = Resource()
        self.queue_opts: Dict[str, _QueueAttr] = {}

    # below this queue count the numpy twin of the water-filling kernel is
    # used — identical semantics, no first-cycle device compile
    DEVICE_MIN_QUEUES = 64

    def on_session_open(self, ssn) -> None:
        from ..ops.fairness import (proportion_deserved,
                                    proportion_deserved_numpy)

        for node in ssn.nodes.values():
            self.total.add(node.allocatable)

        for job in ssn.jobs.values():
            if job.queue not in ssn.queues:
                continue
            queue = ssn.queues[job.queue]
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                attr = _QueueAttr(queue.uid, queue.name, queue.weight)
                attr.capability = queue.capability
                self.queue_opts[job.queue] = attr
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        attr.allocated.add(t.resreq)
                        attr.request.add(t.resreq)
                elif status == TaskStatus.PENDING:
                    for t in tasks.values():
                        attr.request.add(t.resreq)
            if job.podgroup.phase == PodGroupPhase.INQUEUE:
                attr.inqueue.add(job.get_min_resources())

        # -- deserved water-filling on device (proportion.go:132-196) -------
        if self.queue_opts:
            attrs = list(self.queue_opts.values())
            rnames = ResourceNames.discover(
                [self.total] + [a.request for a in attrs]
                + [a.capability for a in attrs if a.capability is not None])
            Q, R = len(attrs), len(rnames)
            total_v = self.total.to_vector(rnames)
            weight_v = np.asarray([a.weight for a in attrs], np.float32)
            request_v = np.stack([a.request.to_vector(rnames) for a in attrs])
            cap_v = np.stack([
                a.capability.to_vector_inf_fill(rnames) if a.capability is not None
                else np.full(R, np.inf, np.float32) for a in attrs])
            alloc_v = np.stack([a.allocated.to_vector(rnames) for a in attrs])
            if len(attrs) < self.DEVICE_MIN_QUEUES:
                res = proportion_deserved_numpy(total_v, weight_v, request_v,
                                                cap_v, alloc_v)
            else:
                import jax.numpy as jnp
                res = proportion_deserved(
                    jnp.asarray(total_v), jnp.asarray(weight_v),
                    jnp.asarray(request_v), jnp.asarray(cap_v),
                    jnp.asarray(alloc_v))
            deserved = np.asarray(res.deserved)
            for i, attr in enumerate(attrs):
                attr.deserved = Resource.from_vector(deserved[i], rnames)
                attr._share_dirty = True
                # expose deserved to the device reclaim engine's
                # proportion-tier replay (actions/evict_tpu.py)
                ssn.queue_deserved[attr.name] = attr.deserved
                metrics.update_queue_metrics(
                    attr.name, attr.allocated.cpu, attr.allocated.memory,
                    attr.deserved.cpu, attr.deserved.memory, attr.share,
                    attr.weight)

        def queue_order(l, r) -> int:
            la = self.queue_opts.get(l.uid)
            ra = self.queue_opts.get(r.uid)
            ls = la.share if la else 0.0
            rs = ra.share if ra else 0.0
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_queue_order_fn(self.NAME, queue_order)

        def reclaimable(reclaimer, reclaimees):
            """Victims from queues allocated above deserved
            (proportion.go:246-271)."""
            victims = []
            allocations: Dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs[reclaimee.job]
                attr = self.queue_opts.get(job.queue)
                if attr is None:
                    continue
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                if not allocated.less_equal(attr.deserved):
                    allocated.sub(reclaimee.resreq)
                    victims.append(reclaimee)
            return victims, PERMIT

        ssn.add_reclaimable_fn(self.NAME, reclaimable)

        def overused(queue) -> bool:
            """allocated exceeds deserved in ANY dimension
            (proportion.go:244: !allocated.LessEqualInAllDimension(deserved))."""
            attr = self.queue_opts.get(queue.uid)
            if attr is None:
                return False
            return not attr.allocated.less_equal(attr.deserved)

        ssn.add_overused_fn(self.NAME, overused)

        def job_enqueueable(job) -> int:
            """minResources-vs-capability gate (proportion.go:273-299)."""
            queue = ssn.queues.get(job.queue)
            attr = self.queue_opts.get(job.queue)
            if queue is None or attr is None:
                return PERMIT
            if queue.capability is None:
                return PERMIT
            if job.podgroup.min_resources is None:
                return PERMIT
            min_req = job.get_min_resources()
            total_would = min_req.clone().add(attr.allocated).add(attr.inqueue)
            from ..api.resource import INFINITY
            if total_would.less_equal(queue.capability, INFINITY):
                attr.inqueue.add(job.get_min_resources())
                return PERMIT
            return REJECT

        ssn.add_job_enqueueable_fn(self.NAME, job_enqueueable)

        def on_allocate(event):
            job = ssn.jobs[event.task.job]
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                return
            attr.allocated.add(event.task.resreq)
            attr._share_dirty = True

        def on_deallocate(event):
            job = ssn.jobs[event.task.job]
            attr = self.queue_opts.get(job.queue)
            if attr is None:
                return
            attr.allocated.sub(event.task.resreq)
            attr._share_dirty = True

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate,
                                           deallocate_func=on_deallocate,
                                           aggregatable=True))

    def on_session_close(self, ssn) -> None:
        # flush final queue gauges once per cycle (the reference updates them
        # per event; same end-of-cycle values, far cheaper)
        for attr in self.queue_opts.values():
            metrics.update_queue_metrics(
                attr.name, attr.allocated.cpu, attr.allocated.memory,
                attr.deserved.cpu, attr.deserved.memory, attr.share,
                attr.weight)
        self.total = Resource()
        self.queue_opts = {}


def New(arguments):
    return ProportionPlugin(arguments)
