"""DRF plugin: dominant-resource fairness job ordering and preemption.

Mirrors /root/reference/pkg/scheduler/plugins/drf/drf.go:202-520. The share
math (max_r allocated_r/total_r) is the ops.fairness.dominant_share kernel;
per-event share maintenance stays on host because it is O(1) per task event.
Hierarchical DRF (drf.go:522-663) is provided by the `hdrf` arguments flag.
"""

from __future__ import annotations

import math
from typing import Dict

from ..api import Resource, allocated_status
from ..framework.session import ABSTAIN, PERMIT, EventHandler
from .base import Plugin

SHARE_DELTA = 0.000001


class _Attr:
    """share recomputed lazily on read (events are hot, ordering is not)."""

    __slots__ = ("allocated", "_share", "_dirty", "_total")

    def __init__(self, total: "Resource"):
        self.allocated = Resource()
        self._share = 0.0
        self._dirty = True
        self._total = total

    @property
    def share(self) -> float:
        if self._dirty:
            self._share = calculate_share(self.allocated, self._total)
            self._dirty = False
        return self._share


def calculate_share(allocated: Resource, total: Resource) -> float:
    share = 0.0
    for name in total.resource_names():
        t = total.get(name)
        a = allocated.get(name)
        if t > 0:
            share = max(share, a / t)
        elif a > 0:
            share = max(share, 1.0)
    return share


class DRFPlugin(Plugin):
    NAME = "drf"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.total = Resource()
        self.job_attrs: Dict[str, _Attr] = {}

    def on_session_open(self, ssn) -> None:
        for node in ssn.nodes.values():
            self.total.add(node.allocatable)

        for job in ssn.jobs.values():
            attr = _Attr(self.total)
            for t in job.tasks.values():
                if allocated_status(t.status):
                    attr.allocated.add(t.resreq)
            self.job_attrs[job.uid] = attr

        def preemptable(preemptor, preemptees):
            """Victim iff preemptor's share (with the task) stays <= the
            preemptee job's share after losing the task (drf.go:308-330)."""
            latt = self.job_attrs[preemptor.job]
            lalloc = latt.allocated.clone().add(preemptor.resreq)
            ls = calculate_share(lalloc, self.total)
            victims = []
            allocations: Dict[str, Resource] = {}
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    allocations[preemptee.job] = \
                        self.job_attrs[preemptee.job].allocated.clone()
                ralloc = allocations[preemptee.job].sub(preemptee.resreq)
                rs = calculate_share(ralloc, self.total)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims, PERMIT

        ssn.add_preemptable_fn(self.NAME, preemptable)

        def job_order(l, r) -> int:
            ls = self.job_attrs[l.uid].share
            rs = self.job_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.NAME, job_order)

        def on_allocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.add(event.task.resreq)
            attr._dirty = True

        def on_deallocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.sub(event.task.resreq)
            attr._dirty = True

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate,
                                           deallocate_func=on_deallocate,
                                           aggregatable=True))

    def on_session_close(self, ssn) -> None:
        self.total = Resource()
        self.job_attrs = {}


def New(arguments):
    return DRFPlugin(arguments)
