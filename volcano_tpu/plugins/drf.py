"""DRF plugin: dominant-resource fairness job ordering and preemption,
plus hierarchical DRF (weighted queue tree with saturation rescaling) and
weighted namespace fairness.

Mirrors /root/reference/pkg/scheduler/plugins/drf/drf.go:
- classic job-level DRF (dominant share = max_r allocated_r/total_r),
  job order + preemptable + event handlers (drf.go:202-520);
- hierarchical DRF (drf.go:522-663): queues carry slash-separated
  ``volcano.sh/hierarchy`` paths with per-level weights; shares propagate
  bottom-up with min-dominant-resource rescaling and saturation (a node is
  saturated when a resource it requests is fully allocated or no longer
  demanding), driving QueueOrderFn and the hierarchy-mode ReclaimableFn;
- weighted namespace fairness (drf.go:431-466): NamespaceOrderFn by
  share/weight, enabled by the ``enabledNamespaceOrder`` flag.

Hierarchy and namespace order are OFF unless explicitly enabled in the
conf tier (the reference requires an explicit true, drf.go:144-168).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from ..api import Resource, allocated_status
from ..framework.session import ABSTAIN, PERMIT, EventHandler
from .base import Plugin

SHARE_DELTA = 0.000001


class _Attr:
    """share recomputed lazily on read (events are hot, ordering is not)."""

    __slots__ = ("allocated", "_share", "_dirty", "_total")

    def __init__(self, total: "Resource"):
        self.allocated = Resource()
        self._share = 0.0
        self._dirty = True
        self._total = total

    @property
    def share(self) -> float:
        if self._dirty:
            self._share = calculate_share(self.allocated, self._total)
            self._dirty = False
        return self._share


def calculate_share(allocated: Resource, total: Resource) -> float:
    share = 0.0
    for name in total.resource_names():
        t = total.get(name)
        a = allocated.get(name)
        if t > 0:
            share = max(share, a / t)
        elif a > 0:
            share = max(share, 1.0)
    return share


class _HNode:
    """hierarchicalNode (drf.go:41-77): one level of the weighted queue
    tree. Leaves are jobs (request = job total request); interior nodes
    aggregate children with min-dominant-share rescaling."""

    __slots__ = ("parent", "allocated", "share", "request", "weight",
                 "saturated", "hierarchy", "children")

    def __init__(self, hierarchy: str, weight: float = 1.0,
                 request: Optional[Resource] = None, leaf: bool = False):
        self.parent: Optional[_HNode] = None
        self.allocated = Resource()
        self.share = 0.0
        self.request = request if request is not None else Resource()
        self.weight = weight
        self.saturated = False
        self.hierarchy = hierarchy
        self.children: Optional[Dict[str, _HNode]] = None if leaf else {}

    def clone(self, parent: Optional["_HNode"] = None) -> "_HNode":
        n = _HNode(self.hierarchy, self.weight, self.request.clone(),
                   leaf=self.children is None)
        n.parent = parent
        n.allocated = self.allocated.clone()
        n.share = self.share
        n.saturated = self.saturated
        if self.children is not None:
            n.children = {k: c.clone(n) for k, c in self.children.items()}
        return n


def _resource_saturated(allocated: Resource, request: Resource,
                        demanding: Dict[str, bool]) -> bool:
    """drf.go:79-94: a job is saturated when a requested resource is fully
    allocated to it, or it requests a resource that is no longer demanding
    (cluster-wide fully allocated)."""
    for name in allocated.resource_names():
        a, r = allocated.get(name), request.get(name)
        if a != 0 and r != 0 and a >= r:
            return True
        if not demanding.get(name, False) and r != 0:
            return True
    return False


class DRFPlugin(Plugin):
    NAME = "drf"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.total = Resource()
        self.total_allocated = Resource()
        self.job_attrs: Dict[str, _Attr] = {}
        self.namespace_opts: Dict[str, _Attr] = {}
        self.root = _HNode("root", 1.0)

    # -- feature flags (explicit true required, drf.go:144-168) -------------

    def _flag_enabled(self, ssn, flag: str) -> bool:
        for tier in ssn.tiers:
            for opt in tier.plugins:
                if opt.name == self.NAME:
                    return opt.enabled.get(flag, False)
        return False

    # -- hierarchy maintenance (drf.go:527-633) ------------------------------

    def _build_hierarchy(self, root: _HNode, job, hierarchy: str,
                         weights: str) -> None:
        inode = root
        paths = hierarchy.split("/")
        wparts = weights.split("/")
        for i in range(1, len(paths)):
            child = inode.children.get(paths[i])
            if child is None:
                try:
                    w = float(wparts[i]) if i < len(wparts) else 1.0
                except ValueError:
                    w = 1.0
                child = _HNode(paths[i], max(w, 1.0))
                child.parent = inode
                inode.children[paths[i]] = child
            inode = child
        leaf = _HNode(job.uid, 1.0, job.total_request.clone(), leaf=True)
        leaf.parent = inode
        inode.children[job.uid] = leaf

    def _leaf_attr(self, root: _HNode, job_uid: str) -> Optional[_HNode]:
        stack = [root]
        while stack:
            n = stack.pop()
            if n.children is None:
                if n.hierarchy == job_uid:
                    return n
                continue
            stack.extend(n.children.values())
        return None

    def _update_hierarchical_share(self, node: _HNode,
                                   demanding: Dict[str, bool],
                                   job_alloc: Dict[str, Resource]) -> None:
        if node.children is None:
            alloc = job_alloc.get(node.hierarchy)
            if alloc is not None:
                node.allocated = alloc.clone()
            node.share = calculate_share(node.allocated, self.total)
            node.saturated = _resource_saturated(node.allocated,
                                                 node.request, demanding)
            return
        mdr = 1.0
        for child in node.children.values():
            self._update_hierarchical_share(child, demanding, job_alloc)
            if child.share != 0 and not child.saturated:
                mdr = min(mdr, calculate_share(child.allocated, self.total))
        node.allocated = Resource()
        saturated = True
        for child in node.children.values():
            if not child.saturated:
                saturated = False
            if child.share != 0:
                if child.saturated:
                    node.allocated.add(child.allocated)
                else:
                    node.allocated.add(
                        child.allocated.clone().multi(mdr / child.share))
        node.share = calculate_share(node.allocated, self.total)
        node.saturated = saturated

    def _demanding(self, total_allocated: Resource) -> Dict[str, bool]:
        return {name: total_allocated.get(name) < self.total.get(name)
                for name in self.total.resource_names()}

    def _refresh_tree(self, root: _HNode, total_allocated: Resource,
                      job_alloc: Dict[str, Resource]) -> None:
        self._update_hierarchical_share(root, self._demanding(total_allocated),
                                        job_alloc)

    def _compare_queues(self, root: _HNode, lq, rq) -> float:
        """drf.go compareQueues: walk both paths level by level; saturated
        nodes sort last, then weighted share."""
        lnode, rnode = root, root
        lpaths = lq.hierarchy.split("/")
        rpaths = rq.hierarchy.split("/")
        depth = min(len(lpaths), len(rpaths))
        for i in range(depth):
            if not lnode.saturated and rnode.saturated:
                return -1.0
            if lnode.saturated and not rnode.saturated:
                return 1.0
            lw = lnode.share / lnode.weight
            rw = rnode.share / rnode.weight
            if lw == rw:
                if i < depth - 1:
                    lnode = (lnode.children or {}).get(lpaths[i + 1])
                    rnode = (rnode.children or {}).get(rpaths[i + 1])
                    if lnode is None or rnode is None:
                        return 0.0
            else:
                return lw - rw
        return 0.0

    # -- session wiring ------------------------------------------------------

    def on_session_open(self, ssn) -> None:
        for node in ssn.nodes.values():
            self.total.add(node.allocatable)

        namespace_order = self._flag_enabled(ssn, "enabledNamespaceOrder")
        hierarchy = self._flag_enabled(ssn, "enabledHierarchy")

        for job in ssn.jobs.values():
            attr = _Attr(self.total)
            for t in job.tasks.values():
                if allocated_status(t.status):
                    attr.allocated.add(t.resreq)
            self.job_attrs[job.uid] = attr
            if namespace_order:
                ns = self.namespace_opts.setdefault(job.namespace,
                                                    _Attr(self.total))
                ns.allocated.add(attr.allocated)
                ns._dirty = True
            if hierarchy:
                queue = ssn.queues.get(job.queue)
                if queue is not None and queue.hierarchy:
                    self.total_allocated.add(attr.allocated)
                    self._build_hierarchy(self.root, job, queue.hierarchy,
                                          queue.hierarchy_weights)
        if hierarchy:
            self._refresh_tree(self.root, self.total_allocated,
                               self._job_alloc_map())

        def preemptable(preemptor, preemptees):
            """Victim iff preemptor's share (with the task) stays <= the
            preemptee job's share after losing the task (drf.go:308-330)."""
            latt = self.job_attrs[preemptor.job]
            lalloc = latt.allocated.clone().add(preemptor.resreq)
            ls = calculate_share(lalloc, self.total)
            victims = []
            allocations: Dict[str, Resource] = {}
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    allocations[preemptee.job] = \
                        self.job_attrs[preemptee.job].allocated.clone()
                ralloc = allocations[preemptee.job].sub(preemptee.resreq)
                rs = calculate_share(ralloc, self.total)
                if ls < rs or abs(ls - rs) <= SHARE_DELTA:
                    victims.append(preemptee)
            return victims, PERMIT

        ssn.add_preemptable_fn(self.NAME, preemptable)

        if hierarchy:
            def queue_order(l, r) -> int:
                ret = self._compare_queues(self.root, l, r)
                if ret < 0:
                    return -1
                if ret > 0:
                    return 1
                return 0

            ssn.add_queue_order_fn(self.NAME, queue_order)

            def hdrf_reclaimable(reclaimer, reclaimees):
                """drf.go:349-414: simulate the tree with the reclaimer's
                task added and each reclaimee's removed; victim iff the
                reclaimer's queue then orders strictly first."""
                victims = []
                total_allocated = self.total_allocated.clone()
                root = self.root.clone()
                ljob = ssn.jobs[reclaimer.job]
                lqueue = ssn.queues[ljob.queue]
                job_alloc = self._job_alloc_map()
                job_alloc[ljob.uid] = (
                    job_alloc.get(ljob.uid, Resource()).clone()
                    .add(reclaimer.resreq))
                total_allocated.add(reclaimer.resreq)
                self._refresh_tree(root, total_allocated, job_alloc)

                for preemptee in reclaimees:
                    rjob = ssn.jobs[preemptee.job]
                    rqueue = ssn.queues[rjob.queue]
                    total_allocated.sub(preemptee.resreq)
                    saved = job_alloc.get(rjob.uid, Resource()).clone()
                    job_alloc[rjob.uid] = saved.clone().sub(preemptee.resreq)
                    self._refresh_tree(root, total_allocated, job_alloc)
                    ret = self._compare_queues(root, lqueue, rqueue)
                    # resume
                    total_allocated.add(preemptee.resreq)
                    job_alloc[rjob.uid] = saved
                    self._refresh_tree(root, total_allocated, job_alloc)
                    if ret < 0:
                        victims.append(preemptee)
                return victims, PERMIT

            ssn.add_reclaimable_fn(self.NAME, hdrf_reclaimable)

        def job_order(l, r) -> int:
            ls = self.job_attrs[l.uid].share
            rs = self.job_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.NAME, job_order)

        if namespace_order:
            def namespace_order_fn(l, r) -> int:
                from ..api.queue_info import DEFAULT_NAMESPACE_WEIGHT
                lw = (ssn.namespaces[l].get_weight()
                      if l in ssn.namespaces else DEFAULT_NAMESPACE_WEIGHT)
                rw = (ssn.namespaces[r].get_weight()
                      if r in ssn.namespaces else DEFAULT_NAMESPACE_WEIGHT)
                lo = self.namespace_opts.setdefault(l, _Attr(self.total))
                ro = self.namespace_opts.setdefault(r, _Attr(self.total))
                lws = lo.share / lw
                rws = ro.share / rw
                if lws == rws:
                    return 0
                return -1 if lws < rws else 1

            ssn.add_namespace_order_fn(self.NAME, namespace_order_fn)

        def on_allocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.add(event.task.resreq)
            attr._dirty = True
            job = ssn.jobs.get(event.task.job)
            if namespace_order and job is not None:
                ns = self.namespace_opts.setdefault(job.namespace,
                                                    _Attr(self.total))
                ns.allocated.add(event.task.resreq)
                ns._dirty = True
            if hierarchy and job is not None:
                self.total_allocated.add(event.task.resreq)
                self._refresh_tree(self.root, self.total_allocated,
                                   self._job_alloc_map())

        def on_deallocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.sub(event.task.resreq)
            attr._dirty = True
            job = ssn.jobs.get(event.task.job)
            if namespace_order and job is not None:
                ns = self.namespace_opts.setdefault(job.namespace,
                                                    _Attr(self.total))
                ns.allocated.sub(event.task.resreq)
                ns._dirty = True
            if hierarchy and job is not None:
                self.total_allocated.sub(event.task.resreq)
                self._refresh_tree(self.root, self.total_allocated,
                                   self._job_alloc_map())

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate,
                                           deallocate_func=on_deallocate,
                                           aggregatable=True))

    def _job_alloc_map(self) -> Dict[str, Resource]:
        return {uid: attr.allocated for uid, attr in self.job_attrs.items()}

    def on_session_close(self, ssn) -> None:
        self.total = Resource()
        self.total_allocated = Resource()
        self.job_attrs = {}
        self.namespace_opts = {}
        self.root = _HNode("root", 1.0)


def New(arguments):
    return DRFPlugin(arguments)
