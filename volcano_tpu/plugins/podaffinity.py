"""Inter-pod affinity/anti-affinity as precomputed pairwise tensors.

Replaces the reference's per-(task, node) k8s InterPodAffinity filter
(/root/reference/pkg/scheduler/plugins/predicates/predicates.go:330-338)
and batch scorer (nodeorder.go:269-340) with a TPU-first design (SURVEY §7
"precompute pairwise masks on host, ship as bitmask tensors"):

- nodes partition into topology DOMAINS per topologyKey; every affinity
  term reduces to "does a matching existing pod live in this domain" — a
  bool per (term, domain) computed once, broadcast to a node vector;
- required podAffinity terms AND-combine, required podAntiAffinity terms
  (and their SYMMETRIC form: existing pods' anti-affinity rejecting the
  incoming task) NAND-combine into the ``feas[T,N]`` mask the placement
  kernels consume;
- preferred terms become a ``score[T,N]`` matrix: weight x count of
  matching existing pods in the node's domain (k8s
  NodeInterPodAffinityPriority's core), normalized to [0,100] like the k8s
  scorer before the plugin weight is applied.

In-cycle placements change the existing-pod set mid-action; like the GPU
card predicate, the plugin registers itself stateful so batched engines
re-validate proposals against the live host predicate.

Pod affinity spec shape follows the k8s API (dict form):
  {"podAffinity": {"requiredDuringSchedulingIgnoredDuringExecution":
       [{"labelSelector": {...}, "topologyKey": "...",
         "namespaces": [...]}, ...],
    "preferredDuringSchedulingIgnoredDuringExecution":
       [{"weight": W, "podAffinityTerm": {...}}, ...]},
   "podAntiAffinity": {...same...}}
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

REQUIRED = "requiredDuringSchedulingIgnoredDuringExecution"
PREFERRED = "preferredDuringSchedulingIgnoredDuringExecution"
MAX_NODE_SCORE = 100.0


def match_label_selector(selector: dict, labels: Dict[str, str]) -> bool:
    """k8s metav1.LabelSelector: matchLabels AND matchExpressions
    (In/NotIn/Exists/DoesNotExist)."""
    if not selector:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key = expr.get("key")
        op = expr.get("operator")
        values = expr.get("values") or []
        if op == "In":
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if labels.get(key) in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            return False
    return True


def _term_namespaces(term: dict, own_namespace: str) -> List[str]:
    """A term with no namespaces list applies to the pod's own namespace."""
    return term.get("namespaces") or [own_namespace]


def _affinity_terms(task, kind: str, required: bool):
    aff = task.affinity or {}
    section = aff.get(kind) or {}
    if required:
        return section.get(REQUIRED) or []
    return section.get(PREFERRED) or []


def has_pod_affinity(task) -> bool:
    # TaskInfo memoizes this at build time (affinity is immutable after
    # construction and clones carry the flag), turning the every-cycle
    # whole-session scan into attribute reads; the fallback covers
    # task-like objects built outside TaskInfo.__init__
    cached = getattr(task, "_has_pod_affinity", None)
    if cached is None:
        cached = bool(_affinity_terms(task, "podAffinity", True)
                      or _affinity_terms(task, "podAntiAffinity", True)
                      or _affinity_terms(task, "podAffinity", False)
                      or _affinity_terms(task, "podAntiAffinity", False))
        try:
            task._has_pod_affinity = cached
        except AttributeError:
            pass
    return cached


class PodAffinityIndex:
    """Per-session topology/pod index for vectorized affinity evaluation.

    Live-updated through session allocate/deallocate events so the host
    predicate sees in-cycle placements (the reference's EventHandler-fed
    k8s nodeMap, predicates.go:80-110)."""

    def __init__(self, nodes: List):
        self.nodes = nodes
        self.node_index = {n.name: i for i, n in enumerate(nodes)}
        self._domains: Dict[str, Tuple[np.ndarray, Dict[str, int]]] = {}
        # existing (running/placed) pods: (task, node index)
        self.existing: List[Tuple[object, int]] = []
        for ni, node in enumerate(nodes):
            for t in node.tasks.values():
                self.existing.append((t, ni))
        self._mask_cache: Dict[str, Optional[np.ndarray]] = {}

    def add_pod(self, task) -> None:
        # order-simulation pseudo-events (_AggTask) carry no placement
        ni = self.node_index.get(getattr(task, "node_name", None))
        if ni is not None:
            self.existing.append((task, ni))
            self._mask_cache.clear()

    def remove_pod(self, task) -> None:
        uid = getattr(task, "uid", None)
        if uid is None:
            return
        self.existing = [(t, ni) for t, ni in self.existing if t.uid != uid]
        self._mask_cache.clear()

    def node_mask_cached(self, task) -> Optional[np.ndarray]:
        if task.uid not in self._mask_cache:
            self._mask_cache[task.uid] = self.node_mask(task)
        return self._mask_cache[task.uid]

    def domains(self, key: str) -> Tuple[np.ndarray, int]:
        """(dom i32[N], n_domains): the node partition for a topologyKey.
        Nodes missing the label form their own singleton domains (a node
        without the topology label can never co-locate)."""
        cached = self._domains.get(key)
        if cached is not None:
            return cached
        values: Dict[str, int] = {}
        dom = np.zeros(len(self.nodes), np.int32)
        next_ix = 0
        for i, node in enumerate(self.nodes):
            val = node.labels.get(key)
            if val is None:
                dom[i] = next_ix
                next_ix += 1
            else:
                if val not in values:
                    values[val] = next_ix
                    next_ix += 1
                dom[i] = values[val]
        self._domains[key] = (dom, next_ix)
        return self._domains[key]

    def _term_domain_counts(self, term: dict, namespaces: List[str],
                            exclude_uid: Optional[str] = None) -> np.ndarray:
        """count of matching existing pods per domain of term.topologyKey."""
        key = term.get("topologyKey") or "kubernetes.io/hostname"
        dom, nd = self.domains(key)
        counts = np.zeros(nd, np.int64)
        selector = term.get("labelSelector") or {}
        nsset = set(namespaces)
        for t, ni in self.existing:
            if t.uid == exclude_uid:
                continue
            if t.namespace not in nsset:
                continue
            if match_label_selector(selector, t.labels):
                counts[dom[ni]] += 1
        return counts[dom]          # broadcast back to a per-node vector

    # -- required terms -> feasibility --------------------------------------

    def node_mask(self, task) -> Optional[np.ndarray]:
        """bool[N] required-term feasibility for one task; None = all-true."""
        masks = []
        aff = [(term, _term_namespaces(term, task.namespace))
               for term in _affinity_terms(task, "podAffinity", True)]
        counts = [self._term_domain_counts(term, ns) for term, ns in aff]
        # k8s bootstrap allowance (upstream InterPodAffinity Filter): only
        # when NO existing pod matches ANY required affinity term AND the
        # pod matches all of its own terms may it start the group anywhere;
        # a partial bootstrap (per-term waiver) would schedule pods
        # upstream leaves Pending.
        bootstrap = (
            bool(aff)
            and all(not cnt.any() for cnt in counts)
            and all(task.namespace in ns
                    and match_label_selector(
                        term.get("labelSelector") or {}, task.labels)
                    for term, ns in aff))
        if not bootstrap:
            for cnt in counts:
                masks.append(cnt > 0)
        for term in _affinity_terms(task, "podAntiAffinity", True):
            cnt = self._term_domain_counts(
                term, _term_namespaces(term, task.namespace),
                exclude_uid=task.uid)
            masks.append(cnt == 0)
        # symmetric anti-affinity: an existing pod's required anti-affinity
        # term that matches THIS task excludes the pod's whole domain
        sym = self._symmetric_anti_mask(task)
        if sym is not None:
            masks.append(sym)
        if not masks:
            return None
        out = masks[0]
        for m in masks[1:]:
            out = out & m
        return out

    def _symmetric_anti_mask(self, task) -> Optional[np.ndarray]:
        out = None
        for t, ni in self.existing:
            for term in _affinity_terms(t, "podAntiAffinity", True):
                if task.namespace not in _term_namespaces(term, t.namespace):
                    continue
                if not match_label_selector(term.get("labelSelector") or {},
                                            task.labels):
                    continue
                key = term.get("topologyKey") or "kubernetes.io/hostname"
                dom, _ = self.domains(key)
                if out is None:
                    out = np.ones(len(self.nodes), bool)
                out &= dom != dom[ni]
        return out

    # -- preferred terms -> scoring -----------------------------------------

    def score_row(self, task) -> Optional[np.ndarray]:
        """f32[N] raw preferred-term score for one task; None when neither
        the task nor any existing pod contributes a term. Includes the k8s
        scorer's SYMMETRIC half: existing pods' preferred terms that match
        the incoming task attract/repel toward their own domains."""
        row = None
        for pref in _affinity_terms(task, "podAffinity", False):
            term = pref.get("podAffinityTerm") or {}
            w = float(pref.get("weight", 1))
            cnt = self._term_domain_counts(
                term, _term_namespaces(term, task.namespace))
            row = (row if row is not None else 0) + w * cnt
        for pref in _affinity_terms(task, "podAntiAffinity", False):
            term = pref.get("podAffinityTerm") or {}
            w = float(pref.get("weight", 1))
            cnt = self._term_domain_counts(
                term, _term_namespaces(term, task.namespace))
            row = (row if row is not None else 0) - w * cnt
        for t, ni in self.existing:
            for kind, sign in (("podAffinity", 1.0), ("podAntiAffinity", -1.0)):
                for pref in _affinity_terms(t, kind, False):
                    term = pref.get("podAffinityTerm") or {}
                    if task.namespace not in _term_namespaces(
                            term, t.namespace):
                        continue
                    if not match_label_selector(
                            term.get("labelSelector") or {}, task.labels):
                        continue
                    key = term.get("topologyKey") or "kubernetes.io/hostname"
                    dom, _ = self.domains(key)
                    w = sign * float(pref.get("weight", 1))
                    contrib = np.where(dom == dom[ni], w, 0.0)
                    row = (row if row is not None else 0) + contrib
        if row is None:
            return None
        return np.asarray(row, np.float32)


def session_has_pod_affinity(ssn) -> bool:
    """True when any session task OR any pod already placed on a node
    (including non-PodGroup pods dropped from ssn.jobs) carries pod
    affinity/anti-affinity — gates all index construction so the common
    no-affinity case costs one cached boolean."""
    flag = getattr(ssn, "_has_pod_affinity", None)
    if flag is None:
        flag = (any(has_pod_affinity(t) for job in ssn.jobs.values()
                    for t in job.tasks.values())
                or any(has_pod_affinity(t) for node in ssn.nodes.values()
                       for t in node.tasks.values()))
        ssn._has_pod_affinity = flag
    return flag


def get_pod_affinity_index(ssn) -> PodAffinityIndex:
    """Session-cached index, subscribed to allocate/evict events. The
    handler is NOT aggregatable, so batched engines fall back to the exact
    Statement replay whenever pod affinity is in play."""
    idx = getattr(ssn, "_pod_affinity_index", None)
    if idx is None:
        from ..framework.session import EventHandler
        idx = PodAffinityIndex(list(ssn.nodes.values()))
        ssn._pod_affinity_index = idx
        ssn.add_event_handler(EventHandler(
            allocate_func=lambda ev: idx.add_pod(ev.task),
            deallocate_func=lambda ev: idx.remove_pod(ev.task),
            aggregatable=False))
    return idx


def normalize_scores(row: np.ndarray) -> np.ndarray:
    """k8s defaultNormalizeScore over [0, 100] with negatives shifted."""
    if row.size == 0:
        return row
    lo, hi = float(row.min()), float(row.max())
    if hi == lo:
        return np.zeros_like(row) if hi == 0 else \
            np.full_like(row, MAX_NODE_SCORE)
    return (row - lo) * MAX_NODE_SCORE / (hi - lo)
