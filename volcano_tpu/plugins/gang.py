"""Gang plugin: the all-or-nothing scheduling votes.

Mirrors /root/reference/pkg/scheduler/plugins/gang/gang.go:45-216.
The actual gang *math* (occupied >= MinAvailable as a segment reduction) runs
inside the placement kernels (ops/place.py, ops/auction.py); this plugin
provides the host-side votes, job validation, ordering, and the session-close
PodGroup condition writeback.
"""

from __future__ import annotations

from .. import metrics
from ..api import PodGroupConditionType, TaskStatus
from ..framework.session import ABSTAIN, PERMIT, REJECT, ValidateResult
from .base import Plugin

NOT_ENOUGH_PODS_OF_TASK = "NotEnoughPodsOfTask"
NOT_ENOUGH_PODS = "NotEnoughTasks"
NOT_ENOUGH_RESOURCES = "NotEnoughResources"


class GangPlugin(Plugin):
    NAME = "gang"

    def on_session_open(self, ssn) -> None:
        def job_valid(job) -> ValidateResult:
            if not job.check_task_min_available():
                return ValidateResult(
                    False, NOT_ENOUGH_PODS_OF_TASK,
                    "Not enough valid pods of each task for gang-scheduling")
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    False, NOT_ENOUGH_PODS,
                    f"Not enough valid tasks for gang-scheduling, valid: {vtn}, "
                    f"min: {job.min_available}")
            return None

        ssn.add_job_valid_fn(self.NAME, job_valid)

        def preemptable(preemptor, preemptees):
            """Victims only from lower-priority jobs (gang.go:83-101)."""
            p_job = ssn.jobs[preemptor.job]
            victims = [t for t in preemptees
                       if p_job.priority > ssn.jobs[t.job].priority]
            return victims, PERMIT

        ssn.add_preemptable_fn(self.NAME, preemptable)
        ssn.add_reclaimable_fn(self.NAME, preemptable)

        def job_order(l, r) -> int:
            """Ready jobs sort last (gang.go:108-131)."""
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready == r_ready:
                return 0
            return 1 if l_ready else -1

        ssn.add_job_order_fn(self.NAME, job_order)
        ssn.add_job_ready_fn(self.NAME, lambda job: job.ready())

        def pipelined(job) -> int:
            occupied = job.waiting_task_num() + job.ready_task_num()
            return PERMIT if occupied >= job.min_available else REJECT

        ssn.add_job_pipelined_fn(self.NAME, pipelined)

        def starving(job) -> bool:
            occupied = job.waiting_task_num() + job.ready_task_num()
            return occupied < job.min_available

        ssn.add_job_starving_fn(self.NAME, starving)

    def on_session_close(self, ssn) -> None:
        """Write PodGroup (Un)schedulable conditions (gang.go:158-216)."""
        unschedulable_jobs = 0
        for job in ssn.jobs.values():
            if not job.ready():
                unready = job.min_available - job.ready_task_num()
                msg = (f"{unready}/{len(job.tasks)} tasks in gang "
                       f"unschedulable: {job.fit_error()}")
                job.job_fit_errors = msg
                unschedulable_jobs += 1
                metrics.update_unschedule_task_count(job.name, int(unready))
                ssn.update_pod_group_condition(job, {
                    "type": PodGroupConditionType.UNSCHEDULABLE.value,
                    "status": "True",
                    "transitionID": ssn.uid,
                    "reason": NOT_ENOUGH_RESOURCES,
                    "message": msg,
                    "lastTransitionTime": ssn.now(),
                })
            else:
                ssn.update_pod_group_condition(job, {
                    "type": PodGroupConditionType.SCHEDULED.value,
                    "status": "True",
                    "transitionID": ssn.uid,
                    "reason": "tasks in gang are ready to be scheduled",
                    "message": "",
                    "lastTransitionTime": ssn.now(),
                })
        for _ in range(unschedulable_jobs):
            metrics.register_unschedule_job()


def New(arguments):
    return GangPlugin(arguments)
