"""Shared plugin helpers.

Mirrors /root/reference/pkg/scheduler/plugins/util/util.go (Permit/Abstain/
Reject live in framework.session; NormalizeScore here).
"""

from __future__ import annotations

from typing import Dict


def normalize_score(max_priority: int, reverse: bool,
                    scores: Dict[str, int]) -> Dict[str, int]:
    """util.go NormalizeScore:276-301 — scale to [0, max_priority] by the
    max entry; with ``reverse`` smaller raw scores map to larger results.
    Returns a new dict (the reference mutates in place)."""
    max_count = max(scores.values(), default=0)
    if max_count == 0:
        return {k: max_priority if reverse else v for k, v in scores.items()}
    out = {}
    for key, score in scores.items():
        score = max_priority * score // max_count
        if reverse:
            score = max_priority - score
        out[key] = score
    return out
