"""task-topology plugin: affinity-bucket co-scheduling within a job.

Mirrors /root/reference/pkg/scheduler/plugins/task-topology/{topology.go,
manager.go,bucket.go,util.go}: tasks of a job are grouped into buckets by
declared task-name affinity/anti-affinity; TaskOrderFn emits bucket-mates
consecutively and NodeOrderFn pulls a bucket onto the node(s) where its
mates already landed.

Topology is declared on the PodGroup annotations
(``volcano.sh/task-topology-affinity``, ``-anti-affinity``, ``-task-order``
— util.go:34-42), each a ``;``-separated list of ``,``-separated task
names, matched against TaskInfo.task_role (the reference matches the
pod's volcano.sh/task-spec annotation).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Set

from ..api import Resource, TaskStatus
from ..framework.session import EventHandler
from .base import Plugin

PLUGIN_NAME = "task-topology"
PLUGIN_WEIGHT = "task-topology.weight"
AFFINITY_ANNOTATION = "volcano.sh/task-topology-affinity"
ANTI_AFFINITY_ANNOTATION = "volcano.sh/task-topology-anti-affinity"
TASK_ORDER_ANNOTATION = "volcano.sh/task-topology-task-order"
OUT_OF_BUCKET = -1
MAX_NODE_SCORE = 100.0

# affinity kind -> task priority (manager.go affinityPriority:41-46)
SELF_ANTI_AFFINITY = "selfAntiAffinity"
INTER_ANTI_AFFINITY = "interAntiAffinity"
SELF_AFFINITY = "selfAffinity"
INTER_AFFINITY = "interAffinity"
AFFINITY_PRIORITY = {SELF_ANTI_AFFINITY: 4, INTER_AFFINITY: 3,
                     SELF_AFFINITY: 2, INTER_ANTI_AFFINITY: 1}


def task_name_of(task) -> str:
    """util.go getTaskName — the task-template name of a replica."""
    return task.task_role or ""


class TaskTopology:
    """Parsed topology annotations (util.go:44-49)."""

    def __init__(self, affinity=None, anti_affinity=None, task_order=None):
        self.affinity: List[List[str]] = affinity or []
        self.anti_affinity: List[List[str]] = anti_affinity or []
        self.task_order: List[str] = task_order or []


def _split_annotation(value: str) -> List[List[str]]:
    return [[t.strip() for t in group.split(",") if t.strip()]
            for group in value.split(";") if group.strip()]


def _affinity_check(job, groups: List[List[str]]) -> bool:
    """topology.go affinityCheck — every named task exists, no duplicates
    inside one group."""
    known = {task_name_of(t) for t in job.tasks.values()}
    for group in groups:
        seen: Set[str] = set()
        for name in group:
            if name not in known or name in seen:
                return False
            seen.add(name)
    return True


def read_topology_from_pg_annotations(job) -> Optional[TaskTopology]:
    """topology.go readTopologyFromPgAnnotations:287-335."""
    annotations = job.podgroup.annotations if job.podgroup else {}
    aff = annotations.get(AFFINITY_ANNOTATION)
    anti = annotations.get(ANTI_AFFINITY_ANNOTATION)
    order = annotations.get(TASK_ORDER_ANNOTATION)
    if aff is None and anti is None and order is None:
        return None
    topo = TaskTopology()
    if aff is not None:
        topo.affinity = _split_annotation(aff)
        if not _affinity_check(job, topo.affinity):
            return None
    if anti is not None:
        topo.anti_affinity = _split_annotation(anti)
        if not _affinity_check(job, topo.anti_affinity):
            return None
    if order is not None:
        topo.task_order = [t.strip() for t in order.split(",") if t.strip()]
        if not _affinity_check(job, [topo.task_order]):
            return None
    return topo


class Bucket:
    """bucket.go:34-110 — one co-placement group."""

    def __init__(self, index: int = 0):
        self.index = index
        self.tasks: Dict[str, object] = {}      # pending tasks by uid
        self.task_name_set: Dict[str, int] = {}
        self.req_score = 0.0
        self.request = Resource()
        self.bound_task = 0
        self.node: Dict[str, int] = {}          # node -> bound mate count

    def _score_of(self, req: Resource) -> float:
        # 1m CPU == 1Mi memory == 1m scalar (bucket.go CalcResReq:64-73)
        return req.cpu + req.memory / (1024 * 1024) + sum(req.scalars.values())

    def add_task(self, task_name: str, task) -> None:
        self.task_name_set[task_name] = self.task_name_set.get(task_name, 0) + 1
        if task.node_name:
            self.node[task.node_name] = self.node.get(task.node_name, 0) + 1
            self.bound_task += 1
            return
        self.tasks[task.uid] = task
        self.req_score += self._score_of(task.resreq)
        self.request.add(task.resreq)

    def task_bound(self, task) -> None:
        self.node[task.node_name] = self.node.get(task.node_name, 0) + 1
        self.bound_task += 1
        if task.uid in self.tasks:
            del self.tasks[task.uid]
            self.req_score -= self._score_of(task.resreq)
            self.request.sub(task.resreq)


class JobManager:
    """manager.go:48-347 — per-job affinity matrices and buckets."""

    def __init__(self, job_id: str):
        self.job_id = job_id
        self.buckets: List[Bucket] = []
        self.pod_in_bucket: Dict[str, int] = {}
        self.pod_in_task: Dict[str, str] = {}
        self.task_affinity_priority: Dict[str, int] = {}
        self.task_exist_order: Dict[str, int] = {}
        self.inter_affinity: Dict[str, Set[str]] = {}
        self.self_affinity: Set[str] = set()
        self.inter_anti_affinity: Dict[str, Set[str]] = {}
        self.self_anti_affinity: Set[str] = set()
        self.bucket_max_size = 0
        self.node_task_set: Dict[str, Dict[str, int]] = {}

    def mark_task_has_topology(self, task_name: str, kind: str) -> None:
        priority = AFFINITY_PRIORITY[kind]
        if priority > self.task_affinity_priority.get(task_name, 0):
            self.task_affinity_priority[task_name] = priority

    def apply_task_topology(self, topo: TaskTopology) -> None:
        """manager.go ApplyTaskTopology:113-151."""
        for group in topo.affinity:
            if len(group) == 1:
                self.self_affinity.add(group[0])
                self.mark_task_has_topology(group[0], SELF_AFFINITY)
                continue
            for i, src in enumerate(group):
                for dst in group[:i]:
                    self.inter_affinity.setdefault(src, set()).add(dst)
                    self.inter_affinity.setdefault(dst, set()).add(src)
                self.mark_task_has_topology(src, INTER_AFFINITY)
        for group in topo.anti_affinity:
            if len(group) == 1:
                self.self_anti_affinity.add(group[0])
                self.mark_task_has_topology(group[0], SELF_ANTI_AFFINITY)
                continue
            for i, src in enumerate(group):
                for dst in group[:i]:
                    self.inter_anti_affinity.setdefault(src, set()).add(dst)
                    self.inter_anti_affinity.setdefault(dst, set()).add(src)
                self.mark_task_has_topology(src, INTER_ANTI_AFFINITY)
        length = len(topo.task_order)
        for index, task_name in enumerate(topo.task_order):
            self.task_exist_order[task_name] = length - index

    def new_bucket(self) -> Bucket:
        bucket = Bucket(index=len(self.buckets))
        self.buckets.append(bucket)
        return bucket

    def add_task_to_bucket(self, bucket_index: int, task_name: str, task) -> None:
        bucket = self.buckets[bucket_index]
        self.pod_in_bucket[task.uid] = bucket_index
        bucket.add_task(task_name, task)
        size = len(bucket.tasks) + bucket.bound_task
        if size > self.bucket_max_size:
            self.bucket_max_size = size

    def task_affinity_order(self, l, r) -> int:
        """manager.go taskAffinityOrder:171-201; 1 means l ranks higher."""
        l_name = self.pod_in_task.get(l.uid, "")
        r_name = self.pod_in_task.get(r.uid, "")
        if l_name == r_name:
            return 0
        l_order = self.task_exist_order.get(l_name, 0)
        r_order = self.task_exist_order.get(r_name, 0)
        if l_order != r_order:
            return 1 if l_order > r_order else -1
        l_prio = self.task_affinity_priority.get(l_name, 0)
        r_prio = self.task_affinity_priority.get(r_name, 0)
        if l_prio != r_prio:
            return 1 if l_prio > r_prio else -1
        return 0

    def check_task_set_affinity(self, task_name: str,
                                task_name_set: Dict[str, int],
                                only_anti: bool) -> int:
        """manager.go checkTaskSetAffinity:230-264 — net affinity score of
        placing `task_name` next to the given name multiset."""
        score = 0
        if not task_name:
            return score
        for name_in_set, count in task_name_set.items():
            same = name_in_set == task_name
            if not only_anti:
                affinity = (task_name in self.self_affinity) if same else \
                    (name_in_set in self.inter_affinity.get(task_name, ()))
                if affinity:
                    score += count
            anti = (task_name in self.self_anti_affinity) if same else \
                (name_in_set in self.inter_anti_affinity.get(task_name, ()))
            if anti:
                score -= count
        return score

    def construct_bucket(self, tasks: Dict[str, object]) -> None:
        """manager.go ConstructBucket:308-320."""
        without_bucket = []
        for task in tasks.values():
            task_name = task_name_of(task)
            if not task_name or task_name not in self.task_affinity_priority:
                self.pod_in_bucket[task.uid] = OUT_OF_BUCKET
                continue
            self.pod_in_task[task.uid] = task_name
            without_bucket.append(task)

        # TaskOrder sort, reversed (util.go:92-118): bound tasks first, then
        # user order, then affinity priority.
        def sort_key(task):
            has_node = 1 if task.node_name else 0
            name = self.pod_in_task.get(task.uid, "")
            return (has_node, self.task_exist_order.get(name, 0),
                    self.task_affinity_priority.get(name, 0), task.node_name)
        without_bucket.sort(key=sort_key, reverse=True)
        self._build_bucket(without_bucket)

    def _build_bucket(self, ordered_tasks) -> None:
        """manager.go buildBucket:266-305."""
        node_bucket: Dict[str, Bucket] = {}
        for task in ordered_tasks:
            task_name = task_name_of(task)
            selected: Optional[Bucket] = None
            max_affinity = -math.inf
            if task.node_name:
                max_affinity = 0
                selected = node_bucket.get(task.node_name)
            else:
                for bucket in self.buckets:
                    aff = self.check_task_set_affinity(
                        task_name, bucket.task_name_set, only_anti=False)
                    if aff > max_affinity:
                        max_affinity = aff
                        selected = bucket
                    elif aff == max_affinity and selected is not None and \
                            bucket.req_score < selected.req_score:
                        selected = bucket
            if max_affinity < 0 or selected is None:
                selected = self.new_bucket()
                if task.node_name:
                    node_bucket[task.node_name] = selected
            self.add_task_to_bucket(selected.index, task_name, task)

    def task_bound(self, task) -> None:
        """manager.go TaskBound:322-337."""
        task_name = task_name_of(task)
        if task_name:
            node_set = self.node_task_set.setdefault(task.node_name, {})
            node_set[task_name] = node_set.get(task_name, 0) + 1
        bucket = self.get_bucket(task)
        if bucket is not None:
            bucket.task_bound(task)

    def get_bucket(self, task) -> Optional[Bucket]:
        index = self.pod_in_bucket.get(task.uid, OUT_OF_BUCKET)
        if index == OUT_OF_BUCKET:
            return None
        return self.buckets[index]


def _no_pending_tasks(job) -> bool:
    return not job.task_status_index.get(TaskStatus.PENDING)


class TaskTopologyPlugin(Plugin):
    NAME = PLUGIN_NAME

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.weight = self.arguments.get_int(PLUGIN_WEIGHT, 1)
        self.managers: Dict[str, JobManager] = {}

    def _init_buckets(self, ssn) -> None:
        """topology.go initBucket:213-238."""
        for job_id, job in ssn.jobs.items():
            if _no_pending_tasks(job):
                continue
            topo = read_topology_from_pg_annotations(job)
            if topo is None:
                continue
            manager = JobManager(job_id)
            manager.apply_task_topology(topo)
            manager.construct_bucket(job.tasks)
            self.managers[job_id] = manager

    def task_order_fn(self, l, r) -> int:
        """topology.go TaskOrderFn:60-132 — -1 ranks l first."""
        l_mgr = self.managers.get(l.job)
        r_mgr = self.managers.get(r.job)
        if l_mgr is None or r_mgr is None:
            return 0
        l_bucket, r_bucket = l_mgr.get_bucket(l), r_mgr.get_bucket(r)
        if (l_bucket is not None) != (r_bucket is not None):
            return -1 if l_bucket is not None else 1
        if l.job != r.job or l_bucket is None:
            return 0
        if len(l_bucket.tasks) != len(r_bucket.tasks):
            return -1 if len(l_bucket.tasks) > len(r_bucket.tasks) else 1
        if l_bucket.index == r_bucket.index:
            return -l_mgr.task_affinity_order(l, r)
        return -1 if l_bucket.index < r_bucket.index else 1

    def _calc_bucket_score(self, task, node):
        """topology.go calcBucketScore:134-186."""
        max_resource = node.idle.clone().add(node.releasing)
        if max_resource.less_in_some_dimension(task.resreq):
            return 0, None
        manager = self.managers.get(task.job)
        if manager is None:
            return 0, None
        bucket = manager.get_bucket(task)
        if bucket is None:
            return 0, manager
        score = bucket.node.get(node.name, 0)
        node_task_set = manager.node_task_set.get(node.name)
        if node_task_set:
            aff = manager.check_task_set_affinity(
                task_name_of(task), node_task_set, only_anti=True)
            if aff < 0:
                score += aff
        score += len(bucket.tasks)
        if bucket.request.less_equal(max_resource):
            return score, manager
        remains = bucket.request.clone()
        for uid, mate in bucket.tasks.items():
            if uid == task.uid:
                continue
            remains.sub(mate.resreq)
            score -= 1
            if remains.less_equal(max_resource):
                break
        return score, manager

    def node_order_fn(self, task, node) -> float:
        score, manager = self._calc_bucket_score(task, node)
        fscore = float(score * self.weight)
        if manager is not None and manager.bucket_max_size != 0:
            fscore = fscore * MAX_NODE_SCORE / manager.bucket_max_size
        return fscore

    def on_session_open(self, ssn) -> None:
        self.managers = {}
        self._init_buckets(ssn)
        ssn.add_task_order_fn(self.NAME, self.task_order_fn)
        ssn.add_node_order_fn(self.NAME, self.node_order_fn)

        def on_allocate(event):
            if not hasattr(event.task, "uid"):  # aggregated order-sim event
                return
            manager = self.managers.get(event.task.job)
            if manager is not None:
                manager.task_bound(event.task)

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate))

    def on_session_close(self, ssn) -> None:
        self.managers = {}


def New(arguments):
    return TaskTopologyPlugin(arguments)
