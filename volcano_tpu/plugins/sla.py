"""SLA plugin: jobs past their waiting-time SLA sort first and force-permit
enqueue/pipeline.

Mirrors /root/reference/pkg/scheduler/plugins/sla/sla.go:60-150.
"""

from __future__ import annotations

import re
from typing import Optional

from ..framework.session import ABSTAIN, PERMIT
from .base import Plugin

JOB_WAITING_TIME = "sla-waiting-time"

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(h|m|s|ms|us|µs|ns)")
_UNITS = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6,
          "µs": 1e-6, "ns": 1e-9}


def parse_duration(text: str) -> Optional[float]:
    """Go-style duration ('1h2m3s') -> seconds."""
    if not text:
        return None
    total, matched = 0.0, False
    for num, unit in _DUR_RE.findall(str(text)):
        total += float(num) * _UNITS[unit]
        matched = True
    return total if matched else None


class SLAPlugin(Plugin):
    NAME = "sla"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.job_waiting_time: Optional[float] = None
        jwt = parse_duration(self.arguments.get(JOB_WAITING_TIME, ""))
        if jwt and jwt > 0:
            self.job_waiting_time = jwt

    def _jwt(self, job) -> Optional[float]:
        """Per-job waiting time (annotation/JobInfo) or the global default
        (sla.go:50-65)."""
        if job.waiting_time is not None:
            return job.waiting_time
        ann = job.podgroup.annotations.get(JOB_WAITING_TIME) if job.podgroup else None
        if ann:
            return parse_duration(ann)
        return self.job_waiting_time

    def on_session_open(self, ssn) -> None:
        def job_order(l, r) -> int:
            ljwt, rjwt = self._jwt(l), self._jwt(r)
            if ljwt is None:
                return 0 if rjwt is None else 1
            if rjwt is None:
                return -1
            ldeadline = l.creation_timestamp + ljwt
            rdeadline = r.creation_timestamp + rjwt
            if ldeadline < rdeadline:
                return -1
            if ldeadline > rdeadline:
                return 1
            return 0

        ssn.add_job_order_fn(self.NAME, job_order)

        def permitable(job) -> int:
            jwt = self._jwt(job)
            if jwt is None:
                return ABSTAIN
            # session clock (vlint VT002): wall time in production,
            # virtual time under sim replay — same timebase as
            # job.creation_timestamp in both worlds
            if ssn.now() - job.creation_timestamp < jwt:
                return ABSTAIN
            return PERMIT

        ssn.add_job_enqueueable_fn(self.NAME, permitable)
        ssn.add_job_pipelined_fn(self.NAME, permitable)


def New(arguments):
    return SLAPlugin(arguments)
