"""Nodeorder plugin: the k8s score-plugin wrap.

Mirrors /root/reference/pkg/scheduler/plugins/nodeorder/nodeorder.go:71-412 —
LeastAllocated/MostAllocated/BalancedAllocation/NodeAffinity per-node scores
plus TaintToleration preference as a batch score. Dynamic (usage-dependent)
terms also register kernel weights; preference terms (node affinity,
taint toleration) are static per session and contribute a static score
matrix for the device path.
"""

from __future__ import annotations

import numpy as np

from .base import Plugin

MAX_NODE_SCORE = 100.0


def _match_expr(labels, expr) -> bool:
    key, op = expr.get("key"), expr.get("operator", "In")
    values = expr.get("values", []) or []
    has = key in labels
    val = labels.get(key)
    if op == "In":
        return has and val in values
    if op == "NotIn":
        return not has or val not in values
    if op == "Exists":
        return has
    if op == "DoesNotExist":
        return not has
    if op == "Gt":
        return has and float(val) > float(values[0])
    if op == "Lt":
        return has and float(val) < float(values[0])
    return False


def match_node_selector_terms(labels, terms) -> bool:
    """OR over terms, AND over matchExpressions within a term."""
    if not terms:
        return True
    for term in terms:
        exprs = term.get("matchExpressions", []) or []
        if all(_match_expr(labels, e) for e in exprs):
            return True
    return False


def node_affinity_preferred_score(task, node) -> float:
    """Sum of matching preferredDuringScheduling term weights."""
    preferred = (task.affinity.get("nodeAffinity", {})
                 .get("preferredDuringSchedulingIgnoredDuringExecution", []))
    score = 0.0
    for pref in preferred or []:
        term = pref.get("preference", {})
        if match_node_selector_terms(node.labels, [term]):
            score += float(pref.get("weight", 0))
    return score


def taint_toleration_score(task, node) -> float:
    """Fraction of PreferNoSchedule taints tolerated, scaled to 100
    (k8s tainttoleration scoring wrapped at nodeorder.go:269-310)."""
    prefer = [t for t in node.taints if t.get("effect") == "PreferNoSchedule"]
    if not prefer:
        return MAX_NODE_SCORE
    intolerable = 0
    for taint in prefer:
        if not any(_toleration_matches(tol, taint) for tol in task.tolerations):
            intolerable += 1
    return (1.0 - intolerable / len(prefer)) * MAX_NODE_SCORE


def _toleration_matches(tol, taint) -> bool:
    if tol.get("effect") and tol.get("effect") != taint.get("effect"):
        return False
    op = tol.get("operator", "Equal")
    if op == "Exists":
        return not tol.get("key") or tol.get("key") == taint.get("key")
    return (tol.get("key") == taint.get("key")
            and tol.get("value", "") == taint.get("value", ""))


class NodeOrderPlugin(Plugin):
    NAME = "nodeorder"

    def __init__(self, arguments=None):
        super().__init__(arguments)
        args = self.arguments
        self.node_affinity_weight = args.get_int("nodeaffinity.weight", 1)
        self.pod_affinity_weight = args.get_int("podaffinity.weight", 1)
        self.least_req_weight = args.get_int("leastrequested.weight", 1)
        self.most_req_weight = args.get_int("mostrequested.weight", 0)
        self.balanced_weight = args.get_int("balancedresource.weight", 1)
        self.taint_toleration_weight = args.get_int("tainttoleration.weight", 1)
        self._ssn = None

    # host-path per-(task,node) scorer
    def _score(self, task, node) -> float:
        score = 0.0
        alloc_c, alloc_m = node.allocatable.cpu, node.allocatable.memory
        used_c = node.used.cpu + task.resreq.cpu
        used_m = node.used.memory + task.resreq.memory
        if self.least_req_weight:
            frac_c = max(0.0, (alloc_c - used_c) / alloc_c) if alloc_c else 0.0
            frac_m = max(0.0, (alloc_m - used_m) / alloc_m) if alloc_m else 0.0
            score += self.least_req_weight * (frac_c + frac_m) / 2 * MAX_NODE_SCORE
        if self.most_req_weight:
            frac_c = used_c / alloc_c if alloc_c else 0.0
            frac_m = used_m / alloc_m if alloc_m else 0.0
            frac_c = 0.0 if frac_c > 1 else frac_c
            frac_m = 0.0 if frac_m > 1 else frac_m
            score += self.most_req_weight * (frac_c + frac_m) / 2 * MAX_NODE_SCORE
        if self.balanced_weight:
            frac_c = min(1.0, used_c / alloc_c) if alloc_c else 0.0
            frac_m = min(1.0, used_m / alloc_m) if alloc_m else 0.0
            mean = (frac_c + frac_m) / 2
            std = (((frac_c - mean) ** 2 + (frac_m - mean) ** 2) / 2) ** 0.5
            score += self.balanced_weight * (1.0 - std) * MAX_NODE_SCORE
        if self.node_affinity_weight:
            score += self.node_affinity_weight * node_affinity_preferred_score(task, node)
        return score

    def _batch_score(self, task, nodes):
        out = {}
        if self.taint_toleration_weight:
            for n in nodes:
                out[n.name] = self.taint_toleration_weight * \
                    taint_toleration_score(task, n)
        # batch InterPodAffinity scoring (nodeorder.go:269-340): preferred
        # affinity/anti-affinity terms against the live pod index,
        # normalized to [0,100] like the k8s scorer
        if self.pod_affinity_weight and self._ssn is not None:
            from .podaffinity import (get_pod_affinity_index,
                                      normalize_scores,
                                      session_has_pod_affinity)
            if session_has_pod_affinity(self._ssn):
                idx = get_pod_affinity_index(self._ssn)
                row = idx.score_row(task)
                if row is not None:
                    sub = np.asarray([row[idx.node_index[n.name]]
                                      for n in nodes], np.float32)
                    norm = normalize_scores(sub)
                    for k, n in enumerate(nodes):
                        out[n.name] = out.get(n.name, 0.0) + \
                            self.pod_affinity_weight * float(norm[k])
        return out

    # device-path static score matrix (preference terms only). Vectorized for
    # the common case — python loops only over tasks with affinity
    # preferences and nodes with PreferNoSchedule taints.
    def _static_matrix(self, ssn, tasks, node_t):
        from ..cache.snapshot import node_infos_for
        node_infos = node_infos_for(ssn, node_t)
        T, N = len(tasks), len(node_infos)
        has_pref_taints = any(
            t.get("effect") == "PreferNoSchedule"
            for n in node_infos for t in n.taints)
        has_affinity_prefs = any(
            (t.affinity.get("nodeAffinity", {})
             .get("preferredDuringSchedulingIgnoredDuringExecution"))
            for t in tasks)
        from .podaffinity import session_has_pod_affinity
        has_pod_aff = bool(self.pod_affinity_weight
                           and session_has_pod_affinity(ssn))
        if not has_pref_taints and not has_affinity_prefs and not has_pod_aff:
            # constant per-task offset — no effect on node choice; skip the
            # [T,N] matrix entirely
            return None
        score = np.zeros((T, N), np.float32)
        if self.taint_toleration_weight:
            score += self.taint_toleration_weight * MAX_NODE_SCORE
            tainted = [(ni, n) for ni, n in enumerate(node_infos)
                       if any(t.get("effect") == "PreferNoSchedule"
                              for t in n.taints)]
            for ni, node in tainted:
                for ti, task in enumerate(tasks):
                    score[ti, ni] = self.taint_toleration_weight * \
                        taint_toleration_score(task, node)
        if self.node_affinity_weight:
            for ti, task in enumerate(tasks):
                preferred = (task.affinity.get("nodeAffinity", {})
                             .get("preferredDuringSchedulingIgnoredDuringExecution"))
                if not preferred:
                    continue
                for ni, node in enumerate(node_infos):
                    score[ti, ni] += self.node_affinity_weight * \
                        node_affinity_preferred_score(task, node)
        if self.pod_affinity_weight:
            from .podaffinity import (get_pod_affinity_index,
                                      normalize_scores,
                                      session_has_pod_affinity)
            if session_has_pod_affinity(ssn):
                idx = get_pod_affinity_index(ssn)
                cols = np.asarray([idx.node_index.get(n, -1)
                                   for n in node_t.names])
                hole = cols < 0             # persistent-tensor hole rows
                for ti, task in enumerate(tasks):
                    row = idx.score_row(task)
                    if row is not None:
                        sub = np.where(hole, 0.0, row[cols])
                        score[ti] += self.pod_affinity_weight * \
                            normalize_scores(sub)
        return score

    def on_session_open(self, ssn) -> None:
        self._ssn = ssn
        ssn.add_node_order_fn(self.NAME, self._score)
        ssn.add_batch_node_order_fn(self.NAME, self._batch_score)
        ssn.set_dynamic_score_weights(
            self.NAME,
            least_req_weight=float(self.least_req_weight),
            most_req_weight=float(self.most_req_weight),
            balanced_weight=float(self.balanced_weight))
        ssn.add_static_score_fn(self.NAME, self._static_matrix)


def New(arguments):
    return NodeOrderPlugin(arguments)
