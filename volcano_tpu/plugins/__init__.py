"""Policy plugins (mirrors /root/reference/pkg/scheduler/plugins/factory.go:38-56).

Importing this package registers all in-tree plugins.
"""

from ..framework.registry import register_plugin_builder
from .base import Plugin
from . import binpack, conformance, drf, elastic_gang, gang, nodeorder
from . import numaaware, overcommit
from . import predicates, priority, proportion, reservation, sla
from . import task_topology, tdm

register_plugin_builder("gang", gang.New)
register_plugin_builder("elastic-gang", elastic_gang.New)
register_plugin_builder("priority", priority.New)
register_plugin_builder("conformance", conformance.New)
register_plugin_builder("drf", drf.New)
register_plugin_builder("proportion", proportion.New)
register_plugin_builder("binpack", binpack.New)
register_plugin_builder("nodeorder", nodeorder.New)
register_plugin_builder("predicates", predicates.New)
register_plugin_builder("overcommit", overcommit.New)
register_plugin_builder("sla", sla.New)
register_plugin_builder("tdm", tdm.New)
register_plugin_builder("reservation", reservation.New)
register_plugin_builder("task-topology", task_topology.New)
register_plugin_builder("numa-aware", numaaware.New)

__all__ = ["Plugin"]
