"""numa-aware plugin: topology-manager-style NUMA placement.

Mirrors /root/reference/pkg/scheduler/plugins/numaaware/numaaware.go:40-284
(predicate + batch node order + event bookkeeping + close-time writeback)
and the cpumanager hint provider
(numaaware/provider/cpumanager/cpu_mng.go:40-170).

Host-side by design: hint merging is tiny combinatorics over <=8 NUMA nodes
per node and only runs for tasks that declare a topology policy; the dense
TPU solve is unaffected except through the predicate feasibility mask.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Set

from ..api.numa_info import (CPU, CPU_MANAGER_POLICY, TOPOLOGY_MANAGER_POLICY,
                             NumatopoInfo, ResNumaSets, TopologyHint,
                             bitmask, generate_node_res_numa_sets,
                             generate_numa_nodes, get_policy,
                             iterate_bitmasks, mask_bits, mask_count,
                             res_sets_allocate, res_sets_clone,
                             res_sets_release)
from ..framework.session import EventHandler
from .base import Plugin
from .util import normalize_score

PLUGIN_NAME = "numa-aware"
MAX_NODE_SCORE = 100


def guaranteed_cpus(task) -> int:
    """cpu_mng.go guaranteedCPUs — whole-CPU request count; 0 when the
    request is fractional (not exclusively allocatable)."""
    mcpu = task.resreq.cpu
    if mcpu <= 0 or mcpu % 1000 != 0:
        return 0
    return int(mcpu // 1000)


def take_by_topology(topo: NumatopoInfo, available: Set[int],
                     count: int) -> Optional[Set[int]]:
    """cpu_assignment.go takeByTopology, simplified to NUMA granularity:
    take whole free NUMA domains first (largest fit first), then fill from
    the domain with the most free CPUs."""
    if count > len(available):
        return None
    by_numa: Dict[int, List[int]] = {}
    for cpu in available:
        detail = topo.cpu_detail.get(cpu)
        if detail is not None:
            by_numa.setdefault(detail.numa_id, []).append(cpu)
    taken: Set[int] = set()
    need = count
    # whole domains, largest first, only if they fit entirely
    for numa_id in sorted(by_numa, key=lambda n: -len(by_numa[n])):
        cpus = by_numa[numa_id]
        if len(cpus) <= need:
            taken.update(cpus)
            need -= len(cpus)
            by_numa[numa_id] = []
    if need > 0:
        # fill the remainder from the fullest remaining domain
        for numa_id in sorted(by_numa, key=lambda n: -len(by_numa[n])):
            cpus = sorted(by_numa[numa_id])[:need]
            taken.update(cpus)
            need -= len(cpus)
            if need == 0:
                break
    return taken if need == 0 else None


class CpuManagerProvider:
    """cpumanager hint provider (cpu_mng.go:40-170)."""

    def name(self) -> str:
        return "cpuMng"

    def get_topology_hints(self, task, topo: NumatopoInfo,
                           res_numa_sets: ResNumaSets) -> Optional[Dict[str, List[TopologyHint]]]:
        request = guaranteed_cpus(task)
        if request == 0:
            return None
        available = set(res_numa_sets.get(CPU, set()))
        # honour reserved CPUs (cpu_mng.go:128-140)
        reserved_mcpu = topo.res_reserved.get(CPU, 0.0)
        if reserved_mcpu:
            n_reserved = int(math.ceil(reserved_mcpu / 1000.0))
            reserved = take_by_topology(topo, set(topo.cpu_detail), n_reserved)
            if reserved:
                available -= reserved
        return {CPU: self._generate_hints(topo, available, request)}

    @staticmethod
    def _generate_hints(topo: NumatopoInfo, available: Set[int],
                        request: int) -> List[TopologyHint]:
        """cpu_mng.go generateCPUTopologyHints: a hint per NUMA combination
        with enough available CPUs; preferred iff the combination is of the
        minimal size that could ever satisfy the request."""
        numa_ids = topo.numa_nodes()
        min_affinity = len(numa_ids)
        hints: List[TopologyHint] = []
        for mask in iterate_bitmasks(numa_ids):
            in_mask = topo.cpus_in_numa_nodes(mask)
            if len(in_mask) >= request and mask_count(mask) < min_affinity:
                min_affinity = mask_count(mask)
            if len(available & in_mask) < request:
                continue
            hints.append(TopologyHint(mask, False))
        for hint in hints:
            if mask_count(hint.affinity) == min_affinity:
                hint.preferred = True
        return hints

    def allocate(self, task, best_hint: TopologyHint, topo: NumatopoInfo,
                 res_numa_sets: ResNumaSets) -> Dict[str, Set[int]]:
        """cpu_mng.go Allocate — take CPUs inside the chosen affinity."""
        request = guaranteed_cpus(task)
        if request == 0:
            return {}
        available = set(res_numa_sets.get(CPU, set()))
        if best_hint.affinity is not None:
            in_mask = topo.cpus_in_numa_nodes(best_hint.affinity)
            preferred = available & in_mask
            if len(preferred) >= request:
                available = preferred
        taken = take_by_topology(topo, available, request)
        return {CPU: taken} if taken else {}


class NumaAwarePlugin(Plugin):
    NAME = PLUGIN_NAME

    def __init__(self, arguments=None):
        super().__init__(arguments)
        self.weight = self.arguments.get_int("weight", 1)
        self.providers = [CpuManagerProvider()]
        # map[task uid][node name] -> ResNumaSets (numaaware.go assignRes)
        self.assign_res: Dict[str, Dict[str, ResNumaSets]] = {}
        self.task_bind_node: Dict[str, str] = {}
        self.node_res_sets: Dict[str, ResNumaSets] = {}

    # -- policy gate (numaaware.go filterNodeByPolicy:185-224) --------------

    def _filter_node_by_policy(self, task, node) -> Optional[str]:
        """Returns an error string when the node must be rejected, "skip"
        semantics via the special value ``"abstain"`` when the plugin has
        nothing to do on this node, None when topology processing should
        proceed."""
        topo = node.numa_info
        policy = task.topology_policy
        if policy and policy != "none":
            if topo is None:
                return "numa info is empty"
            if topo.policies.get(CPU_MANAGER_POLICY) != "static":
                return "cpu manager policy isn't static"
            if policy != topo.policies.get(TOPOLOGY_MANAGER_POLICY):
                return (f"task topology policy[{policy}] is different with "
                        f"node[{topo.policies.get(TOPOLOGY_MANAGER_POLICY)}]")
            if node.name not in self.node_res_sets:
                return "no topo information"
            if not self.node_res_sets[node.name].get(CPU):
                return "cpu allocatable map is empty"
            return None
        # tasks without a policy: only account on static+managed nodes
        if topo is None or topo.policies.get(CPU_MANAGER_POLICY) != "static":
            return "abstain"
        if topo.policies.get(TOPOLOGY_MANAGER_POLICY, "none") in ("", "none"):
            return "abstain"
        return None

    # -- session wiring ------------------------------------------------------

    def on_session_open(self, ssn) -> None:
        numa_nodes = generate_numa_nodes(ssn.nodes)
        self.node_res_sets = generate_node_res_numa_sets(ssn.nodes)

        def _reallocate_live(task, node_sets) -> Dict[str, Set[int]]:
            """Re-derive the task's cpusets against the LIVE per-session
            sets. The predicate computed assign_res from a pre-placement
            snapshot; a batched solve (tpu engines) may have placed a
            sibling on the node since, so stale assignments could overlap."""
            node = ssn.nodes.get(task.node_name)
            if node is None or node.numa_info is None:
                return {}
            topo = node.numa_info
            hints = [p.get_topology_hints(task, topo, node_sets)
                     for p in self.providers]
            best_hint, admit = get_policy(topo).predicate(hints)
            if not admit:
                return {}
            out: Dict[str, Set[int]] = {}
            remaining = res_sets_clone(node_sets)
            for provider in self.providers:
                for res, assign in provider.allocate(
                        task, best_hint, topo, remaining).items():
                    out[res] = out.get(res, set()) | assign
                    remaining[res] -= assign
            return out

        def on_allocate(event):
            task = event.task
            if not hasattr(task, "uid"):    # aggregated order-sim event
                return
            per_node = self.assign_res.get(task.uid)
            if not per_node or task.node_name not in per_node:
                return
            node_sets = self.node_res_sets.get(task.node_name)
            if node_sets is None:
                return
            assigned = per_node[task.node_name]
            stale = any(ids - node_sets.get(res, set())
                        for res, ids in assigned.items())
            if stale:
                assigned = _reallocate_live(task, node_sets)
                per_node[task.node_name] = assigned
                if not assigned:
                    return
            res_sets_allocate(node_sets, assigned)
            self.task_bind_node[task.uid] = task.node_name

        def on_deallocate(event):
            task = event.task
            if not hasattr(task, "uid"):
                return
            per_node = self.assign_res.get(task.uid)
            if not per_node or task.node_name not in per_node:
                return
            node_sets = self.node_res_sets.get(task.node_name)
            if node_sets is None:
                return
            if self.task_bind_node.pop(task.uid, None) is None:
                return      # nothing was subtracted for this task
            res_sets_release(node_sets, per_node[task.node_name])

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate,
                                           deallocate_func=on_deallocate))

        def predicate(task, node) -> None:
            if guaranteed_cpus(task) == 0:
                return  # not a Guaranteed whole-CPU pod (numaaware.go:116)
            verdict = self._filter_node_by_policy(task, node)
            if verdict == "abstain":
                return
            if verdict is not None:
                from .predicates import PredicateError
                raise PredicateError(task, node, f"numa-aware: {verdict}")

            topo = node.numa_info
            res_numa_sets = res_sets_clone(self.node_res_sets[node.name])
            task_policy = get_policy(topo)
            all_assign: Dict[str, Set[int]] = {}
            providers_hints = [p.get_topology_hints(task, topo, res_numa_sets)
                               for p in self.providers]
            best_hint, admit = task_policy.predicate(providers_hints)
            if not admit:
                from .predicates import PredicateError
                raise PredicateError(
                    task, node,
                    f"plugin {self.NAME} predicates failed for task "
                    f"{task.name} on node {node.name}")
            for provider in self.providers:
                for res, assign in provider.allocate(
                        task, best_hint, topo, res_numa_sets).items():
                    all_assign[res] = all_assign.get(res, set()) | assign
                    res_numa_sets[res] -= assign
            self.assign_res.setdefault(task.uid, {})[node.name] = all_assign

        ssn.add_predicate_fn(self.NAME, predicate)
        if self.node_res_sets:
            # cpusets shrink as siblings allocate: device proposals must be
            # re-validated through predicate_fn at replay time
            ssn.stateful_predicates.add(self.NAME)

        def feasibility(ssn_, tasks, node_t):
            """Tensor-path mirror of the predicate: bool[T,N] mask for the
            device engines (None when no task/node pair is NUMA-relevant)."""
            if not self.node_res_sets:
                return None
            relevant = [i for i, t in enumerate(tasks)
                        if guaranteed_cpus(t) > 0]
            if not relevant:
                return None
            import numpy as np
            from .predicates import PredicateError
            from ..cache.snapshot import node_infos_for
            node_infos = node_infos_for(ssn_, node_t)
            mask = np.ones((len(tasks), len(node_infos)), dtype=bool)
            for ti in relevant:
                for ni, node in enumerate(node_infos):
                    try:
                        predicate(tasks[ti], node)
                    except PredicateError:
                        mask[ti, ni] = False
            return mask

        ssn.add_feasibility_fn(self.NAME, feasibility)

        def batch_node_order(task, nodes) -> Dict[str, float]:
            """Fewest NUMA domains touched wins (numaaware.go:158-183)."""
            scores: Dict[str, float] = {}
            if not task.topology_policy or task.topology_policy == "none":
                return scores
            per_node = self.assign_res.get(task.uid)
            if not per_node:
                return scores
            raw: Dict[str, int] = {}
            for node in nodes:
                assigned = per_node.get(node.name, {}).get(CPU)
                if assigned is None or node.numa_info is None:
                    continue
                numa_ids = {node.numa_info.cpu_detail[c].numa_id
                            for c in assigned
                            if c in node.numa_info.cpu_detail}
                raw[node.name] = len(numa_ids)
            normalized = normalize_score(MAX_NODE_SCORE, True, raw)
            return {name: float(score * self.weight)
                    for name, score in normalized.items()}

        ssn.add_batch_node_order_fn(self.NAME, batch_node_order)

    def on_session_close(self, ssn) -> None:
        """Writeback: commit cpusets of tasks that were bound this session
        (numaaware.go OnSessionClose:255-284)."""
        if not self.task_bind_node:
            return
        numa_sets: Dict[str, Dict[str, ResNumaSets]] = {}
        for task_uid, node_name in self.task_bind_node.items():
            assigned = self.assign_res.get(task_uid, {}).get(node_name)
            if not assigned:
                continue
            numa_sets.setdefault(node_name, {})[task_uid] = assigned
        if numa_sets:
            ssn.update_scheduler_numa_info(numa_sets)


def New(arguments):
    return NumaAwarePlugin(arguments)
