"""Control-plane entry points — the cmd/{scheduler,controller-manager}
binaries plus the snapshot-RPC sidecar (ref cmd/scheduler/app/
server.go:57-141, cmd/controller-manager/app/server.go:51-130).

The in-process deployment runs everything in one VolcanoSystem; these
binaries exist for the split topology: a store (or a Go shim against a
real API server) on one side, scheduler/controllers as separate processes
with leader election on the other.
"""

from __future__ import annotations

import argparse
import signal
import threading
from typing import List, Optional


def scheduler_main(argv: Optional[List[str]] = None) -> int:
    """vc-scheduler: the full in-process control plane with the scheduling
    loop in the foreground (flags mirror cmd/scheduler/app/options)."""
    parser = argparse.ArgumentParser(prog="vc-scheduler")
    parser.add_argument("--scheduler-conf", default=None,
                        help="YAML conf path (hot-reloaded on change)")
    parser.add_argument("--schedule-period", type=float, default=1.0)
    parser.add_argument("--default-queue", default="default")
    parser.add_argument("--leader-elect", action="store_true",
                        help="acquire the store lease before scheduling")
    parser.add_argument("--native-store", action="store_true",
                        help="back state with the C++ object store")
    parser.add_argument("--listen-address", type=int, default=0,
                        metavar="PORT",
                        help="serve /metrics and /healthz on this port "
                             "(0 = disabled)")
    parser.add_argument("--journal", default=None, metavar="PATH",
                        help="write-ahead intent journal file "
                             "(docs/robustness.md): bind/evict intents "
                             "are journaled before execution and "
                             "reconciled at startup, so a scheduler "
                             "killed mid-cycle restarts without "
                             "double-binds (VOLCANO_TPU_JOURNAL=0 "
                             "disables)")
    args = parser.parse_args(argv)

    if args.listen_address:
        from . import metrics
        metrics.start_metrics_server(args.listen_address)

    from .system import VolcanoSystem
    sys_ = VolcanoSystem(schedule_period=args.schedule_period,
                         default_queue=args.default_queue,
                         native_store=args.native_store)
    sys_.scheduler.conf_path = args.scheduler_conf
    if args.journal:
        from .cache.journal import IntentJournal
        sys_.cache.attach_journal(IntentJournal(args.journal))
    signal.signal(signal.SIGTERM, lambda *_: sys_.stop())
    try:
        if args.leader_elect:
            sys_.scheduler.run_with_leader_election(sys_.store)
        else:
            sys_.scheduler.run()
    except KeyboardInterrupt:
        sys_.stop()
    return 0


def controller_manager_main(argv: Optional[List[str]] = None) -> int:
    """vc-controller-manager: store + webhooks + controllers, no scheduler
    (the scheduler talks to the same store from its own process via the
    snapshot RPC)."""
    parser = argparse.ArgumentParser(prog="vc-controller-manager")
    parser.add_argument("--leader-elect", action="store_true")
    parser.add_argument("--native-store", action="store_true")
    args = parser.parse_args(argv)

    from .controllers import start_controllers
    from .store import ObjectStore
    from .webhooks import register_webhooks
    if args.native_store:
        from .native import make_object_store
        store = make_object_store(prefer_native=True)
    else:
        store = ObjectStore()
    register_webhooks(store)
    start_controllers(store)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())

    def wait() -> int:
        try:
            stop.wait()
        except KeyboardInterrupt:
            pass
        return 0

    if args.leader_elect:
        from .leaderelection import LeaderElector
        LeaderElector(store, "vc-controller-manager",
                      on_started_leading=wait).run()
        return 0
    return wait()


def snapshot_rpc_main(argv: Optional[List[str]] = None) -> int:
    """vc-snapshot-rpc: the Go-shim-facing scheduler sidecar (SURVEY M2)."""
    parser = argparse.ArgumentParser(prog="vc-snapshot-rpc")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7070)
    parser.add_argument("--scheduler-conf", default=None)
    parser.add_argument("--listen-address", type=int, default=0,
                        help="serve /metrics and /healthz on this port "
                             "(0 = off)")
    args = parser.parse_args(argv)

    conf_text = None
    if args.scheduler_conf:
        with open(args.scheduler_conf) as f:
            conf_text = f.read()
    if args.listen_address:
        from . import metrics
        metrics.start_metrics_server(args.listen_address)
    from .rpc import serve
    server, thread, port = serve(args.host, args.port, conf_text)
    print(f"vc-snapshot-rpc listening on {args.host}:{port}")
    if args.scheduler_conf:
        # conf hot-reload: mtime watch on the mounted file, applied
        # between cycles (pkg/filewatcher + scheduler.go:112-170)
        import os
        import threading
        import time as _time

        def watch():
            last = os.stat(args.scheduler_conf).st_mtime
            while True:
                _time.sleep(2.0)
                try:
                    mtime = os.stat(args.scheduler_conf).st_mtime
                except OSError:
                    continue
                if mtime != last:
                    last = mtime
                    with open(args.scheduler_conf) as f:
                        server.service.reload_conf(f.read())
                    print("vc-snapshot-rpc: scheduler conf reloaded")
        threading.Thread(target=watch, daemon=True,
                         name="conf-watch").start()
    try:
        thread.join()
    except KeyboardInterrupt:
        server.shutdown()
    return 0
