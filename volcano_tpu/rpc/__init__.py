"""Snapshot RPC boundary — the Go-shim-facing service (SURVEY.md M2/§5.8).

The north-star deployment keeps a thin Go shim with client-go against a
real cluster: it serializes the cluster snapshot, ships it here, and
executes the returned bind/evict decisions through its own unchanged
Statement machinery. This package defines that boundary so the in-process
ObjectStore is ONE of two frontends:

- codec:  a versioned JSON wire schema for snapshots (nodes with live
  usage, jobs/podgroups with task status, queues) and decisions (binds,
  evictions, podgroup phase/condition writebacks);
- service: `SchedulerService` runs the real conf pipeline (session,
  actions, plugins — the same code the in-process scheduler uses) over a
  cache rebuilt from a decoded snapshot, with recording executors whose
  output becomes the response;
- server: a length-prefixed TCP server (`serve(...)`) exposing the
  service; the protocol is 4-byte big-endian length + UTF-8 JSON both
  ways, trivially speakable from Go.
"""

from .codec import (decisions_from_recorders, decode_snapshot,
                    encode_snapshot)
from .service import SchedulerService
from .server import SnapshotClient, serve

__all__ = ["encode_snapshot", "decode_snapshot",
           "decisions_from_recorders", "SchedulerService",
           "SnapshotClient", "serve"]
