"""SchedulerService: one snapshot in -> one cycle of the REAL pipeline ->
decisions out.

This is the sidecar half of SURVEY.md M2: the Go shim keeps client-go and
the Statement execution; everything between Snapshot() and Commit() — the
session, the plugin tiers, the TPU placement kernels — runs here, unmodified
from the in-process scheduler.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..cache import SchedulerCache
from ..cache.executors import Binder, Evictor
from ..framework import close_session, get_action, open_session, \
    parse_scheduler_conf
from .codec import decisions_from_recorders, decode_snapshot


class RecordingBinder(Binder):
    """Keyed records with task uids so the shim can map decisions back to
    pods without name parsing ambiguity."""

    def __init__(self):
        self.bind_records: Dict[tuple, str] = {}

    def bind(self, task, hostname: str) -> None:
        self.bind_records[(task.key(), task.uid)] = hostname


class RecordingEvictor(Evictor):
    def __init__(self):
        self.evict_records = []

    def evict(self, task, reason: str) -> None:
        self.evict_records.append((task.key(), task.uid, reason))


class SchedulerService:
    """Stateless per-request scheduling: every call rebuilds the cache from
    the snapshot (the store-is-the-checkpoint stance — SURVEY §5.4 — now
    with the store on the OTHER side of the wire)."""

    def __init__(self, conf_text: Optional[str] = None):
        # actions/plugins register on import
        from .. import actions as _actions  # noqa: F401
        from .. import plugins as _plugins  # noqa: F401
        self.conf = parse_scheduler_conf(conf_text)
        # one snapshot in flight at a time: the transport serves concurrent
        # connections, but a cycle touches process-global state (engine
        # stat counters, jit/solver caches), so concurrent cycles would
        # interleave those in surprising ways
        self._cycle_lock = threading.Lock()

    def schedule(self, snapshot_msg: dict) -> dict:
        with self._cycle_lock:
            return self._schedule_locked(snapshot_msg)

    def reload_conf(self, conf_text: Optional[str]) -> None:
        """Swap the scheduler conf between cycles (the sidecar's
        filewatcher hot-reload — scheduler.go:112-170 analogue)."""
        conf = parse_scheduler_conf(conf_text)
        with self._cycle_lock:
            self.conf = conf

    def _schedule_locked(self, snapshot_msg: dict) -> dict:
        nodes, jobs, queues = decode_snapshot(snapshot_msg)
        binder = RecordingBinder()
        evictor = RecordingEvictor()
        cache = SchedulerCache(binder=binder, evictor=evictor,
                               default_queue="")
        for q in queues:
            cache.add_queue(q)
        for n in nodes:
            cache.add_node(n)
        for j in jobs:
            cache.add_job(j)

        ssn = open_session(cache, self.conf.tiers, self.conf.configurations)
        try:
            for name in self.conf.actions:
                action = get_action(name)
                if action is not None:
                    action.execute(ssn)
        finally:
            close_session(ssn)
        return decisions_from_recorders(binder, evictor,
                                        list(ssn.jobs.values()))
