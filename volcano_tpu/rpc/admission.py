"""Admission over the wire (VERDICT r2 #9): expose the webhook router
through the sidecar protocol so topology-3 writes — which originate
outside the scheduler process — are validated and defaulted before they
reach the API server.

Mirrors /root/reference/cmd/webhook-manager/app/server.go:41-108: where
the reference serves AdmissionReview over TLS HTTP, the sidecar accepts
an ``{"op": "admit"}`` message on the same length-prefixed TCP framing
the snapshot RPC uses. The TLS front is the Go shim's webhook server
(shim/webhook.go, enabled with --webhook-addr and registered by
deploy/kubernetes/webhook.yaml + deploy/gen-admission-secret.sh): it
terminates the API server's AdmissionReview POSTs on the reference
router paths, translates the object to this wire schema, and attaches
the cluster context the validators consult (queues for jobs/validate
queue-state checks, podgroups for the pods gate), keeping the sidecar
stateless per request exactly like the scheduling op. Both sides of the
wire format are pinned to shim/testdata/golden_admission.json
(tests/test_rpc.py here, TestAdmissionGolden on the Go side).

Request:
  {"v": 1, "op": "admit",
   "review": {"kind": "Job|Queue|PodGroup|Pod",
              "operation": "CREATE|UPDATE|DELETE",
              "object": {...}, "old": {...}|null,
              "context": {"queues": [...], "podgroups": [...]}}}
Response:
  {"v": 1, "allowed": true|false, "message": "...",
   "patched": {...}|null}        # mutated object when a mutator changed it

Objects travel as plain JSON mirrors of the apis.objects dataclasses
(enums by value, Resource as the codec RES dict); ``to_wire``/
``from_wire`` are generic over the dataclass type hints so the schema
follows the objects without a parallel codec to maintain.
"""

from __future__ import annotations

import dataclasses
import enum
import re
import typing

from ..api.resource import Resource
from ..apis.objects import Job, Pod, PodGroupCR, QueueCR
from ..store import AdmissionError, ObjectStore
from ..webhooks.admission import register_webhooks
from .codec import VERSION, _res, _res_from

KINDS = {"Job": Job, "Queue": QueueCR, "PodGroup": PodGroupCR, "Pod": Pod}


def to_wire(obj):
    """dataclass / enum / Resource -> JSON-compatible structures."""
    if obj is None:
        return None
    if isinstance(obj, Resource):
        return _res(obj)
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj):
        return {f.name: to_wire(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)([A-Z])", r"_\1", name).lower()


def _strip_optional(tp):
    if typing.get_origin(tp) is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def from_wire(tp, data):
    """Rebuild a typed object from its wire form using the dataclass type
    hints (the inverse of :func:`to_wire`)."""
    tp = _strip_optional(tp)
    if data is None:
        return None
    if tp is Resource:
        return _res_from(data)
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(data)
    if dataclasses.is_dataclass(tp):
        if not isinstance(data, dict):
            raise TypeError(f"{tp.__name__} expects an object, "
                            f"got {type(data).__name__}")
        hints = typing.get_type_hints(tp)
        names = {f.name for f in dataclasses.fields(tp)}
        kwargs = {}
        for key, value in data.items():
            # accept k8s camelCase aliases (a webhook front end forwards
            # AdmissionReview objects verbatim); anything else is a
            # malformed review and must fail CLOSED — silently dropping
            # unknown keys would admit objects with defaulted fields
            name = key if key in names else _snake(key)
            if name not in names:
                raise TypeError(f"{tp.__name__}: unknown field {key!r}")
            kwargs[name] = from_wire(hints[name], value)
        return tp(**kwargs)
    origin = typing.get_origin(tp)
    if origin in (list, tuple):
        if not isinstance(data, list):
            raise TypeError(f"expected a list, got {type(data).__name__}")
        (item_tp,) = typing.get_args(tp) or (typing.Any,)
        return [from_wire(item_tp, v) for v in data]
    if origin is dict:
        if not isinstance(data, dict):
            raise TypeError(f"expected an object, got {type(data).__name__}")
        args = typing.get_args(tp)
        val_tp = args[1] if len(args) == 2 else typing.Any
        return {k: from_wire(val_tp, v) for k, v in data.items()}
    return data


class AdmissionOverWire:
    """One ``admit`` review -> the REAL webhook router verdict.

    Each request builds an ephemeral store seeded with the review context
    (no admission hooks — the context is already-admitted cluster state),
    registers the stock webhook router against it, and replays the hook
    the store would fire for this operation.
    """

    def admit(self, msg: dict) -> dict:
        if msg.get("v") != VERSION:
            return {"v": VERSION, "allowed": False,
                    "message": f"unsupported protocol version "
                               f"{msg.get('v')!r}", "patched": None}
        review = msg.get("review") or {}
        kind = review.get("kind", "")
        operation = review.get("operation", "CREATE")
        cls = KINDS.get(kind)
        if cls is None:
            return {"v": VERSION, "allowed": False,
                    "message": f"unsupported kind {kind!r}", "patched": None}
        try:
            obj = from_wire(cls, review.get("object") or {})
            old = (from_wire(cls, review["old"])
                   if review.get("old") else None)
            ctx = review.get("context") or {}
            ctx_objs = ([from_wire(QueueCR, qd)
                         for qd in ctx.get("queues") or []]
                        + [from_wire(PodGroupCR, pgd)
                           for pgd in ctx.get("podgroups") or []])
            # seed context BEFORE the hooks attach: already-admitted
            # cluster state must not re-run admission
            store = ObjectStore()
            for ctx_obj in ctx_objs:
                store.create(ctx_obj)
        except (TypeError, ValueError, KeyError, AttributeError) as exc:
            return {"v": VERSION, "allowed": False,
                    "message": f"malformed object: {exc}", "patched": None}
        before = to_wire(obj)
        router = register_webhooks(store)

        try:
            mutated = router.hook(operation, kind, obj, old)
        except AdmissionError as exc:
            return {"v": VERSION, "allowed": False, "message": str(exc),
                    "patched": None}
        patched = to_wire(mutated)
        return {"v": VERSION, "allowed": True, "message": "",
                "patched": None if patched == before else patched}
