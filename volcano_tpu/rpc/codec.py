"""Wire schema for the snapshot RPC (SURVEY.md §5.8: "task x node tensors
out, bind decisions back").

Versioned JSON — chosen over a binary layout because the payload is
dominated by per-task rows that a Go shim can emit directly from client-go
objects without a codegen step; at the 10k-pod benchmark scale the encoded
snapshot is a few MB, far below the 1s cycle budget on loopback.

Schema (version 1):

  snapshot = {"v": 1,
    "nodes":  [{"name", "allocatable": RES, "capability": RES, "used": RES,
                "idle": RES, "releasing": RES, "pipelined": RES, "labels",
                "taints", "annotations", "unschedulable"}],
    "queues": [{"name", "weight", "reclaimable", "capability": RES|null,
                "annotations"}],
    "jobs":   [{"uid", "name", "namespace", "queue", "min_available",
                "priority", "phase", "created", "preemptable",
                "revocable_zone", "min_resources": RES|null,
                "tasks": [{"uid", "name", "status", "node", "resreq": RES,
                           "priority", "created", "preemptable",
                           "revocable_zone", "topology_policy", "task_role",
                           "labels", "annotations", "node_selector",
                           "tolerations", "affinity", "host_ports"}]}]}
  RES = {"cpu": milli, "memory": bytes, "scalars": {...},
         "max_task_num": pods}

  Node usage vectors are authoritative on decode: resources consumed by
  pods OUTSIDE the jobs array (daemonsets, system pods on a real cluster)
  stay accounted, and placed tasks attach without re-subtracting.

  decisions = {"v": 1,
    "binds":  [{"uid", "namespace", "name", "node"}],
    "evicts": [{"uid", "namespace", "name", "reason"}],
    "podgroups": [{"uid", "phase", "conditions"}]}
"""

from __future__ import annotations

from typing import Dict, List

from ..api import (JobInfo, NodeInfo, PodGroup, PodGroupPhase, QueueInfo,
                   Resource, TaskInfo, TaskStatus)

VERSION = 1


def _res(r: Resource) -> dict:
    out = {"cpu": r.cpu, "memory": r.memory}
    if r.scalars:
        out["scalars"] = dict(r.scalars)
    if r.max_task_num is not None:
        out["max_task_num"] = r.max_task_num
    return out


def _res_from(d: dict) -> Resource:
    r = Resource(d.get("cpu", 0.0), d.get("memory", 0.0),
                 d.get("scalars") or None)
    if "max_task_num" in d:
        r.max_task_num = d["max_task_num"]
    return r


def encode_snapshot(nodes: List[NodeInfo], jobs: List[JobInfo],
                    queues: List[QueueInfo]) -> dict:
    return {
        "v": VERSION,
        "nodes": [{
            "name": n.name,
            "allocatable": _res(n.allocatable),
            "capability": _res(n.capability),
            "used": _res(n.used),
            "idle": _res(n.idle),
            "releasing": _res(n.releasing),
            "pipelined": _res(n.pipelined),
            "labels": n.labels,
            "taints": n.taints,
            "annotations": n.annotations,
            "unschedulable": n.unschedulable,
        } for n in nodes],
        "queues": [{
            "name": q.name,
            "weight": q.weight,
            "reclaimable": q.reclaimable,
            "capability": _res(q.capability) if q.capability else None,
            "annotations": q.annotations,
        } for q in queues],
        "jobs": [{
            "uid": j.uid,
            "name": j.name,
            "namespace": j.namespace,
            "queue": j.queue,
            "min_available": j.min_available,
            "priority": j.priority,
            "phase": j.podgroup.phase.value,
            "created": j.creation_timestamp,
            "preemptable": j.preemptable,
            "revocable_zone": j.revocable_zone,
            "min_resources": (_res(j.podgroup.min_resources)
                              if j.podgroup.min_resources else None),
            "tasks": [{
                "uid": t.uid,
                "name": t.name,
                "status": t.status.name,
                "node": t.node_name,
                "resreq": _res(t.resreq),
                "priority": t.priority,
                "created": t.creation_timestamp,
                "preemptable": t.preemptable,
                "revocable_zone": t.revocable_zone,
                "topology_policy": t.topology_policy,
                "task_role": t.task_role,
                "labels": t.labels,
                "annotations": t.annotations,
                "node_selector": t.node_selector,
                "tolerations": t.tolerations,
                "affinity": t.affinity,
                "host_ports": [list(p) for p in t.host_ports],
            } for t in j.tasks.values()],
        } for j in jobs],
    }


def decode_snapshot(msg: dict):
    """-> (nodes, jobs, queues) live api objects, placed tasks attached to
    their nodes exactly like the in-process cache snapshot."""
    if msg.get("v") != VERSION:
        raise ValueError(f"unsupported snapshot version {msg.get('v')!r}")
    nodes: Dict[str, NodeInfo] = {}
    for nd in msg["nodes"]:
        node = NodeInfo(name=nd["name"],
                        allocatable=_res_from(nd["allocatable"]),
                        capability=(_res_from(nd["capability"])
                                    if nd.get("capability") else None),
                        labels=nd.get("labels"), taints=nd.get("taints"),
                        annotations=nd.get("annotations"),
                        unschedulable=nd.get("unschedulable", False))
        # the wire usage vectors are authoritative — they include pods
        # outside the jobs array (system pods on a real cluster)
        node.used = _res_from(nd.get("used") or {})
        node.idle = (_res_from(nd["idle"]) if nd.get("idle")
                     else node.allocatable.clone())
        node.releasing = _res_from(nd.get("releasing") or {})
        node.pipelined = _res_from(nd.get("pipelined") or {})
        nodes[node.name] = node
    queues = [QueueInfo(
        name=qd["name"], weight=qd.get("weight", 1),
        reclaimable=qd.get("reclaimable", True),
        capability=(_res_from(qd["capability"])
                    if qd.get("capability") else None),
        annotations=qd.get("annotations")) for qd in msg["queues"]]
    jobs = []
    for jd in msg["jobs"]:
        pg = PodGroup(name=jd["name"], namespace=jd["namespace"],
                      queue=jd["queue"], min_member=jd["min_available"],
                      phase=PodGroupPhase(jd["phase"]),
                      min_resources=(_res_from(jd["min_resources"])
                                     if jd.get("min_resources") else None))
        job = JobInfo(uid=jd["uid"], name=jd["name"],
                      namespace=jd["namespace"], queue=jd["queue"],
                      min_available=jd["min_available"], podgroup=pg,
                      priority=jd.get("priority", 1),
                      creation_timestamp=jd.get("created"))
        job.preemptable = jd.get("preemptable", False)
        job.revocable_zone = jd.get("revocable_zone", "")
        for td in jd["tasks"]:
            task = TaskInfo(
                uid=td["uid"], name=td["name"], namespace=jd["namespace"],
                job=jd["uid"], resreq=_res_from(td["resreq"]),
                status=TaskStatus[td["status"]],
                priority=td.get("priority", 1),
                creation_timestamp=td.get("created"),
                preemptable=td.get("preemptable", False),
                revocable_zone=td.get("revocable_zone", ""),
                topology_policy=td.get("topology_policy", ""),
                task_role=td.get("task_role", ""),
                labels=td.get("labels"), annotations=td.get("annotations"),
                node_selector=td.get("node_selector"),
                tolerations=td.get("tolerations"),
                affinity=td.get("affinity"),
                host_ports=td.get("host_ports"))
            job.add_task_info(task)
            # placement survives even when the node is absent from the
            # snapshot (cordoned / in-flight-bind nodes are skipped, but
            # their tasks keep node context for affinity and eviction)
            own = job.tasks[task.uid]
            own.node_name = td.get("node") or ""
            node = nodes.get(own.node_name)
            if node is not None:
                # attach WITHOUT re-accounting: the wire usage vectors
                # already include every placed task (hostPort claims are
                # not part of the usage vectors, so they ARE accounted)
                clone = own.clone()
                clone.node_name = node.name
                node.tasks[clone.uid] = clone
                for port in clone.host_ports:
                    node.used_ports[port] = node.used_ports.get(port, 0) + 1
        jobs.append(job)
    return list(nodes.values()), jobs, queues


def decisions_from_recorders(binder, evictor, jobs: List[JobInfo]) -> dict:
    """Build the response from the recording executors + session-close
    PodGroup state."""
    return {
        "v": VERSION,
        "binds": [{"uid": uid, "namespace": key.split("/", 1)[0],
                   "name": key.split("/", 1)[1], "node": node}
                  for (key, uid), node in binder.bind_records.items()],
        "evicts": [{"uid": uid, "namespace": key.split("/", 1)[0],
                    "name": key.split("/", 1)[1], "reason": reason}
                   for key, uid, reason in evictor.evict_records],
        "podgroups": [{
            "uid": j.uid,
            "phase": j.podgroup.phase.value,
            "conditions": list(j.podgroup.conditions),
        } for j in jobs],
    }
