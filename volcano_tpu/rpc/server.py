"""Length-prefixed TCP transport for the snapshot RPC.

Protocol (both directions): 4-byte big-endian payload length, then UTF-8
JSON. Any language with sockets speaks it; the Go shim needs ~20 lines.
An error response is {"error": "..."} with the same framing.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Optional, Tuple

from .service import SchedulerService

MAX_MSG = 1 << 30


class BadPayload(Exception):
    """The frame was read intact but its JSON is invalid — recoverable:
    reply with an error and keep the connection."""


def _read_msg(sock) -> Optional[dict]:
    header = _read_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    if length > MAX_MSG:
        # framing is unrecoverable: we cannot skip what we won't read
        raise ValueError(f"message too large: {length}")
    body = _read_exact(sock, length)
    if body is None:
        return None
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise BadPayload(str(exc)) from exc


def _read_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _write_msg(sock, msg: dict) -> None:
    body = json.dumps(msg).encode("utf-8")
    sock.sendall(struct.pack(">I", len(body)) + body)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            try:
                msg = _read_msg(self.request)
            except BadPayload as exc:
                _write_msg(self.request, {"error": f"bad payload: {exc}"})
                continue
            except (ConnectionError, ValueError):
                return
            if msg is None:
                return
            try:
                if msg.get("op") == "admit":
                    out = self.server.admission.admit(msg)
                else:
                    out = self.server.service.schedule(msg)
            except Exception as exc:  # wire errors back, keep serving
                out = {"error": f"{type(exc).__name__}: {exc}"}
            _write_msg(self.request, out)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve(host: str = "127.0.0.1", port: int = 0,
          conf_text: Optional[str] = None,
          ) -> Tuple[_Server, threading.Thread, int]:
    """Start the sidecar; returns (server, thread, bound_port)."""
    server = _Server((host, port), _Handler)
    server.service = SchedulerService(conf_text)
    from .admission import AdmissionOverWire
    server.admission = AdmissionOverWire()
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="vc-snapshot-rpc")
    thread.start()
    return server, thread, server.server_address[1]


class SnapshotClient:
    """The Go shim's role, for tests and Python-side callers: connect,
    send a snapshot, read decisions."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)

    def schedule(self, snapshot_msg: dict) -> dict:
        _write_msg(self.sock, snapshot_msg)
        out = _read_msg(self.sock)
        if out is None:
            raise ConnectionError("server closed the connection")
        if "error" in out:
            raise RuntimeError(out["error"])
        return out

    def admit(self, kind: str, operation: str, obj: dict,
              old: Optional[dict] = None,
              context: Optional[dict] = None) -> dict:
        """Run one admission review through the wire (the webhook-manager
        role for topology 3); returns {"allowed", "message", "patched"}."""
        from .codec import VERSION
        return self.schedule({
            "v": VERSION, "op": "admit",
            "review": {"kind": kind, "operation": operation, "object": obj,
                       "old": old, "context": context or {}}})

    def close(self) -> None:
        self.sock.close()
