"""TaskInfo and JobInfo: the in-memory scheduling model of tasks and gangs.

Mirrors /root/reference/pkg/scheduler/api/pod_info.go and job_info.go:187-600
(gang state: MinAvailable, TaskStatusIndex, ReadyTaskNum, ValidTaskNum,
CheckTaskMinAvailable), re-shaped so a snapshot can be flattened into dense
``f32[T, R]`` request tensors for the TPU solver.
"""

from __future__ import annotations

import itertools
import time as _time
from typing import Dict, List, Optional, TYPE_CHECKING

from . import resource as _res
from .resource import Resource
from .types import PodGroupPhase, TaskStatus, allocated_status

if TYPE_CHECKING:
    from .unschedule_info import FitErrors

_uid_counter = itertools.count()


def _new_uid(prefix: str) -> str:
    return f"{prefix}-{next(_uid_counter)}"


def normalize_host_ports(ports) -> List[tuple]:
    """Container hostPort declarations → canonical ``(host_ip, protocol,
    port)`` tuples (the k8s nodeports plugin's GetContainerPorts shape).

    Accepts bare ints, k8s ContainerPort dicts (only entries with
    ``hostPort > 0`` count), or pre-normalized tuples. ``hostIP`` defaults to
    the 0.0.0.0 wildcard and ``protocol`` to TCP, matching upstream."""
    out: List[tuple] = []
    for p in ports or []:
        if isinstance(p, int):
            out.append(("0.0.0.0", "TCP", p))
        elif isinstance(p, dict):
            hp = int(p.get("hostPort") or 0)
            if hp > 0:
                out.append((p.get("hostIP") or "0.0.0.0",
                            p.get("protocol") or "TCP", hp))
        else:
            ip, proto, port = p
            out.append((ip or "0.0.0.0", proto or "TCP", int(port)))
    return out


class DisruptionBudget:
    """JobInfo disruption budget (job_info.go:354-365)."""

    def __init__(self, min_available: Optional[int] = None,
                 max_unavailable: Optional[int] = None):
        self.min_available = min_available
        self.max_unavailable = max_unavailable


class TaskInfo:
    """One schedulable unit (a pod in the reference, pod_info.go)."""

    def __init__(self, uid: Optional[str] = None, name: str = "", namespace: str = "default",
                 job: str = "", resreq: Optional[Resource] = None,
                 status: TaskStatus = TaskStatus.PENDING, priority: int = 1,
                 node_name: str = "", task_role: str = "",
                 node_selector: Optional[Dict[str, str]] = None,
                 tolerations: Optional[List[dict]] = None,
                 affinity: Optional[dict] = None,
                 labels: Optional[Dict[str, str]] = None,
                 annotations: Optional[Dict[str, str]] = None,
                 preemptable: bool = False, revocable_zone: str = "",
                 topology_policy: str = "",
                 creation_timestamp: Optional[float] = None,
                 host_ports: Optional[List] = None,
                 pod: object = None):
        self.uid = uid or _new_uid("task")
        self.name = name or self.uid
        self.namespace = namespace
        self.job = job                      # owning JobInfo uid
        self.resreq = resreq.clone() if resreq else Resource()
        # InitResreq: request at admission time; Resreq may be zeroed when the
        # task is running on opportunistic resources. We keep them equal unless
        # a caller changes one.
        self.init_resreq = self.resreq.clone()
        self.status = status
        self.priority = priority
        self.node_name = node_name
        # task_role groups replicas of the same task template; per-template
        # minAvailable (job_info.go TaskMinAvailable) is keyed by it.
        self.task_role = task_role or name
        self.node_selector = dict(node_selector or {})
        self.tolerations = list(tolerations or [])
        self.affinity = affinity or {}
        # memoized at build time: consulted for every task on every session
        # open (plugins/podaffinity.session_has_pod_affinity), and clones
        # carry it forward — affinity never changes after construction
        _pa = self.affinity.get("podAffinity") or {}
        _paa = self.affinity.get("podAntiAffinity") or {}
        self._has_pod_affinity = bool(
            _pa.get("requiredDuringSchedulingIgnoredDuringExecution")
            or _paa.get("requiredDuringSchedulingIgnoredDuringExecution")
            or _pa.get("preferredDuringSchedulingIgnoredDuringExecution")
            or _paa.get("preferredDuringSchedulingIgnoredDuringExecution"))
        self.labels = dict(labels or {})
        self.annotations = dict(annotations or {})
        self.preemptable = preemptable
        self.revocable_zone = revocable_zone
        # volcano.sh/numa-topology-policy annotation (pod_info.go
        # TopologyPolicy); consumed by the numaaware plugin.
        self.topology_policy = topology_policy
        # (host_ip, protocol, port) tuples the pod claims on its node
        # (nodeports predicate); treated as immutable after construction.
        self.host_ports: List[tuple] = normalize_host_ports(host_ports)
        self.creation_timestamp = creation_timestamp if creation_timestamp is not None else _time.time()
        self.pod = pod                      # backing store object, if any
        self.volume_ready = False

    @property
    def best_effort(self) -> bool:
        return self.init_resreq.is_empty()

    def clone(self) -> "TaskInfo":
        """Field-sharing copy — the hot path (cache snapshot clones every
        task every cycle). resreq / init_resreq are IMMUTABLE after
        construction: no mutation site exists in the tree (all arithmetic
        happens on node/job aggregate Resources, statuses flip via
        update_task_status), so sharing them is exact and 40k Resource
        copies per 10k-task snapshot vanish. The contract is documented on
        Resource (api/resource.py) and enforced in debug runs by freezing
        the shared instances here."""
        t = TaskInfo.__new__(TaskInfo)
        t.__dict__.update(self.__dict__)
        if _res._MUTATION_GUARD:
            self.resreq.freeze()
            self.init_resreq.freeze()
        return t

    # historical alias from when clone deep-copied the resource vectors;
    # one implementation, one behavior
    shallow_clone = clone

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def __repr__(self) -> str:
        return (f"Task({self.namespace}/{self.name} job={self.job} "
                f"status={self.status.name} node={self.node_name!r})")


class PodGroup:
    """Minimal scheduling/v1beta1 PodGroup mirror carried on JobInfo."""

    def __init__(self, name: str = "", namespace: str = "default", queue: str = "default",
                 min_member: int = 0, min_resources: Optional[Resource] = None,
                 priority_class_name: str = "",
                 phase: PodGroupPhase = PodGroupPhase.PENDING,
                 annotations: Optional[Dict[str, str]] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.namespace = namespace
        self.queue = queue
        self.min_member = min_member
        self.min_resources = min_resources
        self.priority_class_name = priority_class_name
        self.phase = phase
        self.conditions: List[dict] = []
        self.conditions_dirty = False
        self.annotations = dict(annotations or {})
        self.labels = dict(labels or {})
        self.running = 0
        self.succeeded = 0
        self.failed = 0


class JobInfo:
    """A gang: the scheduler-side view of one PodGroup and its tasks."""

    def __init__(self, uid: Optional[str] = None, name: str = "",
                 namespace: str = "default", queue: str = "default",
                 priority: int = 0, min_available: int = 0,
                 podgroup: Optional[PodGroup] = None,
                 creation_timestamp: Optional[float] = None):
        self.uid = uid or _new_uid("job")
        self.name = name or self.uid
        self.namespace = namespace
        self.queue = queue
        self.priority = priority
        self.min_available = min_available
        self.waiting_time: Optional[float] = None
        # when the scheduler first saw this job (job_info.go:216
        # ScheduleStartTimestamp) — the reservation plugin elects the
        # longest-waiting job by it; stamped by the cache on add
        self.schedule_start_timestamp: Optional[float] = None

        self.job_fit_errors = ""
        self.nodes_fit_errors: Dict[str, "FitErrors"] = {}

        self.tasks: Dict[str, TaskInfo] = {}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = {}
        self.task_min_available: Dict[str, int] = {}
        self.task_min_available_total = 0

        self.allocated = Resource()
        self.total_request = Resource()

        self.creation_timestamp = creation_timestamp if creation_timestamp is not None else _time.time()
        self.podgroup = podgroup or PodGroup(name=self.name, namespace=namespace,
                                             queue=queue, min_member=min_available)
        self.preemptable = False
        self.revocable_zone = ""
        self.budget: Optional[DisruptionBudget] = None
        # Mutation witness for the incremental snapshot (cache.snapshot
        # clone-on-dirty, docs/performance.md): every task-state mutation
        # funnels through _add_index/_del_index (add_task_info,
        # update_task_status, delete_task_info, the fused batched replay),
        # so the flag marks any job whose gang state moved since clone().
        self._touched = False

    # -- task bookkeeping (job_info.go:375-437) -----------------------------

    def _add_index(self, task: TaskInfo) -> None:
        self._touched = True
        self.task_status_index.setdefault(task.status, {})[task.uid] = task

    def _del_index(self, task: TaskInfo) -> None:
        self._touched = True
        bucket = self.task_status_index.get(task.status)
        if bucket is not None:
            bucket.pop(task.uid, None)
            if not bucket:
                del self.task_status_index[task.status]

    def add_task_info(self, task: TaskInfo) -> None:
        task.job = self.uid
        self.tasks[task.uid] = task
        self._add_index(task)
        if task.status == TaskStatus.PENDING or allocated_status(task.status):
            self.total_request.add(task.resreq)
        if allocated_status(task.status):
            self.allocated.add(task.resreq)

    def update_task_status(self, task: TaskInfo, status: TaskStatus) -> None:
        own = self.tasks.get(task.uid)
        if own is None:
            raise KeyError(f"task {task.uid} not in job {self.uid}")
        # sub-then-add of the same resreq is a no-op: only cross the
        # allocated boundary (hot at 10k binds/cycle, e.g. BINDING->BOUND)
        was = allocated_status(own.status)
        now = allocated_status(status)
        if was and not now:
            self.allocated.sub(own.resreq)
        self._del_index(own)
        own.status = status
        if now and not was:
            self.allocated.add(own.resreq)
        self._add_index(own)

    def delete_task_info(self, task: TaskInfo) -> None:
        own = self.tasks.pop(task.uid, None)
        if own is None:
            return
        if allocated_status(own.status):
            self.allocated.sub(own.resreq)
        if own.status == TaskStatus.PENDING or allocated_status(own.status):
            self.total_request.sub(own.resreq)
        self._del_index(own)

    # -- gang state (job_info.go:509-600) -----------------------------------

    def ready_task_num(self) -> int:
        """Allocated/Bound/Binding/Running + Succeeded + best-effort Pending."""
        occupied = 0
        for status, tasks in self.task_status_index.items():
            if allocated_status(status) or status == TaskStatus.SUCCEEDED:
                occupied += len(tasks)
            elif status == TaskStatus.PENDING:
                occupied += sum(1 for t in tasks.values() if t.init_resreq.is_empty())
        return occupied

    def waiting_task_num(self) -> int:
        return len(self.task_status_index.get(TaskStatus.PIPELINED, {}))

    def ready(self) -> bool:
        return self.ready_task_num() >= self.min_available

    def pipelined(self) -> bool:
        return self.waiting_task_num() + self.ready_task_num() >= self.min_available

    def valid_task_num(self) -> int:
        occupied = 0
        for status, tasks in self.task_status_index.items():
            if (allocated_status(status) or status in
                    (TaskStatus.SUCCEEDED, TaskStatus.PIPELINED, TaskStatus.PENDING)):
                occupied += len(tasks)
        return occupied

    def check_task_min_available(self) -> bool:
        """Per-task-template minAvailable check (job_info.go:543-570)."""
        if self.min_available < self.task_min_available_total:
            return True
        actual: Dict[str, int] = {}
        for status, tasks in self.task_status_index.items():
            if (allocated_status(status) or status in
                    (TaskStatus.SUCCEEDED, TaskStatus.PIPELINED, TaskStatus.PENDING)):
                for t in tasks.values():
                    actual[t.task_role] = actual.get(t.task_role, 0) + 1
        for role, min_avail in self.task_min_available.items():
            if actual.get(role, 0) < min_avail:
                return False
        return True

    def get_min_resources(self) -> Resource:
        if self.podgroup and self.podgroup.min_resources is not None:
            return self.podgroup.min_resources.clone()
        return Resource()

    def is_pending(self) -> bool:
        return (self.podgroup is None
                or self.podgroup.phase in (PodGroupPhase.PENDING, ""))

    def fit_error(self) -> str:
        """Aggregate pending-reason string (job_info.go:489-507)."""
        counts: Dict[TaskStatus, int] = {}
        for status, tasks in self.task_status_index.items():
            counts[status] = len(tasks)
        sorted_counts = ", ".join(
            f"{n} {s.name}" for s, n in sorted(counts.items(), key=lambda kv: kv[0]))
        return f"job is not ready, task statuses: {sorted_counts}"

    def clone(self) -> "JobInfo":
        job = JobInfo(uid=self.uid, name=self.name, namespace=self.namespace,
                      queue=self.queue, priority=self.priority,
                      min_available=self.min_available, podgroup=self.podgroup,
                      creation_timestamp=self.creation_timestamp)
        job.waiting_time = self.waiting_time
        job.schedule_start_timestamp = self.schedule_start_timestamp
        job.task_min_available = dict(self.task_min_available)
        job.task_min_available_total = self.task_min_available_total
        job.preemptable = self.preemptable
        job.revocable_zone = self.revocable_zone
        job.budget = self.budget
        for task in self.tasks.values():
            job.add_task_info(task.clone())
        job._touched = False        # a fresh clone starts clean
        return job

    def __repr__(self) -> str:
        return (f"Job({self.namespace}/{self.name} queue={self.queue} "
                f"minAvailable={self.min_available} tasks={len(self.tasks)})")
