"""Host-side resource vector model.

Mirrors the semantics of the reference's ``Resource`` type
(/root/reference/pkg/scheduler/api/resource_info.go:49-487) — milli-CPU +
memory + arbitrary scalar resources, epsilon-tolerant comparisons with
Zero/Infinity defaults for missing dimensions — but is designed to round-trip
losslessly into fixed-width ``float32`` vectors (see
:class:`ResourceNames`), because on TPU every resource is one lane of an
``f32[..., R]`` array and all the per-dimension arithmetic becomes vector ops.
"""

from __future__ import annotations

import math
import os
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

# Debug-mode mutation guard for the shared-across-clones immutability
# contract (see Resource docstring): when on, Resources marked frozen()
# raise on any in-place mutation. Off by default — the check costs one
# branch on the hottest arithmetic in the tree. Enable with the env var
# below or set_mutation_guard(True) (chaos/regression rigs).
_MUTATION_GUARD = bool(os.environ.get("VOLCANO_TPU_DEBUG_RESOURCE_FREEZE"))


def set_mutation_guard(on: bool) -> None:
    global _MUTATION_GUARD
    _MUTATION_GUARD = bool(on)

# Epsilon used by the reference for all comparisons
# (resource_info.go:36 `minResource float64 = 0.1`).
MIN_RESOURCE = 0.1

# DimensionDefaultValue (resource_info.go:40-48): how a dimension that is
# absent from a Resource's scalar map is treated during comparisons.
ZERO = "Zero"
INFINITY = "Infinity"

CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
GPU_RESOURCE_NAME = "nvidia.com/gpu"
TPU_RESOURCE_NAME = "google.com/tpu"

# Sentinel the reference uses internally for "infinity" (resource_info.go:457-487).
_INF = math.inf


def _le_eps(l: float, r: float) -> bool:
    """l <= r with the reference's epsilon (resource_info.go:311-316)."""
    return l < r or abs(l - r) < MIN_RESOURCE


class Resource:
    """A resource vector: milli-CPU, memory (bytes), scalar resources.

    ``max_task_num`` mirrors ``MaxTaskNum`` (resource_info.go:57-59): only used
    by predicates (pod-count capacity), never part of arithmetic.

    **Shared-across-clones immutability contract.** The snapshot hot paths
    deliberately SHARE Resource instances instead of copying them:
    ``TaskInfo.clone`` shares ``resreq``/``init_resreq`` and
    ``NodeInfo.clone`` shares ``allocatable``/``capability`` between the
    live cache object and every per-cycle snapshot clone. That is exact
    only because those fields are never mutated after construction — all
    arithmetic happens on the node/job AGGREGATE Resources (idle, used,
    releasing, pipelined, allocated), which the clones do copy. Any new
    code that wants to change a task's request or a node's allocatable
    must REPLACE the Resource (build a new one via clone().add(...)),
    never mutate it in place, or every snapshot sharing it silently
    corrupts. ``freeze()`` plus the VOLCANO_TPU_DEBUG_RESOURCE_FREEZE env
    var (or set_mutation_guard) turn a violation into an immediate
    AssertionError in debug runs: clone sites freeze the shared instances,
    and every in-place mutator checks the mark.
    """

    __slots__ = ("cpu", "memory", "scalars", "max_task_num", "_frozen")

    def __init__(self, cpu: float = 0.0, memory: float = 0.0,
                 scalars: Optional[Dict[str, float]] = None,
                 max_task_num: Optional[int] = None):
        self.cpu = float(cpu)
        self.memory = float(memory)
        self.scalars: Dict[str, float] = dict(scalars) if scalars else {}
        self.max_task_num = max_task_num

    # -- constructors -------------------------------------------------------

    @classmethod
    def empty(cls) -> "Resource":
        return cls()

    @classmethod
    def from_dict(cls, rl: Dict[str, object]) -> "Resource":
        """Build from a resource-list style dict, e.g. ``{"cpu": "2", "memory": "4Gi",
        "nvidia.com/gpu": 1, "pods": 110}`` (NewResource, resource_info.go:68-87)."""
        r = cls()
        for name, q in rl.items():
            if name == CPU:
                r.cpu += parse_quantity(q) * 1000.0
            elif name == MEMORY:
                r.memory += parse_quantity(q)
            elif name == PODS:
                r.max_task_num = int(parse_quantity(q)) + (r.max_task_num or 0)
            else:
                # scalar resources are stored in milli-units like the
                # reference (resource_info.go:80-84)
                r.scalars[name] = r.scalars.get(name, 0.0) + parse_quantity(q) * 1000.0
        return r

    def clone(self) -> "Resource":
        # bypasses __init__ (float() coercions): clone is the hottest
        # Resource path — node aggregates on every snapshot. Clones are
        # freshly mutable: the frozen mark (debug guard) is not copied.
        r = Resource.__new__(Resource)
        r.cpu = self.cpu
        r.memory = self.memory
        r.scalars = dict(self.scalars)
        r.max_task_num = self.max_task_num
        return r

    # -- debug-mode immutability guard (class docstring contract) -----------

    def freeze(self) -> "Resource":
        """Mark this instance as shared/immutable; only enforced when the
        mutation guard is on (clone() output is always fresh/unfrozen)."""
        self._frozen = True
        return self

    def _mutation_check(self) -> None:
        if getattr(self, "_frozen", False):
            raise AssertionError(
                "in-place mutation of a frozen (shared-across-clones) "
                f"Resource <{self}> — replace it instead; see the "
                "immutability contract in api/resource.py")

    # -- accessors ----------------------------------------------------------

    def get(self, name: str) -> float:
        if name == CPU:
            return self.cpu
        if name == MEMORY:
            return self.memory
        return self.scalars.get(name, 0.0)

    def set(self, name: str, value: float) -> None:
        if _MUTATION_GUARD:
            self._mutation_check()
        if name == CPU:
            self.cpu = value
        elif name == MEMORY:
            self.memory = value
        else:
            self.scalars[name] = value

    def resource_names(self) -> List[str]:
        return [CPU, MEMORY] + list(self.scalars)

    def is_empty(self) -> bool:
        """True iff every dimension is below epsilon (resource_info.go:142-155)."""
        if not (self.cpu < MIN_RESOURCE and self.memory < MIN_RESOURCE):
            return False
        return all(q < MIN_RESOURCE for q in self.scalars.values())

    def is_zero(self, name: str) -> bool:
        return self.get(name) < MIN_RESOURCE

    # -- arithmetic (in place, returning self, like the reference) ----------

    def add(self, rr: "Resource") -> "Resource":
        if _MUTATION_GUARD:
            self._mutation_check()
        self.cpu += rr.cpu
        self.memory += rr.memory
        for n, q in rr.scalars.items():
            self.scalars[n] = self.scalars.get(n, 0.0) + q
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract; asserts sufficiency like the reference (resource_info.go:191-206)."""
        if _MUTATION_GUARD:
            self._mutation_check()
        assert rr.less_equal(self, ZERO), \
            f"resource is not sufficient to do operation: <{self}> sub <{rr}>"
        self.cpu -= rr.cpu
        self.memory -= rr.memory
        for n, q in rr.scalars.items():
            if n in self.scalars:
                self.scalars[n] -= q
        return self

    def multi(self, ratio: float) -> "Resource":
        if _MUTATION_GUARD:
            self._mutation_check()
        self.cpu *= ratio
        self.memory *= ratio
        for n in self.scalars:
            self.scalars[n] *= ratio
        return self

    def set_max_resource(self, rr: "Resource") -> "Resource":
        """Per-dimension max (resource_info.go:218-247)."""
        if _MUTATION_GUARD:
            self._mutation_check()
        self.cpu = max(self.cpu, rr.cpu)
        self.memory = max(self.memory, rr.memory)
        for n, q in rr.scalars.items():
            self.scalars[n] = max(self.scalars.get(n, -_INF), q)
        return self

    def fit_delta(self, rr: "Resource") -> "Resource":
        """Available-minus-requested with epsilon margin; negative dimensions
        mark insufficiency (resource_info.go:249-276)."""
        if _MUTATION_GUARD:
            self._mutation_check()
        if rr.cpu > 0:
            self.cpu -= rr.cpu + MIN_RESOURCE
        if rr.memory > 0:
            self.memory -= rr.memory + MIN_RESOURCE
        for n, q in rr.scalars.items():
            if q > 0:
                self.scalars[n] = self.scalars.get(n, 0.0) - q - MIN_RESOURCE
        return self

    def min_dimension_resource(self, rr: "Resource") -> "Resource":
        """Per-dimension min against rr; dimensions missing from rr are
        treated as zero (resource_info.go:428-455)."""
        if _MUTATION_GUARD:
            self._mutation_check()
        self.cpu = min(self.cpu, rr.cpu)
        self.memory = min(self.memory, rr.memory)
        for n in list(self.scalars):
            self.scalars[n] = min(self.scalars[n], rr.scalars.get(n, 0.0))
        return self

    def diff(self, rr: "Resource") -> Tuple["Resource", "Resource"]:
        """(increased, decreased) per dimension (resource_info.go:372-409)."""
        inc, dec = Resource(), Resource()
        for n in set(self.resource_names()) | set(rr.resource_names()):
            d = self.get(n) - rr.get(n)
            (inc if d > 0 else dec).set(n, abs(d))
        return inc, dec

    # -- comparisons --------------------------------------------------------

    def _paired_dims(self, rr: "Resource", default: str) -> Iterable[Tuple[float, float]]:
        """Yield (left, right) for every scalar dimension of the union, with
        missing dimensions replaced by the default (0 or infinity), mirroring
        setDefaultValue (resource_info.go:457-487)."""
        fill = 0.0 if default == ZERO else _INF
        for n in set(self.scalars) | set(rr.scalars):
            yield (self.scalars.get(n, fill), rr.scalars.get(n, fill))

    def less_equal(self, rr: "Resource", default: str = ZERO) -> bool:
        """LessEqualInAllDimension (resource_info.go:310-343)."""
        if not (_le_eps(self.cpu, rr.cpu) and _le_eps(self.memory, rr.memory)):
            return False
        for lv, rv in self._paired_dims(rr, default):
            if rv == _INF:
                continue
            if lv == _INF or not _le_eps(lv, rv):
                return False
        return True

    def less(self, rr: "Resource", default: str = ZERO) -> bool:
        """LessInAllDimension — strict, no epsilon (resource_info.go:278-308)."""
        if not (self.cpu < rr.cpu and self.memory < rr.memory):
            return False
        for lv, rv in self._paired_dims(rr, default):
            if rv == _INF:
                continue
            if lv == _INF or not lv < rv:
                return False
        return True

    def less_in_some_dimension(self, rr: "Resource") -> bool:
        """True if ANY dimension of self is below rr (resource_info.go:345-370)."""
        if self.cpu < rr.cpu or self.memory < rr.memory:
            return True
        for n, q in self.scalars.items():
            if n in rr.scalars and q < rr.scalars[n]:
                return True
        for n, q in rr.scalars.items():
            if n not in self.scalars and q > MIN_RESOURCE:
                return True
        return False

    # -- dunder sugar -------------------------------------------------------

    def __add__(self, rr: "Resource") -> "Resource":
        return self.clone().add(rr)

    def __sub__(self, rr: "Resource") -> "Resource":
        return self.clone().sub(rr)

    def __eq__(self, rr: object) -> bool:
        if not isinstance(rr, Resource):
            return NotImplemented
        names = set(self.resource_names()) | set(rr.resource_names())
        return all(abs(self.get(n) - rr.get(n)) < 1e-9 for n in names)

    def __repr__(self) -> str:
        s = f"cpu {self.cpu:0.2f}, memory {self.memory:0.2f}"
        for n, q in sorted(self.scalars.items()):
            s += f", {n} {q:0.2f}"
        return s

    # -- dense-vector bridge ------------------------------------------------

    def to_vector(self, names: "ResourceNames") -> np.ndarray:
        v = np.zeros(len(names), dtype=np.float32)
        for i, n in enumerate(names.names):
            v[i] = self.get(n)
        return v

    def to_vector_inf_fill(self, names: "ResourceNames") -> np.ndarray:
        """Like to_vector but missing scalar dims become +inf — used for queue
        capabilities, where an unspecified dimension means unlimited."""
        v = np.full(len(names), np.inf, dtype=np.float32)
        v[0] = self.cpu
        v[1] = self.memory
        for i, n in enumerate(names.names):
            if n in self.scalars:
                v[i] = self.scalars[n]
        return v

    @classmethod
    def from_vector(cls, v: np.ndarray, names: "ResourceNames") -> "Resource":
        r = cls()
        for i, n in enumerate(names.names):
            if float(v[i]) != 0.0:
                r.set(n, float(v[i]))
        r.cpu = float(v[0])
        r.memory = float(v[1])
        return r


class ResourceNames:
    """Fixed dimension registry for one snapshot: resource name → lane index.

    Dims 0/1 are always cpu/memory; scalar resources discovered in the
    snapshot follow in sorted order, so every tensor built from the same
    snapshot agrees on lane layout. This is the dense-array replacement for
    the reference's per-Resource scalar maps.
    """

    def __init__(self, scalar_names: Iterable[str] = ()):
        self.names: List[str] = [CPU, MEMORY] + sorted(set(scalar_names) - {CPU, MEMORY})
        self.index: Dict[str, int] = {n: i for i, n in enumerate(self.names)}

    def __len__(self) -> int:
        return len(self.names)

    @classmethod
    def discover(cls, resources: Iterable[Resource]) -> "ResourceNames":
        scalars = set()
        for r in resources:
            scalars.update(r.scalars)
        return cls(scalars)


_SUFFIXES = {
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}


def parse_quantity(q: object) -> float:
    """Parse a Kubernetes quantity ('100m', '4Gi', '2', 1.5) to a float.

    CPU 'm' suffix means milli — callers that want milli-CPU multiply by 1000
    themselves, so here '100m' -> 0.1.
    """
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    if s.endswith("m") and s[:-1].replace(".", "").replace("-", "").isdigit():
        return float(s[:-1]) / 1000.0
    for suf in sorted(_SUFFIXES, key=len, reverse=True):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * _SUFFIXES[suf]
    return float(s)
