"""NUMA topology model: Numatopology CR info + topology-manager hint algebra.

Mirrors /root/reference/pkg/scheduler/api/numa_info.go:38-180 (NumatopoInfo,
ResourceInfo, ResNumaSets and their Allocate/Release set arithmetic) and the
kubelet-style hint machinery the numaaware plugin builds on
(pkg/scheduler/plugins/numaaware/policy/policy.go:24-167, factory.go:30-43).

Representation choices (host-side, TPU-friendly):
- a cpuset is a plain Python ``frozenset``-able ``set[int]``;
- a NUMA-node affinity is a plain ``int`` bitmask (bit i = NUMA node i),
  so merging hints is ``&`` and narrowness is ``bit_count()`` — the same
  trick the dense solver uses for per-node NUMA masks.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from itertools import product
from typing import Dict, Iterable, List, Optional, Set

CPU_MANAGER_POLICY = "CPUManagerPolicy"        # nodeinfo/v1alpha1 PolicyName
TOPOLOGY_MANAGER_POLICY = "TopologyManagerPolicy"

CPU = "cpu"


# ---------------------------------------------------------------------------
# bitmask helpers (k8s topologymanager/bitmask, reimplemented on int)

def bitmask(numa_ids: Iterable[int]) -> int:
    mask = 0
    for i in numa_ids:
        mask |= 1 << i
    return mask


def mask_bits(mask: int) -> List[int]:
    out, i = [], 0
    while mask >> i:
        if (mask >> i) & 1:
            out.append(i)
        i += 1
    return out


def mask_count(mask: int) -> int:
    return bin(mask).count("1")


def is_narrower(a: int, b: int) -> bool:
    """bitmask.IsNarrowerThan: fewer bits set; ties broken by lower value."""
    ca, cb = mask_count(a), mask_count(b)
    if ca == cb:
        return a < b
    return ca < cb


def iterate_bitmasks(numa_ids: List[int]):
    """bitmask.IterateBitMasks — every non-empty combination of NUMA ids."""
    n = len(numa_ids)
    for bits in range(1, 1 << n):
        yield bitmask(numa_ids[i] for i in range(n) if (bits >> i) & 1)


# ---------------------------------------------------------------------------
# topology hints (policy/factory.go:30-36)

@dataclass
class TopologyHint:
    """NUMA affinity proposal for one resource of one task.

    ``affinity is None`` means "any NUMA node" (the nil bitmask in the
    reference)."""
    affinity: Optional[int]
    preferred: bool


@dataclass
class CPUInfo:
    """Per-CPU detail (kubelet topology.CPUDetails entry)."""
    numa_id: int
    socket_id: int = 0
    core_id: int = 0


@dataclass
class ResourceInfo:
    """numa_info.go:39-43 — allocatable cpuset + capacity for one resource."""
    allocatable: Set[int] = field(default_factory=set)
    capacity: int = 0

    def clone(self) -> "ResourceInfo":
        return ResourceInfo(set(self.allocatable), self.capacity)


# ResNumaSets (numa_info.go:157): resource name -> cpuset
ResNumaSets = Dict[str, Set[int]]


def res_sets_allocate(target: ResNumaSets, taken: ResNumaSets) -> None:
    """ResNumaSets.Allocate — remove assigned ids (numa_info.go:160-167)."""
    for res, ids in taken.items():
        if res in target:
            target[res] -= ids


def res_sets_release(target: ResNumaSets, taken: ResNumaSets) -> None:
    """ResNumaSets.Release (numa_info.go:170-177)."""
    for res, ids in taken.items():
        if res in target:
            target[res] |= ids


def res_sets_clone(sets: ResNumaSets) -> ResNumaSets:
    return {res: set(ids) for res, ids in sets.items()}


class NumatopoInfo:
    """Per-node topology-manager state (numa_info.go:45-114)."""

    def __init__(self, name: str = "", namespace: str = "default",
                 policies: Optional[Dict[str, str]] = None,
                 numa_res_map: Optional[Dict[str, ResourceInfo]] = None,
                 cpu_detail: Optional[Dict[int, CPUInfo]] = None,
                 res_reserved: Optional[Dict[str, float]] = None):
        self.name = name
        self.namespace = namespace
        self.policies = dict(policies or {})
        self.numa_res_map = numa_res_map or {}
        self.cpu_detail = cpu_detail or {}
        self.res_reserved = dict(res_reserved or {})

    @classmethod
    def uniform(cls, name: str, numa_nodes: int, cpus_per_node: int,
                topology_policy: str = "best-effort",
                cpu_manager_policy: str = "static") -> "NumatopoInfo":
        """Convenience builder: `numa_nodes` NUMA domains with
        `cpus_per_node` CPUs each, ids laid out contiguously."""
        detail = {}
        for node in range(numa_nodes):
            for k in range(cpus_per_node):
                detail[node * cpus_per_node + k] = CPUInfo(numa_id=node,
                                                           socket_id=node)
        return cls(name=name,
                   policies={CPU_MANAGER_POLICY: cpu_manager_policy,
                             TOPOLOGY_MANAGER_POLICY: topology_policy},
                   numa_res_map={CPU: ResourceInfo(set(detail), len(detail))},
                   cpu_detail=detail)

    def numa_nodes(self) -> List[int]:
        """numa_info.go GenerateNumaNodes per-node part."""
        return sorted({c.numa_id for c in self.cpu_detail.values()})

    def cpus_in_numa_nodes(self, mask: int) -> Set[int]:
        """CPUDetails.CPUsInNUMANodes for an affinity bitmask."""
        return {cpu for cpu, info in self.cpu_detail.items()
                if (mask >> info.numa_id) & 1}

    def deep_copy(self) -> "NumatopoInfo":
        return NumatopoInfo(
            name=self.name, namespace=self.namespace,
            policies=dict(self.policies),
            numa_res_map={r: info.clone()
                          for r, info in self.numa_res_map.items()},
            cpu_detail=dict(self.cpu_detail),
            res_reserved=dict(self.res_reserved))

    def compare(self, new: "NumatopoInfo") -> bool:
        """numa_info.go Compare: True iff no resource's allocatable set is
        shrinking in ``new`` (a shrink means running pods must be re-checked
        against the tighter topology)."""
        for res, info in self.numa_res_map.items():
            new_info = new.numa_res_map.get(res)
            if new_info is None or len(new_info.allocatable) < len(info.allocatable):
                return False
        return True

    def allocate(self, res_sets: ResNumaSets) -> None:
        """numa_info.go Allocate:106-110."""
        for res, ids in res_sets.items():
            if res in self.numa_res_map:
                self.numa_res_map[res].allocatable -= ids

    def release(self, res_sets: ResNumaSets) -> None:
        """numa_info.go Release:113-117."""
        for res, ids in res_sets.items():
            if res in self.numa_res_map:
                self.numa_res_map[res].allocatable |= ids

    def idle_sets(self) -> ResNumaSets:
        """GenerateNodeResNumaSets per-node part (numa_info.go:121-137)."""
        return {res: set(info.allocatable)
                for res, info in self.numa_res_map.items()}


# ---------------------------------------------------------------------------
# hint merge (policy/policy.go:24-167)

def filter_providers_hints(
        providers_hints: List[Dict[str, List[TopologyHint]]]
) -> List[List[TopologyHint]]:
    """policy.go filterProvidersHints — flatten per-provider per-resource
    hints; absent/None means "no preference", empty means "impossible"."""
    all_hints: List[List[TopologyHint]] = []
    for hints in providers_hints:
        if not hints:
            all_hints.append([TopologyHint(None, True)])
            continue
        for resource, res_hints in hints.items():
            if res_hints is None:
                all_hints.append([TopologyHint(None, True)])
            elif len(res_hints) == 0:
                all_hints.append([TopologyHint(None, False)])
            else:
                all_hints.append(res_hints)
    return all_hints


def merge_permutation(default_affinity: int,
                      permutation: Iterable[TopologyHint]) -> TopologyHint:
    """policy.go mergePermutation — AND of affinities; preferred iff all
    are."""
    preferred = True
    merged = default_affinity
    for hint in permutation:
        merged &= default_affinity if hint.affinity is None else hint.affinity
        preferred = preferred and hint.preferred
    return TopologyHint(merged, preferred)


def merge_filtered_hints(numa_ids: List[int],
                         filtered: List[List[TopologyHint]]) -> TopologyHint:
    """policy.go mergeFilteredHints — best (preferred, narrowest) merged
    permutation; falls back to {all-numa, not-preferred}."""
    default_affinity = bitmask(numa_ids)
    best = TopologyHint(default_affinity, False)
    for permutation in product(*filtered) if filtered else []:
        merged = merge_permutation(default_affinity, permutation)
        if merged.affinity == 0:
            continue
        if merged.preferred and not best.preferred:
            best = merged
        elif merged.preferred == best.preferred and \
                is_narrower(merged.affinity, best.affinity):
            best = merged
    return best


# ---------------------------------------------------------------------------
# policies (policy_none/best_effort/restricted/single_numa_node.go)

class Policy:
    def __init__(self, numa_ids: List[int]):
        self.numa_ids = numa_ids

    def predicate(self, providers_hints) -> tuple:
        raise NotImplementedError


class PolicyNone(Policy):
    def predicate(self, providers_hints):
        return TopologyHint(None, True), True


class PolicyBestEffort(Policy):
    def predicate(self, providers_hints):
        best = merge_filtered_hints(self.numa_ids,
                                    filter_providers_hints(providers_hints))
        return best, True


class PolicyRestricted(Policy):
    def predicate(self, providers_hints):
        best = merge_filtered_hints(self.numa_ids,
                                    filter_providers_hints(providers_hints))
        return best, best.preferred


class PolicySingleNumaNode(Policy):
    def predicate(self, providers_hints):
        filtered = filter_providers_hints(providers_hints)
        single = [[h for h in hints
                   if (h.affinity is None and h.preferred)
                   or (h.affinity is not None and mask_count(h.affinity) == 1
                       and h.preferred)]
                  for hints in filtered]
        best = merge_filtered_hints(self.numa_ids, single)
        return best, best.preferred


_POLICIES = {
    "none": PolicyNone,
    "best-effort": PolicyBestEffort,
    "restricted": PolicyRestricted,
    "single-numa-node": PolicySingleNumaNode,
}


def get_policy(topo: NumatopoInfo) -> Policy:
    """factory.go GetPolicy — policy from the node's topology-manager
    policy name."""
    cls = _POLICIES.get(topo.policies.get(TOPOLOGY_MANAGER_POLICY, "none"),
                        PolicyNone)
    return cls(topo.numa_nodes())


# ---------------------------------------------------------------------------
# snapshot helpers (numa_info.go:120-155)

def generate_node_res_numa_sets(nodes: Dict[str, object]) -> Dict[str, ResNumaSets]:
    out = {}
    for node in nodes.values():
        if getattr(node, "numa_info", None) is not None:
            out[node.name] = node.numa_info.idle_sets()
    return out


def generate_numa_nodes(nodes: Dict[str, object]) -> Dict[str, List[int]]:
    out = {}
    for node in nodes.values():
        if getattr(node, "numa_info", None) is not None:
            out[node.name] = node.numa_info.numa_nodes()
    return out
