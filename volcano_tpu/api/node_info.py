"""NodeInfo: per-node resource accounting.

Mirrors /root/reference/pkg/scheduler/api/node_info.go:29-400 — Idle/Used/
Releasing/Pipelined vectors, ``FutureIdle = Idle + Releasing - Pipelined``,
and the per-status AddTask/RemoveTask bookkeeping that the Statement undo log
relies on.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import resource as _res
from .resource import Resource
from .device_info import (GPU_MEMORY_RESOURCE, GPU_NUMBER_RESOURCE,
                          add_gpu_resource, gpu_memory_of_task,
                          make_gpu_devices, sub_gpu_resource)
from .job_info import TaskInfo
from .types import TaskStatus


def ports_conflict(want, existing) -> bool:
    """k8s nodeports conflict rule over canonical (host_ip, protocol, port)
    tuples: conflict iff protocol and port match and the hostIPs are equal or
    either side binds the 0.0.0.0 wildcard."""
    for ip, proto, port in want:
        for eip, eproto, eport in existing:
            if (port == eport and proto == eproto
                    and (ip == eip or ip == "0.0.0.0" or eip == "0.0.0.0")):
                return True
    return False


class NodeInfo:
    def __init__(self, name: str = "", allocatable: Optional[Resource] = None,
                 capability: Optional[Resource] = None,
                 labels: Optional[Dict[str, str]] = None,
                 taints: Optional[List[dict]] = None,
                 unschedulable: bool = False,
                 annotations: Optional[Dict[str, str]] = None):
        self.name = name
        self.allocatable = allocatable.clone() if allocatable else Resource()
        self.capability = capability.clone() if capability else self.allocatable.clone()
        self.idle = self.allocatable.clone()
        self.used = Resource()
        self.releasing = Resource()
        self.pipelined = Resource()
        self.labels = dict(labels or {})
        self.taints = list(taints or [])
        self.unschedulable = unschedulable
        self.annotations = dict(annotations or {})
        # volcano.sh/revocable-zone label marks time-division-multiplexed
        # nodes (tdm plugin)
        self.revocable_zone = self.labels.get("volcano.sh/revocable-zone", "")
        # volcano.sh/topology-zone label names the node's interconnect
        # locality group (rack / NUMA island, the Numatopology CRD reduced
        # to one axis); the elastic-gang compactness term co-locates gang
        # members by it (cache/snapshot.py zone_code)
        self.topology_zone = self.labels.get("volcano.sh/topology-zone", "")
        self.tasks: Dict[str, TaskInfo] = {}
        # Mutation witness for the incremental snapshot (cache.snapshot
        # clone-on-dirty, docs/performance.md): add_task/remove_task — the
        # funnel every placement-accounting mutation goes through — set it,
        # clone() starts the copy clean. The cache reuses a previous
        # snapshot's NodeInfo clone only while BOTH the live node and that
        # clone are untouched, so a session mutation (pipelines, discarded
        # statements) or a direct host-side add_task can never leak into
        # the next cycle's snapshot.
        self._touched = False
        # (host_ip, protocol, port) -> claim count for tasks on this node
        # (k8s nodeports bookkeeping; predicates.go:321 Filter input)
        self.used_ports: Dict[tuple, int] = {}
        # ready mirrors NodePhase; nodes flagged not-ready are skipped in
        # Snapshot (cache.go:822-827 analogue handled by the cache layer).
        self.ready = True
        self.others: Dict[str, object] = {}     # device extensions
        # NumatopoInfo for this node (node_info.go NumaSchedulerInfo),
        # attached by the cache from Numatopology CRs.
        self.numa_info = None
        # task uid -> ResNumaSets committed by the numaaware plugin; the
        # in-process stand-in for the node agent's Numatopology CR resync —
        # lets the cache release cpusets when the task goes away.
        self.numa_allocations: Dict[str, dict] = {}
        # GPU cards (node_info.go:57 GPUDevices). Auto-populated from
        # volcano.sh/gpu-memory + gpu-number capacity scalars like
        # NewNodeInfo -> setNodeGPUInfo (node_info.go:102,116), or set
        # explicitly via set_gpu_info().
        self.gpu_devices: Dict[int, object] = {}
        gpu_mem = self.capability.get(GPU_MEMORY_RESOURCE)
        gpu_num = self.capability.get(GPU_NUMBER_RESOURCE)
        if gpu_mem > 0 and gpu_num > 0:
            # scalars are milli-scaled (resource.py from_dict); memory stays
            # in the milli space so it compares directly with task requests
            self.set_gpu_info(gpu_mem, int(round(gpu_num / 1000.0)))

    def set_gpu_info(self, total_memory: float, card_count: int) -> None:
        """node_info.go setNodeGPUInfo:268-291. ``total_memory`` must be in
        the same (milli-scaled) units as task volcano.sh/gpu-memory
        requests."""
        self.gpu_devices = make_gpu_devices(total_memory, card_count)

    def _account_gpu(self, task: TaskInfo, add: bool) -> None:
        if not self.gpu_devices or gpu_memory_of_task(task) <= 0:
            return
        if add:
            add_gpu_resource(self.gpu_devices, task)
        else:
            sub_gpu_resource(self.gpu_devices, task)

    @property
    def max_task_num(self) -> int:
        return self.allocatable.max_task_num or 0

    def future_idle(self) -> Resource:
        """Idle + Releasing - Pipelined (node_info.go FutureIdle)."""
        return self.idle.clone().add(self.releasing).sub(self.pipelined)

    def _allocate_idle(self, task: TaskInfo) -> None:
        if not task.resreq.less_equal(self.idle):
            raise ValueError(
                f"selected node NotReady: task {task.key()} resreq {task.resreq} "
                f"exceeds idle {self.idle} on node {self.name}")
        self.idle.sub(task.resreq)

    def add_task(self, task: TaskInfo) -> None:
        """Per-status accounting (node_info.go AddTask):

        - RELEASING: consumes idle, counted in both Releasing and Used;
        - PIPELINED: only reserves future resources (Pipelined);
        - otherwise (Allocated/Bound/...): consumes idle, counted in Used.
        """
        if task.node_name and self.name and task.node_name != self.name:
            raise ValueError(f"task {task.key()} already on node {task.node_name}")
        if task.uid in self.tasks:
            raise ValueError(f"task {task.key()} already on node {self.name}")

        self._touched = True
        ti = task.clone()
        if ti.status == TaskStatus.RELEASING:
            self._allocate_idle(ti)
            self.releasing.add(ti.resreq)
            self.used.add(ti.resreq)
        elif ti.status == TaskStatus.PIPELINED:
            self.pipelined.add(ti.resreq)
        else:
            self._allocate_idle(ti)
            self.used.add(ti.resreq)

        task.node_name = self.name
        ti.node_name = self.name
        self.tasks[ti.uid] = ti
        for port in ti.host_ports:
            self.used_ports[port] = self.used_ports.get(port, 0) + 1
        if ti.status != TaskStatus.PIPELINED:
            self._account_gpu(ti, add=True)

    def remove_task(self, task: TaskInfo) -> None:
        own = self.tasks.get(task.uid)
        if own is None:
            return
        self._touched = True
        if own.status == TaskStatus.RELEASING:
            self.releasing.sub(own.resreq)
            self.idle.add(own.resreq)
            self.used.sub(own.resreq)
        elif own.status == TaskStatus.PIPELINED:
            self.pipelined.sub(own.resreq)
        else:
            self.idle.add(own.resreq)
            self.used.sub(own.resreq)
        task.node_name = ""
        del self.tasks[own.uid]
        for port in own.host_ports:
            left = self.used_ports.get(port, 0) - 1
            if left > 0:
                self.used_ports[port] = left
            else:
                self.used_ports.pop(port, None)
        if own.status != TaskStatus.PIPELINED:
            self._account_gpu(own, add=False)

    def update_task(self, task: TaskInfo) -> None:
        self.remove_task(task)
        self.add_task(task)

    def clone(self) -> "NodeInfo":
        """Snapshot copy with DIRECT state transfer, bypassing __init__:
        replaying add_task per task would re-derive idle/used/releasing/
        pipelined (and GPU card state, in a possibly different order) with
        two Resource clones and a sub/add per task, and the constructor
        itself re-clones allocatable/capability and re-runs the GPU scan —
        together ~70% of the whole-cache snapshot cost at 10k bound tasks.
        The aggregates are exact invariants of the task set, and
        allocatable/capability/labels/taints/annotations are IMMUTABLE
        after construction (no mutation site in the tree; cache updates
        replace the NodeInfo), so clones share them — the contract is
        documented on Resource (api/resource.py) and enforced in debug
        runs by freezing the shared instances here."""
        n = NodeInfo.__new__(NodeInfo)
        n.name = self.name
        n.allocatable = self.allocatable
        n.capability = self.capability
        if _res._MUTATION_GUARD:
            self.allocatable.freeze()
            if self.capability is not None:
                self.capability.freeze()
        n.idle = self.idle.clone()
        n.used = self.used.clone()
        n.releasing = self.releasing.clone()
        n.pipelined = self.pipelined.clone()
        n.labels = self.labels
        n.taints = self.taints
        n.unschedulable = self.unschedulable
        n.annotations = self.annotations
        n.revocable_zone = self.revocable_zone
        n.topology_zone = self.topology_zone
        n.used_ports = dict(self.used_ports)
        n.ready = self.ready
        n._touched = False
        n.others = dict(self.others)
        n.numa_info = self.numa_info.deep_copy() if self.numa_info else None
        n.tasks = {}
        for uid, task in self.tasks.items():
            ti = task.clone()
            ti.node_name = self.name
            n.tasks[uid] = ti
        n.gpu_devices = {i: d.clone() for i, d in self.gpu_devices.items()}
        n.numa_allocations = {uid: {res: set(ids) for res, ids in sets.items()}
                              for uid, sets in self.numa_allocations.items()}
        return n

    def has_port_conflict(self, task: TaskInfo) -> bool:
        """True when any of the task's hostPorts collides with a port already
        claimed on this node (k8s nodeports Filter semantics: same
        protocol+port, and hostIPs equal or either the 0.0.0.0 wildcard).
        Pipelined tasks' ports count too — they claim the node's future."""
        if not task.host_ports or not self.used_ports:
            return False
        return ports_conflict(task.host_ports, self.used_ports)

    def pods(self) -> List[TaskInfo]:
        return list(self.tasks.values())

    def __repr__(self) -> str:
        return f"Node({self.name} idle=<{self.idle}> used=<{self.used}>)"
