"""NodeInfo: per-node resource accounting.

Mirrors /root/reference/pkg/scheduler/api/node_info.go:29-400 — Idle/Used/
Releasing/Pipelined vectors, ``FutureIdle = Idle + Releasing - Pipelined``,
and the per-status AddTask/RemoveTask bookkeeping that the Statement undo log
relies on.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .resource import Resource
from .job_info import TaskInfo
from .types import TaskStatus


class NodeInfo:
    def __init__(self, name: str = "", allocatable: Optional[Resource] = None,
                 capability: Optional[Resource] = None,
                 labels: Optional[Dict[str, str]] = None,
                 taints: Optional[List[dict]] = None,
                 unschedulable: bool = False,
                 annotations: Optional[Dict[str, str]] = None):
        self.name = name
        self.allocatable = allocatable.clone() if allocatable else Resource()
        self.capability = capability.clone() if capability else self.allocatable.clone()
        self.idle = self.allocatable.clone()
        self.used = Resource()
        self.releasing = Resource()
        self.pipelined = Resource()
        self.labels = dict(labels or {})
        self.taints = list(taints or [])
        self.unschedulable = unschedulable
        self.annotations = dict(annotations or {})
        # volcano.sh/revocable-zone label marks time-division-multiplexed
        # nodes (tdm plugin)
        self.revocable_zone = self.labels.get("volcano.sh/revocable-zone", "")
        self.tasks: Dict[str, TaskInfo] = {}
        # ready mirrors NodePhase; nodes flagged not-ready are skipped in
        # Snapshot (cache.go:822-827 analogue handled by the cache layer).
        self.ready = True
        self.others: Dict[str, object] = {}     # device extensions (GPU/numa)
        self.numa_info = None

    @property
    def max_task_num(self) -> int:
        return self.allocatable.max_task_num or 0

    def future_idle(self) -> Resource:
        """Idle + Releasing - Pipelined (node_info.go FutureIdle)."""
        return self.idle.clone().add(self.releasing).sub(self.pipelined)

    def _allocate_idle(self, task: TaskInfo) -> None:
        if not task.resreq.less_equal(self.idle):
            raise ValueError(
                f"selected node NotReady: task {task.key()} resreq {task.resreq} "
                f"exceeds idle {self.idle} on node {self.name}")
        self.idle.sub(task.resreq)

    def add_task(self, task: TaskInfo) -> None:
        """Per-status accounting (node_info.go AddTask):

        - RELEASING: consumes idle, counted in both Releasing and Used;
        - PIPELINED: only reserves future resources (Pipelined);
        - otherwise (Allocated/Bound/...): consumes idle, counted in Used.
        """
        if task.node_name and self.name and task.node_name != self.name:
            raise ValueError(f"task {task.key()} already on node {task.node_name}")
        if task.uid in self.tasks:
            raise ValueError(f"task {task.key()} already on node {self.name}")

        ti = task.clone()
        if ti.status == TaskStatus.RELEASING:
            self._allocate_idle(ti)
            self.releasing.add(ti.resreq)
            self.used.add(ti.resreq)
        elif ti.status == TaskStatus.PIPELINED:
            self.pipelined.add(ti.resreq)
        else:
            self._allocate_idle(ti)
            self.used.add(ti.resreq)

        task.node_name = self.name
        ti.node_name = self.name
        self.tasks[ti.uid] = ti

    def remove_task(self, task: TaskInfo) -> None:
        own = self.tasks.get(task.uid)
        if own is None:
            return
        if own.status == TaskStatus.RELEASING:
            self.releasing.sub(own.resreq)
            self.idle.add(own.resreq)
            self.used.sub(own.resreq)
        elif own.status == TaskStatus.PIPELINED:
            self.pipelined.sub(own.resreq)
        else:
            self.idle.add(own.resreq)
            self.used.sub(own.resreq)
        task.node_name = ""
        del self.tasks[own.uid]

    def update_task(self, task: TaskInfo) -> None:
        self.remove_task(task)
        self.add_task(task)

    def clone(self) -> "NodeInfo":
        n = NodeInfo(name=self.name, allocatable=self.allocatable,
                     capability=self.capability, labels=self.labels,
                     taints=self.taints, unschedulable=self.unschedulable,
                     annotations=self.annotations)
        n.ready = self.ready
        n.others = dict(self.others)
        n.numa_info = self.numa_info
        for task in self.tasks.values():
            n.add_task(task.clone())
        return n

    def pods(self) -> List[TaskInfo]:
        return list(self.tasks.values())

    def __repr__(self) -> str:
        return f"Node({self.name} idle=<{self.idle}> used=<{self.used}>)"
